#!/usr/bin/env python
"""Headline benchmark suite: the BASELINE.md scheduler_perf-style configs,
run end-to-end through the full framework (in-memory apiserver -> informers
-> encode -> batched device solve -> bind -> watch confirmation).

Configs (BASELINE.json):
- headline: NodeResourcesFit/LeastAllocated shape, 15k nodes / 30k pods
  (the north-star scale; BENCH_NODES/BENCH_PODS override)
- interpod: InterPodAffinity-heavy, 5k nodes (required hostname
  anti-affinity + preferred zone affinity over app groups)
- spread:   SelectorSpread (PodTopologySpread analog), 3 zones,
  15k nodes / 30k pods with services selecting the app groups

Baseline: the reference kube-scheduler's enforced scheduler_perf threshold
is 30 pods/s at >=1000 fake nodes (hard test failure below it;
test/integration/scheduler_perf/scheduler_test.go:35-38 and BASELINE.md).
vs_baseline = headline value / 30.

Prints exactly ONE JSON line on stdout (headline metric + per-config
extras). Diagnostics go to stderr. Env overrides: BENCH_NODES, BENCH_PODS,
BENCH_TIMEOUT_S, BENCH_CONFIGS (comma list of
headline,interpod,spread,gang,preemption,recovery,chaos,overload,device),
BENCH_GANG_NODES / BENCH_GANG_PODS / BENCH_GANG_SIZE (gang config shape,
default 50k nodes / 24576 pods in 8-wide groups), BENCH_PREEMPT_NODES
(preemption drill size, default 512 nodes saturated with low-priority
filler), BENCH_CHAOS_NODES / BENCH_CHAOS_SEED (convergence-under-chaos
drill: seeded FaultPlane + watch expiry + scheduler crash; reports
chaos_recovery_ms), BENCH_OVERLOAD_NODES / BENCH_OVERLOAD_PODS /
BENCH_OVERLOAD_MULT / BENCH_OVERLOAD_SEED + BENCH_FANOUT_WATCHERS /
BENCH_FANOUT_EVENTS (noisy-tenant APF drill + watch-cache fan-out;
reports overload_p99_ms and watch_fanout_events_per_sec),
BENCH_SOLVERSVC_TENANTS / BENCH_SOLVERSVC_NODES / BENCH_SOLVERSVC_PODS /
BENCH_SOLVERSVC_BATCH_PODS / BENCH_SOLVERSVC_FLOOD (solver-as-a-service
drill: M tenant control planes — one on the stock extender wire — share
one continuous-batching device program; reports per-tenant victim p99
under a noisy flood, aggregate vs solo pods/s, and errors on any
cross-tenant assignment or double bind),
BENCH_E2E_GATE (headline pods/s hard floor at >=1000 nodes, default
15000 — pins the staged host pipeline the way BENCH_DEVICE_GATE pins the
compiled program; 0 disables, and --smoke defaults it off). The headline
extras also carry the staged pipeline's per-stage busy fractions and
inter-stage queue high-water marks (headline_pipeline_*).
BENCH_MONITOR_TARGETS / BENCH_MONITOR_SECONDS / BENCH_MONITOR_INTERVAL
shape the monitoring-plane drill (a Monitor scraping a live ObsServer
fleet; reports scrape p99, samples/s ingested, query p99, and errors on
any scrape failure or unbounded TSDB growth). BENCH_HA_NODES /
BENCH_HA_PODS / BENCH_HA_SEED / BENCH_HA_REPLICAS /
BENCH_HA_FAILOVER_P99_MS shape the rolling-restart HA drill (N stateless
apiserver replicas over one store, each killed once mid-workload — hard
and graceful — while scheduler + informers + a coherence watcher run;
errors on any double-bind, watch gap/duplicate, failover p99 past the
bound, or relists outnumbering resume-from-rv recoveries).
BENCH_DEFRAG_NODES / BENCH_DEFRAG_GANG / BENCH_DEFRAG_MAX_MOVES /
BENCH_DEFRAG_SEED shape the descheduler drill (full default 50k nodes,
8-wide gang): a seeded fragmented cluster where a Pending gang is
unschedulable despite ample aggregate capacity; the descheduler (run
under the RaceDetector store) must first plan in dry-run with zero
executed moves, then restore gang schedulability within the move budget;
errors on non-convergence, dry-run moves, double-binds, or racy writes
(reports defrag_convergence_ms and the probe-solve cost).

The opt-in `sharded` config (BENCH_CONFIGS=...,sharded) runs
headline/gang/preemption plus a device-solve gate with the node axis
GSPMD-sharded across every attached device (BENCH_SHARDED_NODES default
100000, BENCH_SHARDED_PODS, BENCH_SHARDED_GANG_PODS,
BENCH_SHARDED_PREEMPT_NODES, BENCH_SHARDED_DEVICE_PODS,
BENCH_SHARDED_GATE device floor — 0 disables). BENCH_SHARDED_FORCE_HOST=1
(the --smoke default) forces 8 virtual CPU devices via XLA_FLAGS so the
whole multi-chip path runs in CI; extras carry per-shard occupancy and
the StateDB flush-transfer counters proving the hot path never uploads
full-cluster host arrays.

--metrics-snapshot (or BENCH_METRICS_SNAPSHOT=1) embeds the scheduler's
per-phase registry histograms (encode/flush/dispatch/solve/bind/commit:
count, sum_ms, p50_ms, p99_ms) in extras for each throughput config.

--smoke (or BENCH_SMOKE=1) shrinks every config to seconds-scale CI
shapes (hundreds of nodes, no device gate) so the whole bench path —
including the autoscaler config — runs inside a tier-1 test and drift
breaks the suite instead of the next real bench run. Explicit env
overrides still win.

--trace-out PATH (or BENCH_TRACE_OUT) forces trace sampling to 1.0
(KTPU_TRACE_SAMPLE stays overridable) and writes every finished span as
Chrome trace-event JSON — load it in Perfetto / chrome://tracing for one
row per pipeline stage/thread (client, apiserver, encode, dispatch,
settle, commit, kubelet).

--profile (or BENCH_PROFILE=1) runs the continuous profiling plane
(obs/profiling.py) across the whole bench: the sampling host profiler
rides every config and its collapsed flamegraph stacks land in
--profile-out PATH (BENCH_PROFILE_OUT, default bench_profile.collapsed);
the compile registry collects per-variant compile seconds and
cost_analysis flops/bytes; and RESULT.bottleneck names the dominant
stage per config (headline from pipeline busy fractions, defrag from
probe-solve vs plan/execute split) with busy fractions, transfer bytes
and compile-cost totals attached — "name the next wall" as a gated
artifact.
"""

import faulthandler
import json
import os
import signal
import sys

RESULT: dict = {
    "metric": "pods_scheduled_per_sec_15k_nodes",
    "value": None,
    "unit": "pods/s",
    "vs_baseline": None,
}


def _die_with_timeout(signum, frame):
    faulthandler.dump_traceback(file=sys.stderr)
    RESULT["error"] = "benchmark timed out (device unavailable?)"
    print(json.dumps(RESULT), flush=True)
    os._exit(2)


def _flag_value(flag: str) -> str | None:
    """--flag value and --flag=value forms, None when absent."""
    argv = sys.argv[1:]
    for i, a in enumerate(argv):
        if a == flag:
            return argv[i + 1] if i + 1 < len(argv) else None
        if a.startswith(flag + "="):
            return a.split("=", 1)[1]
    return None


def main() -> None:
    smoke = "--smoke" in sys.argv[1:] or \
        os.environ.get("BENCH_SMOKE", "") in ("1", "true")
    profile = "--profile" in sys.argv[1:] or \
        os.environ.get("BENCH_PROFILE", "") in ("1", "true")
    profile_out = _flag_value("--profile-out") or \
        os.environ.get("BENCH_PROFILE_OUT") or "bench_profile.collapsed"
    trace_out = _flag_value("--trace-out") or \
        os.environ.get("BENCH_TRACE_OUT") or None
    if trace_out:
        # the trace artifact is the point of this run: sample every root
        # (set before any kubernetes_tpu import; an explicit env wins)
        os.environ.setdefault("KTPU_TRACE_SAMPLE", "1")
    if smoke:
        # CI shapes: every default shrinks to seconds-scale; explicit env
        # overrides still take precedence below
        os.environ.setdefault("BENCH_NODES", "200")
        os.environ.setdefault("BENCH_PODS", "400")
        os.environ.setdefault("BENCH_GANG_NODES", "256")
        os.environ.setdefault("BENCH_GANG_PODS", "64")
        os.environ.setdefault("BENCH_PREEMPT_NODES", "32")
        os.environ.setdefault("BENCH_CHAOS_NODES", "32")
        os.environ.setdefault("BENCH_AUTOSCALER_PODS", "64")
        os.environ.setdefault("BENCH_OVERLOAD_NODES", "16")
        os.environ.setdefault("BENCH_OVERLOAD_PODS", "32")
        os.environ.setdefault("BENCH_OVERLOAD_MULT", "10")
        os.environ.setdefault("BENCH_FANOUT_WATCHERS", "500")
        os.environ.setdefault("BENCH_FANOUT_EVENTS", "20")
        os.environ.setdefault("BENCH_FANOUT_XL_WATCHERS", "2000")
        os.environ.setdefault("BENCH_FANOUT_XL_EVENTS", "5")
        os.environ.setdefault("BENCH_FANOUT_XL_NOMINAL", "3")
        os.environ.setdefault("BENCH_FANOUT_XL_BASE_WATCHERS", "500")
        os.environ.setdefault("BENCH_FANOUT_XL_SCHED_NODES", "8")
        os.environ.setdefault("BENCH_FANOUT_XL_SCHED_PODS", "16")
        os.environ.setdefault("BENCH_FANOUT_XL_GATE", "0")  # CI: no gate
        os.environ.setdefault("BENCH_SOLVERSVC_TENANTS", "4")
        os.environ.setdefault("BENCH_SOLVERSVC_NODES", "8")
        os.environ.setdefault("BENCH_SOLVERSVC_PODS", "16")
        os.environ.setdefault("BENCH_SOLVERSVC_BATCH_PODS", "32")
        os.environ.setdefault("BENCH_SOLVERSVC_FLOOD", "8")
        os.environ.setdefault("BENCH_MONITOR_TARGETS", "3")
        os.environ.setdefault("BENCH_MONITOR_SECONDS", "2")
        os.environ.setdefault("BENCH_MONITOR_INTERVAL", "0.2")
        os.environ.setdefault("BENCH_HA_NODES", "8")
        os.environ.setdefault("BENCH_HA_PODS", "24")
        os.environ.setdefault("BENCH_DEFRAG_NODES", "24")
        os.environ.setdefault("BENCH_DEFRAG_GANG", "4")
        os.environ.setdefault("BENCH_DEFRAG_MAX_MOVES", "4")
        os.environ.setdefault("BENCH_DEVICE_GATE", "0")  # CPU CI: no gate
        os.environ.setdefault("BENCH_E2E_GATE", "0")     # seconds-scale run
        os.environ.setdefault("BENCH_SHARDED_NODES", "64")
        os.environ.setdefault("BENCH_SHARDED_PODS", "96")
        os.environ.setdefault("BENCH_SHARDED_GANG_PODS", "32")
        os.environ.setdefault("BENCH_SHARDED_PREEMPT_NODES", "16")
        os.environ.setdefault("BENCH_SHARDED_DEVICE_PODS", "64")
        os.environ.setdefault("BENCH_SHARDED_GATE", "0")  # CPU CI: no gate
        os.environ.setdefault("BENCH_SHARDED_FORCE_HOST", "1")
        os.environ.setdefault("BENCH_MULTIPROC_WORKERS", "2")
        os.environ.setdefault("BENCH_MULTIPROC_WATCHERS", "50")
        os.environ.setdefault("BENCH_MULTIPROC_EVENTS", "10")
        os.environ.setdefault("BENCH_MULTIPROC_PODS", "12")
        # 1-vCPU CI: worker processes contend for one core, so the
        # cross-process rate cannot beat in-process — correctness gates
        # stay armed, the perf gate does not
        os.environ.setdefault("BENCH_MULTIPROC_GATE", "0")
        os.environ.setdefault("BENCH_SOAK_NODES", "8")
        os.environ.setdefault("BENCH_SOAK_TICKS", "36")
        os.environ.setdefault("BENCH_SOAK_RATE", "1.5")
        os.environ.setdefault("BENCH_SOAK_TICK_S", "0.02")
        os.environ.setdefault("BENCH_SOAK_P99_MS", "0")  # CI: latency
        # gate off (seconds-scale ticks make p99 meaningless on CPU);
        # the exactly-once/race/stall/memory-ceiling gates stay armed
        os.environ.setdefault("BENCH_SOAK_SNAPSHOT_EVERY", "150")
        os.environ.setdefault("BENCH_SOAK_RSS_SLACK", "0.6")
        os.environ.setdefault("BENCH_STOREHA_NODES", "8")
        os.environ.setdefault("BENCH_STOREHA_PODS", "36")
        os.environ.setdefault("BENCH_FED_CLUSTERS", "3")
        os.environ.setdefault("BENCH_FED_PODS", "16")
        os.environ.setdefault(
            "BENCH_CONFIGS",
            "headline,gang,preemption,autoscaler,sharded,monitor,defrag,"
            "solver-svc,soak,store-ha,fed")
        os.environ.setdefault("BENCH_TIMEOUT_S", "600")
    timeout = int(os.environ.get("BENCH_TIMEOUT_S", "1800"))
    signal.signal(signal.SIGALRM, _die_with_timeout)
    signal.alarm(timeout)

    n_nodes = int(os.environ.get("BENCH_NODES", "15000"))
    n_pods = int(os.environ.get("BENCH_PODS", "30000"))
    configs = os.environ.get(
        "BENCH_CONFIGS",
        "headline,interpod,spread,gang,preemption,recovery,chaos,overload,"
        "device,autoscaler,monitor,ha,fanout-xl,multiproc,defrag,"
        "solver-svc,store-ha,fed")
    configs = [c.strip() for c in configs.split(",") if c.strip()]
    metrics_snapshot = "--metrics-snapshot" in sys.argv[1:] or \
        os.environ.get("BENCH_METRICS_SNAPSHOT", "") in ("1", "true")

    # the sharded config needs >=2 devices; BENCH_SHARDED_FORCE_HOST=1
    # (default in --smoke) forces 8 virtual CPU devices. Must land in
    # XLA_FLAGS before jax is imported anywhere in this process.
    if "sharded" in configs and \
            os.environ.get("BENCH_SHARDED_FORCE_HOST", "") in ("1", "true") \
            and "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8").strip()

    import jax

    from kubernetes_tpu.perf.harness import run_throughput

    if profile:
        # the profiling plane rides the whole bench: sampler thread on,
        # compile registry collecting cost_analysis per jit variant
        from kubernetes_tpu.obs import profiling

        profiling.PROFILER.start(cost_analysis=True)
        RESULT["bottleneck"] = {}

    print(f"bench: devices={jax.devices()} nodes={n_nodes} pods={n_pods} "
          f"configs={configs}", file=sys.stderr, flush=True)

    baseline = 30.0  # reference hard-fail floor at >=1000-node configs
    extras: dict = {}

    if "headline" in configs:
        r = run_throughput(n_nodes, n_pods, node_kwargs={"zones": 3})
        print(f"bench[headline]: {r} | {r.metrics}", file=sys.stderr,
              flush=True)
        RESULT["metric"] = f"pods_scheduled_per_sec_{n_nodes // 1000}k_nodes"
        RESULT["value"] = round(r.pods_per_sec, 1)
        RESULT["vs_baseline"] = round(r.pods_per_sec / baseline, 2)
        extras["headline_e2e_p50_ms"] = round(r.metrics["e2e_p50_ms"], 1)
        extras["headline_e2e_p99_ms"] = round(r.metrics["e2e_p99_ms"], 1)
        if "phase_us_per_pod" in r.metrics:
            extras["headline_phase_us_per_pod"] = r.metrics["phase_us_per_pod"]
        if r.pipeline:
            # where the next wall is: fraction of the timed wave each stage
            # thread was busy + queue-depth high-water marks between stages
            extras["headline_pipeline_busy_frac"] = \
                r.pipeline["stage_busy_frac"]
            extras["headline_pipeline_queue_max"] = \
                r.pipeline["queue_depth_max"]
            extras["headline_pipeline_depth"] = r.pipeline["depth"]
        # e2e regression gate on the headline figure itself (the device
        # gate only pins the compiled program; this one pins the host
        # pipeline too). Default floor is ~75% of the recorded staged-
        # driver rate, so a host-side regression that eats the pipeline
        # win trips the bench even when the device program is untouched.
        e2e_floor = float(os.environ.get("BENCH_E2E_GATE", "15000"))
        if e2e_floor > 0 and n_nodes >= 1000:
            extras["e2e_gate_floor_pods_per_sec"] = e2e_floor
            extras["e2e_gate_ok"] = bool(r.pods_per_sec >= e2e_floor)
            if not extras["e2e_gate_ok"]:
                RESULT["error"] = (
                    f"e2e regression: headline {r.pods_per_sec:.0f} pods/s "
                    f"< gate {e2e_floor:.0f}")
        if metrics_snapshot:
            extras["headline_phase_hist"] = r.phase_hist
        if profile:
            # dominant stage over the timed wave: pipeline busy seconds
            # when staged, phase CPU seconds otherwise
            from kubernetes_tpu.obs import profiling

            busy = (r.pipeline or {}).get("stage_busy_frac") or {}
            if busy:
                costs = {k: v * r.seconds for k, v in busy.items()}
            else:
                costs = {k: v * r.scheduled / 1e6 for k, v in
                         r.metrics.get("phase_us_per_pod", {}).items()}
            RESULT["bottleneck"]["headline"] = profiling.bottleneck_report(
                "headline", costs,
                stage_busy_frac=busy or None,
                queue_depth_max=(r.pipeline or {}).get("queue_depth_max"),
                transfer_bytes=r.transfers,
                compile_totals=profiling.COMPILES.totals(),
                wall_s=r.seconds)

    if "interpod" in configs:
        interpod_nodes = min(n_nodes, 5000)
        r = run_throughput(
            interpod_nodes, 8192,
            node_kwargs={"zones": 3},
            pod_kwargs={"app_groups": 8, "anti_affinity_every": 16,
                        "pref_affinity_every": 2})
        print(f"bench[interpod]: {r} | {r.metrics}", file=sys.stderr,
              flush=True)
        extras["interpod_5k_pods_per_sec"] = round(r.pods_per_sec, 1)
        extras["interpod_vs_baseline"] = round(r.pods_per_sec / baseline, 2)
        if metrics_snapshot:
            extras["interpod_phase_hist"] = r.phase_hist

    if "spread" in configs:
        r = run_throughput(
            15000, 30000,
            node_kwargs={"zones": 3},
            pod_kwargs={"app_groups": 16},
            n_services=16)
        print(f"bench[spread]: {r} | {r.metrics}", file=sys.stderr,
              flush=True)
        extras["spread_15k_pods_per_sec"] = round(r.pods_per_sec, 1)
        extras["spread_vs_baseline"] = round(r.pods_per_sec / baseline, 2)
        extras["spread_e2e_p50_ms"] = round(r.metrics["e2e_p50_ms"], 1)
        if "phase_us_per_pod" in r.metrics:
            extras["spread_phase_us_per_pod"] = r.metrics["phase_us_per_pod"]
        if metrics_snapshot:
            extras["spread_phase_hist"] = r.phase_hist

    if "gang" in configs:
        # gang scheduling at TPU-pod scale: 50k nodes, every pod a member
        # of an 8-wide all-or-nothing group (the multi-host-slice shape) —
        # measures the group-revert solver + group-aware driver end to end
        gang_nodes = int(os.environ.get("BENCH_GANG_NODES", "50000"))
        gang_pods = int(os.environ.get("BENCH_GANG_PODS", "24576"))
        gang_size = int(os.environ.get("BENCH_GANG_SIZE", "8"))
        gang_pods -= gang_pods % gang_size  # no trailing partial group
        r = run_throughput(gang_nodes, gang_pods,
                           node_kwargs={"zones": 3},
                           pod_kwargs={"gang_size": gang_size})
        print(f"bench[gang]: {r} | {r.metrics}", file=sys.stderr, flush=True)
        key = f"gang_{gang_nodes // 1000}k_pods_per_sec"
        extras[key] = round(r.pods_per_sec, 1)
        extras["gang_vs_baseline"] = round(r.pods_per_sec / baseline, 2)
        gang_stats = r.metrics.get("gang", {})
        extras["gang_groups_placed"] = gang_stats.get("placed", 0)
        extras["gang_groups_reverted"] = gang_stats.get("reverted", 0)
        expected_groups = gang_pods // gang_size
        if gang_stats.get("placed", 0) + gang_stats.get("reverted", 0) \
                < expected_groups:
            RESULT["error"] = (
                f"gang bench: only "
                f"{gang_stats.get('placed', 0) + gang_stats.get('reverted', 0)}"
                f"/{expected_groups} groups settled")
        if metrics_snapshot:
            extras["gang_phase_hist"] = r.phase_hist

    if "preemption" in configs:
        from kubernetes_tpu.perf.harness import run_preemption

        # priority/preemption drill: saturate CPU with globalDefault-
        # priority filler, then land a higher-PriorityClass wave through
        # the full unschedulable -> victim-select -> evict+nominate ->
        # rebind path (ROADMAP priority & preemption tentpole)
        pre_nodes = int(os.environ.get("BENCH_PREEMPT_NODES", "512"))
        r = run_preemption(pre_nodes)
        print(f"bench[preemption]: {r}", file=sys.stderr, flush=True)
        extras["preemption_latency_ms"] = round(r.preemption_latency_ms, 1)
        extras["victims_per_sec"] = round(r.victims_per_sec, 1)
        extras["preemption_wave_bound"] = r.bound_wave
        extras["preemption_victims"] = r.victims
        extras["preemption_attempts"] = r.attempts
        if r.bound_wave < r.wave:
            RESULT["error"] = (
                f"preemption bench: only {r.bound_wave}/{r.wave} "
                f"high-priority pods landed")
        elif r.victims == 0:
            RESULT["error"] = ("preemption bench: wave landed without any "
                               "evictions (cluster was not saturated)")

    if "recovery" in configs:
        from kubernetes_tpu.perf.harness import run_recovery

        # headline-scale failure drill (round 5 default 5k hollow nodes;
        # the kill concentrates in one zone so the per-zone disruption
        # machinery engages — the zone state is part of the record)
        rec_nodes = int(os.environ.get("BENCH_RECOVERY_NODES", "5000"))
        r = run_recovery(rec_nodes, 3 * rec_nodes, kill_frac=0.1)
        print(f"bench[recovery]: {r}", file=sys.stderr, flush=True)
        extras[f"recovery_seconds_zonekill_{rec_nodes}n"] = round(
            r.seconds_to_recover, 2)
        extras["recovery_killed_nodes"] = r.killed
        extras["recovery_stranded_pods"] = r.stranded
        extras["recovery_zone_state"] = r.zone_state_during
        if r.zone_state_during not in ("PartialDisruption",
                                       "FullDisruption"):
            RESULT["error"] = (
                "recovery drill: killed zone never left Normal "
                f"({r.zone_state_during!r})")

    if "chaos" in configs:
        from kubernetes_tpu.perf.harness import run_chaos

        # convergence-under-chaos drill: the whole control plane talks
        # through a seeded FaultPlane (5% store 429/Conflict), a forced
        # watch expiry + watcher drop + hard scheduler crash lands
        # mid-workload, and the cluster must converge with every pod
        # bound exactly once (tests/test_faults.py is the assert-heavy
        # twin; this row records the recovery figure on real hardware)
        chaos_nodes = int(os.environ.get("BENCH_CHAOS_NODES", "128"))
        chaos_seed = int(os.environ.get("BENCH_CHAOS_SEED", "1234"))
        # --with-race-detector: run the same drill under the RaceDetector
        # store proxy + event-loop stall watchdog (testing/races.py) and
        # fail the row on any racy write or >100ms stall — the runtime
        # half of the ktpu-lint contract, on real hardware
        race_detect = "--with-race-detector" in sys.argv[1:] or \
            os.environ.get("BENCH_RACE_DETECTOR", "") in ("1", "true")
        r = run_chaos(chaos_nodes, n_pods=max(200, 2 * chaos_nodes),
                      seed=chaos_seed, race_detect=race_detect)
        print(f"bench[chaos]: {r}", file=sys.stderr, flush=True)
        extras["chaos_recovery_ms"] = round(r.recovery_ms, 1)
        extras["chaos_faults_injected"] = r.faults_injected
        extras["chaos_seed"] = r.seed
        if race_detect:
            extras["chaos_racy_writes"] = r.racy_writes
            extras["chaos_loop_stalls"] = r.loop_stalls
            extras["chaos_max_stall_ms"] = round(r.max_stall_ms, 1)
        if not r.converged:
            RESULT["error"] = (
                f"chaos drill did not converge (seed {r.seed}): "
                f"{r.bound}/{r.pods} bound, "
                f"{r.double_binds} double-binds")
        elif race_detect and (r.racy_writes or r.loop_stalls):
            RESULT["error"] = (
                f"chaos drill under race detector (seed {r.seed}): "
                f"{r.racy_writes} racy writes, {r.loop_stalls} event-loop "
                f"stalls (max {r.max_stall_ms:.0f}ms)")

    if "overload" in configs:
        from kubernetes_tpu.perf.harness import run_overload, run_watch_fanout

        # noisy-tenant overload drill: a tenant floods the HTTP apiserver
        # at BENCH_OVERLOAD_MULT x the scheduler's own request rate while
        # a workload schedules through it over TCP. APF must keep the
        # scheduler flow's p99 within 5x the unloaded baseline and every
        # pod bound exactly once; --with-race-detector additionally runs
        # the server under the RaceDetector + loop-stall watchdog
        ovl_nodes = int(os.environ.get("BENCH_OVERLOAD_NODES", "64"))
        ovl_pods = int(os.environ.get("BENCH_OVERLOAD_PODS", "256"))
        ovl_mult = float(os.environ.get("BENCH_OVERLOAD_MULT", "50"))
        ovl_seed = int(os.environ.get("BENCH_OVERLOAD_SEED", "2026"))
        race_detect = "--with-race-detector" in sys.argv[1:] or \
            os.environ.get("BENCH_RACE_DETECTOR", "") in ("1", "true")
        r = run_overload(ovl_nodes, ovl_pods, seed=ovl_seed,
                         flood_multiplier=ovl_mult,
                         race_detect=race_detect)
        print(f"bench[overload]: {r}", file=sys.stderr, flush=True)
        extras["overload_p99_ms"] = round(r.p99_loaded_ms, 2)
        extras["overload_p99_unloaded_ms"] = round(r.p99_unloaded_ms, 2)
        extras["overload_flood_requests"] = r.flood_requests
        extras["overload_flood_rejected"] = r.flood_rejected
        extras["overload_sched_rps"] = round(r.sched_rps, 1)
        extras["overload_seed"] = r.seed
        if race_detect:
            extras["overload_racy_writes"] = r.racy_writes
            extras["overload_loop_stalls"] = r.loop_stalls
            extras["overload_max_stall_ms"] = round(r.max_stall_ms, 1)
        if not r.converged:
            RESULT["error"] = (
                f"overload drill did not converge (seed {r.seed}): "
                f"{r.bound}/{r.pods} bound, "
                f"{r.double_binds} double-binds")
        elif not r.p99_bounded:
            RESULT["error"] = (
                f"overload drill: scheduler-flow p99 {r.p99_loaded_ms:.1f}"
                f"ms breached 5x unloaded baseline "
                f"({r.p99_unloaded_ms:.1f}ms)")
        elif race_detect and (r.racy_writes or r.loop_stalls):
            RESULT["error"] = (
                f"overload drill under race detector (seed {r.seed}): "
                f"{r.racy_writes} racy writes, {r.loop_stalls} event-loop "
                f"stalls (max {r.max_stall_ms:.0f}ms)")

        # watch-cache fan-out twin: N watchers, M events, and the store
        # must do exactly M queue puts (one subscription, the cache fans
        # out) — the O(watchers) -> O(1) write-path claim, measured
        fan_watchers = int(os.environ.get("BENCH_FANOUT_WATCHERS", "10000"))
        fan_events = int(os.environ.get("BENCH_FANOUT_EVENTS", "100"))
        fr = run_watch_fanout(fan_watchers, fan_events)
        print(f"bench[fanout]: {fr}", file=sys.stderr, flush=True)
        extras["watch_fanout_events_per_sec"] = round(fr.events_per_sec, 1)
        extras["watch_fanout_store_puts"] = fr.store_fanout_puts
        extras["watch_fanout_deliveries"] = fr.deliveries
        if fr.store_fanout_puts != fan_events:
            RESULT["error"] = (
                f"watch fanout: store did {fr.store_fanout_puts} puts for "
                f"{fan_events} events (the cache is not the only "
                f"subscriber)")

    if "solver-svc" in configs:
        from kubernetes_tpu.perf.harness import run_solver_svc

        # solver-as-a-service drill: M tenant control planes (tenant-0 an
        # unmodified extender consumer over the wire, the rest native
        # /solve clients) share ONE continuous-batching device program.
        # Gates stay armed even in --smoke: exactly-once binds per tenant
        # under the RaceDetector, zero cross-tenant assignments, a noisy
        # tenant's flood moves the victim's p99 by at most 5x, and the
        # multi-tenant aggregate throughput at least matches one tenant
        # pushing the same total shape through the same warmed service
        svc_tenants = int(os.environ.get("BENCH_SOLVERSVC_TENANTS", "4"))
        svc_nodes = int(os.environ.get("BENCH_SOLVERSVC_NODES", "32"))
        svc_pods = int(os.environ.get("BENCH_SOLVERSVC_PODS", "96"))
        svc_batch = int(os.environ.get("BENCH_SOLVERSVC_BATCH_PODS", "64"))
        svc_flood = int(os.environ.get("BENCH_SOLVERSVC_FLOOD", "12"))
        svc_seed = int(os.environ.get("BENCH_SOLVERSVC_SEED", "2026"))
        race_detect = "--with-race-detector" in sys.argv[1:] or \
            os.environ.get("BENCH_RACE_DETECTOR", "") in ("1", "true")
        rs = run_solver_svc(
            n_tenants=svc_tenants, nodes_per_tenant=svc_nodes,
            pods_per_tenant=svc_pods, seed=svc_seed, batch_pods=svc_batch,
            flood_threads=svc_flood, race_detect=race_detect)
        print(f"bench[solver-svc]: {rs}", file=sys.stderr, flush=True)
        extras["solversvc_agg_pods_per_sec"] = round(rs.agg_pods_per_sec, 1)
        extras["solversvc_solo_pods_per_sec"] = \
            round(rs.solo_pods_per_sec, 1)
        extras["solversvc_victim_p99_ms"] = round(rs.p99_loaded_ms, 2)
        extras["solversvc_victim_p99_unloaded_ms"] = \
            round(rs.p99_unloaded_ms, 2)
        extras["solversvc_flood_requests"] = rs.flood_requests
        extras["solversvc_flood_rejected"] = rs.flood_rejected
        extras["solversvc_steps"] = rs.steps
        extras["solversvc_isolation_violations"] = rs.isolation_violations
        extras["solversvc_seed"] = rs.seed
        if race_detect:
            extras["solversvc_racy_writes"] = rs.racy_writes
        if not rs.converged:
            RESULT["error"] = (
                f"solver-svc drill did not converge (seed {rs.seed}): "
                f"{rs.bound}/{rs.expected_bound} bound, "
                f"{rs.double_binds} double-binds, "
                f"{rs.cross_tenant_assignments} cross-tenant assignments")
        elif rs.isolation_violations:
            RESULT["error"] = (
                f"solver-svc drill: {rs.isolation_violations} isolation "
                f"violations decoded from the shared batch")
        elif not rs.p99_bounded:
            RESULT["error"] = (
                f"solver-svc drill: victim p99 {rs.p99_loaded_ms:.1f}ms "
                f"under flood breached 5x unloaded baseline "
                f"({rs.p99_unloaded_ms:.1f}ms)")
        elif not rs.batching_wins:
            RESULT["error"] = (
                f"solver-svc drill: aggregate {rs.agg_pods_per_sec:.0f} "
                f"pods/s under {rs.tenants} tenants fell below the "
                f"single-tenant headline {rs.solo_pods_per_sec:.0f} at "
                f"the same total shape")
        elif race_detect and rs.racy_writes:
            RESULT["error"] = (
                f"solver-svc drill under race detector (seed {rs.seed}): "
                f"{rs.racy_writes} racy writes")

    if "ha" in configs:
        from kubernetes_tpu.perf.harness import run_rolling_restart

        # rolling-restart HA drill: BENCH_HA_REPLICAS stateless apiservers
        # over ONE shared store serve a live scheduler + informer +
        # coherence-watcher workload while every replica is killed once
        # mid-flight (hard aborts and a graceful drain) and restarted.
        # Contract: every pod bound exactly once, the watcher's rv stream
        # gapless and duplicate-free against the store's own history,
        # failover p99 under BENCH_HA_FAILOVER_P99_MS, and resume-from-rv
        # recoveries at least matching full relists
        ha_nodes = int(os.environ.get("BENCH_HA_NODES", "16"))
        ha_pods = int(os.environ.get("BENCH_HA_PODS", "96"))
        ha_seed = int(os.environ.get("BENCH_HA_SEED", "2027"))
        ha_replicas = int(os.environ.get("BENCH_HA_REPLICAS", "3"))
        ha_p99_bound = float(
            os.environ.get("BENCH_HA_FAILOVER_P99_MS", "2000"))
        race_detect = "--with-race-detector" in sys.argv[1:] or \
            os.environ.get("BENCH_RACE_DETECTOR", "") in ("1", "true")
        r = run_rolling_restart(ha_nodes, ha_pods, seed=ha_seed,
                                replicas=ha_replicas,
                                race_detect=race_detect)
        print(f"bench[ha]: {r}", file=sys.stderr, flush=True)
        extras["ha_replicas"] = r.replicas
        extras["ha_replica_faults"] = len(r.replica_faults)
        extras["ha_failovers"] = r.failovers
        extras["ha_failover_p99_ms"] = round(r.failover_p99_ms, 2)
        extras["ha_resumes"] = r.resumes
        extras["ha_relists"] = r.relists
        extras["ha_watch_resumes"] = r.watch_resumes
        extras["ha_watch_events"] = r.watch_events
        extras["ha_seed"] = r.seed
        if race_detect:
            extras["ha_racy_writes"] = r.racy_writes
            extras["ha_loop_stalls"] = r.loop_stalls
            extras["ha_max_stall_ms"] = round(r.max_stall_ms, 1)
        if not r.converged:
            RESULT["error"] = (
                f"ha drill did not converge (seed {r.seed}): "
                f"{r.bound}/{r.pods} bound, {r.double_binds} double-binds")
        elif r.watch_gaps or r.watch_dupes:
            RESULT["error"] = (
                f"ha drill watch incoherence (seed {r.seed}): "
                f"{r.watch_gaps} gaps, {r.watch_dupes} duplicates across "
                f"{r.watch_events} events")
        elif r.failover_p99_ms > ha_p99_bound:
            RESULT["error"] = (
                f"ha drill: failover p99 {r.failover_p99_ms:.1f}ms past "
                f"the {ha_p99_bound:.0f}ms bound")
        elif r.resumes < r.relists:
            RESULT["error"] = (
                f"ha drill: relists ({r.relists}) outnumbered resume-"
                f"from-rv recoveries ({r.resumes}) — failover is paying "
                f"full relist prices")
        elif race_detect and (r.racy_writes or r.loop_stalls):
            RESULT["error"] = (
                f"ha drill under race detector (seed {r.seed}): "
                f"{r.racy_writes} racy writes, {r.loop_stalls} event-loop "
                f"stalls (max {r.max_stall_ms:.0f}ms)")

    if "store-ha" in configs:
        from kubernetes_tpu.perf.harness import run_store_ha

        # store-HA (fenced failover) drill: BENCH_STOREHA_REPLICAS
        # *replicated stores* (WAL-streamed hot standbys,
        # apiserver/replication.py) serve a live scheduler + coherence
        # witness while the PRIMARY store is killed mid-workload — the
        # last SPOF the stateless `ha` drill can't touch — and later
        # resurrected still believing it rules. Contract: a standby
        # promotes under the lease and mints the next fencing epoch
        # (p99 under BENCH_STOREHA_PROMOTION_P99_MS), every pod binds
        # exactly once, ZERO writes are accepted under the stale epoch
        # (the resurrected primary's first write comes back FencedWrite),
        # and the witness rv stream stays gapless and duplicate-free
        # across the failover
        sha_nodes = int(os.environ.get("BENCH_STOREHA_NODES", "8"))
        sha_pods = int(os.environ.get("BENCH_STOREHA_PODS", "48"))
        sha_seed = int(os.environ.get("BENCH_STOREHA_SEED", "2031"))
        sha_replicas = int(os.environ.get("BENCH_STOREHA_REPLICAS", "3"))
        sha_p99_bound = float(
            os.environ.get("BENCH_STOREHA_PROMOTION_P99_MS", "5000"))
        race_detect = "--with-race-detector" in sys.argv[1:] or \
            os.environ.get("BENCH_RACE_DETECTOR", "") in ("1", "true")
        r = run_store_ha(sha_nodes, sha_pods, seed=sha_seed,
                         replicas=sha_replicas, race_detect=race_detect)
        print(f"bench[store-ha]: {r}", file=sys.stderr, flush=True)
        extras["store_ha_replicas"] = r.replicas
        extras["store_ha_promotions"] = r.promotions
        extras["store_ha_promotion_p99_ms"] = round(r.promotion_p99_ms, 2)
        extras["store_ha_epoch"] = r.epoch
        extras["store_ha_fenced_rejections"] = r.fenced_rejections
        extras["store_ha_fenced_leaks"] = r.fenced_leaks
        extras["store_ha_records_streamed"] = r.records_streamed
        extras["store_ha_snapshots_sent"] = r.snapshots_sent
        extras["store_ha_snapshots_discarded"] = r.snapshots_discarded
        extras["store_ha_watch_events"] = r.watch_events
        extras["store_ha_watch_resumes"] = r.watch_resumes
        extras["store_ha_seed"] = r.seed
        if race_detect:
            extras["store_ha_racy_writes"] = r.racy_writes
            extras["store_ha_loop_stalls"] = r.loop_stalls
            extras["store_ha_max_stall_ms"] = round(r.max_stall_ms, 1)
        if not r.converged:
            RESULT["error"] = (
                f"store-ha drill did not converge (seed {r.seed}): "
                f"{r.bound}/{r.pods} bound")
        elif r.double_binds:
            RESULT["error"] = (
                f"store-ha drill (seed {r.seed}): {r.double_binds} pods "
                f"bound more than once across the failover")
        elif r.fenced_leaks or not r.stale_resurrect_fenced:
            RESULT["error"] = (
                f"store-ha drill (seed {r.seed}): fencing breached — "
                f"{r.fenced_leaks} stale-epoch writes accepted "
                f"(stale primary fenced: {r.stale_resurrect_fenced})")
        elif r.promotions < 1:
            RESULT["error"] = (
                f"store-ha drill (seed {r.seed}): primary killed but no "
                f"standby promoted")
        elif r.watch_gaps or r.watch_dupes:
            RESULT["error"] = (
                f"store-ha drill watch incoherence (seed {r.seed}): "
                f"{r.watch_gaps} gaps, {r.watch_dupes} duplicates across "
                f"{r.watch_events} events")
        elif r.promotion_p99_ms > sha_p99_bound:
            RESULT["error"] = (
                f"store-ha drill: promotion p99 {r.promotion_p99_ms:.1f}ms "
                f"past the {sha_p99_bound:.0f}ms bound")
        elif race_detect and (r.racy_writes or r.loop_stalls):
            RESULT["error"] = (
                f"store-ha drill under race detector (seed {r.seed}): "
                f"{r.racy_writes} racy writes, {r.loop_stalls} event-loop "
                f"stalls (max {r.max_stall_ms:.0f}ms)")

    if "fed" in configs:
        from kubernetes_tpu.perf.harness import run_federation

        # federation global-planning drill: a hub control plane (health +
        # sync + GlobalPlanner) over BENCH_FED_CLUSTERS in-process member
        # control planes places a mixed `placement: global` workload set
        # (incl. one gang) via the stock device solver, then member 0 is
        # saturated mid-run (nodes gone, NodeGroup pinned at max — zero
        # autoscaler headroom). Contract: every workload's replicas land
        # across clusters exactly once (member copies sum to the hub
        # total and match the plan), the planner records >= 1 spillover
        # and drains the victim to zero, convergence within the bench
        # timeout, and zero racy hub writes under the RaceDetector
        fed_clusters = int(os.environ.get("BENCH_FED_CLUSTERS", "4"))
        fed_pods = int(os.environ.get("BENCH_FED_PODS", "24"))
        fed_seed = int(os.environ.get("BENCH_FED_SEED", "2032"))
        race_detect = "--with-race-detector" in sys.argv[1:] or \
            os.environ.get("BENCH_RACE_DETECTOR", "") in ("1", "true")
        r = run_federation(fed_clusters, fed_pods, seed=fed_seed,
                           race_detect=race_detect)
        print(f"bench[fed]: {r}", file=sys.stderr, flush=True)
        extras["fed_clusters"] = r.clusters
        extras["fed_workloads"] = r.workloads
        extras["fed_planned"] = r.planned
        extras["fed_placed"] = r.placed
        extras["fed_spillovers"] = r.spillovers
        extras["fed_cycles"] = r.cycles
        extras["fed_solves"] = r.solves
        extras["fed_solve_ms"] = round(r.solve_p50_ms, 2)
        extras["fed_seed"] = r.seed
        if race_detect:
            extras["fed_racy_writes"] = r.racy_writes
        if not r.converged:
            RESULT["error"] = (
                f"fed drill did not converge (seed {r.seed}): "
                f"{r.planned}/{r.workloads} planned, "
                f"{r.placed} replicas placed")
        elif not r.exactly_once or r.duplicate_placements:
            RESULT["error"] = (
                f"fed drill (seed {r.seed}): placement not exactly-once "
                f"({r.duplicate_placements} duplicated workloads)")
        elif r.spillovers < 1 or not r.victim_drained:
            RESULT["error"] = (
                f"fed drill (seed {r.seed}): saturated member did not "
                f"spill ({r.spillovers} spillovers, victim drained: "
                f"{r.victim_drained})")
        elif race_detect and r.racy_writes:
            RESULT["error"] = (
                f"fed drill under race detector (seed {r.seed}): "
                f"{r.racy_writes} racy hub writes")

    if "fanout-xl" in configs:
        from kubernetes_tpu.perf.harness import run_fanout_xl

        # sharded off-loop watch fan-out drill: BENCH_FANOUT_XL_WATCHERS
        # sink watchers on FanoutShard threads vs the single-loop
        # (KTPU_FANOUT_SHARDS=0) fallback in the same process. Gates
        # (BENCH_FANOUT_XL_GATE=0 disables the perf gates; the
        # correctness gates — O(events) store puts, zero evictions,
        # encode-once, witness coherence — are always armed):
        # deliveries/s >= gate x the single-loop baseline, and scheduler
        # batch-e2e p99 within 5x its unloaded self while the nominal
        # flood runs
        xl_watchers = int(
            os.environ.get("BENCH_FANOUT_XL_WATCHERS", "100000"))
        xl_events = int(os.environ.get("BENCH_FANOUT_XL_EVENTS", "12"))
        xl_nominal = int(os.environ.get("BENCH_FANOUT_XL_NOMINAL", "8"))
        xl_base = int(
            os.environ.get("BENCH_FANOUT_XL_BASE_WATCHERS", "10000"))
        xl_sched_nodes = int(
            os.environ.get("BENCH_FANOUT_XL_SCHED_NODES", "32"))
        xl_sched_pods = int(
            os.environ.get("BENCH_FANOUT_XL_SCHED_PODS", "128"))
        xl_gate = float(os.environ.get("BENCH_FANOUT_XL_GATE", "5"))
        xl_p99_mult = float(os.environ.get("BENCH_FANOUT_XL_P99X", "5"))
        r = run_fanout_xl(xl_watchers, xl_events,
                          nominal_events=xl_nominal,
                          baseline_watchers=xl_base,
                          sched_nodes=xl_sched_nodes,
                          sched_pods=xl_sched_pods)
        print(f"bench[fanout-xl]: {r}", file=sys.stderr, flush=True)
        extras["fanout_xl_watchers"] = r.watchers
        extras["fanout_xl_shards"] = r.shards
        extras["fanout_xl_deliveries"] = r.deliveries
        extras["fanout_xl_events_per_sec"] = round(r.events_per_sec, 1)
        extras["fanout_xl_baseline_events_per_sec"] = round(
            r.baseline_events_per_sec, 1)
        extras["fanout_xl_speedup"] = round(r.speedup, 2)
        extras["fanout_xl_store_puts"] = r.store_fanout_puts
        extras["fanout_xl_evicted"] = r.evicted
        extras["fanout_xl_frames_encoded"] = r.frames_encoded
        extras["fanout_xl_frames_delivered"] = r.frames_delivered
        extras["fanout_xl_encode_ratio"] = round(r.encode_ratio, 1)
        extras["fanout_xl_witness_events"] = r.witness_events
        extras["fanout_xl_sched_p99_base_ms"] = round(
            r.sched_p99_base_ms, 1)
        extras["fanout_xl_sched_p99_flood_ms"] = round(
            r.sched_p99_flood_ms, 1)
        if r.store_fanout_puts != r.events:
            RESULT["error"] = (
                f"fanout-xl: store did {r.store_fanout_puts} puts for "
                f"{r.events} events (the cache is not the only "
                f"subscriber)")
        elif r.evicted:
            RESULT["error"] = (
                f"fanout-xl: {r.evicted} slow-consumer evictions at "
                f"nominal rate (expected 0)")
        elif r.witness_gaps or r.witness_dupes:
            RESULT["error"] = (
                f"fanout-xl witness incoherence: {r.witness_gaps} gaps, "
                f"{r.witness_dupes} duplicates across "
                f"{r.witness_events} events at the fence rv")
        elif r.frames_encoded != r.events:
            RESULT["error"] = (
                f"fanout-xl: {r.frames_encoded} frames encoded for "
                f"{r.events} events — the encode-once contract is "
                f"broken")
        elif r.frames_delivered != r.deliveries + r.witness_events:
            RESULT["error"] = (
                f"fanout-xl: frames_delivered_total "
                f"{r.frames_delivered} != {r.deliveries} sink + "
                f"{r.witness_events} witness deliveries")
        elif xl_gate and r.speedup < xl_gate:
            RESULT["error"] = (
                f"fanout-xl: sharded delivery {r.events_per_sec:.0f}/s "
                f"is only {r.speedup:.1f}x the single-loop "
                f"{r.baseline_events_per_sec:.0f}/s (gate {xl_gate}x)")
        elif xl_gate and r.sched_p99_base_ms > 0 and \
                r.sched_p99_flood_ms > xl_p99_mult * r.sched_p99_base_ms:
            RESULT["error"] = (
                f"fanout-xl: scheduler batch-e2e p99 "
                f"{r.sched_p99_flood_ms:.1f}ms under flood breached "
                f"{xl_p99_mult}x its unloaded {r.sched_p99_base_ms:.1f}"
                f"ms")

    if "multiproc" in configs:
        from kubernetes_tpu.perf.harness import run_multiproc

        # multi-process control plane drill: a store-owner process feeds
        # BENCH_MULTIPROC_WORKERS real worker processes (pinned, own
        # serving loop + fan-out shards) through the shared-memory event
        # ring, A/B'd against the in-process sharded topology at the same
        # total-sink shape. The correctness gates are always armed —
        # encode-once across the process boundary (owner frames_encoded
        # == ring appends == store events, zero worker re-encodes),
        # exactly-once binds across a SIGKILL + respawn, a gapless
        # cross-process witness, and a zero-failure fleet scrape over
        # discovered per-worker /metrics. BENCH_MULTIPROC_GATE (default
        # 1: aggregate must at least match in-process; 0 disables) gates
        # the cross-process delivery rate
        mpw = int(os.environ.get("BENCH_MULTIPROC_WORKERS", "2"))
        mp_watchers = int(os.environ.get("BENCH_MULTIPROC_WATCHERS",
                                         "1000"))
        mp_events = int(os.environ.get("BENCH_MULTIPROC_EVENTS", "12"))
        mp_pods = int(os.environ.get("BENCH_MULTIPROC_PODS", "24"))
        mp_gate = float(os.environ.get("BENCH_MULTIPROC_GATE", "1"))
        r = run_multiproc(workers=mpw,
                          per_worker_watchers=mp_watchers,
                          events=mp_events, n_pods=mp_pods)
        print(f"bench[multiproc]: {r}", file=sys.stderr, flush=True)
        extras["multiproc_workers"] = r.workers
        extras["multiproc_watchers"] = r.watchers
        extras["multiproc_deliveries"] = r.deliveries
        extras["multiproc_events_per_sec"] = round(r.events_per_sec, 1)
        extras["multiproc_inproc_events_per_sec"] = round(
            r.inproc_events_per_sec, 1)
        extras["multiproc_speedup"] = round(r.speedup, 2)
        extras["multiproc_ring_appends"] = r.ring_appends
        extras["multiproc_worker_frames_encoded"] = r.worker_frames_encoded
        extras["multiproc_bound"] = r.bound
        extras["multiproc_bind_conflicts"] = r.bind_conflicts
        extras["multiproc_respawns"] = r.respawns
        extras["multiproc_failovers"] = r.failovers
        extras["multiproc_witness_events"] = r.witness_events
        extras["multiproc_monitor_targets"] = r.monitor_targets
        extras["multiproc_scrape_failures"] = r.scrape_failures
        if r.ring_appends != r.store_events:
            RESULT["error"] = (
                f"multiproc: {r.ring_appends} ring appends for "
                f"{r.store_events} store events — the owner is not "
                f"appending exactly once per event")
        elif r.owner_frames_encoded != r.ring_appends:
            RESULT["error"] = (
                f"multiproc: owner encoded {r.owner_frames_encoded} "
                f"frames for {r.ring_appends} ring appends — the "
                f"encode-once contract is broken at the writer")
        elif r.worker_frames_encoded:
            RESULT["error"] = (
                f"multiproc: workers re-encoded "
                f"{r.worker_frames_encoded} frames that crossed the ring "
                f"as wire bytes (expected 0)")
        elif r.deliveries < r.watchers * r.events:
            RESULT["error"] = (
                f"multiproc: {r.deliveries} sink deliveries for "
                f"{r.watchers} watchers x {r.events} events")
        elif r.bound != r.pods or r.double_binds:
            RESULT["error"] = (
                f"multiproc: {r.bound}/{r.pods} pods bound with "
                f"{r.double_binds} double-binds across the worker kill "
                f"(exactly-once broken)")
        elif r.witness_gaps or r.witness_dupes:
            RESULT["error"] = (
                f"multiproc witness incoherence: {r.witness_gaps} gaps, "
                f"{r.witness_dupes} duplicates across "
                f"{r.witness_events} events at the fence rv")
        elif not r.respawns or 0 not in r.reaped:
            RESULT["error"] = (
                f"multiproc: killed worker was not reaped+respawned "
                f"(reaped={r.reaped}, respawns={r.respawns})")
        elif r.monitor_targets < r.workers or r.scrape_failures:
            RESULT["error"] = (
                f"multiproc: monitor discovered {r.monitor_targets}/"
                f"{r.workers} worker targets with {r.scrape_failures} "
                f"scrape failures")
        elif mp_gate and r.speedup < mp_gate:
            RESULT["error"] = (
                f"multiproc: cross-process delivery "
                f"{r.events_per_sec:.0f}/s is only {r.speedup:.2f}x the "
                f"in-process {r.inproc_events_per_sec:.0f}/s "
                f"(gate {mp_gate}x)")

    if "autoscaler" in configs:
        from kubernetes_tpu.perf.harness import run_autoscaler

        # cluster-autoscaler drill: a pod burst lands on an empty node
        # group; the autoscaler's what-if probe solves must grow the group
        # until everything binds. Reports wall time to all-bound plus the
        # probe-solve cost (the device-batched simulation figure, PERF.md)
        as_pods = int(os.environ.get("BENCH_AUTOSCALER_PODS", "256"))
        as_max = int(os.environ.get("BENCH_AUTOSCALER_GROUP_MAX", "16"))
        r = run_autoscaler(n_pods=as_pods, group_max=as_max)
        print(f"bench[autoscaler]: {r}", file=sys.stderr, flush=True)
        extras["scaleup_convergence_ms"] = round(r.scaleup_convergence_ms, 1)
        extras["autoscaler_nodes_added"] = r.nodes_added
        extras["autoscaler_sim_solves"] = r.sim_solves
        extras["autoscaler_sim_ms_per_solve"] = round(r.sim_ms_per_solve, 2)
        if r.nodes_added == 0:
            RESULT["error"] = ("autoscaler bench: burst bound without any "
                               "scale-up (cluster was not empty)")

    if "defrag" in configs:
        from kubernetes_tpu.perf.harness import run_defrag

        # gang-defragmentation drill: a seeded cluster where every node
        # carries a filler pod (plus a skew pod on a quarter of them), so
        # a Pending gang is unschedulable despite ample aggregate free
        # capacity. The descheduler — run against a RaceDetector store —
        # must plan in dry-run WITHOUT executing, then evict a minimal
        # move set and restore gang schedulability inside the move budget
        # with exactly-once binds throughout
        df_nodes = int(os.environ.get("BENCH_DEFRAG_NODES", "50000"))
        df_gang = int(os.environ.get("BENCH_DEFRAG_GANG", "8"))
        df_moves = int(os.environ.get("BENCH_DEFRAG_MAX_MOVES", "8"))
        df_seed = int(os.environ.get("BENCH_DEFRAG_SEED", "1234"))
        defrag_tb0 = None
        if profile:
            from kubernetes_tpu.perf.harness import _transfer_counters

            defrag_tb0 = _transfer_counters()
        r = run_defrag(n_nodes=df_nodes, gang_size=df_gang,
                       max_moves=df_moves, seed=df_seed)
        print(f"bench[defrag]: {r}", file=sys.stderr, flush=True)
        extras["defrag_convergence_ms"] = round(r.defrag_convergence_ms, 1)
        extras["defrag_moves"] = r.moves
        extras["defrag_dry_run_planned"] = r.dry_run_planned
        extras["defrag_sim_solves"] = r.sim_solves
        extras["defrag_sim_ms_per_solve"] = round(r.sim_ms_per_solve, 2)
        extras["defrag_seed"] = r.seed
        if not r.start_unschedulable:
            RESULT["error"] = (
                f"defrag bench (seed {r.seed}): gang was schedulable "
                f"before any eviction (cluster was not fragmented)")
        elif r.dry_run_moves:
            RESULT["error"] = (
                f"defrag bench (seed {r.seed}): dry-run executed "
                f"{r.dry_run_moves} move(s) (expected 0)")
        elif not r.converged:
            RESULT["error"] = (
                f"defrag bench (seed {r.seed}): gang did not land "
                f"({r.gangs_defragged} defragged, {r.moves} moves, "
                f"{r.rollbacks} rollbacks)")
        elif r.double_binds or r.racy_writes:
            RESULT["error"] = (
                f"defrag bench (seed {r.seed}): {r.double_binds} "
                f"double-binds, {r.racy_writes} racy writes")
        if profile:
            # the defrag bill is probe solves vs everything else (plan
            # + evict + reschedule); PERF.md Round 13's 18×1369 ms story
            # becomes a gated verdict
            from kubernetes_tpu.obs import profiling
            from kubernetes_tpu.perf.harness import _transfer_counters

            tb1 = _transfer_counters()
            sim_s = r.sim_solves * r.sim_ms_per_solve / 1e3
            wall = r.defrag_convergence_ms / 1e3
            RESULT["bottleneck"]["defrag"] = profiling.bottleneck_report(
                "defrag",
                {"probe_solve": sim_s,
                 "plan_and_execute": max(0.0, wall - sim_s)},
                transfer_bytes={k: int(tb1[k] - defrag_tb0[k])
                                for k in defrag_tb0},
                compile_totals=profiling.COMPILES.totals(),
                wall_s=wall)

    if "soak" in configs:
        from kubernetes_tpu.scenario.soak import run_soak
        from kubernetes_tpu.scenario.traces import TraceConfig

        # day-in-the-life soak: a seeded trace tape (diurnal arrivals,
        # Borg-shaped gangs/priorities/lifetimes, deletes, node
        # flaps/drains/adds, watch faults) plays against the FULL control
        # plane — scheduler + autoscaler + descheduler + monitor — under
        # the RaceDetector + stall watchdog. Gates: every pod bound
        # exactly once, zero racy writes, zero >100ms stalls, flat memory
        # ceilings (RSS, WAL live records post-compaction, TSDB series,
        # jit variants) and, when armed, scheduler e2e p99. Any breach is
        # one-command reproducible from the printed replay seed;
        # scenario/search.py shrinks it to a minimal tape
        soak_nodes = int(os.environ.get("BENCH_SOAK_NODES", "15000"))
        soak_ticks = int(os.environ.get("BENCH_SOAK_TICKS", "288"))
        soak_seed = int(os.environ.get(
            "KTPU_SCENARIO_SEED",
            os.environ.get("BENCH_SOAK_SEED", "2026")))
        soak_rate = float(os.environ.get(
            "BENCH_SOAK_RATE", str(max(2.0, soak_nodes / 400))))
        soak_tick_s = float(os.environ.get("BENCH_SOAK_TICK_S", "0.25"))
        soak_p99 = float(os.environ.get("BENCH_SOAK_P99_MS", "2000"))
        soak_snapshot = int(os.environ.get(
            "BENCH_SOAK_SNAPSHOT_EVERY", "20000"))
        soak_slack = float(os.environ.get("BENCH_SOAK_RSS_SLACK", "0.35"))
        cfg = TraceConfig(
            seed=soak_seed, ticks=soak_ticks, nodes=soak_nodes,
            base_rate=soak_rate, flap_rate=0.05,
            autoscale_max=max(2, soak_nodes // 8),
            drain_every=max(2, soak_ticks // 6),
            add_every=max(2, soak_ticks // 5),
            watch_expire_ticks=(soak_ticks // 3,),
            watcher_drop_ticks=(2 * soak_ticks // 3,))
        r = run_soak(cfg, tick_seconds=soak_tick_s,
                     snapshot_every=soak_snapshot, p99_bound_ms=soak_p99,
                     rss_slack_frac=soak_slack)
        print(f"bench[soak]: {r}", file=sys.stderr, flush=True)
        extras["soak_seed"] = r.seed
        extras["soak_pods"] = r.pods_submitted
        extras["soak_bound"] = r.bound
        extras["soak_events_applied"] = r.events_applied
        extras["soak_p99_ms"] = round(r.p99_ms, 1)
        extras["soak_rss_growth_pct"] = round(100 * r.rss_growth_frac, 1)
        extras["soak_wal_compactions"] = r.compactions
        extras["soak_wal_records"] = r.wal_records
        extras["soak_tsdb_series"] = r.tsdb_series
        extras["soak_jit_variants"] = r.jit_variants
        extras["soak_scaleups"] = r.scaleups
        extras["soak_desched_moves"] = r.desched_moves
        extras["soak_node_flaps"] = r.node_flaps
        extras["soak_faults_injected"] = r.faults_injected
        extras["soak_violations"] = list(r.violations)
        if r.violations:
            RESULT["error"] = (f"soak gates breached (seed {r.seed}): "
                               + "; ".join(r.violations))
            # one-command repro: replay exactly this day
            print(f"bench[soak]: replay with KTPU_SCENARIO_SEED={r.seed} "
                  f"BENCH_CONFIGS=soak python bench.py"
                  + (" --smoke" if smoke else ""),
                  file=sys.stderr, flush=True)

    if "monitor" in configs:
        from kubernetes_tpu.perf.harness import run_monitor_bench

        # monitoring-plane overhead drill: the Monitor scrapes a fleet of
        # real ObsServers over churning registries at a fixed interval
        # while instant queries run against the TSDB. Contract: zero
        # scrape failures and a bounded TSDB (series count stable once
        # the fleet's label space is discovered)
        mon_targets = int(os.environ.get("BENCH_MONITOR_TARGETS", "5"))
        mon_seconds = float(os.environ.get("BENCH_MONITOR_SECONDS", "10"))
        mon_interval = float(os.environ.get("BENCH_MONITOR_INTERVAL", "1.0"))
        r = run_monitor_bench(mon_targets, mon_seconds, mon_interval)
        print(f"bench[monitor]: {r}", file=sys.stderr, flush=True)
        extras["monitor_scrape_p99_ms"] = round(r.scrape_p99_ms, 2)
        extras["monitor_samples_per_sec"] = round(r.samples_per_sec, 1)
        extras["monitor_query_p99_ms"] = round(r.query_p99_ms, 3)
        extras["monitor_tsdb_series"] = r.tsdb_series
        extras["monitor_tsdb_samples"] = r.tsdb_samples
        extras["monitor_scrape_failures"] = r.scrape_failures
        if r.scrape_failures:
            RESULT["error"] = (
                f"monitor bench: {r.scrape_failures} scrape failures over "
                f"{r.scrapes} rounds against a healthy fleet")
        elif not r.series_stable:
            RESULT["error"] = (
                f"monitor bench: TSDB series grew past the discovered "
                f"label space ({r.tsdb_series} series — per-scrape "
                f"series leak)")

    if "device" in configs:
        # transport-independent: steady-state compiled-solver throughput
        # with device-resident state (stable vs tunnel weather, PERF.md).
        # Two shapes: P=4096 (the r3/r4 cross-round-comparable row) and
        # P=16384 (the deep-batch steady state after the round-5 op diet
        # removed the old P=8192 layout cliff).
        from kubernetes_tpu.perf.harness import run_device_solve

        r = run_device_solve(min(n_nodes, 15000), batch_pods=4096)
        print(f"bench[device]: {r}", file=sys.stderr, flush=True)
        extras["device_solve_pods_per_sec"] = round(r.pods_per_sec, 1)
        extras["device_solve_ms"] = round(r.ms_per_solve, 2)
        rd = run_device_solve(min(n_nodes, 15000), batch_pods=16384, iters=8)
        print(f"bench[device]: {rd}", file=sys.stderr, flush=True)
        extras["device_solve_deep_pods_per_sec"] = round(rd.pods_per_sec, 1)
        extras["device_solve_deep_ms"] = round(rd.ms_per_solve, 2)
        # device perf regression gate (bench-side, on the real chip — the
        # CPU-mesh pytest floor cannot see TPU regressions): the round-5
        # recorded steady state is 53.1k (deep) / 49.2k (P=4096); tunnel-day
        # swing on these chained-compute numbers is <5%, so a 50k floor
        # (~94% of the recorded deep rate) trips on any real compiled-program
        # regression — in particular a gang-gate leak into non-gang batches.
        gate_floor = float(os.environ.get("BENCH_DEVICE_GATE", "50000"))
        extras["device_gate_floor_pods_per_sec"] = gate_floor
        extras["device_gate_ok"] = bool(rd.pods_per_sec >= gate_floor)
        if not extras["device_gate_ok"]:
            RESULT["error"] = (
                f"device solve regression: deep {rd.pods_per_sec:.0f} pods/s "
                f"< gate {gate_floor:.0f}")

    if "sharded" in configs:
        # multi-chip GSPMD path at 100k+ nodes: headline/gang/preemption
        # end-to-end with the node axis sharded across every device, plus a
        # sharded device-solve gate. The StateDB flush counters prove the
        # hot path never re-materializes full-cluster host arrays
        # (flush_full_total stays at the setup uploads), and shard_rows
        # shows the interleaved row addressing keeping occupancy balanced.
        from kubernetes_tpu.parallel.mesh import make_mesh
        from kubernetes_tpu.perf.harness import run_device_solve, \
            run_preemption

        mesh = make_mesh()
        sh_nodes = int(os.environ.get("BENCH_SHARDED_NODES", "100000"))
        sh_pods = int(os.environ.get("BENCH_SHARDED_PODS", "16384"))
        r = run_throughput(sh_nodes, sh_pods, node_kwargs={"zones": 3},
                           mesh=mesh)
        print(f"bench[sharded]: {r} | {r.sharding}", file=sys.stderr,
              flush=True)
        extras["sharded_nodes"] = sh_nodes
        extras["sharded_devices"] = mesh.size
        extras["sharded_pods_per_sec"] = round(r.pods_per_sec, 1)
        extras["sharded_vs_baseline"] = round(r.pods_per_sec / baseline, 2)
        extras["sharded_shard_rows"] = r.sharding["shard_rows"]
        extras["sharded_flush_rows_total"] = r.sharding["flush_rows_total"]
        extras["sharded_flush_transfers_total"] = \
            r.sharding["flush_transfers_total"]
        extras["sharded_flush_full_total"] = r.sharding["flush_full_total"]
        if r.scheduled < sh_pods:
            RESULT["error"] = (
                f"sharded bench: only {r.scheduled}/{sh_pods} pods bound")
        # incremental flushes must scatter dirty rows, never re-upload the
        # cluster: full uploads are only legal during node registration
        elif r.sharding["flush_full_total"] > 4:
            RESULT["error"] = (
                f"sharded bench: {r.sharding['flush_full_total']} "
                "full-cluster host uploads on the hot path (dirty-row "
                "scatter flush regressed)")

        sh_gang_pods = int(os.environ.get("BENCH_SHARDED_GANG_PODS", "8192"))
        sh_gang_pods -= sh_gang_pods % 8
        rg = run_throughput(sh_nodes, sh_gang_pods,
                            node_kwargs={"zones": 3},
                            pod_kwargs={"gang_size": 8}, mesh=mesh)
        print(f"bench[sharded/gang]: {rg}", file=sys.stderr, flush=True)
        extras["sharded_gang_pods_per_sec"] = round(rg.pods_per_sec, 1)
        gang_stats = rg.metrics.get("gang", {})
        extras["sharded_gang_groups_placed"] = gang_stats.get("placed", 0)
        extras["sharded_gang_groups_reverted"] = gang_stats.get("reverted", 0)
        settled = gang_stats.get("placed", 0) + gang_stats.get("reverted", 0)
        if settled < sh_gang_pods // 8 and "error" not in RESULT:
            RESULT["error"] = (
                f"sharded gang: only {settled}/{sh_gang_pods // 8} "
                "groups settled")

        sh_pre = int(os.environ.get("BENCH_SHARDED_PREEMPT_NODES", "512"))
        rp = run_preemption(sh_pre, mesh=mesh)
        print(f"bench[sharded/preemption]: {rp}", file=sys.stderr, flush=True)
        extras["sharded_preemption_latency_ms"] = \
            round(rp.preemption_latency_ms, 1)
        extras["sharded_preemption_victims"] = rp.victims
        if rp.bound_wave < rp.wave and "error" not in RESULT:
            RESULT["error"] = (
                f"sharded preemption: only {rp.bound_wave}/{rp.wave} "
                "high-priority pods landed")

        sh_dev_pods = int(os.environ.get("BENCH_SHARDED_DEVICE_PODS", "4096"))
        rd = run_device_solve(sh_nodes, batch_pods=sh_dev_pods, iters=8,
                              mesh=mesh)
        print(f"bench[sharded/device]: {rd}", file=sys.stderr, flush=True)
        extras["sharded_device_pods_per_sec"] = round(rd.pods_per_sec, 1)
        extras["sharded_device_solve_ms"] = round(rd.ms_per_solve, 2)
        # the sharded device gate: at 100k+ nodes on real chips the sharded
        # program must beat the single-chip N ceiling's economics; CPU CI
        # disables it (BENCH_SHARDED_GATE=0 in --smoke)
        sh_gate = float(os.environ.get("BENCH_SHARDED_GATE", "50000"))
        extras["sharded_device_gate_floor_pods_per_sec"] = sh_gate
        extras["sharded_device_gate_ok"] = \
            bool(sh_gate <= 0 or rd.pods_per_sec >= sh_gate)
        if not extras["sharded_device_gate_ok"] and "error" not in RESULT:
            RESULT["error"] = (
                f"sharded device solve: {rd.pods_per_sec:.0f} pods/s "
                f"< gate {sh_gate:.0f} at N={sh_nodes}")

    if RESULT["value"] is None and extras:
        # headline config not selected: promote the first metric actually
        # run so a filtered invocation is distinguishable from a failed one
        gang_keys = [k for k in extras
                     if k.startswith("gang_") and k.endswith("_pods_per_sec")]
        for key in ("interpod_5k_pods_per_sec", "spread_15k_pods_per_sec",
                    "sharded_pods_per_sec", "solversvc_agg_pods_per_sec",
                    *gang_keys):
            if key in extras:
                RESULT["metric"] = key
                RESULT["value"] = extras[key]
                RESULT["vs_baseline"] = round(extras[key] / baseline, 2)
                break
    if trace_out:
        from kubernetes_tpu.obs.tracing import TRACER

        with open(trace_out, "w", encoding="utf-8") as f:
            f.write(TRACER.to_chrome())
        extras["trace_out"] = trace_out
        print(f"bench: wrote Chrome trace ({len(TRACER.finished())} "
              f"spans) to {trace_out}", file=sys.stderr, flush=True)
    if profile:
        from kubernetes_tpu.obs import profiling

        profiling.PROFILER.stop()
        with open(profile_out, "w", encoding="utf-8") as f:
            f.write(profiling.PROFILER.profile_text())
        extras["profile_out"] = profile_out
        extras["profile_samples"] = profiling.PROFILER.sampler.sample_count
        extras["profile_compile_variants"] = \
            profiling.COMPILES.totals()["variants"]
        print(f"bench: wrote collapsed stacks "
              f"({extras['profile_samples']} samples) to {profile_out}",
              file=sys.stderr, flush=True)

    RESULT["extras"] = extras
    print(json.dumps(RESULT), flush=True)


if __name__ == "__main__":
    main()
