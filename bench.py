#!/usr/bin/env python
"""Headline benchmark: sustained pods scheduled/sec at 5k nodes.

Config mirrors BASELINE.json's "NodeResourcesFit LeastAllocated scoring,
5k nodes / 10k pending pods" scheduler_perf config, run end-to-end through
the full framework (in-memory apiserver -> informers -> encode -> batched
device solve -> bind -> watch confirmation).

Baseline: the reference kube-scheduler's enforced scheduler_perf threshold is
30 pods/s at >=1000 fake nodes (hard test failure below it;
test/integration/scheduler_perf/scheduler_test.go:35-38 and BASELINE.md).
vs_baseline = value / 30.

Prints exactly ONE JSON line on stdout. Diagnostics go to stderr.
Env overrides: BENCH_NODES, BENCH_PODS, BENCH_TIMEOUT_S.
"""

import faulthandler
import json
import os
import signal
import sys


def _die_with_timeout(signum, frame):
    faulthandler.dump_traceback(file=sys.stderr)
    print(json.dumps({
        "metric": "pods_scheduled_per_sec_5k_nodes",
        "value": 0.0,
        "unit": "pods/s",
        "vs_baseline": 0.0,
        "error": "benchmark timed out (device unavailable?)",
    }), flush=True)
    os._exit(2)


def main() -> None:
    timeout = int(os.environ.get("BENCH_TIMEOUT_S", "1800"))
    signal.signal(signal.SIGALRM, _die_with_timeout)
    signal.alarm(timeout)

    n_nodes = int(os.environ.get("BENCH_NODES", "5000"))
    n_pods = int(os.environ.get("BENCH_PODS", "10000"))

    import jax

    from kubernetes_tpu.perf.harness import run_throughput

    print(f"bench: devices={jax.devices()} nodes={n_nodes} pods={n_pods}",
          file=sys.stderr, flush=True)

    result = run_throughput(n_nodes, n_pods, node_kwargs={"zones": 3})
    print(f"bench: {result} | {result.metrics}", file=sys.stderr, flush=True)

    baseline = 30.0  # reference hard-fail floor at >=1000-node configs
    print(json.dumps({
        "metric": "pods_scheduled_per_sec_5k_nodes",
        "value": round(result.pods_per_sec, 1),
        "unit": "pods/s",
        "vs_baseline": round(result.pods_per_sec / baseline, 2),
    }), flush=True)


if __name__ == "__main__":
    main()
