"""Seeded fault-injection plane over the ObjectStore.

The chaos-engineering lever the convergence suites drive (Basiri et al.,
IEEE Software '16; the reference's APF/429 and etcd-flake behavior seen by
every client): a FaultPlane wraps a live ObjectStore and injects

- TooManyRequests / Conflict on the write+list verbs (seeded probability),
- synthetic request latency,
- forced watch expiry (the history window "shrinks" to nothing, so any
  resume point raises Expired and the Reflector contract kicks in),
- watcher drops (every subscriber is evicted mid-stream),
- device-solve failures via a hook the scheduler driver calls before each
  dispatch (poison pods / fail-the-next-k / hang injection).

Determinism is the point: everything random comes from one
``random.Random(seed)`` stream in op order, so a failing schedule replays
exactly from its seed. Component kills/restarts stay with the existing
ChaosMonkey/ClusterFixture machinery — a FaultPlane composes as the store
those components talk through, while the monkey's disruption callable
fires `expire_watch_history()` / `drop_watchers()` / restarts:

    plane = FaultPlane(store, seed=7, error_rate=0.05)
    sched = Scheduler(plane)            # every verb goes through the plane
    monkey = ChaosMonkey(disruption)    # disruption() pokes the plane
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable

from kubernetes_tpu.apiserver.store import (
    Conflict,
    ObjectStore,
    TooManyRequests,
)

# the verbs that default to fault injection: the write+list plane the
# control-plane components retry around (watch death is a separate lever)
DEFAULT_ERROR_OPS = ("create", "update", "list")


class SolveFault(RuntimeError):
    """Injected device-solve failure (raised from the driver's
    solve_fault_hook before dispatch — host-side only, so the compiled
    program is untouched; the HLO pin test proves it)."""


@dataclass
class _Action:
    """One scheduled disruption: fires once when the plane's op counter
    reaches `after_ops` (deterministic in op order, not wall time)."""

    after_ops: int
    fn: Callable[["FaultPlane"], None]
    name: str = ""
    fired: bool = False


@dataclass
class FaultStats:
    """What actually fired — asserted by tests, exported by the bench."""

    ops: int = 0
    injected: dict = field(default_factory=dict)   # op -> error count
    delayed: int = 0
    solve_faults: int = 0
    actions_fired: list = field(default_factory=list)
    floods: list = field(default_factory=list)     # noisy-tenant bursts
    replica_faults: list = field(default_factory=list)  # HA drill injuries
    node_flaps: list = field(default_factory=list)  # node NotReady dips

    @property
    def injected_total(self) -> int:
        return sum(self.injected.values())


class FaultPlane:
    """Seeded fault-injecting proxy around an ObjectStore.

    Every store verb ticks one op: the tick fires due scheduled actions,
    draws latency, then draws an error for ops in `error_ops` (updates
    alternate Conflict/TooManyRequests — the two retryable write failures;
    everything else raises TooManyRequests, the APF/429 shape). Unknown
    attributes delegate to the wrapped store, so the plane is drop-in
    anywhere an ObjectStore is (scheduler, kubelets, controllers,
    informers)."""

    def __init__(self, store: ObjectStore, seed: int = 0, *,
                 error_rate: float = 0.0,
                 error_ops: Iterable[str] = DEFAULT_ERROR_OPS,
                 latency_s: float = 0.0, latency_rate: float = 0.0,
                 solve_failures: int = 0,
                 solve_poison: Iterable[str] = ()):
        self.inner = store
        self.seed = seed
        self.error_rate = error_rate
        self.error_ops = frozenset(error_ops)
        self.latency_s = latency_s
        self.latency_rate = latency_rate
        # solve hook config: fail the next k solves outright, and/or fail
        # any solve whose batch contains a poison pod key ("ns/name")
        self.solve_failures = solve_failures
        self.solve_poison = set(solve_poison)
        self.solve_hang_s = 0.0
        self.solve_hangs = 0
        self.stats = FaultStats()
        self.bind_counts: dict[str, int] = {}
        self._rng = random.Random(seed)
        self._schedule: list[_Action] = []
        # noisy-tenant hook: flood() calls it with (flow, multiplier, rng)
        # — the overload harness installs the traffic generator here
        self.flood_hook: Callable[[str, float, random.Random], Any] | None \
            = None
        # HA: per-replica controls registered by attach_replica() — any
        # object exposing kill()/drain()/refuse(on)/black_hole(on)
        # (testing.replicas.ReplicaSet hands out compatible handles), so
        # the seeded action schedule can injure a SPECIFIC replica
        self.replicas: dict[int, Any] = {}
        # node-flap targets: hollow kubelets registered by attach_kubelet()
        # so traces schedule node failures like they schedule watch drops
        self.kubelets: dict[str, Any] = {}
        # store-HA: *stateful* store replicas registered by
        # attach_store_replica() — a separate namespace from the stateless
        # apiserver handles because the injury vocabulary differs
        # (kill/partition/heal/resurrect, see StoreReplicaControl)
        self.store_replicas: dict[int, Any] = {}

    # ---- schedule-driven disruptions ----

    def schedule(self, after_ops: int, fn: Callable[["FaultPlane"], None],
                 name: str = "") -> None:
        """Fire `fn(plane)` once, when the op counter reaches `after_ops`
        (op-indexed, so the disruption lands at the same point of the
        workload every replay)."""
        self._schedule.append(_Action(after_ops, fn, name or fn.__name__))

    def expire_watch_history(self) -> None:
        """Shrink the history window to nothing: any watch resume from a
        pre-expiry version now raises Expired (HTTP 410), forcing every
        consumer through the relist path."""
        self.inner._history.clear()

    def drop_watchers(self) -> None:
        """Evict every live watch subscriber mid-stream (their streams end;
        informers must notice and relist)."""
        for watcher in list(self.inner._watchers):
            self.inner._evict_watcher(watcher)

    # ---- node flaps (kubelet heartbeat dips) ----

    def attach_kubelet(self, name: str, kubelet: Any) -> None:
        """Register one node's agent (anything with a ``report_ready``
        flag and a ``_heartbeat()`` — HollowKubelet's shape) under its
        node name so scheduled actions and trace tapes can flap it."""
        self.kubelets[name] = kubelet

    def flap_node(self, name: str) -> None:
        """Soft node failure: the kubelet keeps running but its next
        heartbeats report NotReady (the node_controller flapping shape —
        distinct from ``stop()``, which is silent death). The NotReady
        condition is written synchronously so the flap lands at a
        deterministic point of the replay, not a heartbeat-timer later."""
        kubelet = self.kubelets[name]
        kubelet.report_ready = False
        kubelet._heartbeat()
        self.stats.node_flaps.append({"node": name, "kind": "down"})

    def recover_node(self, name: str) -> None:
        """End a flap: heartbeats report Ready again, written
        synchronously for the same replay-determinism reason."""
        kubelet = self.kubelets[name]
        kubelet.report_ready = True
        kubelet._heartbeat()
        self.stats.node_flaps.append({"node": name, "kind": "up"})

    # ---- per-replica targeting (HA drills) ----

    def attach_replica(self, index: int, control: Any) -> None:
        """Register one replica's control handle (kill/drain/refuse/
        black_hole) under an index the action schedule can name."""
        self.replicas[index] = control

    def kill_replica(self, index: int) -> None:
        """SIGKILL-style: abort the replica's listener and every open
        connection NOW (clients see resets mid-stream)."""
        self.stats.replica_faults.append({"replica": index, "kind": "kill"})
        self.replicas[index].kill()

    def drain_replica(self, index: int) -> None:
        """Graceful shutdown: readyz 503 first, in-flight requests finish,
        watchers get the terminal DRAIN frame."""
        self.stats.replica_faults.append({"replica": index, "kind": "drain"})
        self.replicas[index].drain()

    def refuse_replica(self, index: int, on: bool = True) -> None:
        """Close (or reopen) the replica's listener: new connections are
        refused, established ones keep serving — the half-dead shape a
        crashed accept loop produces."""
        self.stats.replica_faults.append(
            {"replica": index, "kind": "refuse", "on": on})
        self.replicas[index].refuse(on)

    def black_hole_replica(self, index: int, on: bool = True) -> None:
        """Accept connections but never answer a byte — the worst failure
        mode: only client-side I/O timeouts detect it."""
        self.stats.replica_faults.append(
            {"replica": index, "kind": "black_hole", "on": on})
        self.replicas[index].black_hole(on)

    # ---- worker-process targeting (multiproc drills) ----

    def attach_worker(self, index: int, control: Any) -> None:
        """Register one worker *process*'s control handle
        (MultiProcCluster.control: kill = real SIGKILL) under the same
        index namespace as replicas — a worker IS a replica, just behind
        a process boundary."""
        self.replicas[index] = control

    def kill_worker(self, index: int) -> None:
        """Real SIGKILL of the worker process: no drain frames, no shm
        detach, the ring reader slot just stops moving — the owner's
        liveness sweep must notice and reclaim it."""
        self.stats.replica_faults.append(
            {"replica": index, "kind": "worker-kill"})
        self.replicas[index].kill()

    # ---- store-replica targeting (store-HA drills) ----

    def attach_store_replica(self, index: int, control: Any) -> None:
        """Register one replicated-store replica's control handle
        (kill/partition/heal/resurrect — the shape
        testing.replicas.StoreReplicaSet.control hands out) so the seeded
        action schedule can injure the *stateful* layer: kill the
        primary mid-workload, resurrect it stale, partition a standby."""
        self.store_replicas[index] = control

    def kill_store_replica(self, index: int) -> None:
        """SIGKILL the store replica: apiserver, replication stream and
        lease candidacy vanish; state and beliefs freeze for a later
        resurrect (the stale-primary-return shape fencing must catch)."""
        self.stats.replica_faults.append(
            {"replica": index, "kind": "store-kill"})
        self.store_replicas[index].kill()

    def partition_store_replica(self, index: int) -> None:
        """Sever the store replica from coordination quorum and peers: a
        partitioned primary fail-safe rejects writes and loses its lease
        within renew_deadline."""
        self.stats.replica_faults.append(
            {"replica": index, "kind": "store-partition"})
        self.store_replicas[index].partition()

    def heal_store_replica(self, index: int) -> None:
        self.stats.replica_faults.append(
            {"replica": index, "kind": "store-heal"})
        self.store_replicas[index].heal()

    def resurrect_store_replica(self, index: int) -> None:
        """Bring a killed store replica back on its old ports believing
        whatever it believed — if it was the primary, its first write
        attempt must come back FencedWrite, never split-brain."""
        self.stats.replica_faults.append(
            {"replica": index, "kind": "store-resurrect"})
        self.store_replicas[index].resurrect()

    def flood(self, flow: str, rate_multiplier: float) -> None:
        """Noisy-tenant burst: drive `flow`'s request rate to
        `rate_multiplier`x the baseline. The plane records the action and
        derives a child rng from its own seeded stream, so the traffic
        generator installed via `flood_hook` (jitter, payload choice) is
        replayable from KTPU_FAULT_SEED like every other action; without
        a hook it is a recorded no-op (schedules still replay)."""
        self.stats.floods.append(
            {"flow": flow, "multiplier": rate_multiplier})
        if self.flood_hook is not None:
            self.flood_hook(flow, rate_multiplier,
                            random.Random(self._rng.randrange(1 << 32)))

    # ---- the injection tick ----

    def _tick(self, op: str) -> None:
        self.stats.ops += 1
        for action in self._schedule:
            if not action.fired and self.stats.ops >= action.after_ops:
                action.fired = True
                self.stats.actions_fired.append(action.name)
                action.fn(self)
        if self.latency_rate and self._rng.random() < self.latency_rate:
            self.stats.delayed += 1
            # deliberate: injected latency MUST stall the caller exactly
            # where a slow store would (on the loop if the caller is a
            # coroutine — that is the scenario under test)
            time.sleep(self.latency_s)  # ktpu: allow[blocking-in-async]
        if op in self.error_ops and self.error_rate \
                and self._rng.random() < self.error_rate:
            self.stats.injected[op] = self.stats.injected.get(op, 0) + 1
            if op == "update" and self._rng.random() < 0.5:
                raise Conflict(
                    f"injected fault: {op} op #{self.stats.ops} "
                    f"(seed {self.seed})")
            raise TooManyRequests(
                f"injected fault: {op} op #{self.stats.ops} "
                f"(seed {self.seed})")

    # ---- proxied store verbs ----

    def create(self, obj: Any, **kw) -> Any:
        self._tick("create")
        return self.inner.create(obj, **kw)

    def create_many(self, objs: list) -> list:
        self._tick("create")
        return self.inner.create_many(objs)

    def get(self, kind: str, name: str, namespace: str = "default") -> Any:
        self._tick("get")
        return self.inner.get(kind, name, namespace)

    def update(self, obj: Any, **kw) -> Any:
        self._tick("update")
        return self.inner.update(obj, **kw)

    def delete(self, kind: str, name: str,
               namespace: str = "default") -> Any:
        self._tick("delete")
        return self.inner.delete(kind, name, namespace)

    def list(self, *a, **kw) -> list:
        self._tick("list")
        return self.inner.list(*a, **kw)

    def list_with_version(self, kind: str):
        self._tick("list")
        return self.inner.list_with_version(kind)

    def watch(self, kind: str | None = None, since: int | None = None):
        self._tick("watch")
        return self.inner.watch(kind, since=since)

    def bind(self, binding) -> Any:
        self._tick("bind")
        out = self.inner.bind(binding)
        key = f"{binding.namespace or 'default'}/{binding.pod_name}"
        self.bind_counts[key] = self.bind_counts.get(key, 0) + 1
        return out

    def bind_many(self, bindings: list):
        self._tick("bind")
        bound, errors = self.inner.bind_many(bindings)
        for binding, err in zip(bindings, errors):
            if err is None:
                key = f"{binding.namespace or 'default'}/{binding.pod_name}"
                self.bind_counts[key] = self.bind_counts.get(key, 0) + 1
        return bound, errors

    # CAS flows run the *store's* algorithm over the *plane's* get/update,
    # so every inner read/write of a guaranteed_update draws injection
    def guaranteed_update(self, kind: str, name: str, namespace: str,
                          mutate, retries: int = 16) -> Any:
        return ObjectStore.guaranteed_update(self, kind, name, namespace,
                                             mutate, retries=retries)

    def patch(self, kind: str, name: str, namespace: str, patch,
              content_type: str, retries: int = 5) -> Any:
        return ObjectStore.patch(self, kind, name, namespace, patch,
                                 content_type, retries=retries)

    def __getattr__(self, name: str) -> Any:
        return getattr(self.inner, name)

    # ---- device-solve faults (driver hook) ----

    def solve_hook(self, live_keys: list[str]) -> None:
        """Install as ``scheduler.solve_fault_hook``: the driver calls it
        with the batch's pod keys right before dispatch."""
        if self.solve_failures > 0:
            self.solve_failures -= 1
            self.stats.solve_faults += 1
            raise SolveFault(
                f"injected solve failure (seed {self.seed}, "
                f"{self.solve_failures} left)")
        poisoned = self.solve_poison.intersection(live_keys)
        if poisoned:
            self.stats.solve_faults += 1
            raise SolveFault(
                f"injected poison-pod solve failure: {sorted(poisoned)} "
                f"(seed {self.seed})")
        if self.solve_hangs > 0 and self.solve_hang_s > 0:
            # wedged-device injection: runs inside the driver's watchdog
            # thread, so a configured solve timeout fires around it
            self.solve_hangs -= 1
            self.stats.solve_faults += 1
            time.sleep(self.solve_hang_s)  # ktpu: allow[blocking-in-async]
