"""E2E test framework: a whole cluster as one async fixture.

The test/e2e/framework analog (framework.go: per-test namespace, cluster
helpers, teardown) fused with the integration ring's in-process master
(test/integration/framework/master_utils.go:453 RunAMaster): one call
boots store (+ optional WAL), apiserver-equivalent wiring, controller
manager, scheduler, and a kubelet fleet with a fake runtime — the full
control plane the e2e suites drive."""

from __future__ import annotations

import asyncio
import itertools

from kubernetes_tpu.agent.kubelet import KubeletCluster
from kubernetes_tpu.apiserver import ObjectStore
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.state import Capacities

_ns_counter = itertools.count(1)


class ClusterFixture:
    def __init__(self, n_nodes: int = 4, caps: Capacities | None = None,
                 node_lifecycle_kwargs: dict | None = None,
                 capacity: dict | None = None):
        self.store = ObjectStore()
        self.kubelets = KubeletCluster(
            self.store, n_nodes=n_nodes, heartbeat_every=0.2,
            capacity=capacity or {"cpu": "16", "memory": "32Gi",
                                  "pods": "110"})
        self.manager = ControllerManager(
            self.store,
            node_lifecycle_kwargs=node_lifecycle_kwargs
            or dict(monitor_period=0.1, grace_period=0.6,
                    eviction_timeout=0.2, eviction_rate=1000.0))
        self.caps = caps or Capacities(
            num_nodes=max(8, 1 << (n_nodes - 1).bit_length()),
            batch_pods=64)
        self.scheduler = Scheduler(self.store, caps=self.caps)
        self._driver_task: asyncio.Task | None = None

    async def start(self) -> "ClusterFixture":
        await self.kubelets.start()
        await self.manager.start()
        await self.scheduler.start()
        self._driver_task = asyncio.get_running_loop().create_task(
            self.scheduler.run())
        return self

    def stop(self) -> None:
        self.scheduler.stop()
        if self._driver_task is not None:
            self._driver_task.cancel()
        self.manager.stop()
        self.kubelets.stop()

    async def restart_scheduler(self) -> None:
        """Component-restart disruption: kill the scheduler mid-flight and
        bring up a fresh instance that must rebuild all state by relisting
        (the crash-only contract, SURVEY.md §5.4)."""
        self.scheduler.stop()
        if self._driver_task is not None:
            self._driver_task.cancel()
        self.scheduler = Scheduler(self.store, caps=self.caps)
        await self.scheduler.start()
        self._driver_task = asyncio.get_running_loop().create_task(
            self.scheduler.run())

    def namespace(self) -> str:
        """A fresh per-test namespace name (framework.go CreateNamespace)."""
        return f"e2e-{next(_ns_counter)}"

    # ---- assertion helpers ----

    def pods(self, namespace: str | None = None):
        return self.store.list("Pod", namespace, copy_objects=False)

    async def wait_running(self, count: int, namespace: str | None = None,
                           timeout: float = 30.0) -> None:
        async with asyncio.timeout(timeout):
            while sum(1 for p in self.pods(namespace)
                      if p.status.phase == "Running") < count:
                await asyncio.sleep(0.05)
