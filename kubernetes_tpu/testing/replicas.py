"""N stateless apiserver replicas over ONE shared ObjectStore.

The HA control-plane topology packaged for drills and tests: the
reference's N-apiservers-over-shared-etcd shape, where every replica has
its own watch cache, APF queues and obs mux, and coherence comes from the
store's single resourceVersion sequence. The ReplicaSet owns the serving
side; clients talk HTTP through a replica-aware RemoteStore built from
`endpoints`/`client()`.

Single-loop discipline: the shared ObjectStore's watch fan-out
(asyncio.Queue) is loop-affine, so ALL replicas serve on ONE background
event loop — isolation between replicas is the HTTP boundary, exactly as
N processes over one etcd are isolated by the network. Every control
method (`kill`, `drain`, `refuse`, `black_hole`, `restart`) marshals onto
that loop and is safe to call from the client thread.

`control(i)` hands out FaultPlane-compatible handles for
`FaultPlane.attach_replica`, so the seeded action schedule can injure a
specific replica mid-workload:

    with ReplicaSet(store, n=3, watch_cache=True) as rs:
        plane.attach_replica(0, rs.control(0))
        plane.schedule(200, lambda p: p.kill_replica(0), "kill-r0")
        remote = rs.client()          # fails over across all 3
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import os
import shutil
import signal
import tempfile
import threading
import time
from typing import Any

from kubernetes_tpu.apiserver.http import APIServer, RemoteStore
from kubernetes_tpu.apiserver.multiproc import (
    StoreOwner,
    WorkerSpec,
    free_port,
    spawn_worker,
    wait_port,
)
from kubernetes_tpu.apiserver.replication import StoreReplica
from kubernetes_tpu.apiserver.store import ObjectStore


class ReplicaControl:
    """One replica's injury handle (the FaultPlane.attach_replica shape:
    kill/drain/refuse/black_hole, all thread-safe)."""

    def __init__(self, replica_set: "ReplicaSet", index: int):
        self._rs = replica_set
        self.index = index

    def kill(self) -> None:
        self._rs.kill(self.index)

    def drain(self, timeout: float | None = None) -> None:
        self._rs.drain(self.index, timeout)

    def refuse(self, on: bool = True) -> None:
        self._rs.refuse(self.index, on)

    def black_hole(self, on: bool = True) -> None:
        self._rs.black_hole(self.index, on)


class ReplicaSet:
    """N APIServer replicas over one shared store, one serving loop."""

    def __init__(self, store: Any = None, n: int = 3,
                 host: str = "127.0.0.1", watch_cache: bool = True,
                 drain_timeout: float = 5.0, advertise: bool = True,
                 **server_kwargs):
        # `store` may be the raw ObjectStore or any proxy over it
        # (FaultPlane, RaceDetector) — exactly like APIServer itself
        self.store = store if store is not None else ObjectStore()
        self.n = n
        self.host = host
        self.watch_cache = watch_cache
        self.drain_timeout = drain_timeout
        self.advertise = advertise
        self.server_kwargs = server_kwargs
        self.servers: list[APIServer] = []
        self.loop: asyncio.AbstractEventLoop | None = None
        self._ports: list[int] = []
        self._thread: threading.Thread | None = None
        self._started = threading.Event()
        self._startup_error: BaseException | None = None

    # ---- lifecycle ----

    def start(self) -> "ReplicaSet":
        def serve():
            async def main():
                self.loop = asyncio.get_running_loop()
                shutdown = asyncio.Event()
                self._shutdown = shutdown
                try:
                    for i in range(self.n):
                        server = self._make_server(i, port=0)
                        await server.start()
                        if self.advertise:
                            server.advertise()
                        self.servers.append(server)
                        self._ports.append(server.port)
                except BaseException as e:  # surface to the caller thread
                    self._startup_error = e
                    self._started.set()
                    raise
                self._started.set()
                await shutdown.wait()
                for server in self.servers:
                    try:
                        await server.stop()
                    except Exception:
                        pass

            asyncio.run(main())

        self._thread = threading.Thread(
            target=serve, name="ktpu-replicaset", daemon=True)
        self._thread.start()
        if not self._started.wait(timeout=10.0):
            raise RuntimeError("replica set failed to start within 10s")
        if self._startup_error is not None:
            raise RuntimeError("replica startup failed") \
                from self._startup_error
        return self

    def stop(self) -> None:
        if self.loop is not None and not self.loop.is_closed():
            try:
                self.loop.call_soon_threadsafe(self._shutdown.set)
            except RuntimeError:
                pass  # loop already closing
        if self._thread is not None:
            self._thread.join(timeout=10.0)

    def __enter__(self) -> "ReplicaSet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _make_server(self, index: int, port: int) -> APIServer:
        return APIServer(self.store, host=self.host, port=port,
                         watch_cache=self.watch_cache,
                         replica_id=f"replica-{index}",
                         **self.server_kwargs)

    # ---- addressing ----

    @property
    def endpoints(self) -> list[tuple[str, int]]:
        """The full (host, port) set — ports are stable across restart()."""
        return [(self.host, p) for p in self._ports]

    def client(self, **kw) -> RemoteStore:
        """A replica-aware RemoteStore over every endpoint."""
        return RemoteStore(self.host, self._ports[0],
                           endpoints=self.endpoints, **kw)

    def control(self, index: int) -> ReplicaControl:
        return ReplicaControl(self, index)

    def controls(self) -> list[ReplicaControl]:
        return [ReplicaControl(self, i) for i in range(self.n)]

    # ---- loop marshalling ----

    def _on_loop(self) -> bool:
        """True when the caller is already the serving loop — a FaultPlane
        action firing inside a store tick. Blocking on a future there
        would deadlock the loop against itself."""
        try:
            return asyncio.get_running_loop() is self.loop
        except RuntimeError:
            return False

    def _call(self, fn, timeout: float = 10.0) -> Any:
        """Run sync `fn()` on the serving loop, wait for the result."""
        assert self.loop is not None, "replica set not started"
        if self._on_loop():
            return fn()
        fut: concurrent.futures.Future = concurrent.futures.Future()

        def run():
            try:
                fut.set_result(fn())
            except BaseException as e:  # noqa: BLE001 — relay to caller
                fut.set_exception(e)

        self.loop.call_soon_threadsafe(run)
        return fut.result(timeout=timeout)

    # ---- per-replica injuries / lifecycle ----

    def kill(self, index: int) -> None:
        """SIGKILL-style: abort the replica's listener and every open
        connection (clients see mid-stream resets)."""
        server = self.servers[index]
        self._call(server.kill)

    def drain(self, index: int, timeout: float | None = None) -> None:
        """Graceful shutdown: readyz 503s, in-flight finishes, watchers
        get the terminal DRAIN frame. Blocks until the drain completes."""
        server = self.servers[index]
        t = self.drain_timeout if timeout is None else timeout
        assert self.loop is not None, "replica set not started"
        if self._on_loop():
            # fired from a store tick on the serving loop (a scheduled
            # FaultPlane action): run the drain as a task — blocking here
            # would deadlock the loop the drain needs
            self.loop.create_task(server.drain(t))
            return
        asyncio.run_coroutine_threadsafe(
            server.drain(t), self.loop).result(timeout=t + 5.0)

    def refuse(self, index: int, on: bool = True) -> None:
        """Close (reopen) the listener only: new connections are refused,
        established ones keep serving — the half-dead accept-loop shape."""
        server = self.servers[index]
        if on:
            def close():
                if server._server is not None:
                    server._server.close()
                    server._server = None

            self._call(close)
        else:
            assert self.loop is not None, "replica set not started"
            asyncio.run_coroutine_threadsafe(
                server.start(), self.loop).result(timeout=10.0)

    def black_hole(self, index: int, on: bool = True) -> None:
        """Accept but never answer — only client I/O timeouts detect it."""
        server = self.servers[index]

        def flip():
            server._black_holed = on

        self._call(flip)

    def restart(self, index: int) -> APIServer:
        """Bring a fresh stateless replica up on the SAME port (so static
        endpoint lists stay valid): new process state, same shared store —
        the rolling-restart recovery step."""
        port = self._ports[index]

        async def bring_up():
            server = self._make_server(index, port=port)
            await server.start()
            if self.advertise:
                server.advertise()
            return server

        assert self.loop is not None, "replica set not started"
        new = asyncio.run_coroutine_threadsafe(
            bring_up(), self.loop).result(timeout=10.0)
        self.servers[index] = new
        return new


class WorkerControl:
    """One worker *process*'s injury handle (FaultPlane.attach_replica
    shape, process edition: kill is a real SIGKILL)."""

    def __init__(self, cluster: "MultiProcCluster", index: int):
        self._cluster = cluster
        self.index = index

    def kill(self) -> None:
        self._cluster.kill_worker(self.index)

    def drain(self, timeout: float | None = None) -> None:
        self._cluster.terminate_worker(self.index)

    def refuse(self, on: bool = True) -> None:
        raise NotImplementedError("worker processes support kill/drain")

    def black_hole(self, on: bool = True) -> None:
        raise NotImplementedError("worker processes support kill/drain")


class MultiProcCluster:
    """The multi-process control plane packaged for drills and tests:
    THIS process is the store-owner (authoritative ObjectStore + ring
    writer + mutation RPC on a background loop thread, exactly the
    ReplicaSet serving pattern), and `n` real OS worker processes each
    run their own serving loop + fan-out shards over a ring-fed mirror.

    Same addressing surface as ReplicaSet (`endpoints` / `client()`), so
    FailoverWatch, informers, and the rolling-kill drills work unchanged
    across the process boundary.

        with MultiProcCluster(n=2, shards=4) as mp:
            remote = mp.client()
            mp.kill_worker(0)        # SIGKILL, mid-anything
            mp.respawn_worker(0)     # resumes from the ring
    """

    def __init__(self, store: Any = None, n: int = 2,
                 host: str = "127.0.0.1", *,
                 shards: int | None = None,
                 ring_capacity: int = 1 << 22,
                 bench_watchers: int = 0, bench_kind: str = "Pod",
                 advertise: bool = True,
                 heartbeat_s: float | None = None,
                 spawn_timeout: float = 30.0):
        self.store = store if store is not None else ObjectStore()
        self.n = n
        self.host = host
        self.shards = shards
        self.ring_capacity = ring_capacity
        self.bench_watchers = bench_watchers
        self.bench_kind = bench_kind
        self.advertise = advertise
        self.heartbeat_s = heartbeat_s
        self.spawn_timeout = spawn_timeout
        self.owner: StoreOwner | None = None
        self.procs: list[Any] = [None] * n
        self.specs: list[WorkerSpec] = []
        self._ports: list[int] = []
        self.loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._started = threading.Event()
        self._startup_error: BaseException | None = None
        self.respawns = 0

    # ---- lifecycle ----

    def start(self) -> "MultiProcCluster":
        self._ports = [free_port(self.host) for _ in range(self.n)]

        def serve():
            async def main():
                self.loop = asyncio.get_running_loop()
                shutdown = asyncio.Event()
                self._shutdown = shutdown
                try:
                    self.owner = StoreOwner(
                        self.store, ring_capacity=self.ring_capacity,
                        n_slots=max(self.n, 2))
                    await self.owner.start()
                except BaseException as e:
                    self._startup_error = e
                    self._started.set()
                    raise
                self._started.set()
                await shutdown.wait()
                await self.owner.aclose()

            asyncio.run(main())

        self._thread = threading.Thread(
            target=serve, name="ktpu-mp-owner", daemon=True)
        self._thread.start()
        if not self._started.wait(timeout=10.0):
            raise RuntimeError("store owner failed to start within 10s")
        if self._startup_error is not None:
            raise RuntimeError("store owner startup failed") \
                from self._startup_error
        self.specs = [
            WorkerSpec(worker_id=i, ring_name=self.owner.ring.name,
                       rpc_path=self.owner.rpc_path, host=self.host,
                       port=self._ports[i], shards=self.shards,
                       advertise=self.advertise,
                       heartbeat_s=self.heartbeat_s,
                       bench_watchers=self.bench_watchers,
                       bench_kind=self.bench_kind)
            for i in range(self.n)
        ]
        try:
            for i in range(self.n):
                self._spawn(i)
        except BaseException:
            self.stop()
            raise
        return self

    def _spawn(self, index: int) -> None:
        proc = spawn_worker(self.specs[index])
        self.procs[index] = proc
        if not wait_port(self.host, self._ports[index],
                         timeout_s=self.spawn_timeout):
            raise RuntimeError(
                f"worker {index} (pid {proc.pid}) did not come up on "
                f"{self.host}:{self._ports[index]} within "
                f"{self.spawn_timeout}s")

    def stop(self) -> None:
        # graceful first (DRAIN frames, shard joins, shm detach) ...
        for i, proc in enumerate(self.procs):
            if proc is not None and proc.is_alive():
                try:
                    os.kill(proc.pid, signal.SIGTERM)
                except ProcessLookupError:
                    pass
        deadline = time.monotonic() + 5.0
        for proc in self.procs:
            if proc is not None:
                proc.join(timeout=max(0.1, deadline - time.monotonic()))
        # ... SIGKILL stragglers so teardown never hangs a test run
        for proc in self.procs:
            if proc is not None and proc.is_alive():
                proc.kill()
                proc.join(timeout=2.0)
        self.procs = [None] * self.n
        if self.loop is not None and not self.loop.is_closed():
            try:
                self.loop.call_soon_threadsafe(self._shutdown.set)
            except RuntimeError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=10.0)

    # stop() already unlinks the segment (owner.aclose on the loop) and
    # reaps every child; the alias is the ReplicaSet-compatible name
    aclose = stop

    def __enter__(self) -> "MultiProcCluster":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ---- addressing ----

    @property
    def endpoints(self) -> list[tuple[str, int]]:
        return [(self.host, p) for p in self._ports]

    def client(self, **kw) -> RemoteStore:
        return RemoteStore(self.host, self._ports[0],
                           endpoints=self.endpoints, **kw)

    def control(self, index: int) -> WorkerControl:
        return WorkerControl(self, index)

    # ---- owner-loop marshalling ----

    def _call(self, fn, timeout: float = 10.0) -> Any:
        assert self.loop is not None, "cluster not started"
        fut: concurrent.futures.Future = concurrent.futures.Future()

        def run():
            try:
                fut.set_result(fn())
            except BaseException as e:  # noqa: BLE001 — relay to caller
                fut.set_exception(e)

        self.loop.call_soon_threadsafe(run)
        return fut.result(timeout=timeout)

    # ---- worker lifecycle (the crash-and-respawn satellite) ----

    def kill_worker(self, index: int) -> None:
        """Real SIGKILL mid-anything: no drain, no DRAIN frames, the
        ring reader slot simply stops moving."""
        proc = self.procs[index]
        if proc is None:
            return
        try:
            os.kill(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        proc.join(timeout=5.0)

    def terminate_worker(self, index: int) -> None:
        """SIGTERM: the worker drains (terminal DRAIN frames), joins its
        shard threads, detaches from the ring, exits 0."""
        proc = self.procs[index]
        if proc is None:
            return
        try:
            os.kill(proc.pid, signal.SIGTERM)
        except ProcessLookupError:
            pass
        proc.join(timeout=10.0)

    def reap_dead(self) -> list[int]:
        """Owner-side liveness sweep: find reader slots whose pid is
        gone, reclaim them (pid cleared, read_pos/last_rv kept for the
        respawn's no-replay resume)."""
        assert self.owner is not None
        dead = self.owner.dead_workers()
        for wid in dead:
            self.owner.reclaim_slot(wid)
        return dead

    def respawn_worker(self, index: int) -> None:
        """Bring a fresh worker process up on the SAME port and reader
        slot. It snapshots the owner (rv ≥ the dead worker's last_rv),
        resumes the ring at the snapshot position, and inherits the
        slot's last_rv floor — frames the dead process already delivered
        are never replayed."""
        assert self.owner is not None
        proc = self.procs[index]
        if proc is not None and proc.is_alive():
            raise RuntimeError(f"worker {index} is still alive")
        self.owner.reclaim_slot(index)
        self.respawns += 1
        self._spawn(index)


class StoreReplicaControl:
    """One *store* replica's injury handle (the shape
    FaultPlane.attach_store_replica expects: kill/partition/heal/
    resurrect, all thread-safe). Store replicas are stateful, so the
    injury vocabulary differs from the stateless apiserver handles —
    `resurrect` brings the SAME state and beliefs back, the stale-primary
    return the fencing epoch exists to contain."""

    def __init__(self, group: "StoreReplicaSet", index: int):
        self._group = group
        self.index = index

    def kill(self) -> None:
        self._group.kill(self.index)

    def partition(self) -> None:
        self._group.partition(self.index)

    def heal(self) -> None:
        self._group.heal(self.index)

    def resurrect(self) -> None:
        self._group.resurrect(self.index)


class StoreReplicaSet:
    """N replicated *stores* (each with its own apiserver, WAL and
    replication link) over one coordination store — the topology
    `apiserver/replication.py` builds, packaged for drills and tests the
    way ReplicaSet packages stateless apiservers.

    Single-loop discipline, same as ReplicaSet: every replica's asyncio
    pieces (apiserver, replication stream, elector) run on ONE background
    loop; isolation between replicas is the HTTP/TCP boundary. All
    control methods marshal onto that loop and are safe from the client
    thread AND from FaultPlane actions firing on the loop itself.

    `coord_store` may be the raw ObjectStore or any proxy over it
    (FaultPlane, RaceDetector) — the store-HA drill wraps the
    coordination quorum in the plane so elector renew traffic ticks the
    seeded op schedule.

        with StoreReplicaSet(n=3, lease_duration=0.6) as sg:
            plane.attach_store_replica(0, sg.control(0))
            remote = sg.client()       # chases the current primary
            sg.kill(sg.primary_index())
            sg.wait_for_primary()      # a standby promotes, epoch+1
    """

    def __init__(self, coord_store: Any = None, n: int = 3,
                 host: str = "127.0.0.1", *,
                 watch_window: int = 4096,
                 persist_dir: str | None = None,
                 lease_duration: float = 0.6,
                 renew_deadline: float = 0.45,
                 retry_period: float = 0.05,
                 follower_queue: int = 8192,
                 server_kwargs: dict | None = None):
        self.coord_store = coord_store if coord_store is not None \
            else ObjectStore()
        self.n = n
        self.host = host
        self.watch_window = watch_window
        self._own_persist_dir = persist_dir is None
        self.persist_dir = persist_dir
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self.follower_queue = follower_queue
        self.server_kwargs = dict(server_kwargs or {})
        self.replicas: list[StoreReplica] = []
        self.loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._started = threading.Event()
        self._startup_error: BaseException | None = None
        # promotion-latency ledger: outage_mark() (or killing/partitioning
        # the current primary) stamps t0; the next on_promoted callback
        # closes the sample — the drill's promotion-p99 source
        self._outage_at = 0.0
        self.promotion_samples_ms: list[float] = []
        self.promotions: list[tuple[str, int]] = []   # (identity, epoch)

    # ---- lifecycle ----

    def start(self) -> "StoreReplicaSet":
        if self.persist_dir is None:
            self.persist_dir = tempfile.mkdtemp(prefix="ktpu-storeha-")

        def serve():
            async def main():
                self.loop = asyncio.get_running_loop()
                shutdown = asyncio.Event()
                self._shutdown = shutdown
                try:
                    for i in range(self.n):
                        replica = StoreReplica(
                            i, self.coord_store, host=self.host,
                            persist_path=os.path.join(
                                self.persist_dir, f"store-{i}.wal"),
                            watch_window=self.watch_window,
                            lease_duration=self.lease_duration,
                            renew_deadline=self.renew_deadline,
                            retry_period=self.retry_period,
                            follower_queue=self.follower_queue,
                            server_kwargs=self.server_kwargs)
                        replica.on_promoted = self._on_promoted
                        await replica.start()
                        self.replicas.append(replica)
                    # open for business only once a primary rules —
                    # otherwise the first client write races the election
                    deadline = time.monotonic() + 10.0
                    while not any(r.store.role == "primary"
                                  for r in self.replicas):
                        if time.monotonic() > deadline:
                            raise RuntimeError(
                                "no store primary elected within 10s")
                        await asyncio.sleep(0.01)
                except BaseException as e:  # surface to the caller thread
                    self._startup_error = e
                    self._started.set()
                    raise
                self._started.set()
                await shutdown.wait()
                for replica in self.replicas:
                    try:
                        await replica.stop()
                    except Exception:
                        pass

            asyncio.run(main())

        self._thread = threading.Thread(
            target=serve, name="ktpu-storegroup", daemon=True)
        self._thread.start()
        if not self._started.wait(timeout=20.0):
            raise RuntimeError("store replica set failed to start in 20s")
        if self._startup_error is not None:
            raise RuntimeError("store replica startup failed") \
                from self._startup_error
        return self

    def stop(self) -> None:
        if self.loop is not None and not self.loop.is_closed():
            try:
                self.loop.call_soon_threadsafe(self._shutdown.set)
            except RuntimeError:
                pass  # loop already closing
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        if self._own_persist_dir and self.persist_dir:
            shutil.rmtree(self.persist_dir, ignore_errors=True)

    def __enter__(self) -> "StoreReplicaSet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ---- addressing ----

    @property
    def endpoints(self) -> list[tuple[str, int]]:
        """Every replica's apiserver endpoint — ports are stable across
        kill()/resurrect(), so static client lists survive failover."""
        return [(r.host, r.api_port) for r in self.replicas]

    def client(self, **kw) -> RemoteStore:
        """A RemoteStore over every replica. Writes that land on a
        standby (or a deposed primary) come back 409/Fenced with the
        ruling primary's endpoint, and the client steers there."""
        eps = self.endpoints
        return RemoteStore(eps[0][0], eps[0][1], endpoints=eps, **kw)

    def control(self, index: int) -> StoreReplicaControl:
        return StoreReplicaControl(self, index)

    def controls(self) -> list[StoreReplicaControl]:
        return [StoreReplicaControl(self, i) for i in range(self.n)]

    def primary_index(self) -> int:
        """The index of the replica that BELIEVES it is primary with the
        highest epoch (a resurrected stale primary may also believe, at
        a lower epoch), or -1."""
        best, best_epoch = -1, -1
        for i, replica in enumerate(self.replicas):
            if replica.store.role == "primary" \
                    and replica.store.epoch > best_epoch:
                best, best_epoch = i, replica.store.epoch
        return best

    # ---- loop marshalling (the ReplicaSet pattern) ----

    def _on_loop(self) -> bool:
        try:
            return asyncio.get_running_loop() is self.loop
        except RuntimeError:
            return False

    def _call(self, fn, timeout: float = 10.0) -> Any:
        assert self.loop is not None, "store replica set not started"
        if self._on_loop():
            return fn()
        fut: concurrent.futures.Future = concurrent.futures.Future()

        def run():
            try:
                fut.set_result(fn())
            except BaseException as e:  # noqa: BLE001 — relay to caller
                fut.set_exception(e)

        self.loop.call_soon_threadsafe(run)
        return fut.result(timeout=timeout)

    # ---- injuries / lifecycle ----

    def outage_mark(self) -> None:
        """Stamp t0 for the next promotion sample (called implicitly when
        kill()/partition() hits the ruling primary)."""
        self._outage_at = time.monotonic()

    def _on_promoted(self, replica: StoreReplica) -> None:
        # runs on the serving loop, synchronously inside _promote()
        self.promotions.append((replica.identity, replica.store.epoch))
        if self._outage_at:
            self.promotion_samples_ms.append(
                (time.monotonic() - self._outage_at) * 1000.0)
            self._outage_at = 0.0

    def kill(self, index: int) -> None:
        """SIGKILL equivalent: the replica's apiserver, replication link
        and candidacy vanish; its state and beliefs freeze (see
        StoreReplica.kill). Killing the ruling primary starts the
        promotion clock."""
        replica = self.replicas[index]

        def injure():
            if index == self.primary_index():
                self.outage_mark()
            replica.kill()

        self._call(injure)

    def partition(self, index: int) -> None:
        """Sever the replica from the coordination quorum and its peers.
        A partitioned primary fail-safe rejects writes immediately and
        loses the lease within renew_deadline — the promotion clock
        starts now, when clients first feel it."""
        replica = self.replicas[index]

        def injure():
            if index == self.primary_index():
                self.outage_mark()
            replica.partition()

        self._call(injure)

    def heal(self, index: int) -> None:
        replica = self.replicas[index]
        self._call(replica.heal)

    def resurrect(self, index: int) -> None:
        """Bring a killed replica back on the SAME ports, believing
        whatever it believed — the GC-pause return. Safe from the client
        thread and from on-loop FaultPlane actions (runs as a task
        there, exactly like ReplicaSet.drain)."""
        replica = self.replicas[index]
        assert self.loop is not None, "store replica set not started"
        if self._on_loop():
            self.loop.create_task(replica.resurrect())
            return
        asyncio.run_coroutine_threadsafe(
            replica.resurrect(), self.loop).result(timeout=10.0)

    # ---- convergence helpers ----

    def wait_for_primary(self, timeout: float = 10.0) -> int:
        """Block until some live replica rules as primary; -> its index."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            idx = self.primary_index()
            if idx >= 0 and not self.replicas[idx].killed:
                return idx
            time.sleep(0.01)  # ktpu: allow[blocking-in-async]
        raise TimeoutError("no store primary within %.1fs" % timeout)

    def wait_converged(self, rv: int, timeout: float = 10.0) -> bool:
        """Block until every live, unpartitioned replica's clock reaches
        `rv` (replication caught up everywhere)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            live = [r for r in self.replicas
                    if not r.killed and not r.partitioned]
            if live and all(r.store._rv >= rv for r in live):
                return True
            time.sleep(0.01)  # ktpu: allow[blocking-in-async]
        return False
