"""ChaosMonkey: orchestrated disruption around running behaviors-under-test.

The test/e2e/chaosmonkey/chaosmonkey.go analog, same contract: register
Tests that (1) set up and verify steady state, (2) wait for the disruption,
(3) validate the post-disruption world; `Do(disruption)` runs Setup for
every test, fires the disruption once, then runs every Test's validation
(chaosmonkey.go:48 Register, :70 Do)."""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable

Disruption = Callable[[], Awaitable[None]]


class ChaosTest:
    """Override setup()/test() — test() runs after the disruption fired."""

    async def setup(self) -> None:  # pragma: no cover - interface
        pass

    async def test(self) -> None:  # pragma: no cover - interface
        raise NotImplementedError


class FuncChaosTest(ChaosTest):
    def __init__(self, setup=None, test=None):
        self._setup = setup
        self._test = test

    async def setup(self) -> None:
        if self._setup is not None:
            await self._setup()

    async def test(self) -> None:
        if self._test is not None:
            await self._test()


class ChaosMonkey:
    def __init__(self, disruption: Disruption):
        self.disruption = disruption
        self.tests: list[ChaosTest] = []

    def register(self, test: ChaosTest) -> None:
        self.tests.append(test)

    def register_func(self, setup=None, test=None) -> None:
        self.register(FuncChaosTest(setup=setup, test=test))

    async def do(self) -> None:
        """Setup all -> disrupt -> validate all (chaosmonkey.go:70)."""
        for test in self.tests:
            await test.setup()
        await self.disruption()
        results = await asyncio.gather(
            *(test.test() for test in self.tests), return_exceptions=True)
        failures = [r for r in results if isinstance(r, BaseException)]
        if failures:
            raise failures[0]
