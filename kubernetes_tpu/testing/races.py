"""Runtime race/stall detection: what static analysis cannot see.

ktpu-lint (kubernetes_tpu/analysis) proves properties of the SOURCE —
no blocking call inside `async def`, no unguarded update(...) call site.
This module proves properties of an EXECUTION:

- `RaceDetector` is a drop-in ObjectStore proxy (the FaultPlane shape —
  the two compose, detector around plane) that watches every verb and
  records *racy read-modify-write interleavings*: a write that carries no
  resourceVersion precondition AND lands on a version its writer never
  observed — i.e. it just silently destroyed a concurrent writer's
  update. A single-writer heartbeat that read-then-writes back-to-back is
  NOT racy (its last read matches the stored version); the same code
  interleaved with another actor is. It also keeps the exactly-once bind
  ledger, so "zero double-binds" and "zero racy writes" come from one
  witness.

- `LoopStallWatchdog` measures event-loop health from inside the loop: a
  high-frequency sleeper whose oversleep IS the time some callback held
  the loop (the asyncio slow_callback_duration idea, but always-on,
  threshold-tagged and exported via obs as `eventloop_stalls_total` /
  `eventloop_stall_seconds`). The chaos drill runs under both and must
  finish with zero racy writes and zero stalls over 100 ms — the runtime
  complement of lint rules R1/R5.
"""

from __future__ import annotations

import asyncio
import threading
from dataclasses import dataclass
from typing import Any

from kubernetes_tpu.apiserver.store import ObjectStore

STALL_THRESHOLD_S = 0.1   # the "zero stalls > 100 ms" drill contract


def _metrics():
    from kubernetes_tpu.obs import REGISTRY

    return (
        REGISTRY.counter(
            "eventloop_stalls_total",
            "Event-loop stalls longer than the watchdog threshold"),
        REGISTRY.histogram(
            "eventloop_stall_seconds",
            "Observed event-loop stall durations (seconds)",
            buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0)),
    )


@dataclass(frozen=True)
class RacyWrite:
    """One recorded lost-update: `actor` wrote `kind` `key` without a
    version precondition while the stored version was `rv_found`, but the
    last version this actor ever observed for the key was `rv_seen`
    (None: it never read it at all)."""

    kind: str
    key: str            # "namespace/name"
    rv_seen: str | None
    rv_found: str | None
    actor: tuple
    reason: str

    def __str__(self) -> str:
        return (f"racy write: {self.kind} {self.key} ({self.reason}: "
                f"saw rv={self.rv_seen}, stored rv={self.rv_found})")


class RaceDetector:
    """Recording ObjectStore proxy for racy read-modify-write detection.

    Wrap any store-shaped object (a live ObjectStore, a FaultPlane):
    every get/list records the version each actor has SEEN per object;
    every unguarded update (no resourceVersion on the object, or
    check_version=False) is checked against it. Guarded updates are never
    racy — the store's own Conflict is the correctness mechanism. Actors
    are (thread, asyncio task) pairs, so two coroutines interleaving on
    one loop are distinguished exactly like two threads.

    Unknown attributes delegate to the wrapped store, so the detector is
    drop-in anywhere an ObjectStore is (and composes with FaultPlane:
    RaceDetector(FaultPlane(store)) draws injection *and* records races).
    """

    def __init__(self, store: Any):
        self.inner = store
        self.racy_writes: list[RacyWrite] = []
        self.bind_counts: dict[str, int] = {}
        self._seen: dict[tuple, str | None] = {}
        self._lock = threading.Lock()

    # ---- accounting helpers ----

    @staticmethod
    def _actor() -> tuple:
        try:
            task = asyncio.current_task()
        except RuntimeError:
            task = None
        return (threading.get_ident(), id(task) if task is not None else 0)

    @staticmethod
    def _key(obj: Any) -> tuple[str, str]:
        return (obj.kind,
                f"{obj.metadata.namespace or 'default'}/{obj.metadata.name}")

    def _note_seen(self, obj: Any, actor: tuple | None = None) -> None:
        kind, key = self._key(obj)
        with self._lock:
            self._seen[(actor or self._actor(), kind, key)] = \
                obj.metadata.resource_version

    @property
    def double_binds(self) -> int:
        return sum(1 for v in self.bind_counts.values() if v > 1)

    # ---- proxied read verbs (record what each actor has seen) ----

    def get(self, kind: str, name: str, namespace: str = "default") -> Any:
        obj = self.inner.get(kind, name, namespace)
        self._note_seen(obj)
        return obj

    def list(self, *a, **kw) -> list:
        out = self.inner.list(*a, **kw)
        actor = self._actor()
        for obj in out:
            self._note_seen(obj, actor)
        return out

    def list_with_version(self, kind: str):
        out, rv = self.inner.list_with_version(kind)
        actor = self._actor()
        for obj in out:
            self._note_seen(obj, actor)
        return out, rv

    # ---- proxied write verbs (the detection point) ----

    def create(self, obj: Any, **kw) -> Any:
        created = self.inner.create(obj, **kw)
        self._note_seen(created)
        return created

    def create_many(self, objs: list) -> list:
        out = self.inner.create_many(objs)
        actor = self._actor()
        for obj in out:
            self._note_seen(obj, actor)
        return out

    def update(self, obj: Any, **kw) -> Any:
        kind, key = self._key(obj)
        actor = self._actor()
        unguarded = (not obj.metadata.resource_version
                     or kw.get("check_version") is False)
        if unguarded:
            # what does the store hold right now? read the bucket directly
            # (not through a wrapped FaultPlane verb — observation must not
            # draw injection or perturb op order)
            current = self.inner._bucket(kind).get(
                (obj.metadata.namespace or "default", obj.metadata.name))
            rv_found = current.metadata.resource_version \
                if current is not None else None
            with self._lock:
                rv_seen = self._seen.get((actor, kind, key))
            if rv_found is not None and rv_seen != rv_found:
                self.racy_writes.append(RacyWrite(
                    kind, key, rv_seen, rv_found, actor,
                    "write-without-read" if rv_seen is None
                    else "lost-update"))
        out = self.inner.update(obj, **kw)
        self._note_seen(out, actor)
        return out

    def delete(self, kind: str, name: str,
               namespace: str = "default") -> Any:
        return self.inner.delete(kind, name, namespace)

    # CAS helpers run the store's algorithm over OUR get/update, so every
    # inner read/write is accounted (and never racy: the loop carries rv)
    def guaranteed_update(self, kind: str, name: str, namespace: str,
                          mutate, retries: int = 16) -> Any:
        return ObjectStore.guaranteed_update(self, kind, name, namespace,
                                             mutate, retries=retries)

    def patch(self, kind: str, name: str, namespace: str, patch,
              content_type: str, retries: int = 5) -> Any:
        return ObjectStore.patch(self, kind, name, namespace, patch,
                                 content_type, retries=retries)

    # ---- bind ledger (exactly-once witness, FaultPlane-compatible) ----

    def bind(self, binding) -> Any:
        out = self.inner.bind(binding)
        key = f"{binding.namespace or 'default'}/{binding.pod_name}"
        with self._lock:
            self.bind_counts[key] = self.bind_counts.get(key, 0) + 1
        return out

    def bind_many(self, bindings: list):
        bound, errors = self.inner.bind_many(bindings)
        with self._lock:
            for binding, err in zip(bindings, errors):
                if err is None:
                    key = f"{binding.namespace or 'default'}/" \
                          f"{binding.pod_name}"
                    self.bind_counts[key] = self.bind_counts.get(key, 0) + 1
        return bound, errors

    def __getattr__(self, name: str) -> Any:
        return getattr(self.inner, name)


class LoopStallWatchdog:
    """Event-loop stall detector: a tick task that measures its own
    oversleep. When `await asyncio.sleep(tick)` returns `lag` seconds
    late, some callback(s) held the loop for ~`lag` — past the threshold
    that is a recorded stall (and an `eventloop_stalls_total` increment).

    start() from loop code; stop() returns the stall list. `max_stall_s`
    is the drill's headline figure ("zero stalls > 100 ms" = empty
    list at the default threshold)."""

    def __init__(self, threshold_s: float = STALL_THRESHOLD_S,
                 tick_s: float = 0.01):
        self.threshold_s = threshold_s
        self.tick_s = tick_s
        self.stalls: list[float] = []
        self._task: asyncio.Task | None = None

    @property
    def max_stall_s(self) -> float:
        return max(self.stalls, default=0.0)

    def start(self) -> "LoopStallWatchdog":
        self._task = asyncio.get_running_loop().create_task(self._run())
        return self

    def stop(self) -> list[float]:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        return self.stalls

    async def _run(self) -> None:
        counter, hist = _metrics()
        loop = asyncio.get_running_loop()
        last = loop.time()
        while True:
            await asyncio.sleep(self.tick_s)
            now = loop.time()
            lag = now - last - self.tick_s
            last = now
            if lag > self.threshold_s:
                self.stalls.append(lag)
                counter.inc()
                hist.observe(lag)
