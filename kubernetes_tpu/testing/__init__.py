from kubernetes_tpu.testing.framework import ClusterFixture  # noqa: F401
from kubernetes_tpu.testing.chaos import ChaosMonkey  # noqa: F401
from kubernetes_tpu.testing.faults import FaultPlane, SolveFault  # noqa: F401
from kubernetes_tpu.testing.replicas import ReplicaSet  # noqa: F401
