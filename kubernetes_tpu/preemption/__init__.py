"""Pod priority & preemption: batched victim selection for pods that
don't fit.

The reference flow (plugin/pkg/scheduler/core/generic_scheduler.go
Preempt / selectNodesForPreemption / pickOneNodeForPreemption): when a pod
fails scheduling, try evicting lower-priority pods until it fits, pick the
node whose minimal victim set has the lowest highest-priority victim (then
the fewest victims), nominate the pod onto that node, and evict through
the eviction subresource so PodDisruptionBudgets are honored. The
subsystem spans four layers:

- `api/objects.py PriorityClass` — the scheduling.k8s.io priority class
  (value, globalDefault); the Priority admission plugin resolves
  `spec.priorityClassName` to the numeric `spec.priority` at create time;
- `state/pod_batch.py` — a per-pod `priority` column
  (BatchFlags.preempt gates the pass out of batches with no priority
  spread, keeping the pre-preemption program bit-identical);
- `ops/solver.py` — the batched victim-selection scan over a
  `VictimTable` (S lowest-priority bound pods per node, PDB-evictable
  bits precomputed host-side): minimal victim sets per node via a cumsum
  over priority-ascending candidates, node pick mirroring
  pickOneNodeForPreemption, in-batch double-booking prevented by an
  (extra, taken) carry, gangs all-or-nothing;
- `scheduler/driver.py` — records status.nominatedNodeName, issues
  victim evictions through `disruption.can_evict` + graceful delete,
  holds the freed capacity against lower-priority pods until the
  preemptor lands or the hold times out, and exports
  scheduler_preemption_{attempts,victims,success}_total.

Victim identity is positional: host and device share the same ascending
(priority, pod key) slot order, so a device verdict (node, k) names
exactly the first k still-evictable lower-priority slots on that node —
`resolve_victims` reconstructs the set without shipping strings to device.
"""

from __future__ import annotations

from kubernetes_tpu.preemption.victims import (
    DEFAULT_NOMINATION_TTL_S,
    NominatedNodes,
    build_victim_table,
    pdb_evictable,
    resolve_victims,
)

__all__ = [
    "DEFAULT_NOMINATION_TTL_S",
    "NominatedNodes",
    "build_victim_table",
    "pdb_evictable",
    "resolve_victims",
]
