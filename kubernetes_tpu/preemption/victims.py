"""Host-side half of the preemption pass: VictimTable assembly, verdict →
victim-identity resolution, and nominated-node capacity holds.

The device pass (ops/solver.py `_preemption_pass`) sees victims only as
tensors — priorities, request rows, evictability bits — in a fixed slot
order. This module owns that order: slots are the S lowest-priority
accounted pods per node, ascending by (priority, pod key), so a device
verdict "evict k victims on node n" deterministically names the first k
slots still evictable for that preemptor. No pod identity ever crosses
the host/device boundary.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from kubernetes_tpu.state.layout import Resource

INT32_MAX = np.iinfo(np.int32).max

# How long a nominated node's freed capacity is defended against
# lower-priority pods while the preemptor's victims terminate and it
# reschedules (the reference holds the nomination until the pod lands or
# scheduling gives up on it).
DEFAULT_NOMINATION_TTL_S = 30.0


def pdb_evictable(store, pod) -> bool:
    """Read-only mirror of `disruption.can_evict`'s covering check: every
    PDB covering the pod currently has disruptionsAllowed > 0. Used to
    precompute the VictimTable's `ok` bits — the actual budget spend still
    happens at eviction time through `can_evict`, so a budget that drains
    between batch assembly and eviction refuses the evict (the driver then
    drops the nomination)."""
    from kubernetes_tpu.state.podaffinity import (
        PARSE_ERROR,
        canonical_selector,
        selector_matches,
    )

    ns = pod.metadata.namespace
    for pdb in store.list("PodDisruptionBudget", namespace=ns,
                          copy_objects=False):
        canon = canonical_selector(pdb.selector or None)
        if canon in ((), PARSE_ERROR) \
                or not selector_matches(canon, pod.metadata.labels):
            continue
        if int(pdb.status.get("disruptionsAllowed", 0)) <= 0:
            return False
    return True


def build_victim_table(statedb, pods_by_key: dict, *, store=None,
                       evictable=None):
    """Assemble the device VictimTable + the host identity map from the
    StateDB's accounted (bound + assumed) pods.

    pods_by_key: pod key -> Pod for every accounted pod the informer still
    knows (priority comes from the resolved spec.priority; keys missing
    from the map — e.g. a pod deleted between accounting and assembly —
    are skipped). `evictable` overrides the PDB check (tests); otherwise
    `store` is consulted via `pdb_evictable`, and with neither every
    victim is evictable.

    Returns (victims, slots):
    - victims: ops.solver.VictimTable as numpy arrays, or None when no
      node has any candidate (the caller then omits the pass entirely and
      the pre-preemption program runs);
    - slots: node row -> list of (pod_key, priority, evictable) in slot
      order, for `resolve_victims`.

    Only the S = caps.victim_slots lowest-priority pods per node become
    candidates; a node needing deeper eviction than S simply reports no
    feasible set that round (capacity approximation, like every other
    padded universe here).
    """
    from kubernetes_tpu.ops.solver import VictimTable

    caps = statedb.caps
    n, s = caps.num_nodes, caps.victim_slots
    prio = np.full((n, s), INT32_MAX, np.int32)
    req = np.zeros((n, s, Resource.COUNT), np.float32)
    ok = np.zeros((n, s), bool)
    slots: dict[int, list] = {}

    per_node: dict[int, list] = {}
    for key, acc in statedb._accounted.items():
        pod = pods_by_key.get(key)
        if pod is None:
            continue
        row = statedb.table.row_of.get(acc.node_name)
        if row is None:
            continue
        per_node.setdefault(row, []).append(
            (int(pod.spec.priority), key, acc, pod))

    any_candidate = False
    for row, entries in per_node.items():
        entries.sort(key=lambda e: (e[0], e[1]))
        entries = entries[:s]
        slot_list = []
        for i, (p, key, acc, pod) in enumerate(entries):
            prio[row, i] = p
            req[row, i] = acc.requests
            if evictable is not None:
                ev = bool(evictable(pod))
            elif store is not None:
                ev = pdb_evictable(store, pod)
            else:
                ev = True
            ok[row, i] = ev
            any_candidate = any_candidate or ev
            slot_list.append((key, p, ev))
        slots[row] = slot_list

    if not any_candidate:
        return None, slots
    return VictimTable(prio=prio, req=req, ok=ok), slots


def resolve_victims(slots: dict, node_row: int, k: int,
                    preemptor_priority: int, taken: set) -> list[str] | None:
    """Reconstruct the device's chosen victim set for a (node, k) verdict:
    the first k slots on the node that are evictable, strictly lower
    priority than the preemptor, and not already claimed by an earlier
    preemptor this settle (`taken`, which this call extends). Returns the
    pod keys, or None if the table can no longer supply k victims — the
    state moved since the solve; the caller drops the nomination and the
    pod retries next batch."""
    chosen: list[str] = []
    for key, p, ev in slots.get(node_row, ()):
        if len(chosen) == k:
            break
        if not ev or key in taken or p >= preemptor_priority:
            continue
        chosen.append(key)
    if len(chosen) < k:
        return None
    taken.update(chosen)
    return chosen


@dataclass
class _Hold:
    node_name: str
    priority: int
    deadline: float


@dataclass
class NominatedNodes:
    """Capacity holds for preemptors in flight: after victims are evicted,
    the freed room on the nominated node is defended against LOWER-priority
    pods until the preemptor lands there or the hold times out — otherwise
    the next batch backfills the hole and the preemption loops forever
    (the reference keeps pod.Status.NominatedNodeName visible to the
    scheduler's assume cache for exactly this reason)."""

    ttl: float = DEFAULT_NOMINATION_TTL_S
    _holds: dict[str, _Hold] = field(default_factory=dict)

    def nominate(self, pod_key: str, node_name: str, priority: int,
                 now: float) -> None:
        self._holds[pod_key] = _Hold(node_name, priority, now + self.ttl)

    def release(self, pod_key: str) -> None:
        """The preemptor bound (anywhere) or gave up — drop its hold."""
        self._holds.pop(pod_key, None)

    def expire(self, now: float) -> list[str]:
        """Drop stale holds; returns the expired pod keys."""
        dead = [k for k, h in self._holds.items() if h.deadline <= now]
        for k in dead:
            del self._holds[k]
        return dead

    def blocks(self, node_name: str, priority: int, now: float) -> bool:
        """Would placing a pod of `priority` on `node_name` steal an active
        hold from a strictly-higher-priority preemptor?"""
        for h in self._holds.values():
            if h.node_name == node_name and h.priority > priority \
                    and h.deadline > now:
                return True
        return False

    def node_of(self, pod_key: str) -> str | None:
        h = self._holds.get(pod_key)
        return h.node_name if h else None

    def __len__(self) -> int:
        return len(self._holds)
