"""The uniform component observability surface: one handler helper for
GET /metrics, /healthz, /readyz (+/livez alias) shared by the apiserver,
scheduler, kubelet, controller-manager and extender servers, plus a
standalone asyncio server for components with no HTTP surface of their own
(the controller-manager binary).

Check semantics follow the reference's healthz package
(apiserver/pkg/server/healthz): named checks, 200 "ok" when all pass,
500/503 with the failing check names otherwise. /healthz is liveness
(default: always ok once serving), /readyz is readiness (informers synced,
warmup done, ...).
"""

from __future__ import annotations

import asyncio
import json
from typing import Callable, Mapping

from kubernetes_tpu.obs import metrics as _metrics
from kubernetes_tpu.obs import tracing as _tracing

METRICS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
TEXT_CONTENT_TYPE = "text/plain; charset=utf-8"
JSON_CONTENT_TYPE = "application/json"

Check = Callable[[], bool]

TRACE_PATH = "/debug/traces"
ALERTS_PATH = "/alerts"
QUERY_PATH = "/query"
# profiling plane (obs/profiling.py): pprof-style host profile + device
# trace capture windows — only routed on components that pass a profiler
PPROF_PROFILE_PATH = "/debug/pprof/profile"
DEVICE_PROFILE_PATH = "/debug/profile/device"
OBS_PATHS = ("/metrics", "/healthz", "/readyz", "/livez", TRACE_PATH,
             ALERTS_PATH, QUERY_PATH, PPROF_PROFILE_PATH,
             DEVICE_PROFILE_PATH)


def _query_seconds(raw: str, default: float | None) -> float | None:
    """?seconds=N from a raw request target (bad/absent -> default)."""
    import urllib.parse
    qs = raw.split("?", 1)[1] if "?" in raw else ""
    try:
        return float(urllib.parse.parse_qs(qs)["seconds"][0])
    except (KeyError, IndexError, ValueError):
        return default


def _run_checks(checks: Mapping[str, Check] | None
                ) -> tuple[int, bytes]:
    failed = []
    for name, check in (checks or {}).items():
        try:
            ok = bool(check())
        except Exception:  # noqa: BLE001 — a broken check is a failed check
            ok = False
        if not ok:
            failed.append(name)
    if failed:
        return 503, ("checks failed: " + ",".join(sorted(failed))).encode()
    return 200, b"ok"


def obs_response(method: str, path: str,
                 registry: _metrics.Registry | None = None,
                 health_checks: Mapping[str, Check] | None = None,
                 ready_checks: Mapping[str, Check] | None = None,
                 degraded_checks: Mapping[str, Check] | None = None,
                 extra_text: Callable[[], str] | None = None,
                 monitor=None,
                 profiler=None,
                 ) -> tuple[int, bytes, str] | None:
    """-> (status, body, content-type) for the obs endpoints (/metrics,
    health checks, /debug/traces, and — on monitor-hosting components —
    /alerts and /query), or
    None when `path` is not one of them (the caller routes on). Any
    method but GET on an obs path gets 405. `extra_text` appends
    component-local exposition after the registry render (the scheduler's
    per-instance families). `degraded_checks` report on /healthz WITHOUT
    failing it: a degraded component (e.g. the scheduler running its
    serial fallback while pods are quarantined) is alive and must not be
    restarted by a liveness probe — the check names are annotated in the
    200 body instead. `monitor` is an obs.monitor.Monitor: /alerts serves
    its alert states, /query evaluates ?query= instant-vector expressions
    (components without one fall through to their own 404). `profiler`
    is an obs.profiling.ProfilingPlane: /debug/pprof/profile serves the
    collapsed-stack ring (the trailing ?seconds=N window — served from
    the always-on ring, never by blocking the handler),
    /debug/profile/device opens a jax.profiler capture window in a
    background thread and returns its artifact dir immediately."""
    raw = path
    path = path.split("?", 1)[0].rstrip("/") or "/"
    if path not in OBS_PATHS:
        return None
    if path in (ALERTS_PATH, QUERY_PATH) and monitor is None:
        return None
    if path in (PPROF_PROFILE_PATH, DEVICE_PROFILE_PATH) \
            and profiler is None:
        return None
    if method != "GET":
        return 405, b"method not allowed", TEXT_CONTENT_TYPE
    if path == PPROF_PROFILE_PATH:
        seconds = _query_seconds(raw, None)
        body = profiler.profile_text(seconds=seconds)
        return 200, body.encode(), TEXT_CONTENT_TYPE
    if path == DEVICE_PROFILE_PATH:
        seconds = _query_seconds(raw, 5.0)
        payload = profiler.capture_device(seconds)
        status = 409 if payload.get("status") == "busy" else 200
        return status, json.dumps(payload).encode(), JSON_CONTENT_TYPE
    if path == ALERTS_PATH:
        return (200, json.dumps(monitor.alerts_payload()).encode(),
                JSON_CONTENT_TYPE)
    if path == QUERY_PATH:
        import urllib.parse
        qs = raw.split("?", 1)[1] if "?" in raw else ""
        params = urllib.parse.parse_qs(qs)
        expr = (params.get("query") or [""])[0]
        try:
            at = float(params["time"][0]) if "time" in params else None
            vec = monitor.query(expr, now=at)
        except Exception as exc:  # noqa: BLE001 — bad query -> 400
            body = json.dumps({"status": "error", "error": str(exc)})
            return 400, body.encode(), JSON_CONTENT_TYPE
        body = json.dumps({"status": "success",
                           "data": [{"labels": lbl, "value": v}
                                    for lbl, v in vec]})
        return 200, body.encode(), JSON_CONTENT_TYPE
    if path == TRACE_PATH:
        payload = _tracing.TRACER.debug_payload()
        return 200, json.dumps(payload).encode(), JSON_CONTENT_TYPE
    if path == "/metrics":
        body = (registry or _metrics.REGISTRY).render()
        if extra_text is not None:
            body = extra_text() + body
        return 200, body.encode(), METRICS_CONTENT_TYPE
    if path == "/healthz" or path == "/livez":
        status, body = _run_checks(health_checks)
        if status == 200 and degraded_checks:
            _status, report = _run_checks(degraded_checks)
            if _status != 200:
                names = report.decode().removeprefix("checks failed: ")
                body = b"ok\ndegraded: " + names.encode()
    else:
        status, body = _run_checks(ready_checks)
    return status, body, TEXT_CONTENT_TYPE


def http_head(status: int, body: bytes, content_type: str,
              keep_alive: bool = False) -> bytes:
    """A full HTTP/1.1 response for hand-rolled asyncio servers."""
    reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
              405: "Method Not Allowed", 409: "Conflict",
              503: "Service Unavailable"}.get(status, "Error")
    conn = "keep-alive" if keep_alive else "close"
    return (f"HTTP/1.1 {status} {reason}\r\n"
            f"Content-Type: {content_type}\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"Connection: {conn}\r\n\r\n").encode() + body


class ObsServer:
    """Standalone /metrics //healthz //readyz server for components that
    have no other HTTP surface (controller-manager)."""

    def __init__(self, registry: _metrics.Registry | None = None,
                 health_checks: Mapping[str, Check] | None = None,
                 ready_checks: Mapping[str, Check] | None = None,
                 host: str = "127.0.0.1", port: int = 0,
                 monitor=None, profiler=None):
        self.registry = registry
        self.health_checks = health_checks
        self.ready_checks = ready_checks
        self.host = host
        self.port = port
        self.monitor = monitor
        self.profiler = profiler
        self._server: asyncio.AbstractServer | None = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            request_line = await reader.readline()
            if not request_line:
                return
            try:
                method, target, _ = request_line.decode().split(None, 2)
            except ValueError:
                return
            while True:  # drain headers
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
            resp = obs_response(method, target, registry=self.registry,
                                health_checks=self.health_checks,
                                ready_checks=self.ready_checks,
                                monitor=self.monitor,
                                profiler=self.profiler)
            if resp is None:
                resp = (404, b"not found", TEXT_CONTENT_TYPE)
            writer.write(http_head(*resp))
            await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            writer.close()
