"""Process-wide observability: Prometheus-style metrics registry and the
shared /metrics //healthz //readyz HTTP surface every component serves.

`obs.metrics.REGISTRY` is the process-global default registry (the
prometheus.DefaultRegisterer position); `obs.http.obs_response` is the one
handler helper behind the apiserver, scheduler, kubelet, controller-manager
and extender endpoints.
"""

from kubernetes_tpu.obs.metrics import (  # noqa: F401
    REGISTRY,
    Registry,
    exponential_buckets,
)
