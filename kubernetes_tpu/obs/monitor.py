"""The monitoring plane: a fleet scraper + bounded in-memory TSDB with a
small rule engine (recording rules, alerting rules with for-duration
state) and an instant-vector query language.

The metrics-server/Prometheus position in the reference's addon taxonomy
(SURVEY.md: heapster -> metrics-server pipeline feeding HPA and `kubectl
top`), built the way Borg and Monarch treat it — as core cluster
infrastructure: the Monitor discovers targets from the store (Nodes
publishing kubelet endpoints) plus well-known control-plane URLs, scrapes
their 0.0.4 text exposition on a seeded-jitter interval, retains samples
in per-series ring buffers, and continuously evaluates SLO rules whose
firing alerts surface as Events, `/alerts`, and `kubectl get alerts`.

Query language (shared by rules, the HTTP `/query` endpoint, HPA's
MonitorMetrics source and `kubectl top`):

    up{job="scheduler"} < 1
    rate(apiserver_request_total[60s])
    histogram_quantile(0.99, e2e_scheduling_latency_microseconds[60s])
    sum by (flow) (rate(apiserver_flowcontrol_rejected_total[60s]))
      / sum by (flow) (rate(apiserver_flowcontrol_dispatched_total[60s]))

Semantics are the Prometheus subset this framework needs: instant
selectors read the latest sample within `lookback_s`; rate()/increase()
are counter-reset aware (a drop is a restart: the post-reset value counts
in full); histogram_quantile() interpolates over the registry's own
cumulative bucket layout; binary ops join vectors on exact label sets.

Everything here is loop-friendly: scrapes of HTTP targets are async with
a hard timeout, local (in-process) targets render synchronously, and the
TSDB is guarded by one coarse lock so `kubectl top` arriving over HTTP
and HPA syncing on the same loop see consistent reads.
"""

from __future__ import annotations

import asyncio
import json
import math
import random
import re
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from types import SimpleNamespace
from typing import Callable

from kubernetes_tpu.obs import metrics as _metrics

MONITOR_ENDPOINT_NAME = "monitor"
MONITOR_NAMESPACE = "kube-system"
MONITOR_URL_ANNOTATION = "kubernetes-tpu/monitor-url"

Labels = dict[str, str]
Vector = list[tuple[Labels, float]]


class QueryError(ValueError):
    """Malformed or unevaluable query expression."""


# ---------------------------------------------------------------------------
# 0.0.4 text exposition parsing (the scrape side of obs/metrics.py render())


def parse_exposition(text: str) -> list[tuple[str, Labels, float]]:
    """Parse text exposition 0.0.4 into (name, labels, value) samples.
    Comment/HELP/TYPE lines are skipped; label values un-escape the
    backslash/quote/newline sequences render() emits."""
    out: list[tuple[str, Labels, float]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            out.append(_parse_sample_line(line))
        except ValueError:
            continue  # one mangled line must not poison the scrape
    return out


def _parse_sample_line(line: str) -> tuple[str, Labels, float]:
    i = 0
    n = len(line)
    while i < n and (line[i].isalnum() or line[i] in "_:"):
        i += 1
    name = line[:i]
    if not name:
        raise ValueError(f"no metric name in {line!r}")
    labels: Labels = {}
    if i < n and line[i] == "{":
        i += 1
        while True:
            while i < n and line[i] in ", ":
                i += 1
            if i < n and line[i] == "}":
                i += 1
                break
            j = i
            while j < n and line[j] not in "=}":
                j += 1
            key = line[i:j].strip()
            if j >= n or line[j] != "=":
                raise ValueError(f"bad label pair in {line!r}")
            j += 1
            if j >= n or line[j] != '"':
                raise ValueError(f"unquoted label value in {line!r}")
            j += 1
            buf: list[str] = []
            while j < n and line[j] != '"':
                if line[j] == "\\" and j + 1 < n:
                    nxt = line[j + 1]
                    buf.append({"n": "\n", "\\": "\\", '"': '"'}
                               .get(nxt, "\\" + nxt))
                    j += 2
                else:
                    buf.append(line[j])
                    j += 1
            if j >= n:
                raise ValueError(f"unterminated label value in {line!r}")
            labels[key] = "".join(buf)
            i = j + 1
    rest = line[i:].split()
    if not rest:
        raise ValueError(f"no value in {line!r}")
    return name, labels, float(rest[0])


# ---------------------------------------------------------------------------
# TSDB: bounded per-series ring buffers


class _Series:
    __slots__ = ("name", "labels", "samples", "last_t")

    def __init__(self, name: str, labels: Labels, retention: int):
        self.name = name
        self.labels = labels
        self.samples: deque[tuple[float, float]] = deque(maxlen=retention)
        self.last_t = -math.inf

    def add(self, t: float, v: float) -> None:
        self.samples.append((t, v))
        self.last_t = t


class TSDB:
    """Bounded in-memory time-series store.

    Per-series ring buffers (`retention_samples` deep — memory is
    series x retention, a hard ceiling) keyed by name + sorted label
    pairs. When `max_series` is hit, the least-recently-updated series is
    evicted to admit the new one; `gc()` drops series whose latest sample
    is older than the staleness horizon (the target disappeared)."""

    def __init__(self, retention_samples: int = 600,
                 max_series: int = 20000):
        self.retention_samples = int(retention_samples)
        self.max_series = int(max_series)
        self.evictions = 0
        self._series: dict[tuple[str, tuple], _Series] = {}
        self._by_name: dict[str, set[tuple[str, tuple]]] = {}
        self._lock = threading.Lock()

    def add(self, name: str, labels: Labels, value: float,
            t: float) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            s = self._series.get(key)
            if s is None:
                if len(self._series) >= self.max_series:
                    self._evict_locked()
                s = _Series(name, dict(labels), self.retention_samples)
                self._series[key] = s
                self._by_name.setdefault(name, set()).add(key)
            s.add(t, float(value))

    def _evict_locked(self) -> None:
        victim = min(self._series, key=lambda k: self._series[k].last_t)
        self._drop_locked(victim)
        self.evictions += 1

    def _drop_locked(self, key: tuple[str, tuple]) -> None:
        s = self._series.pop(key)
        names = self._by_name.get(s.name)
        if names is not None:
            names.discard(key)
            if not names:
                del self._by_name[s.name]

    def gc(self, now: float, staleness_s: float) -> int:
        """Drop series with no sample newer than `now - staleness_s`."""
        horizon = now - staleness_s
        with self._lock:
            stale = [k for k, s in self._series.items()
                     if s.last_t < horizon]
            for k in stale:
                self._drop_locked(k)
        return len(stale)

    def _match_locked(self, name: str,
                      matchers: list[tuple[str, str, str]]) -> list[_Series]:
        out = []
        for key in self._by_name.get(name, ()):
            s = self._series[key]
            ok = True
            for lbl, op, val in matchers:
                have = s.labels.get(lbl, "")
                if (op == "=" and have != val) or \
                        (op == "!=" and have == val):
                    ok = False
                    break
            if ok:
                out.append(s)
        return out

    def instant(self, name: str, matchers: list[tuple[str, str, str]],
                now: float, lookback_s: float) -> Vector:
        """Latest sample per matching series within the lookback window."""
        out: Vector = []
        with self._lock:
            for s in self._match_locked(name, matchers):
                for t, v in reversed(s.samples):
                    if t <= now:
                        if t >= now - lookback_s:
                            out.append((dict(s.labels), v))
                        break
        return out

    def window(self, name: str, matchers: list[tuple[str, str, str]],
               window_s: float, now: float
               ) -> list[tuple[Labels, list[tuple[float, float]]]]:
        """All samples per matching series inside [now - window_s, now]."""
        lo = now - window_s
        out = []
        with self._lock:
            for s in self._match_locked(name, matchers):
                pts = [(t, v) for t, v in s.samples if lo <= t <= now]
                if pts:
                    out.append((dict(s.labels), pts))
        return out

    def series_count(self) -> int:
        with self._lock:
            return len(self._series)

    def sample_count(self) -> int:
        with self._lock:
            return sum(len(s.samples) for s in self._series.values())


def counter_increase(samples: list[tuple[float, float]]) -> float:
    """Counter-reset-aware increase over a sample window: a drop means the
    target restarted from zero, so the post-reset value counts in full
    (Prometheus extrapolation is skipped — rules divide by the window)."""
    inc = 0.0
    prev = None
    for _t, v in samples:
        if prev is not None:
            inc += v - prev if v >= prev else v
        prev = v
    return inc


# ---------------------------------------------------------------------------
# Query language: tokenizer + recursive-descent parser -> tuple AST

_TOKEN_RE = re.compile(
    r"\s*(?:(?P<number>\d+\.?\d*(?:[eE][+-]?\d+)?)"
    r"|(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"|(?P<string>\"(?:\\.|[^\"\\])*\")"
    r"|(?P<op><=|>=|==|!=|[-+*/(){}\[\],=<>]))")

_AGG_OPS = ("sum", "avg", "min", "max", "count")
_RANGE_FUNCS = ("rate", "increase")
_DURATION_UNITS = {"ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0}


def _tokenize(text: str) -> list[tuple[str, str]]:
    tokens: list[tuple[str, str]] = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            if text[pos:].strip():
                raise QueryError(f"bad token at {text[pos:pos + 20]!r}")
            break
        pos = m.end()
        for kind in ("number", "name", "string", "op"):
            val = m.group(kind)
            if val is not None:
                tokens.append((kind, val))
                break
    return tokens


class _Parser:
    def __init__(self, tokens: list[tuple[str, str]]):
        self._tokens = tokens
        self._i = 0

    def _peek(self, ahead: int = 0) -> tuple[str, str]:
        j = self._i + ahead
        return self._tokens[j] if j < len(self._tokens) else ("eof", "")

    def _next(self) -> tuple[str, str]:
        tok = self._peek()
        self._i += 1
        return tok

    def _expect(self, value: str) -> None:
        kind, val = self._next()
        if val != value:
            raise QueryError(f"expected {value!r}, got {val or kind!r}")

    def parse(self) -> tuple:
        node = self._comparison()
        if self._peek()[0] != "eof":
            raise QueryError(
                f"trailing input at {self._peek()[1]!r}")
        return node

    def _comparison(self) -> tuple:
        node = self._additive()
        kind, val = self._peek()
        if val in (">", "<", ">=", "<=", "==", "!="):
            self._next()
            node = ("bin", val, node, self._additive())
        return node

    def _additive(self) -> tuple:
        node = self._multiplicative()
        while self._peek()[1] in ("+", "-"):
            op = self._next()[1]
            node = ("bin", op, node, self._multiplicative())
        return node

    def _multiplicative(self) -> tuple:
        node = self._unary()
        while self._peek()[1] in ("*", "/"):
            op = self._next()[1]
            node = ("bin", op, node, self._unary())
        return node

    def _unary(self) -> tuple:
        if self._peek()[1] == "-":
            self._next()
            return ("neg", self._unary())
        return self._primary()

    def _primary(self) -> tuple:
        kind, val = self._peek()
        if kind == "number":
            self._next()
            return ("num", float(val))
        if val == "(":
            self._next()
            node = self._comparison()
            self._expect(")")
            return node
        if kind != "name":
            raise QueryError(f"unexpected {val or kind!r}")
        if val in _AGG_OPS:
            return self._aggregation()
        if val in _RANGE_FUNCS or val == "histogram_quantile":
            return self._function()
        return self._selector()

    def _aggregation(self) -> tuple:
        op = self._next()[1]
        by: tuple[str, ...] = ()
        if self._peek()[1] == "by":
            self._next()
            self._expect("(")
            names = []
            while self._peek()[1] != ")":
                k, v = self._next()
                if k != "name":
                    raise QueryError(f"bad grouping label {v!r}")
                names.append(v)
                if self._peek()[1] == ",":
                    self._next()
            self._expect(")")
            by = tuple(names)
        self._expect("(")
        node = self._comparison()
        self._expect(")")
        return ("agg", op, by, node)

    def _function(self) -> tuple:
        fname = self._next()[1]
        self._expect("(")
        if fname == "histogram_quantile":
            qkind, qval = self._next()
            if qkind != "number":
                raise QueryError("histogram_quantile needs a literal "
                                 "quantile first")
            self._expect(",")
            rng = self._selector()
            if rng[0] != "range":
                raise QueryError("histogram_quantile needs a range "
                                 "selector, e.g. name[60s]")
            self._expect(")")
            return ("quantile", float(qval), rng)
        rng = self._selector()
        if rng[0] != "range":
            raise QueryError(f"{fname}() needs a range selector, "
                             "e.g. name[60s]")
        self._expect(")")
        return (fname, rng)

    def _selector(self) -> tuple:
        kind, name = self._next()
        if kind != "name":
            raise QueryError(f"expected metric name, got {name or kind!r}")
        matchers: list[tuple[str, str, str]] = []
        if self._peek()[1] == "{":
            self._next()
            while self._peek()[1] != "}":
                lk, lbl = self._next()
                if lk != "name":
                    raise QueryError(f"bad matcher label {lbl!r}")
                op = self._next()[1]
                if op == "==":
                    op = "="
                if op not in ("=", "!="):
                    raise QueryError(f"bad matcher op {op!r}")
                vk, vv = self._next()
                if vk != "string":
                    raise QueryError("matcher value must be quoted")
                matchers.append((lbl, op, _unquote(vv)))
                if self._peek()[1] == ",":
                    self._next()
            self._expect("}")
        if self._peek()[1] == "[":
            self._next()
            nk, nv = self._next()
            if nk != "number":
                raise QueryError("range duration must be a number")
            unit = 1.0
            if self._peek()[0] == "name":
                uk = self._next()[1]
                if uk not in _DURATION_UNITS:
                    raise QueryError(f"bad duration unit {uk!r}")
                unit = _DURATION_UNITS[uk]
            self._expect("]")
            return ("range", name, matchers, float(nv) * unit)
        return ("sel", name, matchers)


def _unquote(tok: str) -> str:
    body = tok[1:-1]
    return (body.replace('\\"', '"').replace("\\n", "\n")
            .replace("\\\\", "\\"))


def parse_query(expr: str) -> tuple:
    """Parse an expression into an AST for evaluate(); raises QueryError."""
    if not expr or not expr.strip():
        raise QueryError("empty query")
    return _Parser(_tokenize(expr)).parse()


# ---------------------------------------------------------------------------
# Evaluation

_CMPS: dict[str, Callable[[float, float], bool]] = {
    ">": lambda a, b: a > b, "<": lambda a, b: a < b,
    ">=": lambda a, b: a >= b, "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b, "!=": lambda a, b: a != b,
}
_ARITH: dict[str, Callable[[float, float], float]] = {
    "+": lambda a, b: a + b, "-": lambda a, b: a - b,
    "*": lambda a, b: a * b,
    "/": lambda a, b: a / b if b != 0 else math.nan,
}


def evaluate(node: tuple, db: TSDB, now: float,
             lookback_s: float):
    """Evaluate an AST -> float (scalar) or Vector. NaN samples (division
    by zero) are dropped from vector results."""
    kind = node[0]
    if kind == "num":
        return node[1]
    if kind == "neg":
        val = evaluate(node[1], db, now, lookback_s)
        if isinstance(val, float):
            return -val
        return [(lbl, -v) for lbl, v in val]
    if kind == "sel":
        return db.instant(node[1], node[2], now, lookback_s)
    if kind == "range":
        raise QueryError("range selector only valid inside rate(), "
                         "increase() or histogram_quantile()")
    if kind in _RANGE_FUNCS:
        _, name, matchers, window = node[1]
        out: Vector = []
        for labels, pts in db.window(name, matchers, window, now):
            if len(pts) < 2:
                continue
            inc = counter_increase(pts)
            out.append((labels, inc / window if kind == "rate" else inc))
        return out
    if kind == "quantile":
        return _histogram_quantile(node[1], node[2], db, now)
    if kind == "agg":
        return _aggregate(node[1], node[2],
                          _as_vector(evaluate(node[3], db, now, lookback_s)))
    if kind == "bin":
        return _binop(node[1],
                      evaluate(node[2], db, now, lookback_s),
                      evaluate(node[3], db, now, lookback_s))
    raise QueryError(f"unknown node {kind!r}")


def _as_vector(val) -> Vector:
    if isinstance(val, float):
        return [({}, val)]
    return val


def _aggregate(op: str, by: tuple[str, ...], vec: Vector) -> Vector:
    groups: dict[tuple, tuple[Labels, list[float]]] = {}
    for labels, v in vec:
        kept = {k: labels[k] for k in by if k in labels}
        key = tuple(sorted(kept.items()))
        groups.setdefault(key, (kept, []))[1].append(v)
    out: Vector = []
    for kept, vals in groups.values():
        if op == "sum":
            r = sum(vals)
        elif op == "avg":
            r = sum(vals) / len(vals)
        elif op == "min":
            r = min(vals)
        elif op == "max":
            r = max(vals)
        else:  # count
            r = float(len(vals))
        out.append((kept, r))
    return out


def _binop(op: str, lhs, rhs):
    scalar_l = isinstance(lhs, float)
    scalar_r = isinstance(rhs, float)
    if op in _CMPS:
        cmp = _CMPS[op]
        if scalar_l and scalar_r:
            return 1.0 if cmp(lhs, rhs) else 0.0
        if scalar_r:
            return [(lbl, v) for lbl, v in lhs if cmp(v, rhs)]
        if scalar_l:
            return [(lbl, v) for lbl, v in rhs if cmp(lhs, v)]
        joined = _join(lhs, rhs)
        return [(lbl, lv) for lbl, lv, rv in joined if cmp(lv, rv)]
    fn = _ARITH[op]
    if scalar_l and scalar_r:
        return fn(lhs, rhs)
    if scalar_r:
        return [(lbl, fn(v, rhs)) for lbl, v in lhs
                if not math.isnan(fn(v, rhs))]
    if scalar_l:
        return [(lbl, fn(lhs, v)) for lbl, v in rhs
                if not math.isnan(fn(lhs, v))]
    out: Vector = []
    for lbl, lv, rv in _join(lhs, rhs):
        r = fn(lv, rv)
        if not math.isnan(r):
            out.append((lbl, r))
    return out


def _join(lhs: Vector, rhs: Vector) -> list[tuple[Labels, float, float]]:
    """Inner join on exact label sets (the one-to-one vector match)."""
    index = {tuple(sorted(lbl.items())): v for lbl, v in rhs}
    out = []
    for lbl, lv in lhs:
        key = tuple(sorted(lbl.items()))
        if key in index:
            out.append((lbl, lv, index[key]))
    return out


def _histogram_quantile(q: float, rng: tuple, db: TSDB,
                        now: float) -> Vector:
    """histogram_quantile over `name_bucket` series: per-le counter-reset-
    aware increases within the window, grouped by labels minus `le`, then
    linear interpolation inside the bucket holding the q-th observation
    (the last finite bound when it lands in +Inf — the obs/metrics.py
    Histogram.quantile contract)."""
    _, name, matchers, window = rng
    if not name.endswith("_bucket"):
        name += "_bucket"
    groups: dict[tuple, tuple[Labels, list[tuple[float, float]]]] = {}
    for labels, pts in db.window(name, matchers, window, now):
        if len(pts) < 2:
            continue
        le = labels.pop("le", None)
        if le is None:
            continue
        bound = math.inf if le == "+Inf" else float(le)
        key = tuple(sorted(labels.items()))
        groups.setdefault(key, (labels, []))[1].append(
            (bound, counter_increase(pts)))
    out: Vector = []
    for labels, buckets in groups.values():
        buckets.sort()
        # re-impose cumulativity: independent per-le resets can wobble it
        cum = 0.0
        fixed = []
        for bound, c in buckets:
            cum = max(cum, c)
            fixed.append((bound, cum))
        total = fixed[-1][1] if fixed else 0.0
        if total <= 0:
            continue
        rank = q * total
        prev_bound, prev_cum = 0.0, 0.0
        value = fixed[-1][0]
        for bound, c in fixed:
            if c >= rank:
                if math.isinf(bound):
                    value = prev_bound
                else:
                    width = c - prev_cum
                    frac = (rank - prev_cum) / width if width > 0 else 0.0
                    value = prev_bound + (bound - prev_bound) * frac
                break
            prev_bound, prev_cum = (0.0 if math.isinf(bound) else bound), c
        out.append((labels, value))
    return out


# ---------------------------------------------------------------------------
# Rules


class RecordingRule:
    """Evaluate an expression each round and write the result back into
    the TSDB under a new metric name (which must carry a unit/kind suffix
    — ktpu-lint R6 holds recording rules to the same naming discipline as
    hand-registered families)."""

    def __init__(self, record: str, expr: str,
                 labels: Labels | None = None):
        self.record = record
        self.expr = expr
        self.labels = dict(labels or {})
        self.ast = parse_query(expr)


class AlertingRule:
    """An alert expression with for-duration semantics: a labelset must
    stay active `for_s` seconds (pending) before the alert fires; it
    resolves the first round the labelset drops out of the result."""

    def __init__(self, alert: str, expr: str, for_s: float = 0.0,
                 labels: Labels | None = None,
                 annotations: dict[str, str] | None = None):
        self.alert = alert
        self.expr = expr
        self.for_s = float(for_s)
        self.labels = dict(labels or {})
        self.annotations = dict(annotations or {})
        self.ast = parse_query(expr)


def builtin_rules(window_s: float = 60.0,
                  for_s: float = 0.0,
                  e2e_slo_seconds: float = 1.0,
                  apiserver_slo_seconds: float = 1.0,
                  reject_ratio_max: float = 0.5,
                  busy_frac_max: float = 0.95,
                  device_memory_frac_max: float = 0.9) -> list:
    """The built-in SLO rule set: scheduler e2e p99, apiserver request p99
    and per-APF-flow rejection burn rate, pipeline stage busy-fraction,
    event-loop stalls, device-memory high-water (profiling plane), and
    scrape-health (`up`) for the scheduler — the alert the chaos drill
    holds to fires-then-resolves."""
    w = f"[{window_s:g}s]"
    return [
        RecordingRule(
            "scheduler_e2e_p99_seconds",
            f"histogram_quantile(0.99, "
            f"e2e_scheduling_latency_microseconds{w}) / 1000000"),
        RecordingRule(
            "apiserver_request_p99_seconds",
            f"histogram_quantile(0.99, "
            f"apiserver_request_latencies_microseconds{w}) / 1000000"),
        RecordingRule(
            "apiserver_flow_reject_ratio",
            f"sum by (flow) (rate(apiserver_flowcontrol_rejected_total{w}))"
            f" / sum by (flow) "
            f"(rate(apiserver_flowcontrol_dispatched_total{w}))"),
        RecordingRule(
            "scheduler_stage_busy_frac",
            f"sum by (phase) "
            f"(rate(scheduler_phase_duration_seconds_sum{w}))"),
        AlertingRule(
            "SchedulerDown", 'up{job="scheduler"} < 1', for_s=for_s,
            annotations={"summary": "scheduler target failing scrapes"}),
        AlertingRule(
            "SchedulerE2ELatencyHigh",
            f"scheduler_e2e_p99_seconds > {e2e_slo_seconds:g}", for_s=for_s,
            annotations={"summary": "scheduler e2e p99 outside SLO"}),
        AlertingRule(
            "APIServerLatencyHigh",
            f"apiserver_request_p99_seconds > {apiserver_slo_seconds:g}",
            for_s=for_s,
            annotations={"summary": "apiserver request p99 outside SLO"}),
        AlertingRule(
            "APIServerFlowSaturated",
            f"apiserver_flow_reject_ratio > {reject_ratio_max:g}",
            for_s=for_s,
            annotations={"summary": "APF flow shedding beyond burn budget"}),
        AlertingRule(
            "SchedulerStageSaturated",
            f"scheduler_stage_busy_frac > {busy_frac_max:g}", for_s=for_s,
            annotations={"summary": "pipeline stage at capacity"}),
        AlertingRule(
            "EventLoopStalled",
            f"increase(eventloop_stalls_total{w}) > 0", for_s=for_s,
            annotations={"summary": "event loop held >100ms"}),
        # profiling plane (obs/profiling.py): device-memory high-water
        # vs the backend-reported limit. The CPU fallback never exports
        # device_memory_bytes_limit, so the division joins against an
        # empty vector there and the alert cannot fire by construction.
        RecordingRule(
            "device_memory_highwater_frac",
            "device_memory_peak_bytes_in_use"
            " / device_memory_bytes_limit"),
        AlertingRule(
            "DeviceMemoryHigh",
            f"device_memory_highwater_frac > {device_memory_frac_max:g}",
            for_s=for_s,
            annotations={"summary": "device HBM high-water near the "
                                    "backend limit"}),
    ]


# ---------------------------------------------------------------------------
# Targets + Monitor


@dataclass
class Target:
    """One scrape target: either an HTTP exposition URL or an in-process
    render callable (a component's registry in the same interpreter).
    `summary` marks kubelets whose /stats/summary feeds the resource-
    metrics pipeline."""

    job: str
    instance: str
    url: str | None = None
    render: Callable[[], str] | None = None
    summary: bool = False


async def _http_fetch(url: str, timeout: float) -> str:
    """Minimal asyncio HTTP GET. A body shorter than Content-Length (the
    target died mid-response) raises — a partial scrape is a failed
    scrape, never a half-ingested one."""
    m = re.match(r"http://([^/:]+)(?::(\d+))?(/.*)?$", url)
    if m is None:
        raise ValueError(f"unsupported target url {url!r}")
    host, port, path = m.group(1), int(m.group(2) or 80), m.group(3) or "/"

    async def fetch() -> str:
        reader, writer = await asyncio.open_connection(host, port)
        try:
            writer.write((f"GET {path} HTTP/1.1\r\nHost: {host}\r\n"
                          "Connection: close\r\n\r\n").encode())
            await writer.drain()
            status_line = await reader.readline()
            parts = status_line.split(None, 2)
            if len(parts) < 2 or parts[1] != b"200":
                raise RuntimeError(
                    f"scrape {url}: HTTP {parts[1:2] or status_line!r}")
            length = None
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                if line.lower().startswith(b"content-length:"):
                    length = int(line.split(b":", 1)[1])
            if length is not None:
                body = await reader.readexactly(length)
            else:
                body = await reader.read()
            return body.decode("utf-8", "replace")
        finally:
            writer.close()

    return await asyncio.wait_for(fetch(), timeout)


class Monitor:
    """The fleet scraper + TSDB + rule engine.

    Targets come from three places: `add_static_target` (well-known
    control-plane URLs), `add_local_target` (an embedded component's
    registry render in this process), and store discovery (Nodes whose
    status publishes a kubelet endpoint — those are also asked for
    /stats/summary, which becomes the node_*/pod_* usage series HPA and
    `kubectl top` query). Every scrape writes a synthetic
    `up{job,instance}` sample (1 ok / 0 failed) — scrape health is
    itself a queryable series, which is what makes availability alerts
    like SchedulerDown possible.
    """

    def __init__(self, store=None, *, interval: float = 15.0,
                 scrape_timeout: float = 2.0,
                 retention_samples: int = 600, max_series: int = 20000,
                 lookback_s: float | None = None,
                 staleness_s: float | None = None,
                 rules: list | None = None,
                 include_builtin_rules: bool = True,
                 slo_window_s: float | None = None,
                 alert_for_s: float = 0.0,
                 e2e_slo_seconds: float = 1.0,
                 device_memory_frac_max: float = 0.9,
                 seed: int = 0, node_host: str = "127.0.0.1",
                 recorder=None, registry: _metrics.Registry | None = None):
        self.store = store
        self.interval = float(interval)
        self.scrape_timeout = float(scrape_timeout)
        self.lookback_s = (float(lookback_s) if lookback_s is not None
                           else max(10.0, 5 * self.interval))
        self.staleness_s = (float(staleness_s) if staleness_s is not None
                            else max(60.0, 20 * self.interval))
        self.tsdb = TSDB(retention_samples=retention_samples,
                         max_series=max_series)
        self.rules = list(rules or [])
        if include_builtin_rules:
            window = (float(slo_window_s) if slo_window_s is not None
                      else max(4 * self.interval, 1.0))
            self.rules.extend(builtin_rules(
                window_s=window, for_s=alert_for_s,
                e2e_slo_seconds=e2e_slo_seconds,
                device_memory_frac_max=device_memory_frac_max))
        self._node_host = node_host
        self._rnd = random.Random(seed)
        self._recorder = recorder
        if recorder is None and store is not None:
            from kubernetes_tpu.utils.events import EventRecorder
            self._recorder = EventRecorder(store, component="monitor")
        self._targets: list[Target] = []
        self._alert_state: dict[str, dict[tuple, dict]] = {}
        self.alert_log: deque[dict] = deque(maxlen=512)
        self._store_rule_cache: dict[tuple[str, str, float], object] = {}
        self._task: asyncio.Task | None = None
        self.registry = registry or _metrics.Registry()
        self._mx_scrapes = self.registry.counter(
            "monitor_scrape_total", "Scrapes attempted per job", ("job",))
        self._mx_failures = self.registry.counter(
            "monitor_scrape_failures_total", "Failed scrapes per job",
            ("job",))
        self._mx_duration = self.registry.histogram(
            "monitor_scrape_duration_seconds", "Per-target scrape duration",
            buckets=_metrics.exponential_buckets(0.0001, 4, 10))
        self._mx_samples = self.registry.counter(
            "monitor_samples_ingested_total", "Samples written to the TSDB")
        self._mx_series = self.registry.gauge(
            "monitor_tsdb_series", "Live series in the TSDB")
        self._mx_tsdb_samples = self.registry.gauge(
            "monitor_tsdb_samples", "Samples resident in the TSDB")
        self._mx_firing = self.registry.gauge(
            "monitor_alerts_firing", "Alerts currently firing")

    # -- target management --------------------------------------------------

    def add_static_target(self, job: str, url: str,
                          instance: str | None = None,
                          summary: bool = False) -> None:
        self._targets.append(Target(job=job, instance=instance or url,
                                    url=url, summary=summary))

    def add_local_target(self, job: str, render: Callable[[], str],
                         instance: str = "local") -> None:
        self._targets.append(Target(job=job, instance=instance,
                                    render=render))

    def remove_target(self, job: str, instance: str | None = None) -> None:
        self._targets = [
            t for t in self._targets
            if not (t.job == job and (instance is None
                                      or t.instance == instance))]

    def _discovered_targets(self) -> list[Target]:
        if self.store is None:
            return []
        out = []
        try:
            nodes = self.store.list("Node")
        except Exception:  # noqa: BLE001 — discovery is best-effort
            nodes = []
        for node in nodes:
            eps = getattr(node.status, "daemon_endpoints", None) or {}
            port = (eps.get("kubeletEndpoint") or {}).get("Port")
            if port:
                out.append(Target(
                    job="kubelet", instance=node.metadata.name,
                    url=f"http://{self._node_host}:{port}", summary=True))
        # apiserver replicas/worker processes advertise into the
        # well-known default/kubernetes Endpoints (the master-count
        # reconciler shape) — each one scrapes as its own instance, so a
        # multi-process control plane is N per-process /metrics targets
        try:
            ep = self.store.get("Endpoints", "kubernetes", "default")
            for subset in ep.subsets:
                for addr in subset.get("addresses", []):
                    ip, port = addr.get("ip", ""), addr.get("port", 0)
                    if not ip or not port:
                        continue
                    out.append(Target(
                        job="apiserver",
                        instance=addr.get("replica") or f"{ip}:{port}",
                        url=f"http://{ip}:{port}"))
        except Exception:  # noqa: BLE001 — discovery is best-effort
            pass
        return out

    def targets(self) -> list[Target]:
        return list(self._targets) + self._discovered_targets()

    # -- scraping ------------------------------------------------------------

    async def scrape_once(self, now: float | None = None) -> None:
        """One scrape round: every target, then GC, then rule evaluation.
        Per-target failures are counted, marked in `up`, and never abort
        the round."""
        now = time.time() if now is None else now
        for target in self.targets():
            await self._scrape_target(target, now)
        # the monitor's own families are fleet citizens too
        self._mx_series.set(self.tsdb.series_count())
        self._mx_tsdb_samples.set(self.tsdb.sample_count())
        self._ingest_text(self.registry.render(),
                          Target(job="monitor", instance="self"), now)
        self.tsdb.gc(now, self.staleness_s)
        self.evaluate_rules(now)

    async def _scrape_target(self, target: Target, now: float) -> None:
        self._mx_scrapes.labels(target.job).inc()
        t0 = time.perf_counter()
        try:
            if target.render is not None:
                text = target.render()
            else:
                text = await _http_fetch(target.url + "/metrics",
                                         self.scrape_timeout)
            self._ingest_text(text, target, now)
            if target.summary and target.url is not None:
                payload = await _http_fetch(target.url + "/stats/summary",
                                            self.scrape_timeout)
                self._ingest_summary(json.loads(payload), target, now)
            up = 1.0
        except Exception:  # noqa: BLE001 — any failure mode is up=0
            self._mx_failures.labels(target.job).inc()
            up = 0.0
        self._mx_duration.observe(time.perf_counter() - t0)
        self.tsdb.add("up", {"job": target.job, "instance": target.instance},
                      up, now)

    def _ingest_text(self, text: str, target: Target, now: float) -> None:
        n = 0
        for name, labels, value in parse_exposition(text):
            labels.setdefault("job", target.job)
            labels.setdefault("instance", target.instance)
            self.tsdb.add(name, labels, value, now)
            n += 1
        self._mx_samples.inc(n)

    def _ingest_summary(self, payload: dict, target: Target,
                        now: float) -> None:
        """Kubelet /stats/summary -> the resource-metrics series HPA and
        `kubectl top` query. pod_cpu_usage_ratio (fraction of request) is
        only emitted for pods reporting live usage, preserving HPA's
        skip-on-incomplete-coverage semantics."""
        node = payload.get("node") or {}
        node_name = node.get("nodeName", target.instance)
        base = {"job": target.job, "instance": target.instance,
                "node": node_name}
        n = 0
        cpu = (node.get("cpu") or {}).get("usageCores")
        if cpu is not None:
            self.tsdb.add("node_cpu_usage_cores", dict(base),
                          float(cpu), now)
            n += 1
        mem = (node.get("memory") or {}).get("usageMiB")
        if mem is not None:
            self.tsdb.add("node_memory_usage_mib", dict(base),
                          float(mem), now)
            n += 1
        for pod in payload.get("pods") or []:
            ref = pod.get("podRef") or {}
            labels = dict(base)
            labels["namespace"] = ref.get("namespace", "default")
            labels["pod"] = ref.get("name", "")
            pcpu = pod.get("cpu") or {}
            if "usageCores" in pcpu:
                self.tsdb.add("pod_cpu_usage_cores", dict(labels),
                              float(pcpu["usageCores"]), now)
                n += 1
            if "usageRatio" in pcpu:
                self.tsdb.add("pod_cpu_usage_ratio", dict(labels),
                              float(pcpu["usageRatio"]), now)
                n += 1
            pmem = pod.get("memory") or {}
            if "usageMiB" in pmem:
                self.tsdb.add("pod_memory_usage_mib", dict(labels),
                              float(pmem["usageMiB"]), now)
                n += 1
        self._mx_samples.inc(n)

    # -- rules ---------------------------------------------------------------

    def _store_rules(self) -> list:
        """AlertRule objects (monitoring.ktpu.io) -> compiled rules, so
        operators reconfigure alerting with `kubectl create` alone. Parse
        results are cached by (name, expr, for); unparseable specs are
        skipped (validation rejects them at admission, but the store may
        predate a rule-engine upgrade)."""
        if self.store is None:
            return []
        try:
            objs = self.store.list("AlertRule")
        except Exception:  # noqa: BLE001 — no such kind on old stores
            return []
        out = []
        cache: dict[tuple[str, str, float], object] = {}
        for obj in objs:
            spec = getattr(obj, "spec", None) or {}
            expr = spec.get("expr", "")
            record = spec.get("record", "")
            alert = spec.get("alert", "")
            for_s = float(spec.get("for", 0) or 0)
            key = (record or alert, expr, for_s)
            rule = self._store_rule_cache.get(key)
            if rule is None:
                try:
                    if record:
                        rule = RecordingRule(record, expr,
                                             labels=spec.get("labels"))
                    elif alert:
                        rule = AlertingRule(
                            alert, expr, for_s=for_s,
                            labels=spec.get("labels"),
                            annotations=spec.get("annotations"))
                    else:
                        continue
                except QueryError:
                    continue
            cache[key] = rule
            out.append(rule)
        self._store_rule_cache = cache
        return out

    def evaluate_rules(self, now: float | None = None) -> None:
        now = time.time() if now is None else now
        rules = self.rules + self._store_rules()
        live = set()
        for rule in rules:
            try:
                result = evaluate(rule.ast, self.tsdb, now, self.lookback_s)
            except QueryError:
                continue
            if isinstance(rule, RecordingRule):
                for labels, value in _as_vector(result):
                    merged = dict(labels)
                    merged.update(rule.labels)
                    self.tsdb.add(rule.record, merged, value, now)
            else:
                live.add(rule.alert)
                self._eval_alert(rule, result, now)
        # rules removed from the store resolve their tracked alerts
        for name in list(self._alert_state):
            if name not in live:
                for state in self._alert_state.pop(name).values():
                    if state["state"] == "firing":
                        self._transition(name, state, "resolved", now)
        self._mx_firing.set(sum(
            1 for states in self._alert_state.values()
            for s in states.values() if s["state"] == "firing"))

    def _eval_alert(self, rule: AlertingRule, result, now: float) -> None:
        if isinstance(result, float):
            active = ({(): ({}, result)} if result != 0 else {})
        else:
            active = {tuple(sorted(lbl.items())): (lbl, v)
                      for lbl, v in result}
        states = self._alert_state.setdefault(rule.alert, {})
        for key, (labels, value) in active.items():
            s = states.get(key)
            if s is None:
                merged = dict(labels)
                merged.update(rule.labels)
                s = {"state": "pending", "since": now, "labels": merged,
                     "annotations": rule.annotations}
                states[key] = s
            s["value"] = value
            if s["state"] == "pending" and now - s["since"] >= rule.for_s:
                s["state"] = "firing"
                s["firing_since"] = now
                self._transition(rule.alert, s, "firing", now)
        for key in [k for k in states if k not in active]:
            s = states.pop(key)
            if s["state"] == "firing":
                self._transition(rule.alert, s, "resolved", now)

    def _transition(self, alert: str, state: dict, to: str,
                    now: float) -> None:
        self.alert_log.append({
            "alert": alert, "state": to, "labels": dict(state["labels"]),
            "value": state.get("value"), "t": now})
        if self._recorder is None:
            return
        # alerts surface as Events anchored on a synthetic AlertRule ref,
        # so `kubectl get events` shows the firing history
        anchor = SimpleNamespace(
            kind="AlertRule",
            metadata=SimpleNamespace(name=_dns_name(alert),
                                     namespace=MONITOR_NAMESPACE, uid=""))
        label_str = ",".join(f"{k}={v}"
                             for k, v in sorted(state["labels"].items()))
        try:
            self._recorder.record(
                anchor, "Warning" if to == "firing" else "Normal",
                "AlertFiring" if to == "firing" else "AlertResolved",
                f"{alert}{{{label_str}}} value={state.get('value')}")
        except Exception:  # noqa: BLE001 — events are best-effort
            pass

    # -- queries + payloads --------------------------------------------------

    def query(self, expr: str, now: float | None = None) -> Vector:
        """Evaluate an instant query -> [(labels, value), ...]; scalars
        come back as one sample with empty labels. Raises QueryError."""
        now = time.time() if now is None else now
        return _as_vector(
            evaluate(parse_query(expr), self.tsdb, now, self.lookback_s))

    def active_alerts(self) -> list[dict]:
        out = []
        for alert, states in self._alert_state.items():
            for s in states.values():
                out.append({"alert": alert, "state": s["state"],
                            "labels": dict(s["labels"]),
                            "value": s.get("value"),
                            "since": s["since"],
                            "firing_since": s.get("firing_since"),
                            "annotations": dict(s.get("annotations") or {})})
        out.sort(key=lambda a: (a["alert"],
                                sorted(a["labels"].items())))
        return out

    def alerts_payload(self) -> dict:
        return {"alerts": self.active_alerts(),
                "transitions": list(self.alert_log)}

    def fired(self, alert: str) -> bool:
        return any(e["alert"] == alert and e["state"] == "firing"
                   for e in self.alert_log)

    def resolved(self, alert: str) -> bool:
        return any(e["alert"] == alert and e["state"] == "resolved"
                   for e in self.alert_log)

    # -- lifecycle -----------------------------------------------------------

    def publish(self, url: str) -> None:
        """Advertise this monitor's query/alerts URL in the store (an
        Endpoints object in kube-system — the same object family leader
        election locks on), so kubectl and remote HPAs can find it."""
        if self.store is None:
            return
        from kubernetes_tpu.api.objects import Endpoints, ObjectMeta
        from kubernetes_tpu.apiserver.store import AlreadyExists, NotFound
        try:
            try:
                self.store.guaranteed_update(
                    "Endpoints", MONITOR_ENDPOINT_NAME, MONITOR_NAMESPACE,
                    lambda ep: ep.metadata.annotations.update(
                        {MONITOR_URL_ANNOTATION: url}))
            except NotFound:
                self.store.create(Endpoints(metadata=ObjectMeta(
                    name=MONITOR_ENDPOINT_NAME,
                    namespace=MONITOR_NAMESPACE,
                    annotations={MONITOR_URL_ANNOTATION: url})))
        except AlreadyExists:
            pass

    async def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self._loop())

    def stop(self) -> None:
        # sync like every controller's stop(): cancel, don't await (the
        # loop task dies at the next scheduler pass)
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        while True:
            # seeded jitter de-phases a fleet of monitors from their
            # targets' own periodic work (and from each other)
            await asyncio.sleep(self.interval
                                * (0.9 + 0.2 * self._rnd.random()))
            try:
                await self.scrape_once()
            except Exception:  # noqa: BLE001 — the loop must survive
                self._mx_failures.labels("_round").inc()


def _dns_name(alert: str) -> str:
    """CamelCase alert name -> DNS-1123 event anchor name."""
    return re.sub(r"(?<!^)(?=[A-Z])", "-", alert).lower()


def find_monitor_url(store) -> str | None:
    """The published monitor URL, or None when no monitor runs."""
    try:
        ep = store.get("Endpoints", MONITOR_ENDPOINT_NAME,
                       MONITOR_NAMESPACE)
    except Exception:  # noqa: BLE001 — no monitor published
        return None
    return (getattr(ep.metadata, "annotations", None) or {}).get(
        MONITOR_URL_ANNOTATION)
