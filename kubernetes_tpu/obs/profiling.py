"""Continuous profiling & device introspection: where the time and the
bytes go, answered by the process itself.

The metrics plane (obs/metrics.py) answers *what happened*, the tracing
plane (obs/tracing.py) *in what order*; this plane answers *where* — the
question the first chip session asks before anything else. Four pillars:

  * `SamplingProfiler` — a low-overhead daemon thread that walks
    `sys._current_frames()` on a fixed interval and folds each thread's
    stack into collapsed flamegraph lines (`thread;frame;...;leaf N`).
    Because the StagedPipeline names its stage threads
    (`ktpu-dispatch-stage`/`ktpu-settle-stage`/`ktpu-commit-stage`) and
    the fan-out shards name theirs, the per-thread attribution joins
    pipeline stages for free. An always-on ring keeps the recent window
    so `/debug/pprof/profile?seconds=N` serves the trailing N seconds
    without blocking the obs handler (lint R1: handlers never park).
  * `CompileRegistry` — per-jit-cache-entry compile accounting for the
    solver variant cache (scheduler/driver.py `_get_schedule_fn`):
    compile seconds from `jax.monitoring`'s backend-compile events with
    a first-call wall fallback, plus `Compiled.cost_analysis()` flops /
    bytes-accessed where the backend provides it (AOT lower+compile,
    gated — any failure falls back to the plain jit callable).
  * `DeviceMemoryMonitor` — `device.memory_stats()` high-water gauges
    with a graceful CPU-backend fallback (memory_stats() is None there)
    that accounts the StateDB's device blob buffers by dtype/shape, so
    the CPU harness still sees what WOULD sit in HBM.
  * `DeviceTraceCapture` — on-demand `jax.profiler.trace` windows
    (`/debug/profile/device?seconds=N` -> artifact dir) so the first
    chip session is a curl, not a code change.

`bottleneck_report()` folds pipeline busy fractions, phase CPU time,
transfer bytes and compile cost into a single "name the next wall"
verdict; bench.py --profile emits it as RESULT.bottleneck per config.

Thread discipline: the sampler and capture threads never touch the
event loop (lint R1 tier-3) and pace themselves with Event.wait, never
time.sleep (tier-2).
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque

from kubernetes_tpu.obs import metrics as _metrics
from kubernetes_tpu.utils.clock import Clock, SYSTEM_CLOCK

# frames deeper than this fold into the cap (runaway recursion guard)
MAX_STACK_DEPTH = 64


def _fold_stack(frame, limit: int = MAX_STACK_DEPTH) -> str:
    """Leaf frame -> one interned root-first `file.py:fn;file.py:fn;...`
    string. Interning collapses the ring's storage to one copy per
    distinct stack, which is what makes an always-on ring affordable."""
    entries: list[str] = []
    f = frame
    while f is not None and len(entries) < limit:
        code = f.f_code
        entries.append(f"{os.path.basename(code.co_filename)}"
                       f":{code.co_name}")
        f = f.f_back
    entries.reverse()
    return sys.intern(";".join(entries))


class SamplingProfiler:
    """Walk `sys._current_frames()` on an interval; keep a ring of
    (timestamp, {thread_name: folded_stack}) samples.

    The walk itself runs under the GIL so it is a consistent snapshot;
    the sampler's own thread is excluded (its stack is always the walk).
    `clock` stamps ring entries — tests inject a ManualClock and call
    `sample_once(...)` directly for deterministic windows; the real
    thread paces with Event.wait so stop() is prompt and lint R1's
    time.sleep audit stays clean."""

    def __init__(self, interval_s: float = 0.01,
                 ring_s: float = 300.0,
                 registry: _metrics.Registry | None = None,
                 clock: Clock | None = None):
        self.interval_s = float(interval_s)
        self.ring_s = float(ring_s)
        self.clock = clock or SYSTEM_CLOCK
        maxlen = max(16, int(self.ring_s / max(self.interval_s, 1e-4)))
        self._ring: deque = deque(maxlen=maxlen)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        r = registry or _metrics.REGISTRY
        self._m_samples = r.counter(
            "profiling_samples_total",
            "Stack-walk samples folded into the profile ring.")
        self._m_walk = r.histogram(
            "profiling_sample_walk_seconds",
            "Cost of one sys._current_frames() walk+fold (the sampler's "
            "own overhead).",
            buckets=_metrics.exponential_buckets(1e-5, 4.0, 8))

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> None:
        if self.running:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._sample_loop, name="ktpu-profiler-sample",
            daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._thread = None

    def _sample_loop(self) -> None:
        # off-loop thread: paces on Event.wait (never time.sleep, never
        # the event loop) so stop() interrupts a pending interval
        while not self._stop.wait(self.interval_s):
            self.sample_once()

    def sample_once(self, now: float | None = None) -> dict[str, str]:
        """One walk: {thread_name: folded_stack}, appended to the ring
        stamped `now` (default: the injected clock)."""
        t0 = time.perf_counter()
        if now is None:
            now = self.clock.now()
        names = {t.ident: t.name for t in threading.enumerate()}
        me = threading.get_ident()
        stacks: dict[str, str] = {}
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            stacks[names.get(tid, f"tid-{tid}")] = _fold_stack(frame)
        with self._lock:
            self._ring.append((now, stacks))
        self._m_samples.inc()
        self._m_walk.observe(time.perf_counter() - t0)
        return stacks

    @property
    def sample_count(self) -> int:
        with self._lock:
            return len(self._ring)

    def collapsed(self, seconds: float | None = None,
                  now: float | None = None) -> str:
        """Collapsed flamegraph text (`thread;frame;...;leaf count`) for
        the trailing `seconds` window (None: the whole ring). Sorted for
        byte-stable output under a fixed sample set."""
        if now is None:
            now = self.clock.now()
        cutoff = None if seconds is None else now - float(seconds)
        with self._lock:
            ring = list(self._ring)
        counts: dict[str, int] = {}
        for ts, stacks in ring:
            if cutoff is not None and ts < cutoff:
                continue
            for tname, stack in stacks.items():
                key = f"{tname};{stack}" if stack else tname
                counts[key] = counts.get(key, 0) + 1
        lines = [f"{k} {v}" for k, v in sorted(counts.items())]
        return "\n".join(lines) + ("\n" if lines else "")


def _new_compile_record(variant: str) -> dict:
    return {"variant": variant, "calls": 0, "compile_seconds": 0.0,
            "compile_events": 0, "first_call_seconds": None,
            "flops": None, "bytes_accessed": None,
            "cost_analysis": False}


class CompileRegistry:
    """Per-variant compile accounting for jit cache entries.

    `instrument(variant, fn)` wraps a FRESH jit callable (a cache miss in
    the solver variant cache): the first call is timed wall-clock and
    attributed backend-compile seconds via a `jax.monitoring` duration
    listener (thread-local attribution — concurrent first calls on
    different variants don't cross-credit); subsequent calls are a
    counter bump and a dict hit. With `cost_analysis_enabled` the first
    call AOT-lowers and compiles so `Compiled.cost_analysis()` flops /
    bytes-accessed land in the record — any AOT or runtime mismatch
    falls back to the original jit callable permanently, so profiling
    can never take the solve path down."""

    def __init__(self, registry: _metrics.Registry | None = None):
        r = registry or _metrics.REGISTRY
        self._m_compile = r.histogram(
            "compile_seconds",
            "First-call compile cost per solver variant (BatchFlags).",
            labels=("variant",),
            buckets=_metrics.exponential_buckets(0.01, 4.0, 10))
        self._m_variants = r.gauge(
            "profiling_compile_variants",
            "Distinct jit variants seen by the compile registry.")
        self._lock = threading.Lock()
        self._variants: dict[str, dict] = {}
        self._local = threading.local()
        self._listener_on = False
        self.cost_analysis_enabled = False

    def _install_listener(self) -> None:
        if self._listener_on:
            return
        try:
            from jax import monitoring
            monitoring.register_event_duration_secs_listener(
                self._on_event)
            self._listener_on = True
        except Exception:
            self._listener_on = True  # no jax: wall fallback only

    def _on_event(self, event: str, duration: float, **kw) -> None:
        # jax fires this for every timed event; only backend compiles of
        # the variant currently first-calling on THIS thread are ours
        variant = getattr(self._local, "variant", None)
        if variant is None or "backend_compile" not in event:
            return
        with self._lock:
            rec = self._variants.get(variant)
            if rec is not None:
                rec["compile_seconds"] += float(duration)
                rec["compile_events"] += 1

    def instrument(self, variant: str, fn):
        """Wrap `fn` (a fresh jit callable) with first-call compile
        accounting under `variant`."""
        self._install_listener()
        with self._lock:
            rec = self._variants.setdefault(
                variant, _new_compile_record(variant))
            self._m_variants.set(len(self._variants))
        state = {"fn": fn, "pending": True}
        gate = threading.Lock()

        def profiled_call(*args, **kwargs):
            if state["pending"]:
                with gate:
                    if state["pending"]:
                        return self._first_call(rec, state, variant,
                                                args, kwargs)
            rec["calls"] += 1
            return state["fn"](*args, **kwargs)

        # the jit surface callers inspect (HLO pins lower().as_text())
        # stays reachable through the wrapper
        lower = getattr(fn, "lower", None)
        if lower is not None:
            profiled_call.lower = lower
        return profiled_call

    def _first_call(self, rec, state, variant, args, kwargs):
        self._local.variant = variant
        t0 = time.perf_counter()
        try:
            if self.cost_analysis_enabled:
                aot = self._try_aot(rec, state["fn"], args, kwargs)
                if aot is not None:
                    state["fn"] = aot
            out = state["fn"](*args, **kwargs)
        finally:
            dt = time.perf_counter() - t0
            self._local.variant = None
            state["pending"] = False
            with self._lock:
                rec["calls"] += 1
                rec["first_call_seconds"] = dt
                if not rec["compile_events"]:
                    # no backend events (listener missing / cache hit
                    # from a prior process): first-call wall is the
                    # best available bound
                    rec["compile_seconds"] = dt
            self._m_compile.labels(variant).observe(dt)
        return out

    def _try_aot(self, rec, fn, args, kwargs):
        """AOT lower+compile so cost_analysis() is available. Returns a
        callable running the compiled executable (falling back to the
        jit original on any runtime mismatch), or None when AOT itself
        fails — profiling never changes solve-path behavior."""
        try:
            compiled = fn.lower(*args, **kwargs).compile()
            cost = compiled.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            rec["flops"] = float(cost.get("flops", 0.0))
            rec["bytes_accessed"] = float(cost.get("bytes accessed", 0.0))
            rec["cost_analysis"] = True
        except Exception:
            return None

        def run_compiled(*a, **k):
            try:
                return compiled(*a, **k)
            except Exception:
                # signature drift (e.g. a victims pytree appearing):
                # fall back to the retracing jit original
                return fn(*a, **k)

        return run_compiled

    def snapshot(self) -> dict[str, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._variants.items()}

    def totals(self) -> dict:
        with self._lock:
            recs = [dict(v) for v in self._variants.values()]
        return {
            "variants": len(recs),
            "compile_seconds_total": round(
                sum(r["compile_seconds"] for r in recs), 6),
            "flops_total": sum(r["flops"] or 0.0 for r in recs),
            "bytes_accessed_total": sum(
                r["bytes_accessed"] or 0.0 for r in recs),
        }


class DeviceMemoryMonitor:
    """`device.memory_stats()` gauges with a local high-water, plus the
    CPU fallback: the CPU backend returns None there, so the monitor
    accounts the StateDB's device blob buffers by dtype/shape instead —
    the exact bytes that WOULD occupy HBM on a real chip.

    `device_memory_bytes_limit` is only exported when the backend
    reports one; the DeviceMemoryHigh alert divides peak by limit, and
    a missing limit series makes that join an empty vector — the alert
    can never fire on the CPU fallback by construction."""

    def __init__(self, registry: _metrics.Registry | None = None):
        r = registry or _metrics.REGISTRY
        self._g_in_use = r.gauge(
            "device_memory_bytes_in_use",
            "Live device allocation per device (memory_stats).",
            labels=("device",))
        self._g_limit = r.gauge(
            "device_memory_bytes_limit",
            "Backend-reported allocatable bytes per device; absent on "
            "backends without memory_stats (CPU).",
            labels=("device",))
        self._g_peak = r.gauge(
            "device_memory_peak_bytes_in_use",
            "High-water device allocation per device (max of backend "
            "peak and every observed in_use).",
            labels=("device",))
        self._g_blob = r.gauge(
            "device_memory_statedb_bytes",
            "CPU-fallback accounting: StateDB device blob bytes by "
            "dtype (what would sit in HBM).",
            labels=("dtype",))
        self._peaks: dict[str, float] = {}
        self.backend_supported: bool | None = None

    def collect(self, statedbs=()) -> dict:
        """Refresh the gauges (called at scrape time) and return the
        snapshot: backend stats per device where supported, StateDB
        blob accounting always."""
        devices = []
        try:
            import jax
            devices = list(jax.devices())
        except Exception:
            jax = None
        supported = False
        per_device: dict[str, dict] = {}
        for dev in devices:
            try:
                stats = dev.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue
            supported = True
            label = f"{dev.platform}:{dev.id}"
            in_use = float(stats.get("bytes_in_use", 0.0))
            peak = max(self._peaks.get(label, 0.0),
                       float(stats.get("peak_bytes_in_use", 0.0)),
                       in_use)
            self._peaks[label] = peak
            self._g_in_use.labels(label).set(in_use)
            self._g_peak.labels(label).set(peak)
            if "bytes_limit" in stats:
                self._g_limit.labels(label).set(
                    float(stats["bytes_limit"]))
            per_device[label] = dict(stats)
        self.backend_supported = supported
        by_dtype: dict[str, int] = {}
        by_shape: dict[str, int] = {}
        if jax is not None:
            for db in statedbs:
                tree = getattr(db, "_device", None)
                if tree is None:
                    continue
                for leaf in jax.tree_util.tree_leaves(tree):
                    nbytes = int(getattr(leaf, "nbytes", 0) or 0)
                    if not nbytes:
                        continue
                    dt = str(getattr(leaf, "dtype", "unknown"))
                    shape = tuple(getattr(leaf, "shape", ()))
                    by_dtype[dt] = by_dtype.get(dt, 0) + nbytes
                    skey = f"{dt}[{','.join(str(d) for d in shape)}]"
                    by_shape[skey] = by_shape.get(skey, 0) + nbytes
        for dt, nbytes in by_dtype.items():
            self._g_blob.labels(dt).set(nbytes)
        return {"backend_supported": supported,
                "devices": per_device,
                "statedb_bytes_by_dtype": by_dtype,
                "statedb_bytes_by_shape": by_shape,
                "statedb_bytes_total": sum(by_dtype.values())}


class DeviceTraceCapture:
    """On-demand `jax.profiler.trace` windows. `capture(seconds)` spawns
    a capture thread and returns immediately (the obs handler must not
    park, lint R1); one window at a time — a second request while one is
    open reports busy."""

    def __init__(self, artifact_root: str | None = None):
        import tempfile
        self.artifact_root = (
            artifact_root
            or os.environ.get("KTPU_PROFILE_DIR")
            or os.path.join(tempfile.gettempdir(), "ktpu-device-traces"))
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._seq = 0
        self.captures: list[dict] = []

    def capture(self, seconds: float) -> dict:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return {"status": "busy",
                        "artifact_dir": self.captures[-1]["artifact_dir"]
                        if self.captures else None}
            self._seq += 1
            outdir = os.path.join(self.artifact_root,
                                  f"capture-{self._seq:04d}")
            rec = {"status": "capturing", "artifact_dir": outdir,
                   "seconds": float(seconds)}
            self.captures.append(rec)
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._capture_window,
                args=(outdir, float(seconds), rec),
                name="ktpu-profiler-device", daemon=True)
            self._thread.start()
            return dict(rec)

    def _capture_window(self, outdir: str, seconds: float,
                        rec: dict) -> None:
        # off-loop thread: Event.wait pacing, no asyncio (lint R1)
        try:
            import jax
            os.makedirs(outdir, exist_ok=True)
            jax.profiler.start_trace(outdir)
            try:
                self._stop.wait(seconds)
            finally:
                jax.profiler.stop_trace()
            rec["status"] = "done"
        except Exception as exc:
            rec["status"] = f"error: {exc}"

    def join(self, timeout: float = 30.0) -> None:
        t = self._thread
        if t is not None:
            t.join(timeout)


# hint table keyed by dominant cost: what the named wall usually means
# and the first lever to pull (ties into ROADMAP open items 1-3)
BOTTLENECK_HINTS = {
    "dispatch": "host->device submit bound: grow the batch or overlap "
                "dispatch with encode",
    "settle": "device->host readback bound: donate result buffers and "
              "np.asarray only the sliced outputs",
    "commit": "store write-back bound: widen commit fan-out or batch "
              "bind writes",
    "apply": "state apply bound: keep the row scatter fully on-device",
    "encode": "host encode bound: vectorize pod/node packing",
    "probe_solve": "defrag probe solves dominate: batch what-if solves "
                   "on one device call or pre-warm the variant cache",
}


def bottleneck_report(config: str, costs: dict,
                      *, stage_busy_frac: dict | None = None,
                      queue_depth_max: dict | None = None,
                      transfer_bytes: dict | None = None,
                      compile_totals: dict | None = None,
                      wall_s: float | None = None,
                      hints: dict | None = None) -> dict:
    """Fold the evidence into one verdict: `dominant` names the largest
    cost bucket; busy fractions, queue high-waters, transfer bytes and
    compile totals ride along so the report is auditable, and `hint`
    says what that wall usually means."""
    costs = {k: max(0.0, float(v)) for k, v in (costs or {}).items()}
    dominant = max(costs, key=lambda k: costs[k]) if costs else "unknown"
    total = sum(costs.values()) or 1.0
    report: dict = {
        "config": config,
        "dominant": dominant,
        "costs_seconds": {k: round(v, 4) for k, v in sorted(
            costs.items(), key=lambda kv: -kv[1])},
        "cost_fractions": {k: round(v / total, 4) for k, v in sorted(
            costs.items(), key=lambda kv: -kv[1])},
    }
    if stage_busy_frac:
        report["stage_busy_frac"] = {
            k: round(float(v), 4) for k, v in stage_busy_frac.items()}
    if queue_depth_max:
        report["queue_depth_max"] = dict(queue_depth_max)
    if transfer_bytes:
        report["transfer_bytes"] = {
            k: int(v) for k, v in transfer_bytes.items()}
    if compile_totals:
        report["compile"] = dict(compile_totals)
    if wall_s is not None:
        report["wall_seconds"] = round(float(wall_s), 3)
    hint = (hints if hints is not None else BOTTLENECK_HINTS).get(
        dominant)
    if hint:
        report["hint"] = hint
    return report


# host<->device transfer accounting: the settle-stage readback side.
# (The upload side rides the statedb_flush_* seams in state/statedb.py.)
_M_READBACK = _metrics.REGISTRY.counter(
    "device_readback_bytes_total",
    "Bytes materialized device->host (settle-stage np.asarray reads).")


def record_readback(*arrays) -> int:
    """Count a device->host materialization; returns the bytes added."""
    total = 0
    for arr in arrays:
        nbytes = getattr(arr, "nbytes", None)
        if nbytes:
            total += int(nbytes)
    if total:
        _M_READBACK.inc(total)
    return total


# process-global compile registry: the driver's variant cache feeds it
# whether or not a plane is started (records are cheap; cost analysis
# stays off until a plane enables it)
COMPILES = CompileRegistry()


class ProfilingPlane:
    """The facade a component hands to the obs mux: owns the sampler,
    device-memory monitor and capture windows, and fronts the process
    CompileRegistry."""

    def __init__(self, registry: _metrics.Registry | None = None,
                 clock: Clock | None = None,
                 interval_s: float = 0.01):
        self.sampler = SamplingProfiler(
            interval_s=interval_s, registry=registry, clock=clock)
        self.memory = DeviceMemoryMonitor(registry=registry)
        self.capture = DeviceTraceCapture()
        self.compiles = COMPILES

    @property
    def running(self) -> bool:
        return self.sampler.running

    def start(self, cost_analysis: bool = True) -> None:
        if cost_analysis:
            self.compiles.cost_analysis_enabled = True
        self.sampler.start()

    def stop(self) -> None:
        self.sampler.stop()

    def profile_text(self, seconds: float | None = None) -> str:
        return self.sampler.collapsed(seconds=seconds)

    def capture_device(self, seconds: float) -> dict:
        return self.capture.capture(seconds)


# the process-global plane (obs.metrics.REGISTRY position): components
# route /debug/pprof/* here; bench --profile starts/stops it
PROFILER = ProfilingPlane()
