"""Dapper-style distributed tracing for the scheduling pipeline.

One pod's life — client POST → APF queue → encode → dispatch → solve →
settle → bind → kubelet Running — crosses an HTTP hop, four pipeline
threads, and the kubelet sync loop.  contextvars do not survive the
``ktpu-dispatch/settle/commit`` thread boundaries (each stage is a plain
worker thread fed by a queue), so propagation here is *explicit*:

- over HTTP as a W3C ``traceparent`` header
  (``00-{trace_id:32x}-{span_id:16x}-{01|00}``),
- across pipeline stages as a ``Span`` carried on the queue item
  (``_BatchWork.span``),
- across the bind boundary as a pod annotation
  (``trace.ktpu.io/context``) that the kubelet joins on sync.

Sampling is head-based: the root span decides (``KTPU_TRACE_SAMPLE``,
default 1%) and children inherit, so the headline path pays only the
coin-flip.  Unsampled spans are real objects with ``sampled=False`` —
callers never branch — but they are never recorded.

Finished spans land in a bounded ring served at ``/debug/traces`` on the
obs mux and exportable as JSON-lines or Chrome trace-event JSON
(Perfetto-loadable; one row per pipeline stage/thread).

Span lifecycle discipline is lint-enforced (R6 ``span-discipline``):
``start_span`` must be used as a context manager or ended in a
``finally``; ``begin_span`` is the sanctioned escape hatch for explicit
cross-thread handoff and is tracked in the tracer's open-span table so
orphans are still observable (``Tracer.open_spans``).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass

TRACE_ANNOTATION = "trace.ktpu.io/context"

# Stage rows every stitched trace is expected to carry (also the Chrome
# export's thread names).  Order is the pipeline order.
STAGE_TIDS = ("client", "apiserver", "encode", "dispatch", "settle",
              "commit", "kubelet")


def wall_now() -> float:
    """Wall-clock timestamp for span records. Lives HERE (obs/ sits
    outside the R4 determinism lint scope) so solve-path modules can
    timestamp trace spans without tripping seed-replay checks: a span's
    ts never feeds a scheduling decision."""
    return time.time()


@dataclass(frozen=True)
class SpanContext:
    """Immutable (trace_id, span_id, sampled) triple — the wire identity."""

    trace_id: str   # 32 lowercase hex chars
    span_id: str    # 16 lowercase hex chars
    sampled: bool

    def to_traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-" \
               f"{'01' if self.sampled else '00'}"


def parse_traceparent(value: str | None) -> SpanContext | None:
    """Parse a W3C traceparent header; None on any malformation."""
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16 \
            or len(flags) != 2:
        return None
    try:
        int(trace_id, 16), int(span_id, 16), int(flags, 16)
    except ValueError:
        return None
    if version == "ff" or int(trace_id, 16) == 0 or int(span_id, 16) == 0:
        return None
    return SpanContext(trace_id.lower(), span_id.lower(),
                       sampled=bool(int(flags, 16) & 1))


class Span:
    """One timed operation on one thread row.

    Use ``with tracer.start_span(...)`` for scoped spans; ``end()`` is
    idempotent so explicit-handoff paths can double up on safety nets.
    """

    __slots__ = ("_tracer", "name", "context", "parent_id", "tid",
                 "start_wall", "_start_perf", "attrs", "_ended")

    def __init__(self, tracer: "Tracer", name: str, context: SpanContext,
                 parent_id: str | None, tid: str,
                 attrs: dict | None = None):
        self._tracer = tracer
        self.name = name
        self.context = context
        self.parent_id = parent_id
        self.tid = tid
        self.start_wall = time.time()
        self._start_perf = time.perf_counter()
        self.attrs = attrs
        self._ended = False

    @property
    def sampled(self) -> bool:
        return self.context.sampled

    def set_attr(self, key: str, value) -> None:
        if not self.context.sampled:
            return
        if self.attrs is None:
            self.attrs = {}
        self.attrs[key] = value

    def child(self, name: str, tid: str | None = None) -> "Span":
        """Begin a child span (explicit handoff — caller must end it)."""
        return self._tracer.begin_span(name, parent=self.context,
                                       tid=tid or self.tid)

    def end(self, status: str = "ok") -> None:
        if self._ended:
            return
        self._ended = True
        dur = time.perf_counter() - self._start_perf
        self._tracer._finish(self, dur, status)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.end("error" if exc_type is not None else "ok")


class Tracer:
    """Process-wide span factory + bounded finished-span ring.

    ``sample_rate`` None defers to ``KTPU_TRACE_SAMPLE`` (read per root
    span, so late env changes — e.g. bench setting it before the heavy
    imports — take effect); tests pin ``TRACER.sample_rate = 1.0``.
    """

    def __init__(self, sample_rate: float | None = None,
                 capacity: int = 512):
        self.sample_rate = sample_rate
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        self._open: dict[str, Span] = {}
        self.dropped_unfinished = 0

    # -- sampling ----------------------------------------------------------

    def _rate(self) -> float:
        if self.sample_rate is not None:
            return self.sample_rate
        try:
            return float(os.environ.get("KTPU_TRACE_SAMPLE", "0.01"))
        except ValueError:
            return 0.0

    def _sample_root(self) -> bool:
        rate = self._rate()
        if rate <= 0.0:
            return False
        if rate >= 1.0:
            return True
        # obs/ is outside the R4 determinism scope: tracing is diagnostic,
        # never part of the solve path, so ambient entropy is fine here.
        return os.urandom(2)[0] / 256.0 < rate

    @staticmethod
    def _gen_id(nbytes: int) -> str:
        return os.urandom(nbytes).hex()

    # -- span creation -----------------------------------------------------

    def start_span(self, name: str, parent: SpanContext | None = None,
                   tid: str = "main", attrs: dict | None = None) -> Span:
        """Scoped span: use as a context manager (R6-enforced)."""
        return self.begin_span(name, parent=parent, tid=tid, attrs=attrs)

    def begin_span(self, name: str, parent: SpanContext | None = None,
                   tid: str = "main", attrs: dict | None = None) -> Span:
        """Explicit-handoff span: the caller owns ``end()``.

        Sanctioned for queue items that cross thread boundaries; tracked
        in the open-span table so orphans stay visible.
        """
        if parent is not None:
            sampled = parent.sampled
            trace_id = parent.trace_id
            parent_id = parent.span_id
        else:
            sampled = self._sample_root()
            trace_id = self._gen_id(16)
            parent_id = None
        ctx = SpanContext(trace_id, self._gen_id(8), sampled)
        span = Span(self, name, ctx, parent_id, tid, attrs)
        if sampled:
            with self._lock:
                self._open[ctx.span_id] = span
        return span

    def record_span(self, name: str, parent: SpanContext | None,
                    start_wall: float, dur_s: float, tid: str = "main",
                    status: str = "ok", attrs: dict | None = None) -> None:
        """Record a retroactive span (already timed by the caller)."""
        if parent is None or not parent.sampled:
            return
        rec = {
            "trace_id": parent.trace_id,
            "span_id": self._gen_id(8),
            "parent_id": parent.span_id,
            "name": name,
            "tid": tid,
            "ts_us": int(start_wall * 1e6),
            "dur_us": max(int(dur_s * 1e6), 0),
            "status": status,
        }
        if attrs:
            rec["attrs"] = attrs
        with self._lock:
            self._ring.append(rec)

    def _finish(self, span: Span, dur_s: float, status: str) -> None:
        if not span.context.sampled:
            return
        rec = {
            "trace_id": span.context.trace_id,
            "span_id": span.context.span_id,
            "parent_id": span.parent_id,
            "name": span.name,
            "tid": span.tid,
            "ts_us": int(span.start_wall * 1e6),
            "dur_us": max(int(dur_s * 1e6), 0),
            "status": status,
        }
        if span.attrs:
            rec["attrs"] = span.attrs
        with self._lock:
            self._open.pop(span.context.span_id, None)
            self._ring.append(rec)

    # -- inspection / export -----------------------------------------------

    def open_spans(self) -> list[Span]:
        """Sampled spans begun but not yet ended (orphan detector)."""
        with self._lock:
            return list(self._open.values())

    def finished(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self.dropped_unfinished += len(self._open)
            self._open.clear()

    def debug_payload(self) -> dict:
        """The /debug/traces body: finished spans grouped by trace."""
        spans = self.finished()
        traces: dict[str, list] = {}
        for rec in spans:
            traces.setdefault(rec["trace_id"], []).append(rec)
        for recs in traces.values():
            recs.sort(key=lambda r: r["ts_us"])
        return {
            "num_traces": len(traces),
            "num_spans": len(spans),
            "open_spans": len(self.open_spans()),
            "traces": traces,
        }

    def to_jsonl(self) -> str:
        return "\n".join(json.dumps(rec, sort_keys=True)
                         for rec in self.finished())

    def to_chrome(self) -> str:
        """Chrome trace-event JSON: ph:"X" duration events, one row per
        stage thread (ph:"M" thread_name metadata), Perfetto-loadable."""
        events = []
        tids: dict[str, int] = {}

        def tid_row(name: str) -> int:
            if name not in tids:
                row = len(tids) + 1
                tids[name] = row
                events.append({
                    "ph": "M", "pid": 1, "tid": row,
                    "name": "thread_name", "args": {"name": name},
                })
            return tids[name]

        # seed the pipeline rows in pipeline order so the viewer lays
        # them out top-to-bottom regardless of which span finished first
        for stage in STAGE_TIDS:
            tid_row(stage)
        for rec in self.finished():
            events.append({
                "ph": "X", "pid": 1,
                "tid": tid_row(rec["tid"]),
                "name": rec["name"],
                "cat": rec.get("status", "ok"),
                "ts": rec["ts_us"],
                "dur": rec["dur_us"],
                "args": {
                    "trace_id": rec["trace_id"],
                    "span_id": rec["span_id"],
                    "parent_id": rec.get("parent_id"),
                    **(rec.get("attrs") or {}),
                },
            })
        return json.dumps({"traceEvents": events,
                           "displayTimeUnit": "ms"})


TRACER = Tracer()


def pod_trace_context(pod) -> SpanContext | None:
    """Extract the trace context stamped on a pod, if any and sampled."""
    meta = getattr(pod, "metadata", None)
    ann = getattr(meta, "annotations", None) or {}
    ctx = parse_traceparent(ann.get(TRACE_ANNOTATION))
    if ctx is not None and ctx.sampled:
        return ctx
    return None
