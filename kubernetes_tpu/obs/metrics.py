"""Prometheus-style metrics: Counter/Gauge/Histogram families with labels
and text exposition format 0.0.4.

The client_golang shape the reference's binaries register against
(prometheus.MustRegister in plugin/pkg/scheduler/metrics/metrics.go:31-50,
apiserver request metrics, the client-go workqueue metrics provider), cut
down to what this framework scrapes: label escaping, cumulative histogram
buckets, and a default process-global registry. Registration is
get-or-create so components constructed many times per process (tests,
benches) share one family instead of colliding.

Thread safety: servers run on whatever thread owns their event loop while
tests scrape from another, so child creation is guarded by the registry
lock and every sample update by a per-child lock (uncontended in the
single-loop steady state).
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Iterable

# client_golang prometheus.DefBuckets (seconds)
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                   2.5, 5.0, 10.0)


def exponential_buckets(start: float, factor: float, count: int
                        ) -> tuple[float, ...]:
    """prometheus.ExponentialBuckets (histogram.go): `count` upper bounds
    starting at `start`, each `factor` times the previous."""
    return tuple(start * factor ** i for i in range(count))


def _escape_label(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(text: str) -> str:
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(v: float) -> str:
    """Sample value formatting: integral values render bare (the Go %v
    shape tests pin, e.g. `scheduler_pods_scheduled_total 1`)."""
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class _Child:
    __slots__ = ("_lock",)

    def __init__(self):
        self._lock = threading.Lock()


class Counter(_Child):
    __slots__ = ("value",)

    def __init__(self):
        super().__init__()
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self.value += amount


class Gauge(_Child):
    __slots__ = ("value",)

    def __init__(self):
        super().__init__()
        self.value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)


class Histogram(_Child):
    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets: tuple[float, ...]):
        super().__init__()
        self.buckets = buckets            # finite upper bounds, ascending
        self.counts = [0] * (len(buckets) + 1)  # per-bucket, last = +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        i = bisect_left(self.buckets, value)
        with self._lock:
            self.counts[i] += 1
            self.sum += value
            self.count += 1

    def quantile(self, q: float) -> float:
        """histogram_quantile-style estimate: linear interpolation within
        the bucket holding the q-th sample (0.0 when empty; the last finite
        bound when the sample lands in the +Inf bucket)."""
        with self._lock:
            total = self.count
            counts = list(self.counts)
        if total == 0:
            return 0.0
        rank = q * total
        cumulative = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cumulative + c >= rank:
                if i >= len(self.buckets):       # +Inf bucket
                    return self.buckets[-1] if self.buckets else 0.0
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = self.buckets[i]
                return lo + (hi - lo) * max(0.0, rank - cumulative) / c
            cumulative += c
        return self.buckets[-1] if self.buckets else 0.0


_CHILD_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class Family:
    """One metric family: name + type + label names, children per label
    values. An unlabeled family proxies sample methods to its single
    child, so `registry.counter(...).inc()` works without `.labels()`."""

    def __init__(self, name: str, help_text: str, kind: str,
                 labelnames: tuple[str, ...],
                 buckets: tuple[float, ...] | None = None):
        self.name = name
        self.help = help_text
        self.kind = kind
        self.labelnames = labelnames
        self.buckets = buckets
        self._children: dict[tuple[str, ...], _Child] = {}
        self._lock = threading.Lock()

    def labels(self, *values) -> Counter | Gauge | Histogram:
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {len(values)} values")
        key = tuple(str(v) for v in values)
        # double-checked create: the lock-free first read is a plain dict
        # get (atomic under the GIL and never a partial object, because
        # the child is fully constructed before the guarded insert); the
        # re-check inside the lock stops two racing threads from each
        # installing a child and splitting the family's samples
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    if self.kind == "histogram":
                        child = Histogram(self.buckets)
                    else:
                        child = _CHILD_TYPES[self.kind]()
                    self._children[key] = child
        return child

    def children(self) -> list[tuple[tuple[str, ...], _Child]]:
        with self._lock:
            return sorted(self._children.items())

    # unlabeled convenience: family as the single child
    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def set(self, value: float) -> None:
        self.labels().set(value)

    def dec(self, amount: float = 1.0) -> None:
        self.labels().dec(amount)

    def observe(self, value: float) -> None:
        self.labels().observe(value)

    def quantile(self, q: float) -> float:
        return self.labels().quantile(q)

    def _label_str(self, values: tuple[str, ...],
                   extra: str = "") -> str:
        pairs = [f'{n}="{_escape_label(v)}"'
                 for n, v in zip(self.labelnames, values)]
        if extra:
            pairs.append(extra)
        return "{" + ",".join(pairs) + "}" if pairs else ""

    def render(self) -> list[str]:
        lines = [f"# HELP {self.name} {_escape_help(self.help)}",
                 f"# TYPE {self.name} {self.kind}"]
        for values, child in self.children():
            if self.kind == "histogram":
                assert isinstance(child, Histogram)
                cumulative = 0
                with child._lock:
                    counts = list(child.counts)
                    total, s = child.count, child.sum
                for bound, c in zip(child.buckets, counts):
                    cumulative += c
                    le = self._label_str(values, f'le="{bound:g}"')
                    lines.append(f"{self.name}_bucket{le} {cumulative}")
                inf = self._label_str(values, 'le="+Inf"')
                lines.append(f"{self.name}_bucket{inf} {total}")
                lbl = self._label_str(values)
                lines.append(f"{self.name}_sum{lbl} {_fmt(s)}")
                lines.append(f"{self.name}_count{lbl} {total}")
            else:
                lines.append(f"{self.name}{self._label_str(values)} "
                             f"{_fmt(child.value)}")
        return lines


class Registry:
    """Get-or-create family registry + text exposition renderer."""

    def __init__(self):
        self._families: dict[str, Family] = {}
        self._lock = threading.Lock()

    def _register(self, name: str, help_text: str, kind: str,
                  labelnames: Iterable[str],
                  buckets: tuple[float, ...] | None = None) -> Family:
        labelnames = tuple(labelnames)
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}{fam.labelnames}, not "
                        f"{kind}{labelnames}")
                # a family's bucket layout is fixed at first registration:
                # re-registering with different EXPLICIT bounds would split
                # one family's observations across incompatible layouts and
                # silently corrupt every histogram_quantile over it, so it's
                # an error; omitting buckets keeps get-or-create semantics
                if kind == "histogram" and buckets is not None \
                        and fam.buckets != buckets:
                    raise ValueError(
                        f"metric {name!r} already registered with buckets "
                        f"{fam.buckets}, not {buckets}")
                return fam
            if kind == "histogram" and buckets is None:
                buckets = DEFAULT_BUCKETS
            fam = Family(name, help_text, kind, labelnames, buckets)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help_text: str = "",
                labels: Iterable[str] = ()) -> Family:
        return self._register(name, help_text, "counter", labels)

    def gauge(self, name: str, help_text: str = "",
              labels: Iterable[str] = ()) -> Family:
        return self._register(name, help_text, "gauge", labels)

    def histogram(self, name: str, help_text: str = "",
                  labels: Iterable[str] = (),
                  buckets: Iterable[float] | None = None) -> Family:
        """Explicit `buckets` override the per-family boundaries at first
        registration (latency SLO quantiles want domain-shaped layouts,
        e.g. exponential_buckets); omitted, the family keeps
        client_golang's DefBuckets. A later registration may omit buckets
        (get-or-create) but passing a DIFFERENT explicit layout raises."""
        bounds = tuple(sorted(buckets)) if buckets is not None else None
        return self._register(name, help_text, "histogram", labels,
                              buckets=bounds)

    def get(self, name: str) -> Family | None:
        with self._lock:
            return self._families.get(name)

    def render(self) -> str:
        with self._lock:
            families = [self._families[k] for k in sorted(self._families)]
        lines: list[str] = []
        for fam in families:
            lines.extend(fam.render())
        return "\n".join(lines) + "\n" if lines else ""


# the process-global default (prometheus.DefaultRegisterer position)
REGISTRY = Registry()
