"""Gang-defragmentation descheduler (solver-driven rebalancing)."""

from kubernetes_tpu.descheduler.core import (
    COOLDOWN_ANNOTATION,
    PARKED_SCHEDULER,
    PARKED_UNTIL_ANNOTATION,
    DefragPlan,
    Descheduler,
    cooldown_active,
)

__all__ = ["COOLDOWN_ANNOTATION", "PARKED_SCHEDULER",
           "PARKED_UNTIL_ANNOTATION", "DefragPlan", "Descheduler",
           "cooldown_active"]
