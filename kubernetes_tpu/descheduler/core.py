"""Descheduler: continuous gang-defragmentation on the what-if simulator.

ROADMAP open item 3's payoff: the batched solver stops being only a
placer and becomes a cluster optimizer. A Pending gang can be blocked not
by capacity but by *fragmentation* — aggregate free space covers the
gang's demand, yet no quorum of nodes has room, because fillers are
scattered one per node. Upstream's descheduler is a bolt-on heuristic
evictor; this loop is a solver-driven planner: every candidate move set
is scored by ONE device what-if (`ScaleSimulator.probe_defrag`) that
answers the joint question "does the gang land at quorum after these
evictions, and does every evicted pod re-fit elsewhere?".

One pass (`run_once`):

1. detect — a gang is *fragmented* when its members are Pending, a
   baseline solve places fewer than quorum, and host-side aggregate free
   capacity (eligible nodes only) covers the gang's aggregate request.
2. plan — victim candidates are bound non-gang pods at or below the
   priority cutoff that PDBs allow evicting, ordered lowest-priority /
   smallest-key first (the preemption VictimTable ordering); candidate
   sets are prefixes of that order, so at most `max_moves` probe solves
   score a cycle and the smallest winning prefix is the plan.
3. execute — under the autoscaler's safety ladder: cooldown-stamp the
   source nodes (the shared annotation the autoscaler's scale-down
   honors, preventing evict/shrink ping-pong), then per victim
   `can_evict` (the spending PDB gate) -> delete -> recreate unbound but
   PARKED under a sentinel schedulerName the real scheduler ignores.
   Parking is what makes the freed space stick: recreated fillers would
   otherwise race the gang's backoff retry and re-pack onto the emptied
   nodes (spreading prefers them) before the gang's next solve. Nodes
   carrying the autoscaler's ToBeDeletedByClusterAutoscaler taint are
   never victim sources, and the solver's taint predicate keeps them out
   of move targets.
4. verify — later ticks watch the in-flight plan: once the gang is bound
   at quorum the displaced pods are released (schedulerName restored, a
   pod MODIFIED event re-enqueues them) and the plan succeeds when every
   one rebound; past the deadline the plan rolls back (release whatever
   is still parked, emit `DefragRolledBack`, back the gang off). Parked
   pods are durable store objects carrying their own wall-clock release
   deadline, so a descheduler killed mid-plan strands nothing: any
   successor's sweep releases expired parked pods.

The loop is leader-electable (cmd/descheduler.py) and lives in the
controller-manager behind `enable_descheduler=True`, mirroring the
monitor's wiring.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field

from kubernetes_tpu.api.quantity import parse_quantity
from kubernetes_tpu.apiserver.store import (
    AlreadyExists,
    Conflict,
    NotFound,
    ObjectStore,
)
from kubernetes_tpu.autoscaler.core import DELETION_TAINT, _node_ready, _pod_pending
from kubernetes_tpu.autoscaler.simulator import ScaleSimulator
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.controllers.disruption import can_evict
from kubernetes_tpu.gang import annotation_min, pod_group_key
from kubernetes_tpu.models.policy import DEFAULT_POLICY
from kubernetes_tpu.preemption.victims import pdb_evictable
from kubernetes_tpu.state.layout import Capacities
from kubernetes_tpu.utils.clock import SYSTEM_CLOCK, Clock
from kubernetes_tpu.utils.events import EventRecorder

log = logging.getLogger(__name__)

# shared evict/scale-down cooldown stamp: wall-clock unix-seconds expiry.
# The descheduler stamps a plan's source nodes; the autoscaler's
# scale-down skips unexpired nodes (and vice versa, the descheduler never
# evicts from a stamped node) — neither loop undoes the other's move.
COOLDOWN_ANNOTATION = "descheduling.ktpu.io/cooldown-until"

# displaced pods are recreated under this sentinel schedulerName: the real
# scheduler's _wants() skips them, holding the freed space for the gang
# until the plan releases them (or any descheduler's sweep does, once the
# parked-until stamp expires — the crash-recovery path)
PARKED_SCHEDULER = "descheduling.ktpu.io/parked"
PARKED_UNTIL_ANNOTATION = "descheduling.ktpu.io/parked-until"
PARKED_ORIGIN_ANNOTATION = "descheduling.ktpu.io/origin-scheduler"

SCAN_INTERVAL = 2.0       # between passes (reference descheduler: 5m)
MAX_MOVES = 8             # evictions per plan, DeschedulePolicy override
PRIORITY_CUTOFF = 0       # only pods at/below this priority may move
COOLDOWN = 300.0          # node stamp horizon (seconds, wall clock)
ROLLBACK_AFTER = 60.0     # plan deadline before DefragRolledBack

_mx_cache: tuple | None = None


def _metrics() -> tuple:
    """(cycles, moves, rollbacks, gangs_defragged, sim_seconds) — the
    descheduler_* families."""
    global _mx_cache
    if _mx_cache is None:
        from kubernetes_tpu.obs import metrics as m

        _mx_cache = (
            m.REGISTRY.counter("descheduler_cycles_total",
                               "Defragmentation passes run."),
            m.REGISTRY.counter("descheduler_moves_total",
                               "Pods evicted-to-move by executed plans."),
            m.REGISTRY.counter("descheduler_rollbacks_total",
                               "Plans abandoned at the deadline or "
                               "refused mid-eviction."),
            m.REGISTRY.counter("descheduler_gangs_defragged_total",
                               "Pending gangs that landed after a plan."),
            m.REGISTRY.histogram("descheduler_simulation_seconds",
                                 "Wall time of one what-if probe solve."),
        )
    return _mx_cache


def cooldown_active(node, wall_now: float) -> bool:
    """True while `node` carries an unexpired cooldown stamp (malformed
    stamps read as expired — a stuck annotation must not pin a node)."""
    raw = node.metadata.annotations.get(COOLDOWN_ANNOTATION)
    if not raw:
        return False
    try:
        return float(raw) > wall_now
    except ValueError:
        return False


@dataclass
class DefragPlan:
    """One in-flight move set: evictions done, waiting for the gang and
    the displaced pods to land (or for the deadline)."""

    gang_key: str
    quorum: int
    deadline: float                       # monotonic
    displaced: list[str] = field(default_factory=list)   # pod keys moved
    stamped: list[str] = field(default_factory=list)     # node names
    released: bool = False                # parked pods handed back yet?


class Descheduler:
    """One periodic pass (`run_once`) over pending gangs — like the
    autoscaler, the whole cluster is a single reconciliation unit."""

    name = "descheduler"

    def __init__(self, store: ObjectStore, *,
                 caps: Capacities | None = None,
                 policy=DEFAULT_POLICY,
                 node_informer: Informer | None = None,
                 pod_informer: Informer | None = None,
                 scan_interval: float = SCAN_INTERVAL,
                 max_moves: int = MAX_MOVES,
                 priority_cutoff: int = PRIORITY_CUTOFF,
                 cooldown: float = COOLDOWN,
                 rollback_after: float = ROLLBACK_AFTER,
                 dry_run: bool = False,
                 now=time.monotonic,
                 clock: Clock = SYSTEM_CLOCK):
        self.store = store
        self.scan_interval = scan_interval
        self.max_moves = max_moves
        self.priority_cutoff = priority_cutoff
        self.cooldown = cooldown
        self.rollback_after = rollback_after
        self.dry_run = dry_run
        self.now = now
        # wall-clock cooldown stamps ride the injectable clock (they must
        # be legible to the autoscaler's process); plan deadlines and
        # backoffs stay on the monotonic `now` above
        self.clock = clock
        self._own_informers = node_informer is None or pod_informer is None
        self.nodes = node_informer or Informer(store, "Node")
        self.pods = pod_informer or Informer(store, "Pod")
        self.simulator = ScaleSimulator(caps=caps, policy=policy)
        self.nodes.add_handler(self._on_node_event)
        self.pods.add_handler(self._on_pod_event)
        self.events = EventRecorder(store, component="descheduler")
        self._plan: DefragPlan | None = None
        # gang key -> monotonic deadline before which it is not replanned
        self._gang_backoff: dict[str, float] = {}
        self._task = None
        # counters mirrored as attributes for tests/bench
        self.cycles = 0
        self.moves = 0
        self.rollbacks = 0
        self.gangs_defragged = 0
        self.planned_moves = 0      # dry-run: moves a plan WOULD make

    # ---- informer mirror (the autoscaler's shape) ----

    def _on_node_event(self, event) -> None:
        node = event.obj
        if event.type == "DELETED":
            if self.simulator.has_node(node.metadata.name):
                self.simulator.remove_node(node.metadata.name)
            return
        self.simulator.upsert_node(node)

    def _on_pod_event(self, event) -> None:
        pod = event.obj
        if event.type == "DELETED":
            self.simulator.remove_pod(pod.key)
            return
        if pod.spec.node_name:
            self.simulator.add_pod(pod)

    def _sweep_accounting(self) -> None:
        for pod in self.pods.items():
            if pod.spec.node_name \
                    and not self.simulator.is_accounted(pod.key) \
                    and self.simulator.has_node(pod.spec.node_name):
                self.simulator.add_pod(pod)

    # ---- lifecycle ----

    async def start(self) -> None:
        import asyncio

        if self._own_informers:
            self.nodes.start()
            self.pods.start()
            await self.nodes.wait_for_sync()
            await self.pods.wait_for_sync()
        self._task = asyncio.get_running_loop().create_task(self._loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        if self._own_informers:
            self.nodes.stop()
            self.pods.stop()

    async def _loop(self) -> None:
        import asyncio

        while True:
            await asyncio.sleep(self.scan_interval)
            try:
                self.run_once()
            except Exception:  # noqa: BLE001 — the loop must not die
                log.exception("descheduler pass failed")

    # ---- one pass ----

    def run_once(self) -> None:
        from kubernetes_tpu.obs.tracing import TRACER

        now = self.now()
        self.cycles += 1
        _metrics()[0].inc()
        policy = self._load_policy()
        with TRACER.start_span("descheduler.cycle",
                               attrs={"cycle": self.cycles}):
            self._sweep_accounting()
            self._sweep_cooldowns()
            self._sweep_parked()
            if self._plan is not None:
                self._check_plan(now)
            else:
                self._defrag_pass(now, policy)
        self._write_status(policy)

    # ---- DeschedulePolicy (store knobs override ctor defaults) ----

    def _load_policy(self):
        try:
            policies = self.store.list("DeschedulePolicy")
        except Exception:  # noqa: BLE001 — knobs are optional
            return None
        if not policies:
            return None
        policy = min(policies, key=lambda p: p.key)
        self.max_moves = policy.max_moves_per_cycle
        self.priority_cutoff = policy.priority_cutoff
        self.cooldown = policy.cooldown_seconds
        self.rollback_after = policy.rollback_seconds
        self.dry_run = policy.dry_run
        return policy

    def _write_status(self, policy) -> None:
        if policy is None:
            return
        status = {"cycles": self.cycles, "moves": self.moves,
                  "rollbacks": self.rollbacks,
                  "gangsDefragged": self.gangs_defragged}

        def mutate(obj):
            obj.status = status
            return obj

        try:
            self.store.guaranteed_update("DeschedulePolicy",
                                         policy.metadata.name,
                                         policy.metadata.namespace, mutate)
        except (NotFound, Conflict):
            pass

    # ---- cooldown stamps ----

    def _sweep_cooldowns(self) -> None:
        """Drop expired stamps so a finished (or abandoned) plan leaves
        no annotation litter — also the recovery path when a descheduler
        died mid-plan and a successor inherits its stamps."""
        wall = self.clock.now()
        for node in self.nodes.items():
            raw = node.metadata.annotations.get(COOLDOWN_ANNOTATION)
            if raw is None or cooldown_active(node, wall):
                continue

            def mutate(obj):
                obj.metadata.annotations.pop(COOLDOWN_ANNOTATION, None)
                return obj

            try:
                self.store.guaranteed_update("Node", node.metadata.name,
                                             "default", mutate)
            except (NotFound, Conflict):
                pass

    def _sweep_parked(self) -> None:
        """Release parked pods whose hold expired — normally the owning
        plan releases them first; this is the recovery path for a
        descheduler that died between evicting and releasing (malformed
        stamps read as expired for the same no-stranded-pods reason)."""
        wall = self.clock.now()
        for pod in self.pods.items():
            if pod.spec.scheduler_name != PARKED_SCHEDULER:
                continue
            raw = pod.metadata.annotations.get(PARKED_UNTIL_ANNOTATION)
            try:
                if raw is not None and float(raw) > wall:
                    continue
            except ValueError:
                pass
            self._unpark(pod.key)

    def _stamp_cooldown(self, name: str) -> None:
        until = str(self.clock.now() + self.cooldown)

        def mutate(node):
            node.metadata.annotations[COOLDOWN_ANNOTATION] = until
            return node

        try:
            self.store.guaranteed_update("Node", name, "default", mutate)
        except (NotFound, Conflict):
            pass

    # ---- detection ----

    def _eligible_node(self, node, wall: float) -> bool:
        """May this node participate in a plan (free-space accounting and
        victim source)? Autoscaler-cordoned and cooldown-stamped nodes
        are out — composing, not fighting."""
        if not _node_ready(node) or node.spec.unschedulable:
            return False
        if any(t.key == DELETION_TAINT for t in node.spec.taints):
            return False
        return not cooldown_active(node, wall)

    @staticmethod
    def _pod_demand(pod) -> tuple[float, float]:
        cpu = mem = 0.0
        for c in pod.spec.containers:
            if "cpu" in c.requests:
                cpu += float(parse_quantity(c.requests["cpu"]))
            if "memory" in c.requests:
                mem += float(parse_quantity(c.requests["memory"]))
        return cpu, mem

    def _aggregate_free(self, eligible: dict[str, object]) -> tuple[float,
                                                                    float]:
        """Summed (cpu, memory) headroom across eligible nodes — host
        arithmetic, no solve. Enough headroom + a failed baseline solve
        is the fragmentation signature."""
        used: dict[str, tuple[float, float]] = {}
        for pod in self.pods.items():
            name = pod.spec.node_name
            if not name or name not in eligible \
                    or pod.status.phase in ("Succeeded", "Failed"):
                continue
            cpu, mem = self._pod_demand(pod)
            have = used.get(name, (0.0, 0.0))
            used[name] = (have[0] + cpu, have[1] + mem)
        free_cpu = free_mem = 0.0
        for name, node in eligible.items():
            alloc = node.status.effective_allocatable()
            cap_cpu = float(parse_quantity(alloc.get("cpu", "0") or "0"))
            cap_mem = float(parse_quantity(alloc.get("memory", "0") or "0"))
            cpu, mem = used.get(name, (0.0, 0.0))
            free_cpu += max(0.0, cap_cpu - cpu)
            free_mem += max(0.0, cap_mem - mem)
        return free_cpu, free_mem

    def _pending_gangs(self) -> list[tuple[str, int, list]]:
        """[(gang key, quorum, members)] with full membership pending,
        members sorted for a deterministic batch shape."""
        groups: dict[str, list] = {}
        for pod in self.pods.items():
            if not _pod_pending(pod):
                continue
            key = pod_group_key(pod)
            if key is not None:
                groups.setdefault(key, []).append(pod)
        out = []
        for key in sorted(groups):
            members = sorted(groups[key], key=lambda p: p.key)
            quorum = annotation_min(members[0]) or len(members)
            if len(members) >= quorum:
                out.append((key, quorum, members))
        return out

    # ---- planning + execution ----

    def _victim_candidates(self, eligible: dict[str, object]) -> list:
        """Move candidates in VictimTable order: lowest priority first,
        key-ascending within a class — bound, non-gang, at/below the
        cutoff, PDB-evictable, on an eligible node."""
        try:
            # per-pod pdb_evictable re-lists PDBs; a PDB-less cluster
            # (the common fleet shape) skips 50k redundant lists per pass
            has_pdbs = bool(self.store.list("PodDisruptionBudget"))
        except Exception:  # noqa: BLE001 — fail closed: check per pod
            has_pdbs = True
        out = []
        for pod in self.pods.items():
            if not pod.spec.node_name \
                    or pod.spec.node_name not in eligible \
                    or pod.metadata.deletion_timestamp \
                    or pod.status.phase in ("Succeeded", "Failed"):
                continue
            if pod_group_key(pod) is not None:
                continue  # never split a placed gang to seat another
            if (pod.spec.priority or 0) > self.priority_cutoff:
                continue
            if has_pdbs and not pdb_evictable(self.store, pod):
                continue
            out.append(pod)
        out.sort(key=lambda p: (p.spec.priority or 0, p.key))
        return out

    def _probe(self, victims: list, gang: list) -> bool:
        t0 = time.perf_counter()
        try:
            return self.simulator.probe_defrag(victims, gang)
        finally:
            _metrics()[4].observe(time.perf_counter() - t0)

    def _defrag_pass(self, now: float, policy) -> None:
        wall = self.clock.now()
        eligible = {n.metadata.name: n for n in self.nodes.items()
                    if self._eligible_node(n, wall)}
        if not eligible:
            return
        gangs = self._pending_gangs()
        if not gangs:
            return
        free_cpu, free_mem = self._aggregate_free(eligible)
        candidates = None  # built lazily, once, against current state
        for gang_key, quorum, members in gangs:
            if now < self._gang_backoff.get(gang_key, 0.0):
                continue
            need_cpu = need_mem = 0.0
            for pod in members[:quorum]:
                cpu, mem = self._pod_demand(pod)
                need_cpu += cpu
                need_mem += mem
            if free_cpu < need_cpu or free_mem < need_mem:
                continue  # true capacity shortfall: the autoscaler's job
            t0 = time.perf_counter()
            baseline = self.simulator.baseline_placed(members)
            _metrics()[4].observe(time.perf_counter() - t0)
            if baseline >= quorum:
                continue  # fits as-is: the scheduler's job
            if candidates is None:
                candidates = self._victim_candidates(eligible)
            victims = self._plan_moves(candidates, members)
            if victims is None:
                self._gang_backoff[gang_key] = now + self.scan_interval * 4
                continue
            self._execute(gang_key, quorum, members, victims, now)
            return  # one plan in flight at a time

    def _plan_moves(self, candidates: list, gang: list) -> list | None:
        """Smallest winning prefix of the victim order, each prefix
        scored by one joint what-if solve; None when no prefix within
        max_moves unblocks the gang."""
        for k in range(1, min(self.max_moves, len(candidates)) + 1):
            prefix = candidates[:k]
            if self._probe(prefix, gang):
                return prefix
        return None

    def _execute(self, gang_key: str, quorum: int, members: list,
                 victims: list, now: float) -> None:
        if self.dry_run:
            self.planned_moves += len(victims)
            self.events.record(members[0], "Normal", "DefragPlanned",
                               f"dry-run: {len(victims)} move(s) would "
                               f"unblock gang {gang_key}")
            log.info("defrag (dry-run): gang %s plan = %d move(s), not "
                     "executed", gang_key, len(victims))
            return
        plan = DefragPlan(gang_key=gang_key, quorum=quorum,
                          deadline=now + self.rollback_after)
        for name in sorted({v.spec.node_name for v in victims}):
            self._stamp_cooldown(name)
            plan.stamped.append(name)
        for pod in victims:
            if not can_evict(self.store, pod):
                # the PDB budget moved under us: stop evicting and roll
                # back what's planned (already-displaced pods reschedule
                # through the scheduler on their own)
                self._rollback(plan, "eviction refused mid-plan")
                return
            if not self._move(pod):
                self._rollback(plan, f"move of {pod.key} failed")
                return
            plan.displaced.append(pod.key)
            self.moves += 1
            _metrics()[1].inc()
        self._plan = plan
        log.info("defrag: gang %s, evicted %d pod(s), deadline in %.0fs",
                 gang_key, len(plan.displaced), self.rollback_after)

    def _move(self, pod) -> bool:
        """Evict-to-move: delete the bound pod and recreate it unbound
        AND parked (sentinel schedulerName + wall-clock release stamp) so
        the freed space waits for the gang instead of being backfilled.
        The plan releases it once the gang lands; each displaced pod then
        reschedules through the real scheduler — one fresh bind per pod,
        the exactly-once accounting the chaos drill checks."""
        clone = pod.clone()
        clone.spec.node_name = ""
        # delete+create, not an update: the fresh object must not carry
        # the dead incarnation's version
        clone.metadata.resource_version = ""  # ktpu: allow[store-rmw]
        clone.metadata.uid = ""
        clone.metadata.deletion_timestamp = None
        clone.status.phase = "Pending"
        clone.status.nominated_node_name = ""
        clone.metadata.annotations[PARKED_ORIGIN_ANNOTATION] = \
            pod.spec.scheduler_name
        clone.metadata.annotations[PARKED_UNTIL_ANNOTATION] = \
            str(self.clock.now() + self.rollback_after)
        clone.spec.scheduler_name = PARKED_SCHEDULER
        try:
            self.store.delete("Pod", pod.metadata.name,
                              pod.metadata.namespace)
        except NotFound:
            pass
        try:
            self.store.create(clone)
        except (AlreadyExists, Conflict):
            return False
        return True

    def _unpark(self, key: str) -> None:
        """Hand a parked pod back to its original scheduler: restore
        schedulerName (the pod MODIFIED event re-enqueues it) and drop
        the parking annotations."""
        namespace, _, name = key.partition("/")

        def mutate(pod):
            origin = pod.metadata.annotations.pop(
                PARKED_ORIGIN_ANNOTATION, "") or "default-scheduler"
            pod.metadata.annotations.pop(PARKED_UNTIL_ANNOTATION, None)
            if pod.spec.scheduler_name == PARKED_SCHEDULER:
                pod.spec.scheduler_name = origin
            return pod

        try:
            self.store.guaranteed_update("Pod", name, namespace, mutate)
        except (NotFound, Conflict):
            pass

    # ---- plan verification / rollback ----

    def _gang_bound(self, gang_key: str) -> int:
        return sum(1 for p in self.pods.items()
                   if pod_group_key(p) == gang_key and p.spec.node_name)

    def _displaced_rebound(self, plan: DefragPlan) -> bool:
        for key in plan.displaced:
            namespace, _, name = key.partition("/")
            pod = self.pods.get(name, namespace)
            if pod is None or not pod.spec.node_name:
                return False
        return True

    def _check_plan(self, now: float) -> None:
        plan = self._plan
        if self._gang_bound(plan.gang_key) >= plan.quorum:
            if not plan.released:
                # the gang has the space — hand the displaced pods back
                # to the real scheduler for the "elsewhere" placements
                # the probe already verified
                for key in plan.displaced:
                    self._unpark(key)
                plan.released = True
            if self._displaced_rebound(plan):
                self._plan = None
                self._gang_backoff.pop(plan.gang_key, None)
                self.gangs_defragged += 1
                _metrics()[3].inc()
                log.info("defrag: gang %s landed (%d move(s))",
                         plan.gang_key, len(plan.displaced))
                return
        if now >= plan.deadline:
            self._rollback(plan, "gang did not land before the deadline")

    def _rollback(self, plan: DefragPlan, why: str) -> None:
        """Stop evicting and abandon the plan: release anything still
        parked (the displaced pods reschedule through the scheduler on
        their own — nothing is force-undone) and let the cooldown stamps
        keep both loops off the touched nodes until the dust settles
        (the sweep clears them at expiry)."""
        if not plan.released:
            for key in plan.displaced:
                self._unpark(key)
            plan.released = True
        self._plan = None
        self._gang_backoff[plan.gang_key] = self.now() + self.cooldown
        self.rollbacks += 1
        _metrics()[2].inc()
        witness = next((p for p in self.pods.items()
                        if pod_group_key(p) == plan.gang_key), None)
        if witness is not None:
            self.events.record(witness, "Warning", "DefragRolledBack",
                               f"defrag plan for gang {plan.gang_key} "
                               f"rolled back: {why}")
        log.info("defrag: plan for gang %s rolled back: %s", plan.gang_key,
                 why)
