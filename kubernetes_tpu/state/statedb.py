"""StateDB: host-canonical cluster state incrementally mirrored to device.

The stateful shell around `ClusterState` playing the role of the scheduler
cache (reference plugin/pkg/scheduler/schedulercache/cache.go): it aggregates
node objects + accounted pods (bound and assumed) into the SoA arrays, tracks
dirtiness at field-group granularity (the generation-counter analog,
node_info.go:60), and hands the device a fresh view only when something
actually changed.

Two commit paths keep the hot loop off the PCIe bus:
- `add_pod`/`remove_pod` mutate host numpy and mark the ledger dirty; the next
  `flush()` re-uploads ledger arrays (external writes: pods bound by other
  components, deletions, node changes).
- `commit_ledger(result, ...)` accepts the solver's *device-resident* output
  ledger as the new truth (batch-to-batch chaining never leaves the device)
  while mirroring the same arithmetic into host numpy for rollback/re-encode;
  host and device stay equal without a transfer.

Assume/forget semantics (cache.go:109 AssumePod, scheduler.go:224 rollback):
the driver accounts an assignment optimistically via either path; a failed
bind calls `remove_pod` which both fixes host numpy and marks the ledger
dirty, forcing re-upload of the corrected truth.
"""

from __future__ import annotations

import numpy as np

import jax

from kubernetes_tpu.api.objects import Node, Pod
from kubernetes_tpu.state.cluster_state import (
    ClusterState,
    NodeTable,
    _fill_node_row,
    apply_pending_refreshes,
    empty_state,
    pod_nonzero_requests,
    pod_requests,
)
from kubernetes_tpu.state.layout import Capacities


class StateDB:
    def __init__(self, caps: Capacities, mesh=None):
        self.caps = caps
        self.mesh = mesh
        self.host: ClusterState = empty_state(caps)
        self.table = NodeTable(caps)
        # pod key -> (node_name, requests, nonzero, port_onehot) for removal
        self._accounted: dict[str, tuple[str, np.ndarray, np.ndarray, np.ndarray]] = {}
        self._dirty_nodes = True   # static node fields changed
        self._dirty_ledger = True  # requested/nonzero/ports changed on host
        self._device: ClusterState | None = None

    # ---- node lifecycle ----

    def upsert_node(self, node: Node) -> None:
        row = self.table.assign_row(node.metadata.name)
        _fill_node_row(self.host, self.table, row, node)
        self.table.bump(row)
        self._dirty_nodes = True

    def remove_node(self, name: str) -> None:
        if name not in self.table.row_of:
            return
        row = self.table.release_row(name)
        for key in [k for k, v in self._accounted.items() if v[0] == name]:
            del self._accounted[key]
        from kubernetes_tpu.state.cluster_state import NODE_AXIS_FIELDS
        for field in NODE_AXIS_FIELDS:
            arr = getattr(self.host, field)
            arr[row] = -1 if field == "topology" else 0
        self._dirty_nodes = True
        self._dirty_ledger = True

    def has_node(self, name: str) -> bool:
        return name in self.table.row_of

    # ---- pod accounting (bound + assumed) ----

    def _apply_pod(self, row: int, req, nz, port_onehot: np.ndarray, sign: int) -> None:
        self.host.requested[row] += sign * req
        self.host.nonzero_requested[row] += sign * nz
        self.host.port_count[row] += sign * port_onehot
        self.table.bump(row)

    def add_pod(self, pod: Pod, node_name: str | None = None, *,
                mirror_only: bool = False) -> bool:
        """Account a pod against its node. Returns False if the node is
        unknown (cache-miss pods are skipped, like the reference cache).

        mirror_only: host-side bookkeeping for a change already present in
        the device ledger (commit_ledger path) — don't mark dirty.
        """
        node_name = node_name or pod.spec.node_name
        row = self.table.row_of.get(node_name)
        if row is None:
            return False
        if pod.key in self._accounted:
            return True  # already accounted (assume then confirm)
        req = pod_requests(pod)
        nz = pod_nonzero_requests(pod)
        onehot = self.table.port_onehot(pod.host_ports())
        self._apply_pod(row, req, nz, onehot, +1)
        self._accounted[pod.key] = (node_name, req, nz, onehot)
        if not mirror_only:
            self._dirty_ledger = True
        return True

    def remove_pod(self, pod_key: str) -> None:
        entry = self._accounted.pop(pod_key, None)
        if entry is None:
            return
        node_name, req, nz, onehot = entry
        row = self.table.row_of.get(node_name)
        if row is None:
            return  # node vanished; its rows were zeroed already
        self._apply_pod(row, req, nz, onehot, -1)
        self._dirty_ledger = True

    def is_accounted(self, pod_key: str) -> bool:
        return pod_key in self._accounted

    def mark_ledger_dirty(self) -> None:
        """Force the next flush() to re-upload the host ledger — used when the
        device-side ledger is known to carry charges the host truth does not
        (e.g. a solver assignment whose binding was rolled back)."""
        self._dirty_ledger = True

    # ---- device mirror ----

    def flush(self) -> ClusterState:
        """Return the device view, re-uploading only what changed. Newly
        interned selector terms / requirements (from pod encoding) refill
        their membership columns first."""
        dirty_membership = apply_pending_refreshes(self.host, self.table)
        if self._device is None or self._dirty_nodes:
            dev = self._put(self.host)
        elif self._dirty_ledger or dirty_membership:
            dev = self._device
            if self._dirty_ledger:
                dev = dev.replace(
                    requested=self._put_arr(self.host.requested),
                    nonzero_requested=self._put_arr(self.host.nonzero_requested),
                    port_count=self._put_arr(self.host.port_count),
                )
            if dirty_membership:
                dev = dev.replace(
                    sel_member=self._put_arr(self.host.sel_member),
                    req_member=self._put_arr(self.host.req_member))
        else:
            return self._device
        self._device = dev
        self._dirty_nodes = False
        self._dirty_ledger = False
        return dev

    def commit_ledger(self, new_requested, new_nonzero, new_port_count,
                      assignments: list[tuple[Pod, str]]) -> None:
        """Adopt the solver's output ledger as the device truth and mirror
        the same assignments into host numpy (no transfer either way)."""
        if self._device is None:
            raise RuntimeError("commit_ledger before flush")
        self._device = self._device.replace(
            requested=new_requested, nonzero_requested=new_nonzero,
            port_count=new_port_count)
        for pod, node_name in assignments:
            self.add_pod(pod, node_name, mirror_only=True)

    def _put(self, state: ClusterState) -> ClusterState:
        if self.mesh is not None:
            from kubernetes_tpu.parallel.mesh import shard_state
            return shard_state(state, self.mesh)
        return jax.tree.map(lambda a: jax.device_put(np.asarray(a)), state)

    def _put_arr(self, arr: np.ndarray):
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            from kubernetes_tpu.parallel.mesh import NODE_AXIS
            return jax.device_put(
                np.asarray(arr), NamedSharding(self.mesh, PartitionSpec(NODE_AXIS)))
        return jax.device_put(np.asarray(arr))
