"""StateDB: host-canonical cluster state incrementally mirrored to device.

The stateful shell around `ClusterState` playing the role of the scheduler
cache (reference plugin/pkg/scheduler/schedulercache/cache.go): it aggregates
node objects + accounted pods (bound and assumed) into the SoA arrays, tracks
dirtiness at field-group granularity (the generation-counter analog,
node_info.go:60), and hands the device a fresh view only when something
actually changed.

Two commit paths keep the hot loop off the PCIe bus:
- `add_pod`/`remove_pod` mutate host numpy and mark the ledger dirty; the next
  `flush()` re-uploads ledger arrays (external writes: pods bound by other
  components, deletions, node changes).
- `commit_result(result, ...)` accepts the solver's *device-resident* full
  output ledger (resources, ports, inter-pod affinity counts, volume and
  attach counts) as the new truth — batch-to-batch chaining never leaves the
  device — while mirroring the same arithmetic into host numpy from the
  batch's pre-encoded rows; host and device stay equal without a transfer.

Assume/forget semantics (cache.go:109 AssumePod, scheduler.go:224 rollback):
the driver accounts an assignment optimistically via either path; a failed
bind calls `remove_pod` which both fixes host numpy and marks the ledger
dirty, forcing re-upload of the corrected truth.
"""

from __future__ import annotations

import numpy as np

import jax

from dataclasses import dataclass

from kubernetes_tpu.api.objects import Node, Pod
from kubernetes_tpu.state.cluster_state import (
    ClusterState,
    NodeTable,
    _fill_node_row,
    apply_pending_refreshes,
    carried_term_row,
    empty_state,
    intern_pod_affinity_terms,
    pod_match_row,
    pod_nonzero_requests,
    pod_requests,
)
from kubernetes_tpu.state.layout import Capacities


@dataclass
class AccountedPod:
    """Removal + refill record for one accounted pod."""

    node_name: str
    requests: np.ndarray
    nonzero: np.ndarray
    port_onehot: np.ndarray
    match_row: np.ndarray    # f32[UQ] at accounting time (refilled on growth)
    carry_row: np.ndarray    # f32[UE] carried-term multiplicities
    namespace: str
    labels: dict
    vol_any_row: np.ndarray | None = None   # f32[UV] conflict-atom counts
    vol_rw_row: np.ndarray | None = None
    att_row: np.ndarray | None = None       # f32[UA] attach atoms


class StateDB:
    def __init__(self, caps: Capacities, mesh=None, volume_ctx=None):
        self.caps = caps
        self.mesh = mesh
        self.volume_ctx = volume_ctx  # VolumeContext for claim resolution
        self.host: ClusterState = empty_state(caps)
        self.table = NodeTable(
            caps, shards=(mesh.size if mesh is not None else 1))
        self._accounted: dict[str, AccountedPod] = {}
        self._dirty_nodes = True    # static node fields changed
        self._dirty_ledger = True   # requested/nonzero/ports changed on host
        self._dirty_affinity = False  # podsel/term counts changed on host only
        self._device: ClusterState | None = None
        # exact ledger rows behind _dirty_ledger/_dirty_affinity: when the
        # set is known and small, flush() scatters just those rows into the
        # device ledger (one batched transfer) instead of re-uploading whole
        # [N, W] arrays; _dirty_rows_all falls back to the full path
        self._dirty_rows: set[int] = set()
        self._dirty_rows_all = False
        self._row_updaters: dict = {}   # (fields, K_padded) -> jitted scatter
        # flush transfer accounting (plain ints mirrored to the obs
        # registry): rows_total counts ledger rows uploaded, transfers_total
        # host->device upload operations, full_total whole-state uploads —
        # the "no full-cluster host materialization on the hot path" figure
        self.flush_rows_total = 0
        self.flush_transfers_total = 0
        self.flush_full_total = 0
        self.flush_bytes_total = 0
        from kubernetes_tpu.obs import REGISTRY
        self._m_rows = REGISTRY.counter(
            "statedb_flush_rows_total",
            "ledger rows uploaded to device by StateDB.flush")
        self._m_transfers = REGISTRY.counter(
            "statedb_flush_transfers_total",
            "host->device transfers issued by StateDB.flush")
        self._m_bytes = REGISTRY.counter(
            "statedb_flush_bytes_total",
            "host->device bytes uploaded by StateDB.flush (the upload "
            "side of the transfer ledger; readback is "
            "device_readback_bytes_total)")

    # ---- node lifecycle ----

    def upsert_node(self, node: Node) -> None:
        row = self.table.assign_row(node.metadata.name)
        _fill_node_row(self.host, self.table, row, node)
        self.table.bump(row)
        self._dirty_nodes = True

    def remove_node(self, name: str) -> None:
        if name not in self.table.row_of:
            return
        row = self.table.release_row(name)
        for key in [k for k, v in self._accounted.items()
                    if v.node_name == name]:
            del self._accounted[key]
        from kubernetes_tpu.state.cluster_state import NODE_AXIS_FIELDS
        for field in NODE_AXIS_FIELDS:
            arr = getattr(self.host, field)
            arr[row] = -1 if field == "topology" else 0
        self._dirty_nodes = True
        self._dirty_ledger = True

    def has_node(self, name: str) -> bool:
        return name in self.table.row_of

    # ---- pod accounting (bound + assumed) ----

    def _apply_pod(self, row: int, acc: AccountedPod, sign: int) -> None:
        self._dirty_rows.add(row)
        self.host.requested[row] += sign * acc.requests
        self.host.nonzero_requested[row] += sign * acc.nonzero
        self.host.port_count[row] += sign * acc.port_onehot
        self.host.podsel_count[row] += sign * acc.match_row
        self.host.term_count[row] += sign * acc.carry_row
        if acc.vol_any_row is not None:
            self.host.vol_any[row] += sign * acc.vol_any_row
            self.host.vol_rw[row] += sign * acc.vol_rw_row
        if acc.att_row is not None:
            self.host.attach_count[row] += sign * acc.att_row
        self.table.bump(row)

    def add_pod(self, pod: Pod, node_name: str | None = None, *,
                mirror_only: bool = False) -> bool:
        """Account a pod against its node. Returns False if the node is
        unknown (cache-miss pods are skipped, like the reference cache).
        Batch commits go through the vectorized `commit_batch` instead.

        mirror_only: host-side bookkeeping for a change already present in
        the device ledger — don't mark dirty.
        """
        node_name = node_name or pod.spec.node_name
        row = self.table.row_of.get(node_name)
        if row is None:
            return False
        if pod.key in self._accounted:
            return True  # already accounted (assume then confirm)
        eids, _ = intern_pod_affinity_terms(self.table, pod)
        vol_any_row = vol_rw_row = att_row = None
        if pod.spec.volumes:
            from kubernetes_tpu.state.volumes import EMPTY_CONTEXT

            vol_any_row, vol_rw_row = self.table.vol_rows(pod)
            att_row = self.table.attach_row(
                pod, self.volume_ctx or EMPTY_CONTEXT, permissive=True)
        acc = AccountedPod(
            node_name=node_name,
            requests=pod_requests(pod),
            nonzero=pod_nonzero_requests(pod),
            port_onehot=self.table.port_onehot(pod.host_ports()),
            match_row=pod_match_row(self.table, pod),
            carry_row=carried_term_row(self.table, eids),
            namespace=pod.metadata.namespace,
            labels=dict(pod.metadata.labels),
            vol_any_row=vol_any_row,
            vol_rw_row=vol_rw_row,
            att_row=att_row,
        )
        self._apply_pod(row, acc, +1)
        self._accounted[pod.key] = acc
        if not mirror_only:
            self._dirty_ledger = True
        return True

    def remove_pod(self, pod_key: str) -> None:
        acc = self._accounted.pop(pod_key, None)
        if acc is None:
            return
        row = self.table.row_of.get(acc.node_name)
        if row is None:
            return  # node vanished; its rows were zeroed already
        self._apply_pod(row, acc, -1)
        self._dirty_ledger = True

    def is_accounted(self, pod_key: str) -> bool:
        return pod_key in self._accounted

    @property
    def ledger_dirty(self) -> bool:
        """True when the next flush() will re-upload ledger/affinity/node
        arrays from host truth — a pipelined driver must settle any
        in-flight batch first, or its device-side charges get overwritten."""
        return (self._dirty_nodes or self._dirty_ledger or self._dirty_affinity
                or bool(self.table.pending_podsel_refresh))

    def adopt_result(self, result) -> None:
        """Chain the solver's (possibly still in-flight) full output ledger
        as the device truth without synchronizing — host mirroring happens
        at settle time via commit_result. Kernels a batch could not touch
        return the input arrays unchanged, so this is alias bookkeeping,
        not data movement."""
        if self._device is None:
            raise RuntimeError("adopt_result before flush")
        self._device = self._device.replace(
            requested=result.new_requested,
            nonzero_requested=result.new_nonzero,
            port_count=result.new_port_count,
            podsel_count=result.new_podsel,
            term_count=result.new_term,
            vol_any=result.new_vol_any,
            vol_rw=result.new_vol_rw,
            attach_count=result.new_attach)

    def mark_ledger_dirty(self) -> None:
        """Force the next flush() to re-upload the host ledger — used when the
        device-side ledger is known to carry charges the host truth does not
        (e.g. a solver assignment whose binding was rolled back). The stale
        device rows are unknown here, so the row-scatter fast path is off."""
        self._dirty_ledger = True
        self._dirty_rows_all = True

    # ---- device mirror ----

    def _refill_podsel(self) -> None:
        """Fill podsel_count columns for selector entries interned after pods
        were accounted (the accounted-pod analog of membership refills)."""
        if not self.table.pending_podsel_refresh:
            return
        from kubernetes_tpu.state.podaffinity import selector_matches

        for qid in self.table.pending_podsel_refresh:
            ns_key, canon = self.table.podsel_attrs[qid]
            for acc in self._accounted.values():
                if acc.match_row[qid]:
                    continue  # accounted after the intern: already counted
                if acc.namespace in ns_key and selector_matches(canon, acc.labels):
                    row = self.table.row_of.get(acc.node_name)
                    if row is not None:
                        self.host.podsel_count[row, qid] += 1.0
                        self._dirty_rows.add(row)
                        acc.match_row[qid] = 1.0
        self.table.pending_podsel_refresh.clear()
        self._dirty_affinity = True

    def _ledger_fields(self) -> tuple[str, ...]:
        """Ledger groups a dirty-ledger/affinity flush must refresh (the
        f32[N, W] arrays pod accounting mutates), in a stable order."""
        names = ["requested", "nonzero_requested", "port_count"]
        if self.table.vol_atoms:
            names += ["vol_any", "vol_rw"]
        if self.table.attach_atoms:
            names.append("attach_count")
        if self.table.podsels:
            names += ["podsel_count", "term_count"]
        return tuple(names)

    def _row_updater(self, fields: tuple[str, ...], k_padded: int):
        """Jitted per-shard row scatter: (device arrays, rows, packed
        values) -> updated arrays, keeping node-sharded layout under a
        mesh. Cached per (field set, padded row count) so steady-state
        flushes never recompile."""
        key = (fields, k_padded)
        fn = self._row_updaters.get(key)
        if fn is None:
            widths = [getattr(self.host, f).shape[1] for f in fields]

            def upd(arrays, rows, packed):
                out = []
                off = 0
                for arr, w in zip(arrays, widths):
                    out.append(arr.at[rows].set(packed[:, off:off + w]))
                    off += w
                return tuple(out)

            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec
                from kubernetes_tpu.parallel.mesh import NODE_AXIS
                nodes = NamedSharding(self.mesh, PartitionSpec(NODE_AXIS))
                repl = NamedSharding(self.mesh, PartitionSpec())
                fn = jax.jit(
                    upd,
                    in_shardings=(tuple(nodes for _ in fields), repl, repl),
                    out_shardings=tuple(nodes for _ in fields))
            else:
                fn = jax.jit(upd)
            self._row_updaters[key] = fn
        return fn

    def _scatter_rows(self, dev: ClusterState, rows: list[int]) -> ClusterState:
        """Coalesce the flush's dirty rows into ONE batched host->device
        transfer: gather every dirty ledger group's rows into a packed
        (K, sum W) matrix, upload it once, and scatter on device (per shard
        under a mesh — GSPMD routes each row update to its owning shard).
        K pads to the next power of two (duplicating row 0's update, which
        re-sets identical values) to bound compile-cache growth."""
        fields = self._ledger_fields()
        k = len(rows)
        kp = 1 << max(0, (k - 1).bit_length())
        idx = np.empty((kp,), np.int32)
        idx[:k] = rows
        idx[k:] = rows[0]
        packed = np.concatenate(
            [getattr(self.host, f)[idx] for f in fields], axis=1)
        fn = self._row_updater(fields, kp)
        new = fn(tuple(getattr(dev, f) for f in fields), idx, packed)
        self.flush_rows_total += k
        self.flush_transfers_total += 1
        self._m_rows.inc(k)
        self._m_transfers.inc()
        self._count_flush_bytes(int(packed.nbytes) + int(idx.nbytes))
        return dev.replace(**dict(zip(fields, new)))

    def flush(self) -> ClusterState:
        """Return the device view, re-uploading only what changed. Newly
        interned selector terms / requirements (from pod encoding) refill
        their membership columns first. Ledger dirtiness with a known,
        small row set takes the coalesced row-scatter path (one batched
        transfer); everything else re-uploads whole arrays."""
        self._refill_podsel()
        dirty_membership = apply_pending_refreshes(self.host, self.table)
        ledger_work = self._dirty_ledger or self._dirty_affinity
        rows = (sorted(self._dirty_rows)
                if ledger_work and not self._dirty_rows_all else None)
        can_scatter = (
            rows is not None and 0 < len(rows)
            and len(rows) * 4 <= self.caps.num_nodes)
        if self._device is None or self._dirty_nodes:
            dev = self._put(self.host)
            self.flush_full_total += 1
            self.flush_rows_total += self.caps.num_nodes
            self.flush_transfers_total += 1
            self._m_rows.inc(self.caps.num_nodes)
            self._m_transfers.inc()
        elif ledger_work or dirty_membership:
            dev = self._device
            if can_scatter and ledger_work:
                dev = self._scatter_rows(dev, rows)
            elif self._dirty_ledger:
                self.flush_full_total += 1
                self.flush_rows_total += self.caps.num_nodes
                self._m_rows.inc(self.caps.num_nodes)
                dev = dev.replace(
                    requested=self._put_arr(self.host.requested),
                    nonzero_requested=self._put_arr(self.host.nonzero_requested),
                    port_count=self._put_arr(self.host.port_count),
                )
                if self.table.vol_atoms:
                    dev = dev.replace(
                        vol_any=self._put_arr(self.host.vol_any),
                        vol_rw=self._put_arr(self.host.vol_rw))
                if self.table.attach_atoms:
                    dev = dev.replace(
                        attach_count=self._put_arr(self.host.attach_count))
            if not (can_scatter and ledger_work) and \
                    (self._dirty_ledger or self._dirty_affinity) and \
                    self.table.podsels:
                dev = dev.replace(
                    podsel_count=self._put_arr(self.host.podsel_count),
                    term_count=self._put_arr(self.host.term_count))
            if dirty_membership:
                dev = dev.replace(
                    sel_member=self._put_arr(self.host.sel_member),
                    req_member=self._put_arr(self.host.req_member),
                    topology=self._put_arr(self.host.topology),
                    volsel_member=self._put_arr(self.host.volsel_member),
                    attach_type=jax.device_put(np.asarray(self.host.attach_type)),
                    term_q=jax.device_put(np.asarray(self.host.term_q)),
                    term_tkey=jax.device_put(np.asarray(self.host.term_tkey)),
                    term_weight=jax.device_put(np.asarray(self.host.term_weight)),
                    term_kind=jax.device_put(np.asarray(self.host.term_kind)),
                    term_poison=jax.device_put(np.asarray(self.host.term_poison)),
                )
        else:
            return self._device
        self._device = dev
        self._dirty_nodes = False
        self._dirty_ledger = False
        self._dirty_affinity = False
        self._dirty_rows.clear()
        self._dirty_rows_all = False
        return dev

    def shard_occupancy(self) -> list[int]:
        """Live node rows per mesh shard (a single-element list without a
        mesh) — the bench[sharded] balance extra. Row addressing interleaves
        assignments across shards (NodeTable), so these stay within one of
        each other until nodes churn."""
        shards = self.mesh.size if self.mesh is not None else 1
        chunk = self.caps.num_nodes // shards
        counts = [0] * shards
        for row in self.table.row_of.values():
            counts[row // chunk] += 1
        return counts

    def commit_batch(self, result, fblob: np.ndarray,
                     committed: list[tuple[Pod, str, int]],
                     replace_device: bool = True,
                     coverage: tuple[bool, bool, bool] = (True, True, True),
                     ) -> None:
        """Adopt the solver's full output ledger as the device truth and
        mirror the same assignments into host numpy straight from the packed
        float blob (every mirrored ledger column is f32) — one vectorized
        scatter-add per ledger group instead of per-pod row arithmetic
        (no transfer either way, no re-matching).

        committed: (pod, node_name, batch_row_index) triples.

        replace_device=False commits the host mirror only — the pipelined
        driver already chained this batch's output via adopt_result() before
        dispatching its successor; re-replacing here would regress the
        device ledger to the older batch's arrays.

        coverage: solver.ledger_coverage(policy, flags) — rows that touch a
        group the compiled program passed through untracked must dirty that
        group for re-upload from host truth."""
        from kubernetes_tpu.state.pod_batch import _layout

        if self._device is None:
            raise RuntimeError("commit_batch before flush")
        if replace_device:
            self.adopt_result(result)
        live = [(pod, node_name, i) for pod, node_name, i in committed
                if pod.key not in self._accounted
                and node_name in self.table.row_of]
        if not live:
            return
        idx = np.fromiter((i for _, _, i in live), np.int64, len(live))
        rows = np.fromiter((self.table.row_of[n] for _, n, _ in live),
                           np.int64, len(live))
        layout, _f, _i = _layout(self.caps)
        gathered = fblob[idx]                       # (K, F) one fancy copy

        def colv(name):
            _blob, off, width, _trailing, _dtype = layout[name]
            return gathered[:, off:off + width]

        req = colv("requests")
        nz = colv("nonzero_requests")
        ports = colv("port_onehot")
        match = colv("pod_matches_q")
        carry = colv("pod_carries_e")
        want_rw = colv("vol_want_rw")
        vol_any = want_rw + colv("vol_want_ro")
        att = colv("att_onehot")

        host = self.host
        from kubernetes_tpu import native

        if native.scatter_add_cols is not None:
            # native path: one row-ordered pass per ledger group straight
            # from the gathered blob — no sort, no segmented reduction
            # (numpy's argsort+reduceat formulation below measured
            # ~17 µs/pod of the ~31 µs/pod commit phase at bench scale)
            def scat(dst, ref):
                _blob, off, width, _t, _d = layout[ref]
                if width == 0:
                    return 0
                return native.scatter_add_cols(dst, gathered, off, rows,
                                               width)

            scat(host.requested, "requests")
            scat(host.nonzero_requested, "nonzero_requests")
            scat(host.port_count, "port_onehot")
            scat(host.podsel_count, "pod_matches_q")
            scat(host.term_count, "pod_carries_e")
            if scat(host.vol_any, "vol_want_rw"):
                scat(host.vol_rw, "vol_want_rw")
            scat(host.vol_any, "vol_want_ro")
            scat(host.attach_count, "att_onehot")
        else:
            # one sort + segmented reduction over the WHOLE packed blob,
            # then per-group slices += at the unique rows — np.add.at is
            # 10-50× slower than reduceat on wide duplicate-heavy scatters
            order = np.argsort(rows, kind="stable")
            rows_sorted = rows[order]
            boundaries = np.flatnonzero(
                np.diff(rows_sorted, prepend=rows_sorted[0] - 1))
            uniq = rows_sorted[boundaries]
            sums = np.add.reduceat(gathered[order], boundaries, axis=0)

            def colsum(ref):
                _blob, off, width, _trailing, _dtype = layout[ref]
                return sums[:, off:off + width]

            host.requested[uniq] += colsum("requests")
            host.nonzero_requested[uniq] += colsum("nonzero_requests")
            host.port_count[uniq] += colsum("port_onehot")
            host.podsel_count[uniq] += colsum("pod_matches_q")
            host.term_count[uniq] += colsum("pod_carries_e")
            if vol_any.any():
                rw_sum = colsum("vol_want_rw")
                host.vol_any[uniq] += rw_sum + colsum("vol_want_ro")
                host.vol_rw[uniq] += rw_sum
            if att.any():
                host.attach_count[uniq] += colsum("att_onehot")
        gen0 = self.table._gen_counter
        self.table.generation[rows] = np.arange(
            gen0 + 1, gen0 + 1 + len(rows))
        self.table._gen_counter = gen0 + len(rows)

        accounted = self._accounted
        for k, (pod, node_name, _i) in enumerate(live):
            # labels shared, not copied: informer-cache objects are
            # read-only by contract, and this loop is on the e2e hot path
            accounted[pod.key] = AccountedPod(
                node_name, req[k], nz[k], ports[k], match[k], carry[k],
                pod.metadata.namespace, pod.metadata.labels,
                vol_any[k], want_rw[k], att[k])

        ipa_cov, vol_cov, attach_cov = coverage
        if not ipa_cov and (match.any() or carry.any()):
            self._dirty_affinity = True
            self._dirty_rows.update(rows.tolist())
        if not vol_cov and vol_any.any():
            self._dirty_ledger = True
            self._dirty_rows.update(rows.tolist())
        if not attach_cov and att.any():
            self._dirty_ledger = True
            self._dirty_rows.update(rows.tolist())

    def _count_flush_bytes(self, nbytes: int) -> None:
        self.flush_bytes_total += nbytes
        self._m_bytes.inc(nbytes)

    def _put(self, state: ClusterState) -> ClusterState:
        host = jax.tree.map(np.asarray, state)
        self._count_flush_bytes(sum(
            int(leaf.nbytes) for leaf in jax.tree_util.tree_leaves(host)))
        if self.mesh is not None:
            from kubernetes_tpu.parallel.mesh import shard_state
            return shard_state(state, self.mesh)
        # ONE batched transfer for the whole pytree — per-leaf puts pay a
        # per-call round trip each on remote-device transports
        return jax.device_put(host)

    def _put_arr(self, arr: np.ndarray):
        self.flush_transfers_total += 1
        self._m_transfers.inc()
        self._count_flush_bytes(int(np.asarray(arr).nbytes))
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            from kubernetes_tpu.parallel.mesh import NODE_AXIS
            return jax.device_put(
                np.asarray(arr), NamedSharding(self.mesh, PartitionSpec(NODE_AXIS)))
        return jax.device_put(np.asarray(arr))
