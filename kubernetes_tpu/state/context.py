"""EncodeContext: lister access the pod/node encoders need.

The analog of the reference's PluginFactoryArgs (factory/plugins.go): the
predicate/priority factories receive PVInfo/PVCInfo and the Service/RC/RS/
StatefulSet listers; here one context object carries the same lookups into
encoding. Every field has an empty default so fixture paths work without a
store; the driver builds a store-backed instance.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable


def _none(*_a, **_k):
    return None


def _empty(*_a, **_k):
    return []


@dataclass
class EncodeContext:
    # ---- volume resolution (PVInfo/PVCInfo) ----
    get_pvc: Callable = _none          # (namespace, name) -> PVC | None
    get_pv: Callable = _none           # (name) -> PV | None
    # feature gate for NoVolumeNodeConflict (PersistentLocalVolumes,
    # pkg/features/kube_features.go — alpha, default off)
    local_volumes_enabled: bool = False

    # ---- workload listers (SelectorSpread / ServiceAffinity) ----
    get_services: Callable = _empty    # (namespace) -> [Service]
    get_rcs: Callable = _empty         # (namespace) -> [ReplicationController]
    get_rss: Callable = _empty         # (namespace) -> [ReplicaSet]
    get_sss: Callable = _empty         # (namespace) -> [StatefulSet]
    list_pods: Callable = _empty       # (namespace) -> [Pod]
    get_node: Callable = _none         # (name) -> Node | None

    # ServiceAffinity predicate labels from the policy (predicates.go:793);
    # the per-pod affinity terms are only computed when this is set.
    service_affinity_labels: tuple = ()
    # True when a ServiceAntiAffinity priority is configured: per-pod service
    # totals depend on the live pod list, so rows must not be cached.
    service_anti: bool = False


EMPTY_CONTEXT = EncodeContext()
