"""Pod-encoding equivalence cache.

The reference dedups predicate work across pods from the same controller via
an equivalence-class hash (core/equivalence_cache.go:55: pods with identical
scheduling-relevant specs share cached fit results). Here the expensive
per-pod work is *encoding* (quantity parsing + FNV hashing of
selectors/tolerations/ports); pods with identical scheduling-relevant specs
share one encoded row, copied into the batch by array assignment.

The fingerprint covers exactly the fields the encoder reads — requests,
host ports, nodeSelector, tolerations, nodeName, QoS class, namespace +
labels (pod-affinity matching), affinity terms, and volumes. Pods with
claim-backed volumes bypass the cache: their encoding depends on PVC/PV
objects that can change between batches (a bind event re-resolving a claim
must not be served a stale row). LRU-bounded.
"""

from __future__ import annotations

import json
from collections import OrderedDict

import numpy as np

from kubernetes_tpu.api.objects import Pod
from kubernetes_tpu.state.cluster_state import NodeTable
from kubernetes_tpu.state.layout import Capacities
from kubernetes_tpu.state.pod_batch import PodBatch, empty_batch, encode_pod_into

# PodBatch fields with a per-pod row (everything in the pytree)
_FIELDS = tuple(PodBatch.__dataclass_fields__)


def cacheable(pod: Pod) -> bool:
    """Claim-backed volumes resolve through mutable PVC/PV state — never
    cache those rows (and synthetic missing-claim atoms are per-pod-uid,
    so they could not be shared anyway)."""
    return not any("persistentVolumeClaim" in v for v in pod.spec.volumes)


def pod_fingerprint(pod: Pod) -> tuple:
    """Hashable equivalence class of everything the encoder reads."""
    spec = pod.spec
    return (
        tuple(
            (tuple(sorted(c.requests.items())),
             tuple(p.host_port for p in c.ports if p.host_port),
             bool(c.requests or c.limits))
            for c in spec.containers
        ),
        tuple(sorted(spec.node_selector.items())),
        tuple((t.key, t.operator, t.value, t.effect) for t in spec.tolerations),
        spec.node_name,
        # the preemption pass reads the resolved priority column
        spec.priority,
        # pod-affinity matching reads namespace + labels (pod_match_row)
        pod.metadata.namespace,
        tuple(sorted(pod.metadata.labels.items())),
        # image locality reads container images; prefer-avoid the controller
        tuple(c.image for c in spec.containers),
        _controller_ref(pod),
        # affinity + direct volumes as canonical JSON
        json.dumps(spec.affinity, sort_keys=True) if spec.affinity else "",
        json.dumps(spec.volumes, sort_keys=True) if spec.volumes else "",
    )


def _controller_ref(pod: Pod):
    for ref in pod.metadata.owner_references:
        if ref.get("controller"):
            return (ref.get("kind", ""), ref.get("uid", ""))
    return None


class EncodeCache:
    def __init__(self, caps: Capacities, table: NodeTable, max_entries: int = 4096,
                 volume_ctx=None):
        self.caps = caps
        self.table = table
        self.volume_ctx = volume_ctx
        self.max_entries = max_entries
        self._rows: OrderedDict[tuple, tuple[np.ndarray, ...]] = OrderedDict()
        self._packed: OrderedDict[tuple, tuple[np.ndarray, np.ndarray]] = OrderedDict()
        # pod key -> (epoch, generation, shared packed row): premade rows
        # point at the SAME arrays as _packed (no copies), so the map costs
        # one small tuple per pending pod
        self._by_key: OrderedDict[str, tuple] = OrderedDict()
        self.max_premade = 1 << 16
        self._scratch = empty_batch(caps)
        self.hits = 0
        self.misses = 0

    # bumped by the driver on Service/RC/RS/StatefulSet events: spreading
    # entries in cached rows depend on the workload objects
    generation = 0

    def _must_reencode(self, pod: Pod) -> bool:
        # claim-backed volumes resolve through mutable PVC/PV state, and
        # ServiceAffinity terms / ServiceAntiAffinity totals depend on
        # other pods' placements — all must re-encode every batch
        return not cacheable(pod) or (
            self.volume_ctx is not None
            and bool(self.volume_ctx.service_affinity_labels
                     or self.volume_ctx.service_anti))

    def encode_into(self, batch: PodBatch, i: int, pod: Pod) -> None:
        if self._must_reencode(pod):
            encode_pod_into(batch, i, pod, self.caps, self.table,
                            ctx=self.volume_ctx)
            return
        fp = (pod_fingerprint(pod), self.table.pod_row_epoch, self.generation)
        row = self._rows.get(fp)
        if row is None:
            self.misses += 1
            encode_pod_into(self._scratch, 0, pod, self.caps, self.table,
                            ctx=self.volume_ctx)
            row = tuple(np.copy(getattr(self._scratch, f)[0]) for f in _FIELDS)
            self._rows[fp] = row
            if len(self._rows) > self.max_entries:
                self._rows.popitem(last=False)
        else:
            self.hits += 1
            self._rows.move_to_end(fp)
        for f, val in zip(_FIELDS, row):
            getattr(batch, f)[i] = val

    def _packed_row(self, pod: Pod) -> tuple[np.ndarray, np.ndarray]:
        """The shared packed row for this pod's equivalence class (encoding
        it on first sight)."""
        from kubernetes_tpu.state.pod_batch import pack_row

        fp = (pod_fingerprint(pod), self.table.pod_row_epoch, self.generation)
        packed = self._packed.get(fp)
        if packed is None:
            self.misses += 1
            encode_pod_into(self._scratch, 0, pod, self.caps, self.table,
                            ctx=self.volume_ctx)
            packed = pack_row(self._scratch, 0, self.caps)
            self._packed[fp] = packed
            if len(self._packed) > self.max_entries:
                self._packed.popitem(last=False)
        else:
            self.hits += 1
            self._packed.move_to_end(fp)
        return packed

    def premake(self, pod: Pod) -> None:
        """Encode-on-watch: fingerprint + encode at informer-event time —
        which overlaps the previous batch's device solve and transport
        waits — and pin the class's shared packed row under the pod's key,
        so batch assembly on the critical path is one dict hit plus two row
        memcpys (~1.5 us/pod) instead of a ~10 us fingerprint+lookup. The
        epoch/generation stamp is validated at use; a stale entry just
        falls back to the fingerprint path."""
        if self._must_reencode(pod):
            # the pod may have MOVED into the non-cacheable class (e.g. a
            # claim-backed volume added): a premade row from its cacheable
            # past must not be served
            self.forget(pod.key)
            return
        self._by_key[pod.key] = (self.table.pod_row_epoch, self.generation,
                                 self._packed_row(pod))
        if len(self._by_key) > self.max_premade:
            self._by_key.popitem(last=False)

    def forget(self, key: str) -> None:
        """Drop a premade row (pod bound or deleted)."""
        self._by_key.pop(key, None)

    def encode_packed_into(self, fblob: np.ndarray, iblob: np.ndarray,
                           i: int, pod: Pod) -> None:
        """Encode one pod directly into packed blob row i: a premade hit is
        two row memcpys; a class hit is a fingerprint + two memcpys (vs ~45
        per-field assignments), which is what makes host encoding ~µs/pod
        under sustained template load."""
        pre = self._by_key.get(pod.key)
        if pre is not None and pre[0] == self.table.pod_row_epoch \
                and pre[1] == self.generation:
            fblob[i], iblob[i] = pre[2]
            self.hits += 1
            return
        if self._must_reencode(pod):
            from kubernetes_tpu.state.pod_batch import pack_row

            encode_pod_into(self._scratch, 0, pod, self.caps, self.table,
                            ctx=self.volume_ctx)
            frow, irow = pack_row(self._scratch, 0, self.caps)
            fblob[i], iblob[i] = frow, irow
            return
        packed = self._packed_row(pod)
        fblob[i] = packed[0]
        iblob[i] = packed[1]
