"""Pending-pod batch encoding: P pods -> padded arrays for one solver call.

The reference schedules one pod at a time (scheduler.go:253 scheduleOne); here
a whole batch of pending pods is encoded as a padded (P, ...) pytree and
scheduled in one device program. Padding rows have valid=False and are ignored
by the solver.

Selector terms and host ports are interned into the cluster's universes
(cluster_state.NodeTable), producing one-hot rows that pair with the node
membership matrices for MXU matching. Encoding a pod can therefore grow the
universes — callers holding a device state must apply pending membership
refreshes (cluster_state.apply_pending_refreshes / StateDB.flush) before
scheduling the batch.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np
from flax import struct

from kubernetes_tpu.api.objects import Pod
from kubernetes_tpu.state.cluster_state import (
    ClusterState,
    NodeTable,
    apply_pending_refreshes,
    pod_nonzero_requests,
    pod_requests,
)
from kubernetes_tpu.state.layout import Capacities, CapacityError, Effect, Resource, TolOp
from kubernetes_tpu.utils.hashing import hash32, hash_lanes


@struct.dataclass
class PodBatch:
    valid: np.ndarray           # bool[P]
    requests: np.ndarray        # f32[P, R]
    nonzero_requests: np.ndarray  # f32[P, 2] (cpu, mem) scoring requests
    port_onehot: np.ndarray     # f32[P, UP] — interned host-port counts
    sel_onehot: np.ndarray      # f32[P, US] — required selector terms
    sel_count: np.ndarray       # f32[P] — number of required terms
    tol_key: np.ndarray         # u32[P, T] hash32(key), 0 = empty key (matches all)
    tol_val_lo: np.ndarray      # u32[P, T] hash lanes of the toleration *value*
    tol_val_hi: np.ndarray      # u32[P, T]
    tol_op: np.ndarray          # i32[P, T] TolOp codes, NONE = unused slot
    tol_effect: np.ndarray      # i32[P, T] Effect codes, NONE = all effects
    node_name_lo: np.ndarray    # u32[P] spec.nodeName hash lanes, 0 = unset
    node_name_hi: np.ndarray    # u32[P]
    best_effort: np.ndarray     # bool[P] BestEffort QoS (pressure-check exemption)

    @property
    def batch_pods(self) -> int:
        return self.valid.shape[0]


def empty_batch(caps: Capacities) -> PodBatch:
    p = caps.batch_pods
    return PodBatch(
        valid=np.zeros((p,), np.bool_),
        requests=np.zeros((p, Resource.COUNT), np.float32),
        nonzero_requests=np.zeros((p, 2), np.float32),
        port_onehot=np.zeros((p, caps.port_universe), np.float32),
        sel_onehot=np.zeros((p, caps.selector_universe), np.float32),
        sel_count=np.zeros((p,), np.float32),
        tol_key=np.zeros((p, caps.toleration_slots), np.uint32),
        tol_val_lo=np.zeros((p, caps.toleration_slots), np.uint32),
        tol_val_hi=np.zeros((p, caps.toleration_slots), np.uint32),
        tol_op=np.zeros((p, caps.toleration_slots), np.int32),
        tol_effect=np.zeros((p, caps.toleration_slots), np.int32),
        node_name_lo=np.zeros((p,), np.uint32),
        node_name_hi=np.zeros((p,), np.uint32),
        best_effort=np.zeros((p,), np.bool_),
    )


def encode_pod_into(batch: PodBatch, i: int, pod: Pod, caps: Capacities,
                    table: NodeTable) -> None:
    batch.valid[i] = True
    batch.requests[i] = pod_requests(pod)
    batch.nonzero_requests[i] = pod_nonzero_requests(pod)
    batch.port_onehot[i] = table.port_onehot(pod.host_ports())

    batch.sel_onehot[i] = 0.0
    selector = pod.spec.node_selector
    for k, v in selector.items():
        batch.sel_onehot[i, table.intern_sel_term(k, v)] = 1.0
    batch.sel_count[i] = float(len(selector))

    tols = pod.spec.tolerations
    if len(tols) > caps.toleration_slots:
        raise CapacityError(f"pod {pod.key}: {len(tols)} tolerations > "
                            f"{caps.toleration_slots} slots")
    batch.tol_key[i] = 0
    batch.tol_val_lo[i] = 0
    batch.tol_val_hi[i] = 0
    batch.tol_op[i] = TolOp.NONE
    batch.tol_effect[i] = Effect.NONE
    for t, tol in enumerate(tols):
        batch.tol_key[i, t] = hash32(tol.key) if tol.key else 0
        val_lo, val_hi = hash_lanes(tol.value)
        batch.tol_val_lo[i, t] = val_lo
        batch.tol_val_hi[i, t] = val_hi
        batch.tol_op[i, t] = TolOp.EXISTS if tol.operator == "Exists" else TolOp.EQUAL
        batch.tol_effect[i, t] = Effect.NAMES.get(tol.effect, Effect.NONE)

    if pod.spec.node_name:
        lo, hi = hash_lanes(pod.spec.node_name)
        batch.node_name_lo[i] = lo
        batch.node_name_hi[i] = hi
    else:
        batch.node_name_lo[i] = 0
        batch.node_name_hi[i] = 0
    batch.best_effort[i] = pod.is_best_effort()


def encode_pods(pods: Sequence[Pod], caps: Capacities, table: NodeTable,
                state: ClusterState | None = None) -> PodBatch:
    """Encode a batch against the cluster's universes. When `state` is given,
    membership columns for newly interned terms are refilled in place."""
    if len(pods) > caps.batch_pods:
        raise CapacityError(f"{len(pods)} pods > batch capacity {caps.batch_pods}")
    batch = empty_batch(caps)
    for i, pod in enumerate(pods):
        encode_pod_into(batch, i, pod, caps, table)
    if state is not None:
        apply_pending_refreshes(state, table)
    return batch


def encode_cluster(nodes, pods, caps: Capacities):
    """One-shot fixture encoding: nodes + pending pods with a shared
    universe, membership fully consistent. Returns (state, batch, table)."""
    from kubernetes_tpu.state.cluster_state import encode_nodes

    table = NodeTable(caps)
    batch = encode_pods(pods, caps, table)
    state, _ = encode_nodes(nodes, caps, table=table)
    apply_pending_refreshes(state, table)
    return state, batch, table
