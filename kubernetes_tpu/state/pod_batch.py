"""Pending-pod batch encoding: P pods -> padded arrays for one solver call.

The reference schedules one pod at a time (scheduler.go:253 scheduleOne); here
a whole batch of pending pods is encoded as a padded (P, ...) pytree and
scheduled in one device program. Padding rows have valid=False and are ignored
by the solver.

Selector terms and host ports are interned into the cluster's universes
(cluster_state.NodeTable), producing one-hot rows that pair with the node
membership matrices for MXU matching. Encoding a pod can therefore grow the
universes — callers holding a device state must apply pending membership
refreshes (cluster_state.apply_pending_refreshes / StateDB.flush) before
scheduling the batch.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np
from flax import struct

from kubernetes_tpu.api.objects import Pod
from kubernetes_tpu.state.cluster_state import (
    ClusterState,
    NodeTable,
    apply_pending_refreshes,
    carried_term_row,
    intern_pod_affinity_terms,
    pod_match_row,
    pod_nonzero_requests,
    pod_requests,
)
from kubernetes_tpu.state.layout import Capacities, CapacityError, Effect, Resource, TolOp
from kubernetes_tpu.utils.hashing import hash32, hash_lanes, hash_lanes_many


@struct.dataclass
class PodBatch:
    valid: np.ndarray           # bool[P]
    requests: np.ndarray        # f32[P, R]
    nonzero_requests: np.ndarray  # f32[P, 2] (cpu, mem) scoring requests
    port_onehot: np.ndarray     # f32[P, UP] — interned host-port counts
    sel_onehot: np.ndarray      # f32[P, US] — required selector terms
    sel_count: np.ndarray       # f32[P] — number of required terms
    tol_key: np.ndarray         # u32[P, T] hash32(key), 0 = empty key (matches all)
    tol_val_lo: np.ndarray      # u32[P, T] hash lanes of the toleration *value*
    tol_val_hi: np.ndarray      # u32[P, T]
    tol_op: np.ndarray          # i32[P, T] TolOp codes, NONE = unused slot
    tol_effect: np.ndarray      # i32[P, T] Effect codes, NONE = all effects
    node_name_lo: np.ndarray    # u32[P] spec.nodeName hash lanes, 0 = unset
    node_name_hi: np.ndarray    # u32[P]
    best_effort: np.ndarray     # bool[P] BestEffort QoS (pressure-check exemption)
    # required node affinity: OR over terms, each term an AND over interned
    # requirements (one-hot into the requirement universe UR)
    naff_has: np.ndarray        # bool[P] — pod carries a required NodeSelector
    naff_onehot: np.ndarray     # f32[P, AT, UR]
    naff_count: np.ndarray      # f32[P, AT] — requirements in term t
    naff_ok: np.ndarray         # bool[P, AT] — term is live (non-empty, parsed)
    # preferred node affinity terms (NodeAffinityPriority)
    pref_onehot: np.ndarray     # f32[P, TP, UR]
    pref_count: np.ndarray      # f32[P, TP]
    pref_weight: np.ndarray     # f32[P, TP] — 0 for unused/invalid slots
    # inter-pod affinity (state/podaffinity.py; ops/interpod.py)
    pod_matches_q: np.ndarray   # f32[P, UQ] — pod matches selector entry q
    pod_carries_e: np.ndarray   # f32[P, UE] — carried-term multiplicities
    paff_q: np.ndarray          # i32[P, IA] required affinity: selector id, -1 unused
    paff_tkey: np.ndarray       # i32[P, IA] topo slot (TKEY_INVALID impossible
                                #            here — encoded via ipaff_fail)
    panti_q: np.ndarray         # i32[P, IA] required anti-affinity
    panti_tkey: np.ndarray      # i32[P, IA]
    ipaff_fail: np.ndarray      # bool[P] — a required term is unschedulable
                                #           (empty topologyKey / bad selector)
    ppref_q: np.ndarray         # i32[P, IP] preferred terms, -1 unused
    ppref_tkey: np.ndarray      # i32[P, IP] slot or TKEY_DEFAULT_UNION
    ppref_w: np.ndarray         # f32[P, IP] signed weight (anti negative)
    # volumes (state/volumes.py atom grammars)
    vol_want_rw: np.ndarray     # f32[P, UV] conflict atoms wanted read-write
    vol_want_ro: np.ndarray     # f32[P, UV] conflict atoms wanted read-only
    att_onehot: np.ndarray      # f32[P, UA] attach atoms (0/1, unique per pod)
    att_fail: np.ndarray        # bool[P] MaxPDVolumeCount resolution error
    vz_onehot: np.ndarray       # f32[P, US] zone/region selector terms from PVs
    vz_count: np.ndarray        # f32[P]
    vz_fail: np.ndarray         # bool[P] VolumeZone resolution error
    vs_onehot: np.ndarray       # f32[P, UVS] PV node-affinity selectors
    vs_count: np.ndarray        # f32[P]
    vs_fail: np.ndarray         # bool[P] VolumeNode resolution error
    # spreading / service state (state/spreading.py)
    spread_q: np.ndarray        # i32[P] controller-selector union entry, -1 none
    spread_svc_q: np.ndarray    # i32[P] services-only union (ServiceSpreading)
    svcanti_q: np.ndarray       # i32[P] first-service selector entry, -1 none
    svcanti_total: np.ndarray   # f32[P] matching same-namespace pods anywhere
    svcaff_onehot: np.ndarray   # f32[P, UR] ServiceAffinity requirement terms
    svcaff_count: np.ndarray    # f32[P]
    svcaff_fail: np.ndarray     # bool[P] backfill pod unbound: hard error
    # image locality / prefer-avoid
    img_onehot: np.ndarray      # f32[P, UI] container-image multiplicities
    avoid_onehot: np.ndarray    # f32[P, UO] controllerRef signature, if interned
    # gang scheduling (all-or-nothing groups; ops/solver.py group revert).
    # gang_id is a batch-local group index, 0 = not a gang member (zeroed
    # padding rows are therefore automatically non-gang). Members of one
    # group MUST be contiguous in the batch — the scan's revert window is a
    # contiguous run; the driver never splits a group across batches.
    gang_id: np.ndarray         # i32[P] batch-local group index, 0 = none
    gang_min: np.ndarray        # i32[P] group minMember quorum (0 when no gang)
    # pod priority (spec.priority, admission-resolved from the
    # PriorityClass); read by the preemption pass — a pod may only evict
    # victims of strictly lower priority
    priority: np.ndarray        # i32[P]

    @property
    def batch_pods(self) -> int:
        return self.valid.shape[0]


def empty_batch(caps: Capacities) -> PodBatch:
    p = caps.batch_pods
    return PodBatch(
        valid=np.zeros((p,), np.bool_),
        requests=np.zeros((p, Resource.COUNT), np.float32),
        nonzero_requests=np.zeros((p, 2), np.float32),
        port_onehot=np.zeros((p, caps.port_universe), np.float32),
        sel_onehot=np.zeros((p, caps.selector_universe), np.float32),
        sel_count=np.zeros((p,), np.float32),
        tol_key=np.zeros((p, caps.toleration_slots), np.uint32),
        tol_val_lo=np.zeros((p, caps.toleration_slots), np.uint32),
        tol_val_hi=np.zeros((p, caps.toleration_slots), np.uint32),
        tol_op=np.zeros((p, caps.toleration_slots), np.int32),
        tol_effect=np.zeros((p, caps.toleration_slots), np.int32),
        node_name_lo=np.zeros((p,), np.uint32),
        node_name_hi=np.zeros((p,), np.uint32),
        best_effort=np.zeros((p,), np.bool_),
        naff_has=np.zeros((p,), np.bool_),
        naff_onehot=np.zeros((p, caps.affinity_terms, caps.req_universe), np.float32),
        naff_count=np.zeros((p, caps.affinity_terms), np.float32),
        naff_ok=np.zeros((p, caps.affinity_terms), np.bool_),
        pref_onehot=np.zeros((p, caps.pref_terms, caps.req_universe), np.float32),
        pref_count=np.zeros((p, caps.pref_terms), np.float32),
        pref_weight=np.zeros((p, caps.pref_terms), np.float32),
        pod_matches_q=np.zeros((p, caps.podsel_universe), np.float32),
        pod_carries_e=np.zeros((p, caps.term_universe), np.float32),
        paff_q=np.full((p, caps.interpod_slots), -1, np.int32),
        paff_tkey=np.zeros((p, caps.interpod_slots), np.int32),
        panti_q=np.full((p, caps.interpod_slots), -1, np.int32),
        panti_tkey=np.zeros((p, caps.interpod_slots), np.int32),
        ipaff_fail=np.zeros((p,), np.bool_),
        ppref_q=np.full((p, caps.interpod_pref_slots), -1, np.int32),
        ppref_tkey=np.zeros((p, caps.interpod_pref_slots), np.int32),
        ppref_w=np.zeros((p, caps.interpod_pref_slots), np.float32),
        vol_want_rw=np.zeros((p, caps.volume_universe), np.float32),
        vol_want_ro=np.zeros((p, caps.volume_universe), np.float32),
        att_onehot=np.zeros((p, caps.attach_universe), np.float32),
        att_fail=np.zeros((p,), np.bool_),
        vz_onehot=np.zeros((p, caps.selector_universe), np.float32),
        vz_count=np.zeros((p,), np.float32),
        vz_fail=np.zeros((p,), np.bool_),
        vs_onehot=np.zeros((p, caps.volsel_universe), np.float32),
        vs_count=np.zeros((p,), np.float32),
        vs_fail=np.zeros((p,), np.bool_),
        spread_q=np.full((p,), -1, np.int32),
        spread_svc_q=np.full((p,), -1, np.int32),
        svcanti_q=np.full((p,), -1, np.int32),
        svcanti_total=np.zeros((p,), np.float32),
        svcaff_onehot=np.zeros((p, caps.req_universe), np.float32),
        svcaff_count=np.zeros((p,), np.float32),
        svcaff_fail=np.zeros((p,), np.bool_),
        img_onehot=np.zeros((p, caps.image_universe), np.float32),
        avoid_onehot=np.zeros((p, caps.avoid_universe), np.float32),
        gang_id=np.zeros((p,), np.int32),
        gang_min=np.zeros((p,), np.int32),
        priority=np.zeros((p,), np.int32),
    )


def _batch_layout(caps: Capacities):
    """Column layout for blob transport: field -> (blob, offset, width,
    trailing_shape, dtype). Uploading a batch as ~45 small arrays pays ~45
    per-transfer latencies on remote-device transports; two contiguous blobs
    (one f32, one i32 that also carries u32 bitcast and bools) pay two."""
    proto = empty_batch(caps)
    layout = {}
    offsets = {"f": 0, "i": 0}
    for name in PodBatch.__dataclass_fields__:
        arr = getattr(proto, name)
        trailing = arr.shape[1:]
        width = int(np.prod(trailing)) if trailing else 1
        blob = "f" if arr.dtype == np.float32 else "i"
        layout[name] = (blob, offsets[blob], width, trailing, arr.dtype)
        offsets[blob] += width
    return layout, offsets["f"], offsets["i"]


_LAYOUTS: dict = {}


def _layout(caps: Capacities):
    lay = _LAYOUTS.get(caps)
    if lay is None:
        lay = _LAYOUTS[caps] = _batch_layout(caps)
    return lay


def pack_batch(batch: PodBatch, caps: Capacities,
               out: tuple[np.ndarray, np.ndarray] | None = None):
    """Host-side: pack a numpy PodBatch into (f32[P, F], i32[P, I]) blobs.
    Pass `out` to reuse transfer buffers across batches."""
    layout, f_width, i_width = _layout(caps)
    p = batch.batch_pods
    if out is None:
        out = (np.empty((p, f_width), np.float32),
               np.empty((p, i_width), np.int32))
    fblob, iblob = out
    for name, (blob, off, width, _trailing, dtype) in layout.items():
        arr = getattr(batch, name)
        flat = arr.reshape(p, width)
        if blob == "f":
            fblob[:, off:off + width] = flat
        elif dtype == np.uint32:
            iblob[:, off:off + width] = flat.view(np.int32)
        else:
            iblob[:, off:off + width] = flat
    return fblob, iblob


def pack_row(batch: PodBatch, i: int, caps: Capacities):
    """Pack one encoded batch row into (f32[F], i32[I]) row vectors — the
    unit the EncodeCache stores, so a cache hit is two memcpys instead of
    ~45 per-field assignments."""
    layout, f_width, i_width = _layout(caps)
    frow = np.empty((f_width,), np.float32)
    irow = np.empty((i_width,), np.int32)
    for name, (blob, off, width, _trailing, dtype) in layout.items():
        flat = getattr(batch, name)[i].reshape(width)
        if blob == "f":
            frow[off:off + width] = flat
        elif dtype == np.uint32:
            irow[off:off + width] = flat.view(np.int32)
        else:
            irow[off:off + width] = flat
    return frow, irow


def blob_col(fblob, iblob, name: str, caps: Capacities, n: int | None = None):
    """Host-side view of one field's packed columns: [P(, W)] in storage
    dtype (u32 fields arrive bitcast as i32, bools as i32 0/1)."""
    layout, _f, _i = _layout(caps)
    blob, off, width, trailing, _dtype = layout[name]
    src = fblob if blob == "f" else iblob
    rows = src if n is None else src[:n]
    col = rows[:, off:off + width]
    return col.reshape((col.shape[0], *trailing)) if trailing else col[:, 0]


def packed_batch_flags(fblob, iblob, n: int, table, caps: Capacities):
    """BatchFlags from packed blobs (ops.solver.batch_flags equivalent for
    the blob-encoding driver path)."""
    from kubernetes_tpu.ops.solver import BatchFlags

    def any_(name):
        return bool(np.asarray(blob_col(fblob, iblob, name, caps, n)).any())

    def any_id(name):  # i32 id columns, -1 = unused
        return bool((np.asarray(blob_col(fblob, iblob, name, caps, n)) >= 0).any())

    from kubernetes_tpu.ops.solver import table_has_prefer_taints

    requests = np.asarray(blob_col(fblob, iblob, "requests", caps, n))
    return BatchFlags(
        ipa=bool(table.terms) or any_id("paff_q") or any_id("panti_q")
        or any_id("ppref_q") or any_("ipaff_fail"),
        spread=any_id("spread_q") or any_id("spread_svc_q"),
        svcanti=any_id("svcanti_q"),
        vol=any_("vol_want_rw") or any_("vol_want_ro"),
        attach=any_("att_onehot") or any_("att_fail"),
        tt=table_has_prefer_taints(table),
        na=bool((np.asarray(blob_col(fblob, iblob, "pref_weight", caps, n))
                 > 0).any()),
        ports=any_("port_onehot"),
        gpu=bool(requests[:, Resource.GPU].any()),
        storage=bool(requests[:, Resource.SCRATCH].any()
                     or requests[:, Resource.OVERLAY].any()),
        gang=bool((np.asarray(blob_col(fblob, iblob, "gang_id", caps, n))
                   > 0).any()),
        # absent (all-zero) priorities can never out-rank anything: the
        # preemption pass is provably neutral, so skip compiling it
        preempt=bool((np.asarray(blob_col(fblob, iblob, "priority", caps, n))
                      != 0).any()),
    )


def unpack_batch(fblob, iblob, caps: Capacities) -> PodBatch:
    """Device-side (jit-traceable): rebuild the PodBatch pytree by slicing
    the blobs — pure views for XLA, no data movement."""
    import jax.numpy as jnp
    from jax import lax

    layout, _f, _i = _layout(caps)
    p = fblob.shape[0]
    fields = {}
    for name, (blob, off, width, trailing, dtype) in layout.items():
        src = fblob if blob == "f" else iblob
        col = src[:, off:off + width].reshape((p, *trailing))
        if dtype == np.uint32:
            col = lax.bitcast_convert_type(col, jnp.uint32)
        elif dtype == np.bool_:
            col = col != 0
        fields[name] = col
    return PodBatch(**fields)


def encode_pod_into(batch: PodBatch, i: int, pod: Pod, caps: Capacities,
                    table: NodeTable, ctx=None) -> None:
    batch.valid[i] = True
    batch.requests[i] = pod_requests(pod)
    batch.nonzero_requests[i] = pod_nonzero_requests(pod)
    batch.port_onehot[i] = table.port_onehot(pod.host_ports())

    batch.sel_onehot[i] = 0.0
    selector = pod.spec.node_selector
    for k, v in selector.items():
        batch.sel_onehot[i, table.intern_sel_term(k, v)] = 1.0
    batch.sel_count[i] = float(len(selector))

    tols = pod.spec.tolerations
    if len(tols) > caps.toleration_slots:
        raise CapacityError(f"pod {pod.key}: {len(tols)} tolerations > "
                            f"{caps.toleration_slots} slots")
    batch.tol_key[i] = 0
    batch.tol_val_lo[i] = 0
    batch.tol_val_hi[i] = 0
    batch.tol_op[i] = TolOp.NONE
    batch.tol_effect[i] = Effect.NONE
    # one native batch call hashes every toleration value (hash_lanes_many)
    value_lanes = hash_lanes_many([tol.value for tol in tols])
    for t, tol in enumerate(tols):
        batch.tol_key[i, t] = hash32(tol.key) if tol.key else 0
        batch.tol_val_lo[i, t], batch.tol_val_hi[i, t] = value_lanes[t]
        batch.tol_op[i, t] = TolOp.EXISTS if tol.operator == "Exists" else TolOp.EQUAL
        batch.tol_effect[i, t] = Effect.NAMES.get(tol.effect, Effect.NONE)

    if pod.spec.node_name:
        lo, hi = hash_lanes(pod.spec.node_name)
        batch.node_name_lo[i] = lo
        batch.node_name_hi[i] = hi
    else:
        batch.node_name_lo[i] = 0
        batch.node_name_hi[i] = 0
    batch.best_effort[i] = pod.is_best_effort()
    batch.priority[i] = pod.spec.priority
    _encode_node_affinity(batch, i, pod, caps, table)
    _encode_interpod_affinity(batch, i, pod, caps, table)
    _encode_volumes(batch, i, pod, caps, table, ctx)
    _encode_workloads(batch, i, pod, caps, table, ctx)


def _encode_workloads(batch: PodBatch, i: int, pod: Pod, caps: Capacities,
                      table: NodeTable, ctx) -> None:
    """Spreading entries, service (anti-)affinity, image locality and
    prefer-avoid rows (state/spreading.py, state/volumes.py)."""
    from kubernetes_tpu.state.context import EMPTY_CONTEXT
    from kubernetes_tpu.state.layout import ReqOp
    from kubernetes_tpu.state.spreading import (
        first_service_entry,
        service_affinity_terms,
        spread_entry,
    )
    from kubernetes_tpu.state.volumes import pod_controller_ref

    ctx = ctx or EMPTY_CONTEXT
    batch.spread_q[i] = spread_entry(pod, ctx, table)
    batch.spread_svc_q[i] = spread_entry(pod, ctx, table, services_only=True)
    batch.svcanti_q[i], batch.svcanti_total[i] = \
        first_service_entry(pod, ctx, table)
    # these entries were interned AFTER pod_matches_q was filled; the pod
    # matches its own union/service entries by construction (they are built
    # from selectors that select it), so set the columns directly — the
    # in-batch ledger needs them to count same-batch placements
    for q in (batch.spread_q[i], batch.spread_svc_q[i], batch.svcanti_q[i]):
        if q >= 0:
            batch.pod_matches_q[i, q] = 1.0

    batch.svcaff_onehot[i] = 0.0
    batch.svcaff_count[i] = 0.0
    batch.svcaff_fail[i] = False
    if ctx.service_affinity_labels:
        terms = service_affinity_terms(pod, ctx, ctx.service_affinity_labels)
        if terms is None:
            batch.svcaff_fail[i] = True
        else:
            rids = {table.intern_requirement(k, ReqOp.IN, (v,))
                    for k, v in terms}
            for rid in rids:
                batch.svcaff_onehot[i, rid] = 1.0
            batch.svcaff_count[i] = float(len(rids))

    # image locality: interned lookups only — an image on no node scores 0
    # everywhere, and interning it here keeps the row valid if a node
    # reports it later (img_size columns refill at node encode)
    batch.img_onehot[i] = 0.0
    for c in pod.spec.containers:
        if c.image:
            batch.img_onehot[i, table.intern_image(c.image)] += 1.0

    # prefer-avoid: lookup only — signatures are interned by node
    # annotations; an unseen signature cannot be avoided by any node
    batch.avoid_onehot[i] = 0.0
    sig = pod_controller_ref(pod)
    if sig is not None:
        oid = table.avoids.get(sig)
        if oid is not None:
            batch.avoid_onehot[i, oid] = 1.0


def _encode_volumes(batch: PodBatch, i: int, pod: Pod, caps: Capacities,
                    table: NodeTable, ctx) -> None:
    """Conflict/attach/zone/node-affinity rows for the pod's volumes. The
    per-predicate fail bits mirror the reference's error returns: each bit
    only takes effect when the corresponding predicate is in the policy."""
    from kubernetes_tpu.state.volumes import (
        EMPTY_CONTEXT,
        VolumeError,
        pod_volume_node_selectors,
        pod_zone_terms,
    )

    batch.vol_want_rw[i] = 0.0
    batch.vol_want_ro[i] = 0.0
    batch.att_onehot[i] = 0.0
    batch.att_fail[i] = False
    batch.vz_onehot[i] = 0.0
    batch.vz_count[i] = 0.0
    batch.vz_fail[i] = False
    batch.vs_onehot[i] = 0.0
    batch.vs_count[i] = 0.0
    batch.vs_fail[i] = False
    if not pod.spec.volumes:
        return
    ctx = ctx or EMPTY_CONTEXT

    any_row, rw_row = table.vol_rows(pod)
    batch.vol_want_rw[i] = rw_row
    batch.vol_want_ro[i] = any_row - rw_row

    try:
        batch.att_onehot[i] = table.attach_row(pod, ctx)
    except VolumeError:
        batch.att_fail[i] = True

    try:
        terms = {term: None for term in pod_zone_terms(pod, ctx)}  # dedup
        for key, value in terms:
            batch.vz_onehot[i, table.intern_sel_term(key, value)] = 1.0
        batch.vz_count[i] = float(len(terms))
    except VolumeError:
        batch.vz_fail[i] = True

    try:
        vsids = {table.intern_volsel(sel)
                 for sel in pod_volume_node_selectors(pod, ctx)}
        for vsid in vsids:
            batch.vs_onehot[i, vsid] = 1.0
        batch.vs_count[i] = float(len(vsids))
    except VolumeError:
        batch.vs_fail[i] = True


def _encode_interpod_affinity(batch: PodBatch, i: int, pod: Pod,
                              caps: Capacities, table: NodeTable) -> None:
    """Encode the pod's own pod-(anti-)affinity terms and (provisionally) its
    match/carry rows. The rows depend on the *final* universe contents, so
    batch encoders must re-run fill_batch_affinity after every pod has
    interned its terms; the inline fill here keeps the single-pod path
    (extender) correct without a second call."""
    from kubernetes_tpu.state.layout import TKEY_INVALID
    from kubernetes_tpu.state.podaffinity import PARSE_ERROR

    eids, terms = intern_pod_affinity_terms(table, pod)

    fail = False
    for lst, q_arr, tk_arr in ((terms.aff_req, batch.paff_q, batch.paff_tkey),
                               (terms.anti_req, batch.panti_q, batch.panti_tkey)):
        if len(lst) > caps.interpod_slots:
            raise CapacityError(
                f"pod {pod.key}: {len(lst)} required pod-affinity terms > "
                f"{caps.interpod_slots} slots")
        q_arr[i] = -1
        for t_idx, t in enumerate(lst):
            tk = table.tkey_code(t.topology_key, required=True)
            if tk == TKEY_INVALID or t.selector == PARSE_ERROR:
                # empty topologyKey or unparseable selector on a required
                # term: the pod cannot schedule anywhere
                # (predicates.go:1014,1162,1191-1196)
                fail = True
                continue
            q_arr[i, t_idx] = table.intern_podsel(t.namespaces, t.selector)
            tk_arr[i, t_idx] = tk
    batch.ipaff_fail[i] = fail

    pref = ([(t, +1.0) for t in terms.aff_pref]
            + [(t, -1.0) for t in terms.anti_pref])
    pref = [(t, sign) for t, sign in pref if t.weight != 0]
    if len(pref) > caps.interpod_pref_slots:
        raise CapacityError(
            f"pod {pod.key}: {len(pref)} preferred pod-affinity terms > "
            f"{caps.interpod_pref_slots} slots")
    batch.ppref_q[i] = -1
    for t_idx, (t, sign) in enumerate(pref):
        batch.ppref_q[i, t_idx] = table.intern_podsel(t.namespaces, t.selector)
        batch.ppref_tkey[i, t_idx] = table.tkey_code(t.topology_key,
                                                     required=False)
        batch.ppref_w[i, t_idx] = sign * float(t.weight)

    batch.pod_matches_q[i] = pod_match_row(table, pod)
    batch.pod_carries_e[i] = carried_term_row(table, eids)


def fill_batch_affinity(batch: PodBatch, pods: Sequence[Pod],
                        table: NodeTable) -> None:
    """Recompute match/carry rows once the universes are final (terms
    interned by later pods in the batch, or by assigned pods)."""
    if not table.podsels and not table.terms:
        return  # no affinity anywhere: rows are already all-zero
    for i, pod in enumerate(pods):
        eids, _ = intern_pod_affinity_terms(table, pod)
        batch.pod_matches_q[i] = pod_match_row(table, pod)
        batch.pod_carries_e[i] = carried_term_row(table, eids)


def fill_batch_avoid(batch: PodBatch, pods: Sequence[Pod],
                     table: NodeTable) -> None:
    """Recompute prefer-avoid rows once node annotations have interned their
    signatures (avoid atoms only come from nodes; a batch encoded before its
    nodes would miss them)."""
    if not table.avoids:
        return
    from kubernetes_tpu.state.volumes import pod_controller_ref

    for i, pod in enumerate(pods):
        batch.avoid_onehot[i] = 0.0
        sig = pod_controller_ref(pod)
        if sig is not None:
            oid = table.avoids.get(sig)
            if oid is not None:
                batch.avoid_onehot[i, oid] = 1.0


def _valid_requirement(expr: dict) -> bool:
    """Mirror labels.NewRequirement validation (selector.go): operator must be
    known; In/NotIn need >=1 value; Exists/DoesNotExist need none; Gt/Lt need
    exactly one."""
    from kubernetes_tpu.state.layout import ReqOp

    op = expr.get("operator", "")
    values = expr.get("values") or []
    if op in (ReqOp.IN, ReqOp.NOT_IN):
        return len(values) >= 1
    if op in (ReqOp.EXISTS, ReqOp.DOES_NOT_EXIST):
        return len(values) == 0
    if op in (ReqOp.GT, ReqOp.LT):
        return len(values) == 1
    return False


def _encode_node_affinity(batch: PodBatch, i: int, pod: Pod, caps: Capacities,
                          table: NodeTable) -> None:
    from kubernetes_tpu.api.objects import parse_node_affinity

    req_terms, preferred = parse_node_affinity(pod.spec.affinity)
    batch.naff_onehot[i] = 0.0
    batch.naff_count[i] = 0.0
    batch.naff_ok[i] = False
    batch.naff_has[i] = req_terms is not None
    if req_terms is not None:
        if len(req_terms) > caps.affinity_terms:
            raise CapacityError(
                f"pod {pod.key}: {len(req_terms)} nodeSelectorTerms > "
                f"{caps.affinity_terms} slots")
        # a parse error in ANY term makes the whole term list match nothing
        # (nodeMatchesNodeSelectorTerms returns false on error,
        # predicates.go:628-631)
        poisoned = any(not _valid_requirement(e) for exprs in req_terms
                       for e in exprs)
        if not poisoned:
            for t, exprs in enumerate(req_terms):
                if not exprs:
                    continue  # empty term: labels.Nothing, matches no node
                # count distinct interned ids: duplicate expressions in a term
                # collapse to one one-hot column
                rids = {table.intern_requirement(
                    e.get("key", ""), e["operator"], tuple(e.get("values") or ()))
                    for e in exprs}
                for rid in rids:
                    batch.naff_onehot[i, t, rid] = 1.0
                batch.naff_count[i, t] = float(len(rids))
                batch.naff_ok[i, t] = True

    batch.pref_onehot[i] = 0.0
    batch.pref_count[i] = 0.0
    batch.pref_weight[i] = 0.0
    if preferred:
        if len(preferred) > caps.pref_terms:
            raise CapacityError(
                f"pod {pod.key}: {len(preferred)} preferred terms > "
                f"{caps.pref_terms} slots")
        for t, (weight, exprs) in enumerate(preferred):
            # weight<=0 skipped (node_affinity.go skips 0; API validation
            # forbids negatives); empty/invalid expressions never match, so
            # the slot contributes nothing
            if weight <= 0 or not exprs or any(not _valid_requirement(e)
                                               for e in exprs):
                continue
            rids = {table.intern_requirement(
                e.get("key", ""), e["operator"], tuple(e.get("values") or ()))
                for e in exprs}
            for rid in rids:
                batch.pref_onehot[i, t, rid] = 1.0
            batch.pref_count[i, t] = float(len(rids))
            batch.pref_weight[i, t] = float(weight)


def encode_pods(pods: Sequence[Pod], caps: Capacities, table: NodeTable,
                state: ClusterState | None = None, ctx=None) -> PodBatch:
    """Encode a batch against the cluster's universes. When `state` is given,
    membership columns for newly interned terms are refilled in place."""
    if len(pods) > caps.batch_pods:
        raise CapacityError(f"{len(pods)} pods > batch capacity {caps.batch_pods}")
    batch = empty_batch(caps)
    for i, pod in enumerate(pods):
        encode_pod_into(batch, i, pod, caps, table, ctx=ctx)
    fill_batch_affinity(batch, pods, table)
    if state is not None:
        apply_pending_refreshes(state, table)
    return batch


def encode_cluster(nodes, pods, caps: Capacities, assigned_pods=(), ctx=None):
    """One-shot fixture encoding: nodes (+ assigned pods) + pending pods with
    a shared universe, membership fully consistent. Returns
    (state, batch, table)."""
    from kubernetes_tpu.state.cluster_state import encode_nodes

    table = NodeTable(caps)
    batch = encode_pods(pods, caps, table, ctx=ctx)
    state, _ = encode_nodes(nodes, caps, assigned_pods=assigned_pods,
                            table=table, ctx=ctx)
    # assigned pods may have interned new selector entries, and nodes new
    # avoid signatures: refresh the batch rows against the final universes
    fill_batch_affinity(batch, pods, table)
    fill_batch_avoid(batch, pods, table)
    apply_pending_refreshes(state, table)
    table.pending_podsel_refresh.clear()  # counts were built post-interning
    return state, batch, table
