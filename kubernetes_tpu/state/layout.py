"""Fixed tensor layout shared by the cluster-state database and pod batches.

Everything scheduled on device has a static, padded shape: TPU/XLA compiles
one program per shape bucket, so capacities are part of the compile key.
`Capacities` is hashable and frozen — pass it as a static argument to jitted
functions.

Units (chosen so common values are exact in float32):
- cpu: milli-cores (reference Resource.MilliCPU, schedulercache/node_info.go)
- memory / storage: MiB (reference uses int64 bytes; MiB keeps terabyte-range
  clusters inside float32's 2^24 exact-integer window)
- gpu / pods: counts
"""

from __future__ import annotations

from dataclasses import dataclass

MEM_UNIT = 2**20  # bytes per device-side memory unit (MiB)


class Resource:
    """Row indices of the resource axis (reference scheduler Resource struct:
    plugin/pkg/scheduler/schedulercache/node_info.go:45-52)."""

    PODS = 0
    CPU = 1        # milli-cores
    MEMORY = 2     # MiB
    GPU = 3        # count (alpha.kubernetes.io/nvidia-gpu)
    SCRATCH = 4    # MiB (storage.kubernetes.io/scratch)
    OVERLAY = 5    # MiB (storage.kubernetes.io/overlay)
    COUNT = 6

    # v1 resource-name -> (row, converter kind)
    NAMES = {
        "pods": (PODS, "count"),
        "cpu": (CPU, "milli"),
        "memory": (MEMORY, "mem"),
        "alpha.kubernetes.io/nvidia-gpu": (GPU, "count"),
        "storage.kubernetes.io/scratch": (SCRATCH, "mem"),
        "storage.kubernetes.io/overlay": (OVERLAY, "mem"),
    }


class Effect:
    """Taint-effect codes (0 reserved for empty slot)."""

    NONE = 0
    NO_SCHEDULE = 1
    PREFER_NO_SCHEDULE = 2
    NO_EXECUTE = 3

    NAMES = {"NoSchedule": NO_SCHEDULE, "PreferNoSchedule": PREFER_NO_SCHEDULE,
             "NoExecute": NO_EXECUTE}


class TolOp:
    """Toleration operator codes (0 reserved for empty slot)."""

    NONE = 0
    EQUAL = 1
    EXISTS = 2


class ReqOp:
    """NodeSelectorRequirement operators (reference v1.NodeSelectorOperator,
    staging/src/k8s.io/api/core/v1/types.go; semantics of
    labels.Requirement.Matches in apimachinery/pkg/labels/selector.go:
    NotIn/DoesNotExist are satisfied by a missing key)."""

    IN = "In"
    NOT_IN = "NotIn"
    EXISTS = "Exists"
    DOES_NOT_EXIST = "DoesNotExist"
    GT = "Gt"
    LT = "Lt"

    ALL = (IN, NOT_IN, EXISTS, DOES_NOT_EXIST, GT, LT)


class Condition:
    """Bits of the per-node condition mask. Bit set == the *bad* state, so an
    all-zero mask is a healthy schedulable node (reference:
    CheckNodeCondition predicates.go:1306, pressure checks :1274,:1296, and
    the unschedulable filter in factory.go's node lister predicate)."""

    NOT_READY = 1 << 0
    MEMORY_PRESSURE = 1 << 1
    DISK_PRESSURE = 1 << 2
    NETWORK_UNAVAILABLE = 1 << 3
    OUT_OF_DISK = 1 << 4
    UNSCHEDULABLE = 1 << 5


# Topology keys interned into the per-node topology table, in row order.
# Slots 0-2 are the reference's default failure domains
# (kubeletapis.DefaultFailureDomains); slot 3 is a virtual composite
# (zone, region) domain used for inclusion-exclusion when a preferred
# pod-anti-affinity term has an empty topologyKey ("match any default
# domain", priorityutil.Topologies). Custom topology keys from affinity
# terms intern from slot FIRST_CUSTOM_TOPO up to `topology_slots`.
TOPOLOGY_KEYS = (
    "kubernetes.io/hostname",
    "failure-domain.beta.kubernetes.io/zone",
    "failure-domain.beta.kubernetes.io/region",
)
TOPO_HOSTNAME = 0
TOPO_ZONE = 1
TOPO_REGION = 2
TOPO_ZONE_REGION = 3    # virtual composite slot (both present)
TOPO_SPREAD_ZONE = 4    # virtual GetZoneKey slot: (region, zone) with either
                        # present (pkg/util/node/node.go:115 — the zone id
                        # SelectorSpreadPriority aggregates by)
FIRST_CUSTOM_TOPO = 5

# Sentinel topology-slot codes used in affinity-term encodings.
TKEY_INVALID = -1       # empty/uninternable topologyKey on a required term
TKEY_DEFAULT_UNION = -2  # empty topologyKey on a preferred term: any default domain


class TermKind:
    """Carried pod-affinity-term kinds (the existing-pod side of matching:
    predicates.go getMatchingAntiAffinityTerms + interpod_affinity.go
    symmetric weighting)."""

    ANTI_REQ = 0   # required anti-affinity: predicate, hard fail
    AFF_REQ = 1    # required affinity: priority, weight = hardPodAffinityWeight
    AFF_PREF = 2   # preferred affinity: priority, +weight
    ANTI_PREF = 3  # preferred anti-affinity: priority, -weight

class VolType:
    """Attachable-volume type codes for MaxPDVolumeCount (reference
    EBSVolumeFilter/GCEPDVolumeFilter/AzureDiskVolumeFilter,
    predicates.go:323-373). ANY marks synthetic atoms for unresolvable PVCs,
    which the reference counts toward every filter ("assuming PVC matches
    predicate", predicates.go:240-243)."""

    EBS = 0
    GCE = 1
    AZURE = 2
    ANY = 3
    EMPTY = -1

    COUNT = 3  # real types (ANY matches all of them)


# Reference attach limits (defaults.go:35-41 + aws.DefaultMaxEBSVolumes=39);
# overridable via KUBE_MAX_PD_VOLS (defaults.go getMaxVols) at Policy build.
DEFAULT_MAX_EBS_VOLUMES = 39
DEFAULT_MAX_GCE_PD_VOLUMES = 16
DEFAULT_MAX_AZURE_DISK_VOLUMES = 16

# Scoring-time defaults for pods with no requests (reference
# plugin/pkg/scheduler/algorithm/priorities/util/non_zero.go:29-31).
DEFAULT_NONZERO_CPU_MILLI = 100.0
DEFAULT_NONZERO_MEM_MIB = 200.0 * 1024 * 1024 / MEM_UNIT  # 200 MB in MiB

MAX_PRIORITY = 10  # schedulerapi.MaxPriority


@dataclass(frozen=True)
class Capacities:
    """Static padding capacities — the compile-time shape key.

    Encoders raise `CapacityError` when an object exceeds a capacity; pick
    capacities for the workload (defaults cover scheduler_perf-style fixtures
    and typical clusters).

    The `*_universe` capacities size the interned matching universes: distinct
    nodeSelector terms, distinct node taints, and distinct host ports get
    small global integer ids, per-node membership matrices f32[N, U], and
    per-pod one-hot rows — so selector/taint/port matching over (P x N) is a
    single MXU matmul instead of slot-wise compare loops.
    """

    num_nodes: int = 1024          # N: node axis (pad to multiple of mesh size)
    batch_pods: int = 256          # P: pending pods per solver batch
    selector_universe: int = 128   # US: distinct nodeSelector key=value terms
    taint_universe: int = 64       # UT: distinct (key, value, effect) taints
    port_universe: int = 64        # UP: distinct host ports in use
    req_universe: int = 64         # UR: distinct NodeSelectorRequirements
    podsel_universe: int = 32      # UQ: distinct (namespaces, labelSelector)
    term_universe: int = 32        # UE: distinct carried pod-affinity terms
    domain_universe: int = 64      # D: domains per non-hostname topology slot
    toleration_slots: int = 8      # tolerations per pod
    topology_slots: int = 8        # 3 defaults + 1 virtual + custom keys
    affinity_terms: int = 4        # required node-affinity OR-terms per pod
    pref_terms: int = 4            # preferred node-affinity terms per pod
    interpod_slots: int = 4        # required pod-(anti-)affinity terms per pod
    interpod_pref_slots: int = 4   # preferred pod-(anti-)affinity terms per pod
    volume_universe: int = 32      # UV: distinct disk-conflict atoms
    attach_universe: int = 32      # UA: distinct attachable-volume atoms
    image_universe: int = 64       # UI: distinct container-image names
    avoid_universe: int = 16       # UO: distinct preferAvoidPods signatures
    volsel_universe: int = 16      # UVS: distinct PV node-affinity selectors
    victim_slots: int = 16         # S: preemption victim candidates per node


class CapacityError(ValueError):
    """An object does not fit the static tensor capacities."""
