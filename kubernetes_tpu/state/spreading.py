"""Host-side spreading/service analysis: controller selectors + service
affinity inference.

Mirrors the lister-driven halves of SelectorSpreadPriority
(selector_spreading.go:61-89 getSelectors), ServiceAntiAffinityPriority
(selector_spreading.go:190-250) and the ServiceAffinity predicate's
precomputation (predicates.go:762-781). Everything resolves to interned
integer ids: the pod's controller selectors become ONE pod-selector-universe
entry with match-any union semantics (so per-node counts never double-count a
pod matching two selectors, selector_spreading.go:123-131), and service
affinity becomes requirement-universe terms.
"""

from __future__ import annotations

from kubernetes_tpu.api.objects import Pod
from kubernetes_tpu.state.context import EncodeContext
from kubernetes_tpu.state.podaffinity import (
    canonical_selector,
    map_selector,
    PARSE_ERROR,
    selector_matches,
    union_selector,
)


def pod_controller_selectors(pod: Pod, ctx: EncodeContext,
                             services_only: bool = False) -> list:
    """Canonical selectors of services/RCs/RSs/StatefulSets matching the pod
    (getSelectors, selector_spreading.go:61; `services_only` is the
    ServiceSpreadingPriority variant, defaults.go:97-104).

    Lister semantics: nil selectors match nothing (the listers' explicit
    guards; a non-nil empty map matches everything,
    service_expansion.go:45-50); the RC/RS/SS listers error out for
    label-less pods (ignored by getSelectors), the service lister does not."""
    ns = pod.metadata.namespace
    labels = pod.metadata.labels
    out = []
    for svc in ctx.get_services(ns):
        sel = svc.selector
        if sel is not None and selector_matches(map_selector(sel), labels):
            out.append(map_selector(sel))
    if services_only or not labels:
        return out
    for rc in ctx.get_rcs(ns):
        sel = rc.selector
        if sel and selector_matches(map_selector(sel), labels):
            out.append(map_selector(sel))
    for rs in ctx.get_rss(ns):
        canon = canonical_selector(rs.selector or None)
        if canon != PARSE_ERROR and canon != () \
                and selector_matches(canon, labels):
            out.append(canon)
    for ss in ctx.get_sss(ns):
        canon = canonical_selector(ss.selector or None)
        if canon != PARSE_ERROR and canon != () \
                and selector_matches(canon, labels):
            out.append(canon)
    return out


def spread_entry(pod: Pod, ctx: EncodeContext, table,
                 services_only: bool = False) -> int:
    """Pod-selector-universe id of the pod's spreading union, or -1 when the
    pod has no matching controllers (score degenerates to uniform
    MaxPriority, selector_spreading.go:157-167)."""
    canons = pod_controller_selectors(pod, ctx, services_only=services_only)
    if not canons:
        return -1
    return table.intern_podsel(frozenset([pod.metadata.namespace]),
                               union_selector(canons))


def first_service_entry(pod: Pod, ctx: EncodeContext, table):
    """(qid, total) for ServiceAntiAffinityPriority: the first matching
    service's selector (selector_spreading.go:228 'just use the first
    service') interned same-namespace, plus the total count of matching
    same-namespace *assigned* pods (nsServicePods comes from the scheduler
    cache's pod lister, factory.go:139, which holds only bound pods)."""
    ns = pod.metadata.namespace
    for svc in ctx.get_services(ns):
        sel = svc.selector
        if sel is not None and selector_matches(map_selector(sel),
                                                pod.metadata.labels):
            canon = map_selector(sel)
            qid = table.intern_podsel(frozenset([ns]), canon)
            total = sum(1 for p in ctx.list_pods(ns)
                        if p.spec.node_name
                        and selector_matches(canon, p.metadata.labels))
            return qid, float(total)
    return -1, 0.0


def service_affinity_terms(pod: Pod, ctx: EncodeContext,
                           labels: tuple) -> list[tuple[str, str]] | None:
    """The ServiceAffinity predicate's affinity-label set for one pod
    (serviceAffinityPrecomputation + checkServiceAffinity,
    predicates.go:762-855): pinned nodeSelector values first; unset labels
    backfilled from the node of the first existing same-namespace pod whose
    labels the pod's label set selects, when the pod belongs to a service.
    The backfill candidates are *assigned* pods only — the reference's
    podLister is the scheduler cache (factory.go:139), which holds only
    bound pods, so a service's first pod schedules unconstrained and pins
    the labels. Returns (key, value) terms the node must carry, or None
    when a bound backfill pod's node lookup fails (GetNodeInfo error ->
    attempt fails)."""
    affinity = {k: pod.spec.node_selector[k] for k in labels
                if k in pod.spec.node_selector}
    if len(affinity) < len(labels):
        ns = pod.metadata.namespace
        services = [s for s in ctx.get_services(ns)
                    if s.selector is not None and selector_matches(
                        map_selector(s.selector), pod.metadata.labels)]
        if services:
            own_sel = map_selector(pod.metadata.labels)
            matching = [p for p in ctx.list_pods(ns)
                        if p.spec.node_name
                        and selector_matches(own_sel, p.metadata.labels)]
            if matching:
                first = matching[0]
                node = ctx.get_node(first.spec.node_name)
                if node is None:
                    return None  # bound pod, unknown node: hard error path
                for k in labels:
                    if k not in affinity and k in node.metadata.labels:
                        affinity[k] = node.metadata.labels[k]
    return sorted(affinity.items())
