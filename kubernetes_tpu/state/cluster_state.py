"""Device-resident cluster state: a structure-of-arrays tensor database.

This is the TPU-native re-design of the scheduler cache's per-node `NodeInfo`
aggregate (reference plugin/pkg/scheduler/schedulercache/node_info.go:34-74:
pods, requested/allocatable Resource, usedPorts, taints, conditions,
generation). Instead of N Go structs behind a mutex, the whole cluster is a
handful of padded arrays with the node axis outermost, so predicates/priorities
evaluate as masked vector ops over every node at once and the node axis shards
across a device mesh.

Host-side bookkeeping (name->row mapping, topology-domain interning,
generation counters for incremental scatter) lives in `NodeTable`; the arrays
themselves are a pure pytree (`ClusterState`) safe to close over in jit.
"""

from __future__ import annotations

from typing import Iterable, Sequence

import numpy as np
from flax import struct

from kubernetes_tpu.api.objects import Node, Pod
from kubernetes_tpu.api.quantity import parse_quantity
from kubernetes_tpu.state.layout import (
    TOPOLOGY_KEYS,
    Capacities,
    CapacityError,
    Condition,
    Effect,
    MEM_UNIT,
    Resource,
)
from kubernetes_tpu.utils.hashing import hash32, hash_kv, hash_lanes


@struct.dataclass
class ClusterState:
    """Pure pytree of padded device arrays; node axis is dim 0 everywhere."""

    valid: np.ndarray          # bool[N] — row holds a live node
    allocatable: np.ndarray    # f32[N, R]
    requested: np.ndarray      # f32[N, R] — sum of requests of assigned pods
    nonzero_requested: np.ndarray  # f32[N, 2] — (cpu, mem) with per-pod defaults
    ports: np.ndarray          # i32[N, PORT_SLOTS], -1 = empty
    label_key: np.ndarray      # u32[N, L] hash32(key), 0 = empty
    label_kv_lo: np.ndarray    # u32[N, L] lane of hash(key=value)
    label_kv_hi: np.ndarray    # u32[N, L]
    taint_key: np.ndarray      # u32[N, T], 0 = empty
    taint_val_lo: np.ndarray   # u32[N, T] hash lanes of the taint *value*
    taint_val_hi: np.ndarray   # u32[N, T]
    taint_effect: np.ndarray   # i32[N, T], Effect codes
    conditions: np.ndarray     # u32[N] Condition bitmask (0 == healthy)
    name_lo: np.ndarray        # u32[N] node-name hash lanes
    name_hi: np.ndarray        # u32[N]
    topology: np.ndarray       # i32[N, TK] interned domain id, -1 = unknown

    @property
    def num_nodes(self) -> int:
        return self.valid.shape[0]


def empty_state(caps: Capacities) -> ClusterState:
    n = caps.num_nodes
    r = Resource.COUNT
    return ClusterState(
        valid=np.zeros((n,), np.bool_),
        allocatable=np.zeros((n, r), np.float32),
        requested=np.zeros((n, r), np.float32),
        nonzero_requested=np.zeros((n, 2), np.float32),
        ports=np.full((n, caps.node_port_slots), -1, np.int32),
        label_key=np.zeros((n, caps.label_slots), np.uint32),
        label_kv_lo=np.zeros((n, caps.label_slots), np.uint32),
        label_kv_hi=np.zeros((n, caps.label_slots), np.uint32),
        taint_key=np.zeros((n, caps.taint_slots), np.uint32),
        taint_val_lo=np.zeros((n, caps.taint_slots), np.uint32),
        taint_val_hi=np.zeros((n, caps.taint_slots), np.uint32),
        taint_effect=np.zeros((n, caps.taint_slots), np.int32),
        conditions=np.zeros((n,), np.uint32),
        name_lo=np.zeros((n,), np.uint32),
        name_hi=np.zeros((n,), np.uint32),
        topology=np.full((n, caps.topology_slots), -1, np.int32),
    )


def resource_rows(quantities: dict[str, str]) -> np.ndarray:
    """v1 resource map -> f32[R] in device units."""
    out = np.zeros((Resource.COUNT,), np.float32)
    for name, qty in quantities.items():
        entry = Resource.NAMES.get(name)
        if entry is None:
            continue  # opaque int resources: not yet modeled on device
        row, kind = entry
        frac = parse_quantity(qty)
        if kind == "milli":
            out[row] = float(frac * 1000)
        elif kind == "mem":
            out[row] = float(frac / MEM_UNIT)
        else:
            out[row] = float(frac)
    return out


def condition_mask(node: Node) -> int:
    mask = 0
    ready_seen = False
    for cond in node.status.conditions:
        if cond.type == "Ready":
            ready_seen = True
            if cond.status != "True":
                mask |= Condition.NOT_READY
        elif cond.type == "MemoryPressure" and cond.status == "True":
            mask |= Condition.MEMORY_PRESSURE
        elif cond.type == "DiskPressure" and cond.status == "True":
            mask |= Condition.DISK_PRESSURE
        elif cond.type == "NetworkUnavailable" and cond.status == "True":
            mask |= Condition.NETWORK_UNAVAILABLE
        elif cond.type == "OutOfDisk" and cond.status == "True":
            mask |= Condition.OUT_OF_DISK
    if not ready_seen and node.status.conditions:
        # Conditions reported but no Ready condition: treat as not ready
        # (reference CheckNodeCondition treats missing Ready as unknown).
        mask |= Condition.NOT_READY
    if node.spec.unschedulable:
        mask |= Condition.UNSCHEDULABLE
    return mask


class NodeTable:
    """Host-side index over the device state: row assignment, free-list,
    topology-domain interning, per-row generation (the analog of
    NodeInfo.generation, node_info.go:60) for incremental device updates."""

    def __init__(self, caps: Capacities):
        self.caps = caps
        self.row_of: dict[str, int] = {}
        self.name_of: list[str | None] = [None] * caps.num_nodes
        self.free: list[int] = list(range(caps.num_nodes - 1, -1, -1))
        self.generation: np.ndarray = np.zeros((caps.num_nodes,), np.int64)
        self._gen_counter = 0
        # topology interning: per topology key, domain string -> id
        self.domains: list[dict[str, int]] = [dict() for _ in TOPOLOGY_KEYS]

    def assign_row(self, name: str) -> int:
        row = self.row_of.get(name)
        if row is None:
            if not self.free:
                raise CapacityError(
                    f"node capacity {self.caps.num_nodes} exhausted adding {name!r}")
            row = self.free.pop()
            self.row_of[name] = row
            self.name_of[row] = name
        return row

    def release_row(self, name: str) -> int:
        row = self.row_of.pop(name)
        self.name_of[row] = None
        self.free.append(row)
        return row

    def bump(self, row: int) -> None:
        self._gen_counter += 1
        self.generation[row] = self._gen_counter

    def intern_domain(self, key_idx: int, value: str) -> int:
        table = self.domains[key_idx]
        did = table.get(value)
        if did is None:
            did = len(table)
            table[value] = did
        return did


def _fill_node_row(state: ClusterState, table: NodeTable, row: int, node: Node) -> None:
    caps = table.caps
    state.valid[row] = True
    state.allocatable[row] = resource_rows(node.status.effective_allocatable())
    state.conditions[row] = condition_mask(node)
    lo, hi = hash_lanes(node.metadata.name)
    state.name_lo[row], state.name_hi[row] = lo, hi

    labels = node.metadata.labels
    if len(labels) > caps.label_slots:
        raise CapacityError(
            f"node {node.metadata.name!r}: {len(labels)} labels > {caps.label_slots} slots")
    state.label_key[row] = 0
    state.label_kv_lo[row] = 0
    state.label_kv_hi[row] = 0
    for i, (k, v) in enumerate(sorted(labels.items())):
        state.label_key[row, i] = hash32(k)
        kv_lo, kv_hi = hash_kv(k, v)
        state.label_kv_lo[row, i] = kv_lo
        state.label_kv_hi[row, i] = kv_hi

    taints = node.spec.taints
    if len(taints) > caps.taint_slots:
        raise CapacityError(
            f"node {node.metadata.name!r}: {len(taints)} taints > {caps.taint_slots} slots")
    state.taint_key[row] = 0
    state.taint_val_lo[row] = 0
    state.taint_val_hi[row] = 0
    state.taint_effect[row] = Effect.NONE
    for i, t in enumerate(taints):
        state.taint_key[row, i] = hash32(t.key)
        val_lo, val_hi = hash_lanes(t.value)
        state.taint_val_lo[row, i] = val_lo
        state.taint_val_hi[row, i] = val_hi
        state.taint_effect[row, i] = Effect.NAMES.get(t.effect, Effect.NONE)

    state.topology[row] = -1
    for ki, key in enumerate(TOPOLOGY_KEYS):
        val = labels.get(key)
        if key == "kubernetes.io/hostname" and val is None:
            val = node.metadata.name  # hostname domain defaults to node name
        if val is not None:
            state.topology[row, ki] = table.intern_domain(ki, val)


def pod_requests(pod: Pod) -> np.ndarray:
    """Sum of container requests in device units, +1 pod slot (reference
    GetResourceRequest, predicates.go; pods row mirrors the
    len(nodeInfo.Pods())+1 > allowedPodNumber check at predicates.go:561)."""
    out = np.zeros((Resource.COUNT,), np.float32)
    out[Resource.PODS] = 1.0
    for c in pod.spec.containers:
        out += resource_rows(c.requests)
    out[Resource.PODS] = 1.0
    return out


def pod_nonzero_requests(pod: Pod) -> np.ndarray:
    """(cpu_milli, mem_mib) with per-container defaults for scoring (reference
    priorities/util/non_zero.go GetNonzeroRequests)."""
    from kubernetes_tpu.state.layout import (
        DEFAULT_NONZERO_CPU_MILLI,
        DEFAULT_NONZERO_MEM_MIB,
    )

    cpu = 0.0
    mem = 0.0
    for c in pod.spec.containers:
        c_rows = resource_rows(c.requests)
        cpu += c_rows[Resource.CPU] if c_rows[Resource.CPU] > 0 else DEFAULT_NONZERO_CPU_MILLI
        mem += c_rows[Resource.MEMORY] if c_rows[Resource.MEMORY] > 0 else DEFAULT_NONZERO_MEM_MIB
    return np.array([cpu, mem], np.float32)


def insert_port(port_row: np.ndarray, port: int) -> None:
    """Fill the first empty (-1) slot of a node's port row."""
    empty = np.nonzero(port_row == -1)[0]
    if empty.size == 0:
        raise CapacityError(f"port slots ({port_row.shape[0]}) exhausted")
    port_row[empty[0]] = port


def remove_port(port_row: np.ndarray, port: int) -> None:
    """Clear one occurrence of `port` from a node's port row."""
    hit = np.nonzero(port_row == port)[0]
    if hit.size:
        port_row[hit[0]] = -1


def add_pod_to_state(state: ClusterState, table: NodeTable, pod: Pod, row: int) -> None:
    """Account an assigned pod against a node row (the analog of
    NodeInfo.addPod, node_info.go:171)."""
    state.requested[row] += pod_requests(pod)
    state.nonzero_requested[row] += pod_nonzero_requests(pod)
    for port in pod.host_ports():
        insert_port(state.ports[row], port)
    table.bump(row)


def encode_nodes(
    nodes: Iterable[Node],
    caps: Capacities,
    assigned_pods: Sequence[Pod] = (),
) -> tuple[ClusterState, NodeTable]:
    """Full (re-)encode: the List half of list+watch. Incremental updates go
    through `statedb.StateDB` which scatters only changed rows."""
    state = empty_state(caps)
    table = NodeTable(caps)
    for node in nodes:
        row = table.assign_row(node.metadata.name)
        _fill_node_row(state, table, row, node)
        table.bump(row)
    for pod in assigned_pods:
        if not pod.spec.node_name:
            continue
        row = table.row_of.get(pod.spec.node_name)
        if row is None:
            continue  # pod bound to an unknown node: ignored, like cache misses
        add_pod_to_state(state, table, pod, row)
    return state, table
