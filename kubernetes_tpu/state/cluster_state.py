"""Device-resident cluster state: a structure-of-arrays tensor database.

This is the TPU-native re-design of the scheduler cache's per-node `NodeInfo`
aggregate (reference plugin/pkg/scheduler/schedulercache/node_info.go:34-74:
pods, requested/allocatable Resource, usedPorts, taints, conditions,
generation). Instead of N Go structs behind a mutex, the whole cluster is a
handful of padded arrays with the node axis outermost, so predicates and
priorities evaluate as masked vector ops over every node at once and the node
axis shards across a device mesh.

**Universe interning.** The irregular, string-keyed parts of matching
(nodeSelector terms, taints, host ports) are interned into small global
universes on the host: each distinct selector key=value term, each distinct
(key, value, effect) taint, and each distinct host port gets an integer id.
The device then carries *membership matrices* — `sel_member[n, u] = 1` iff
node n's labels satisfy term u; `taint_*_member[n, u] = 1` iff node n carries
universe taint u; `port_count[n, u]` = occurrences of port u on node n — and
the (pods x nodes) matching in ops/predicates.py becomes one one-hot matmul
on the MXU per predicate, replacing the reference's per-node string-matching
loops (predicates.go:686,859,1241).

Host-side bookkeeping (name->row mapping, universe interning, label/taint
source data, generation counters) lives in `NodeTable`; the arrays themselves
are a pure pytree (`ClusterState`) safe to pass through jit.
"""

from __future__ import annotations

from typing import Iterable, Sequence

import numpy as np
from flax import struct

from kubernetes_tpu.api.objects import Node, Pod
from kubernetes_tpu.api.quantity import parse_quantity
from kubernetes_tpu.state.layout import (
    TOPOLOGY_KEYS,
    Capacities,
    CapacityError,
    Condition,
    Effect,
    MEM_UNIT,
    ReqOp,
    Resource,
    VolType,
)
from kubernetes_tpu.utils.hashing import hash32, hash_lanes

# ClusterState fields whose dim 0 is the node axis (shard across the mesh);
# everything else is cluster-global and replicated.
NODE_AXIS_FIELDS = frozenset({
    "valid", "allocatable", "requested", "nonzero_requested", "port_count",
    "sel_member", "req_member", "taint_hard_member", "taint_prefer_member",
    "conditions", "name_lo", "name_hi", "topology", "podsel_count",
    "term_count", "vol_any", "vol_rw", "attach_count", "img_size",
    "avoid_member", "volsel_member",
})


@struct.dataclass
class ClusterState:
    """Pure pytree of padded device arrays."""

    valid: np.ndarray             # bool[N] — row holds a live node
    allocatable: np.ndarray       # f32[N, R]
    requested: np.ndarray         # f32[N, R] — sum of requests of assigned pods
    nonzero_requested: np.ndarray  # f32[N, 2] — (cpu, mem) with scoring defaults
    port_count: np.ndarray        # f32[N, UP] — pods using interned port u
    sel_member: np.ndarray        # f32[N, US] — node satisfies selector term u
    req_member: np.ndarray        # f32[N, UR] — node satisfies requirement u
    taint_hard_member: np.ndarray    # f32[N, UT] — NoSchedule/NoExecute taints
    taint_prefer_member: np.ndarray  # f32[N, UT] — PreferNoSchedule taints
    # taint universe attributes (dim 0 = UT, replicated across the mesh)
    taint_u_key: np.ndarray       # u32[UT] hash32(key), 0 = empty slot
    taint_u_val_lo: np.ndarray    # u32[UT] value hash lanes
    taint_u_val_hi: np.ndarray    # u32[UT]
    taint_u_effect: np.ndarray    # i32[UT] Effect codes
    conditions: np.ndarray        # u32[N] Condition bitmask (0 == healthy)
    name_lo: np.ndarray           # u32[N] node-name hash lanes
    name_hi: np.ndarray           # u32[N]
    topology: np.ndarray          # i32[N, TK] interned domain id, -1 = unknown
    # volume state (see state/volumes.py)
    vol_any: np.ndarray           # f32[N, UV] — pods on n using conflict atom u
    vol_rw: np.ndarray            # f32[N, UV] — of those, not read-only
    attach_count: np.ndarray      # f32[N, UA] — pods on n using attach atom u
    attach_type: np.ndarray       # i32[UA] VolType codes, EMPTY = free slot
    img_size: np.ndarray          # f32[N, UI] — bytes of image u if present
    avoid_member: np.ndarray      # f32[N, UO] — node prefers to avoid sig u
    volsel_member: np.ndarray     # f32[N, UVS] — node matches PV selector u
    # inter-pod affinity state (see state/podaffinity.py)
    podsel_count: np.ndarray      # f32[N, UQ] — pods on n matching selector q
    term_count: np.ndarray        # f32[N, UE] — pods on n carrying term e
    # carried-term attributes (dim 0 = UE, replicated across the mesh)
    term_q: np.ndarray            # i32[UE] selector-universe id, -1 = empty slot
    term_tkey: np.ndarray         # i32[UE] topo slot / TKEY_* sentinel
    term_weight: np.ndarray       # f32[UE] signed preferred weight
    term_kind: np.ndarray         # i32[UE] TermKind codes
    term_poison: np.ndarray       # bool[UE] unparseable selector on a required
                                  #          anti term: carriers poison scheduling

    @property
    def num_nodes(self) -> int:
        return self.valid.shape[0]


def empty_state(caps: Capacities) -> ClusterState:
    n = caps.num_nodes
    return ClusterState(
        valid=np.zeros((n,), np.bool_),
        allocatable=np.zeros((n, Resource.COUNT), np.float32),
        requested=np.zeros((n, Resource.COUNT), np.float32),
        nonzero_requested=np.zeros((n, 2), np.float32),
        port_count=np.zeros((n, caps.port_universe), np.float32),
        sel_member=np.zeros((n, caps.selector_universe), np.float32),
        req_member=np.zeros((n, caps.req_universe), np.float32),
        taint_hard_member=np.zeros((n, caps.taint_universe), np.float32),
        taint_prefer_member=np.zeros((n, caps.taint_universe), np.float32),
        taint_u_key=np.zeros((caps.taint_universe,), np.uint32),
        taint_u_val_lo=np.zeros((caps.taint_universe,), np.uint32),
        taint_u_val_hi=np.zeros((caps.taint_universe,), np.uint32),
        taint_u_effect=np.zeros((caps.taint_universe,), np.int32),
        conditions=np.zeros((n,), np.uint32),
        name_lo=np.zeros((n,), np.uint32),
        name_hi=np.zeros((n,), np.uint32),
        topology=np.full((n, caps.topology_slots), -1, np.int32),
        vol_any=np.zeros((n, caps.volume_universe), np.float32),
        vol_rw=np.zeros((n, caps.volume_universe), np.float32),
        attach_count=np.zeros((n, caps.attach_universe), np.float32),
        attach_type=np.full((caps.attach_universe,), VolType.EMPTY, np.int32),
        img_size=np.zeros((n, caps.image_universe), np.float32),
        avoid_member=np.zeros((n, caps.avoid_universe), np.float32),
        volsel_member=np.zeros((n, caps.volsel_universe), np.float32),
        podsel_count=np.zeros((n, caps.podsel_universe), np.float32),
        term_count=np.zeros((n, caps.term_universe), np.float32),
        term_q=np.full((caps.term_universe,), -1, np.int32),
        term_tkey=np.zeros((caps.term_universe,), np.int32),
        term_weight=np.zeros((caps.term_universe,), np.float32),
        term_kind=np.zeros((caps.term_universe,), np.int32),
        term_poison=np.zeros((caps.term_universe,), np.bool_),
    )


def resource_rows(quantities: dict[str, str]) -> np.ndarray:
    """v1 resource map -> f32[R] in device units."""
    out = np.zeros((Resource.COUNT,), np.float32)
    for name, qty in quantities.items():
        entry = Resource.NAMES.get(name)
        if entry is None:
            continue  # opaque int resources: not yet modeled on device
        row, kind = entry
        frac = parse_quantity(qty)
        if kind == "milli":
            out[row] = float(frac * 1000)
        elif kind == "mem":
            out[row] = float(frac / MEM_UNIT)
        else:
            out[row] = float(frac)
    return out


def condition_mask(node: Node) -> int:
    mask = 0
    ready_seen = False
    for cond in node.status.conditions:
        if cond.type == "Ready":
            ready_seen = True
            if cond.status != "True":
                mask |= Condition.NOT_READY
        elif cond.type == "MemoryPressure" and cond.status == "True":
            mask |= Condition.MEMORY_PRESSURE
        elif cond.type == "DiskPressure" and cond.status == "True":
            mask |= Condition.DISK_PRESSURE
        elif cond.type == "NetworkUnavailable" and cond.status == "True":
            mask |= Condition.NETWORK_UNAVAILABLE
        elif cond.type == "OutOfDisk" and cond.status == "True":
            mask |= Condition.OUT_OF_DISK
    if not ready_seen and node.status.conditions:
        # Conditions reported but no Ready condition: treat as not ready
        # (reference CheckNodeCondition treats missing Ready as unknown).
        mask |= Condition.NOT_READY
    if node.spec.unschedulable:
        mask |= Condition.UNSCHEDULABLE
    return mask


_INT64_MAX = 2**63 - 1
_INT64_MIN = -(2**63)


def parse_int64(s: str) -> int | None:
    """Go strconv.ParseInt(s, 10, 64) semantics: optional sign + ASCII digits
    only (no whitespace, underscores, or other bases), int64 range. Returns
    None on failure (Gt/Lt requirements fail closed, selector.go)."""
    body = s[1:] if s[:1] in "+-" else s
    if not body or not body.isascii() or not body.isdigit():
        return None
    v = int(s)
    if not (_INT64_MIN <= v <= _INT64_MAX):
        return None
    return v


def match_requirement(labels: dict[str, str], key: str, op: str,
                      values: tuple[str, ...]) -> bool:
    """Evaluate one NodeSelectorRequirement against a label set, with the
    reference's labels.Requirement.Matches semantics
    (apimachinery/pkg/labels/selector.go: NotIn/DoesNotExist are satisfied by
    a missing key; Gt/Lt parse both sides as int64 and fail closed)."""
    has = key in labels
    if op == ReqOp.IN:
        return has and labels[key] in values
    if op == ReqOp.NOT_IN:
        return not has or labels[key] not in values
    if op == ReqOp.EXISTS:
        return has
    if op == ReqOp.DOES_NOT_EXIST:
        return not has
    if op in (ReqOp.GT, ReqOp.LT):
        if not has or len(values) != 1:
            return False
        lhs = parse_int64(labels[key])
        rhs = parse_int64(values[0])
        if lhs is None or rhs is None:
            return False
        return lhs > rhs if op == ReqOp.GT else lhs < rhs
    return False


class NodeTable:
    """Host-side index over the device state: row assignment + free-list,
    universe interning (selector terms, taints, ports), per-row source data
    for membership refills, topology-domain interning, and per-row generation
    counters (the NodeInfo.generation analog, node_info.go:60)."""

    def __init__(self, caps: Capacities, shards: int = 1):
        self.caps = caps
        self.shards = shards if caps.num_nodes % max(shards, 1) == 0 else 1
        self.row_of: dict[str, int] = {}
        self.name_of: list[str | None] = [None] * caps.num_nodes
        if self.shards > 1:
            # shard-interleaved addressing (mesh attached): consecutive
            # assignments land on consecutive shards, so a partially filled
            # cluster keeps live rows balanced across devices instead of
            # saturating shard 0 first. Popped from the end -> reversed.
            chunk = caps.num_nodes // self.shards
            self.free: list[int] = [
                s * chunk + loc
                for loc in range(chunk) for s in range(self.shards)][::-1]
        else:
            self.free = list(range(caps.num_nodes - 1, -1, -1))
        self.generation: np.ndarray = np.zeros((caps.num_nodes,), np.int64)
        self._gen_counter = 0
        # universes
        self.sel_terms: dict[tuple[str, str], int] = {}
        self.taints: dict[tuple[str, str, str], int] = {}
        self.ports: dict[int, int] = {}
        self.reqs: dict[tuple[str, str, tuple[str, ...]], int] = {}
        # volume universes (state/volumes.py atom grammars)
        self.vol_atoms: dict[tuple, int] = {}
        self.attach_atoms: dict[tuple, int] = {}
        self.attach_types: dict[int, int] = {}   # aid -> VolType
        # bumped when node-side interning can invalidate encoded pod rows
        # (a new preferAvoidPods signature: pods encoded earlier lack the
        # one-hot). EncodeCache folds this into its fingerprint.
        self.pod_row_epoch = 0
        self.images: dict[str, int] = {}
        self.avoids: dict[tuple[str, str], int] = {}
        self.volsels: dict[str, int] = {}        # canon json -> vsid
        self.volsel_attrs: list[list] = []       # vsid -> parsed terms
        self.pending_volsel_refresh: list[int] = []
        self.dirty_attach_attrs = False          # attach_type rows changed
        # pod-selector universe: (namespaces, canonical selector) -> qid
        self.podsels: dict[tuple, int] = {}
        self.podsel_attrs: list[tuple] = []          # qid -> (ns_key, canon)
        # carried-term universe: (qid, tkey_code, weight, kind, poison) -> eid
        self.terms: dict[tuple, int] = {}
        self.term_attrs: list[tuple] = []            # eid -> same tuple
        # terms interned after nodes were encoded: columns awaiting refill
        self.pending_sel_refresh: list[tuple[int, str, str]] = []
        self.pending_req_refresh: list[tuple[int, str, str, tuple[str, ...]]] = []
        self.pending_podsel_refresh: list[int] = []  # qids needing pod refills
        self.pending_topo_refresh: list[int] = []    # topo slots needing refills
        self.dirty_term_attrs = False                # term attr arrays changed
        # per-row source data for refills on universe growth
        self.labels_of: list[dict[str, str] | None] = [None] * caps.num_nodes
        # topology interning: slot -> (domain value -> id); key -> slot
        self.domains: list[dict] = [dict() for _ in range(caps.topology_slots)]
        self.topo_key_of: dict[str, int] = {k: i for i, k in enumerate(TOPOLOGY_KEYS)}

    # ---- rows ----

    def assign_row(self, name: str) -> int:
        row = self.row_of.get(name)
        if row is None:
            if not self.free:
                raise CapacityError(
                    f"node capacity {self.caps.num_nodes} exhausted adding {name!r}")
            row = self.free.pop()
            self.row_of[name] = row
            self.name_of[row] = name
        return row

    def release_row(self, name: str) -> int:
        row = self.row_of.pop(name)
        self.name_of[row] = None
        self.labels_of[row] = None
        self.free.append(row)
        return row

    def bump(self, row: int) -> None:
        self._gen_counter += 1
        self.generation[row] = self._gen_counter

    # ---- universes ----

    def intern_sel_term(self, key: str, value: str) -> int:
        """Intern a selector term; newly seen terms are queued in
        `pending_sel_refresh` for a membership-column refill
        (apply_pending_refreshes)."""
        term = (key, value)
        tid = self.sel_terms.get(term)
        if tid is not None:
            return tid
        if len(self.sel_terms) >= self.caps.selector_universe:
            raise CapacityError(
                f"selector universe {self.caps.selector_universe} exhausted "
                f"interning {term!r}")
        tid = len(self.sel_terms)
        self.sel_terms[term] = tid
        self.pending_sel_refresh.append((tid, key, value))
        return tid

    def intern_requirement(self, key: str, op: str, values) -> int:
        """Intern a NodeSelectorRequirement (values canonicalized by sorting —
        In/NotIn set semantics). Newly seen requirements queue a membership
        refill in `pending_req_refresh`."""
        req = (key, op, tuple(sorted(values)))
        rid = self.reqs.get(req)
        if rid is not None:
            return rid
        if len(self.reqs) >= self.caps.req_universe:
            raise CapacityError(
                f"requirement universe {self.caps.req_universe} exhausted "
                f"interning {req!r}")
        rid = len(self.reqs)
        self.reqs[req] = rid
        self.pending_req_refresh.append((rid, *req))
        return rid

    def intern_taint(self, taint) -> int:
        key = (taint.key, taint.value, taint.effect)
        tid = self.taints.get(key)
        if tid is not None:
            return tid
        if len(self.taints) >= self.caps.taint_universe:
            raise CapacityError(
                f"taint universe {self.caps.taint_universe} exhausted "
                f"interning {key!r}")
        tid = len(self.taints)
        self.taints[key] = tid
        return tid

    def intern_port(self, port: int) -> int:
        pid = self.ports.get(port)
        if pid is not None:
            return pid
        if len(self.ports) >= self.caps.port_universe:
            raise CapacityError(
                f"port universe {self.caps.port_universe} exhausted "
                f"interning {port}")
        pid = len(self.ports)
        self.ports[port] = pid
        return pid

    def intern_domain(self, key_idx: int, value) -> int:
        from kubernetes_tpu.state.layout import TOPO_HOSTNAME

        table = self.domains[key_idx]
        did = table.get(value)
        if did is None:
            did = len(table)
            # hostname-slot domains are per-node (unbounded by design); all
            # other slots must fit the device domain axis
            if key_idx != TOPO_HOSTNAME and did >= self.caps.domain_universe:
                raise CapacityError(
                    f"domain universe {self.caps.domain_universe} exhausted "
                    f"for topology slot {key_idx} interning {value!r}")
            table[value] = did
        return did

    def intern_topo_key(self, key: str) -> int:
        """Intern a custom topology key from an affinity term; newly seen keys
        queue a topology-column refill."""
        slot = self.topo_key_of.get(key)
        if slot is not None:
            return slot
        from kubernetes_tpu.state.layout import FIRST_CUSTOM_TOPO

        # next free slot after the defaults and the virtual composite slot
        # (slot 3 is TOPO_ZONE_REGION, never present in topo_key_of)
        slot = max(max(self.topo_key_of.values()) + 1, FIRST_CUSTOM_TOPO)
        if slot >= self.caps.topology_slots:
            raise CapacityError(
                f"topology slots {self.caps.topology_slots} exhausted "
                f"interning key {key!r}")
        self.topo_key_of[key] = slot
        self.pending_topo_refresh.append(slot)
        return slot

    def tkey_code(self, key: str, *, required: bool) -> int:
        """Map a term's topologyKey to a device code: a topo slot, or
        TKEY_INVALID (empty key on a required term fails everywhere,
        predicates.go:1014,1162), or TKEY_DEFAULT_UNION (empty key on a
        preferred term matches any default failure domain,
        priorityutil.Topologies)."""
        from kubernetes_tpu.state.layout import TKEY_DEFAULT_UNION, TKEY_INVALID

        if not key:
            return TKEY_INVALID if required else TKEY_DEFAULT_UNION
        try:
            return self.intern_topo_key(key)
        except CapacityError:
            if required:
                raise
            return TKEY_INVALID

    def intern_podsel(self, ns_key: frozenset, canon) -> int:
        entry = (ns_key, canon)
        qid = self.podsels.get(entry)
        if qid is not None:
            return qid
        if len(self.podsels) >= self.caps.podsel_universe:
            raise CapacityError(
                f"pod-selector universe {self.caps.podsel_universe} exhausted")
        qid = len(self.podsels)
        self.podsels[entry] = qid
        self.podsel_attrs.append(entry)
        self.pending_podsel_refresh.append(qid)
        # cached pod rows carry pod_matches_q over the old universe: a new
        # entry invalidates them (they may match it)
        self.pod_row_epoch += 1
        return qid

    def intern_term(self, qid: int, tkey_code: int, weight: float, kind: int,
                    poison: bool) -> int:
        entry = (qid, tkey_code, float(weight), int(kind), bool(poison))
        eid = self.terms.get(entry)
        if eid is not None:
            return eid
        if len(self.terms) >= self.caps.term_universe:
            raise CapacityError(
                f"carried-term universe {self.caps.term_universe} exhausted")
        eid = len(self.terms)
        self.terms[entry] = eid
        self.term_attrs.append(entry)
        self.dirty_term_attrs = True
        return eid

    def intern_vol_atom(self, atom: tuple) -> int:
        vid = self.vol_atoms.get(atom)
        if vid is not None:
            return vid
        if len(self.vol_atoms) >= self.caps.volume_universe:
            raise CapacityError(
                f"volume universe {self.caps.volume_universe} exhausted "
                f"interning {atom!r}")
        vid = len(self.vol_atoms)
        self.vol_atoms[atom] = vid
        return vid

    def intern_attach_atom(self, vtype: int, atom: tuple) -> int:
        aid = self.attach_atoms.get(atom)
        if aid is not None:
            return aid
        if len(self.attach_atoms) >= self.caps.attach_universe:
            raise CapacityError(
                f"attach universe {self.caps.attach_universe} exhausted "
                f"interning {atom!r}")
        aid = len(self.attach_atoms)
        self.attach_atoms[atom] = aid
        self.attach_types[aid] = vtype
        self.dirty_attach_attrs = True
        return aid

    def intern_image(self, name: str) -> int:
        iid = self.images.get(name)
        if iid is not None:
            return iid
        if len(self.images) >= self.caps.image_universe:
            raise CapacityError(
                f"image universe {self.caps.image_universe} exhausted "
                f"interning {name!r}")
        iid = len(self.images)
        self.images[name] = iid
        return iid

    def intern_avoid(self, sig: tuple[str, str]) -> int:
        oid = self.avoids.get(sig)
        if oid is not None:
            return oid
        if len(self.avoids) >= self.caps.avoid_universe:
            raise CapacityError(
                f"avoid universe {self.caps.avoid_universe} exhausted "
                f"interning {sig!r}")
        oid = len(self.avoids)
        self.avoids[sig] = oid
        self.pod_row_epoch += 1
        return oid

    def intern_volsel(self, terms: list) -> int:
        from kubernetes_tpu.state.volumes import node_selector_canon

        canon = node_selector_canon(terms)
        vsid = self.volsels.get(canon)
        if vsid is not None:
            return vsid
        if len(self.volsels) >= self.caps.volsel_universe:
            raise CapacityError(
                f"volume-selector universe {self.caps.volsel_universe} "
                f"exhausted")
        vsid = len(self.volsels)
        self.volsels[canon] = vsid
        self.volsel_attrs.append(terms)
        self.pending_volsel_refresh.append(vsid)
        return vsid

    def vol_rows(self, pod) -> tuple[np.ndarray, np.ndarray]:
        """(any, rw) conflict-atom count rows for one pod's volumes."""
        from kubernetes_tpu.state.volumes import pod_conflict_atoms

        any_row = np.zeros((self.caps.volume_universe,), np.float32)
        rw_row = np.zeros((self.caps.volume_universe,), np.float32)
        for atom, read_only in pod_conflict_atoms(pod):
            vid = self.intern_vol_atom(atom)
            any_row[vid] += 1.0
            if not read_only:
                rw_row[vid] += 1.0
        return any_row, rw_row

    def attach_row(self, pod, ctx, permissive: bool = False) -> np.ndarray:
        """0/1 attach-atom row for one pod (unique per pod by construction,
        mirroring the per-pod filteredVolumes set, predicates.go:226)."""
        from kubernetes_tpu.state.volumes import pod_attach_atoms

        row = np.zeros((self.caps.attach_universe,), np.float32)
        for vtype, atom in pod_attach_atoms(pod, ctx, permissive=permissive):
            row[self.intern_attach_atom(vtype, atom)] = 1.0
        return row

    def port_onehot(self, ports: Iterable[int]) -> np.ndarray:
        out = np.zeros((self.caps.port_universe,), np.float32)
        for port in ports:
            out[self.intern_port(port)] += 1.0
        return out


def _fill_node_row(state: ClusterState, table: NodeTable, row: int, node: Node) -> None:
    state.valid[row] = True
    state.allocatable[row] = resource_rows(node.status.effective_allocatable())
    state.conditions[row] = condition_mask(node)
    lo, hi = hash_lanes(node.metadata.name)
    state.name_lo[row], state.name_hi[row] = lo, hi

    labels = dict(node.metadata.labels)
    table.labels_of[row] = labels
    # membership against every interned selector term / requirement
    state.sel_member[row] = 0.0
    for (k, v), tid in table.sel_terms.items():
        if labels.get(k) == v:
            state.sel_member[row, tid] = 1.0
    state.req_member[row] = 0.0
    for (k, op, values), rid in table.reqs.items():
        if match_requirement(labels, k, op, values):
            state.req_member[row, rid] = 1.0

    # taints: intern and set membership + universe attributes
    state.taint_hard_member[row] = 0.0
    state.taint_prefer_member[row] = 0.0
    for t in node.spec.taints:
        tid = table.intern_taint(t)
        state.taint_u_key[tid] = hash32(t.key)
        val_lo, val_hi = hash_lanes(t.value)
        state.taint_u_val_lo[tid] = val_lo
        state.taint_u_val_hi[tid] = val_hi
        effect = Effect.NAMES.get(t.effect, Effect.NONE)
        state.taint_u_effect[tid] = effect
        if effect in (Effect.NO_SCHEDULE, Effect.NO_EXECUTE):
            state.taint_hard_member[row, tid] = 1.0
        elif effect == Effect.PREFER_NO_SCHEDULE:
            state.taint_prefer_member[row, tid] = 1.0

    # container images present on the node (ImageLocalityPriority source,
    # node.Status.Images, image_locality.go:71-80)
    state.img_size[row] = 0.0
    for image in node.status.images:
        size = float(image.get("sizeBytes") or 0)
        for img_name in image.get("names") or []:
            state.img_size[row, table.intern_image(img_name)] = size

    # preferAvoidPods signatures (NodePreferAvoidPodsPriority source)
    from kubernetes_tpu.state.volumes import parse_avoid_signatures

    state.avoid_member[row] = 0.0
    for sig in parse_avoid_signatures(node.metadata.annotations):
        state.avoid_member[row, table.intern_avoid(sig)] = 1.0

    # PV node-affinity selector membership (NoVolumeNodeConflict)
    from kubernetes_tpu.state.volumes import node_selector_matches

    state.volsel_member[row] = 0.0
    for canon, vsid in table.volsels.items():
        if node_selector_matches(table.volsel_attrs[vsid], labels):
            state.volsel_member[row, vsid] = 1.0

    state.topology[row] = -1
    from kubernetes_tpu.state.layout import TOPO_HOSTNAME, TOPO_ZONE_REGION

    for key, slot in table.topo_key_of.items():
        val = labels.get(key)
        if slot == TOPO_HOSTNAME and val is None:
            val = node.metadata.name  # hostname domain defaults to node name
        if val is not None:
            state.topology[row, slot] = table.intern_domain(slot, val)
    # virtual (zone, region) composite domain for default-union
    # inclusion-exclusion (see layout.TOPO_ZONE_REGION)
    z = labels.get(TOPOLOGY_KEYS[1])
    r = labels.get(TOPOLOGY_KEYS[2])
    if z is not None and r is not None:
        state.topology[row, TOPO_ZONE_REGION] = table.intern_domain(
            TOPO_ZONE_REGION, (z, r))
    # virtual GetZoneKey domain (either half present) for zone-weighted
    # selector spreading (layout.TOPO_SPREAD_ZONE)
    from kubernetes_tpu.state.layout import TOPO_SPREAD_ZONE

    if z is not None or r is not None:
        state.topology[row, TOPO_SPREAD_ZONE] = table.intern_domain(
            TOPO_SPREAD_ZONE, (r or "", z or ""))


def apply_pending_refreshes(state: ClusterState, table: NodeTable) -> bool:
    """Fill membership columns for selector terms / requirements interned
    after nodes were encoded. Returns True if any column changed (device
    re-upload needed)."""
    changed = False
    for term_id, key, value in table.pending_sel_refresh:
        changed = True
        for row, labels in enumerate(table.labels_of):
            if labels is not None and labels.get(key) == value:
                state.sel_member[row, term_id] = 1.0
    table.pending_sel_refresh.clear()
    for rid, key, op, values in table.pending_req_refresh:
        changed = True
        for row, labels in enumerate(table.labels_of):
            if labels is not None and match_requirement(labels, key, op, values):
                state.req_member[row, rid] = 1.0
    table.pending_req_refresh.clear()
    # topology columns for custom keys interned after nodes were encoded
    if table.pending_topo_refresh:
        slot_key = {s: k for k, s in table.topo_key_of.items()}
        for slot in table.pending_topo_refresh:
            changed = True
            key = slot_key[slot]
            for row, labels in enumerate(table.labels_of):
                if labels is not None and key in labels:
                    state.topology[row, slot] = table.intern_domain(
                        slot, labels[key])
        table.pending_topo_refresh.clear()
    # PV node-affinity selector columns interned after nodes were encoded
    if table.pending_volsel_refresh:
        from kubernetes_tpu.state.volumes import node_selector_matches

        for vsid in table.pending_volsel_refresh:
            changed = True
            terms = table.volsel_attrs[vsid]
            for row, labels in enumerate(table.labels_of):
                if labels is not None and node_selector_matches(terms, labels):
                    state.volsel_member[row, vsid] = 1.0
        table.pending_volsel_refresh.clear()
    # attach-atom type attributes (tiny, replicated)
    if table.dirty_attach_attrs:
        changed = True
        for aid, vtype in table.attach_types.items():
            state.attach_type[aid] = vtype
        table.dirty_attach_attrs = False
    # carried-term attribute rows (tiny, replicated)
    if table.dirty_term_attrs:
        changed = True
        for eid, (qid, tk, w, kind, poison) in enumerate(table.term_attrs):
            state.term_q[eid] = qid
            state.term_tkey[eid] = tk
            state.term_weight[eid] = w
            state.term_kind[eid] = kind
            state.term_poison[eid] = poison
        table.dirty_term_attrs = False
    return changed


def pod_requests(pod: Pod) -> np.ndarray:
    """Sum of container requests in device units, +1 pod slot (reference
    GetResourceRequest, predicates.go; pods row mirrors the
    len(nodeInfo.Pods())+1 > allowedPodNumber check at predicates.go:561)."""
    out = np.zeros((Resource.COUNT,), np.float32)
    for c in pod.spec.containers:
        out += resource_rows(c.requests)
    out[Resource.PODS] = 1.0
    return out


def pod_nonzero_requests(pod: Pod) -> np.ndarray:
    """(cpu_milli, mem_mib) with per-container defaults for scoring (reference
    priorities/util/non_zero.go GetNonzeroRequests)."""
    from kubernetes_tpu.state.layout import (
        DEFAULT_NONZERO_CPU_MILLI,
        DEFAULT_NONZERO_MEM_MIB,
    )

    cpu = 0.0
    mem = 0.0
    for c in pod.spec.containers:
        c_rows = resource_rows(c.requests)
        cpu += c_rows[Resource.CPU] if c_rows[Resource.CPU] > 0 else DEFAULT_NONZERO_CPU_MILLI
        mem += c_rows[Resource.MEMORY] if c_rows[Resource.MEMORY] > 0 else DEFAULT_NONZERO_MEM_MIB
    return np.array([cpu, mem], np.float32)


def intern_pod_affinity_terms(table: NodeTable, pod: Pod):
    """Intern every pod-affinity term a pod carries into the selector and
    carried-term universes. Returns (carried eids, parsed terms)."""
    from kubernetes_tpu.state.layout import TermKind
    from kubernetes_tpu.state.podaffinity import PARSE_ERROR, parse_pod_affinity

    terms = parse_pod_affinity(pod.spec.affinity, pod.metadata.namespace)
    eids: list[int] = []
    for kind, lst, required in (
        (TermKind.ANTI_REQ, terms.anti_req, True),
        (TermKind.AFF_REQ, terms.aff_req, True),
        (TermKind.AFF_PREF, terms.aff_pref, False),
        (TermKind.ANTI_PREF, terms.anti_pref, False),
    ):
        for t in lst:
            qid = table.intern_podsel(t.namespaces, t.selector)
            tk = table.tkey_code(t.topology_key, required=required)
            if kind == TermKind.AFF_PREF:
                w = float(t.weight)
            elif kind == TermKind.ANTI_PREF:
                w = -float(t.weight)
            else:
                w = 0.0
            # a required anti term whose selector cannot be parsed poisons
            # scheduling for every incoming pod while a carrier exists
            # (getMatchingAntiAffinityTerms error path, predicates.go:1108)
            poison = kind == TermKind.ANTI_REQ and t.selector == PARSE_ERROR
            eids.append(table.intern_term(qid, tk, w, kind, poison))
    return eids, terms


def pod_match_row(table: NodeTable, pod: Pod) -> np.ndarray:
    """f32[UQ]: which pod-selector-universe entries this pod matches
    (PodMatchesTermsNamespaceAndSelector against every interned entry)."""
    from kubernetes_tpu.state.podaffinity import pod_matches_entry

    out = np.zeros((table.caps.podsel_universe,), np.float32)
    for qid, (ns_key, canon) in enumerate(table.podsel_attrs):
        if pod_matches_entry(pod, ns_key, canon):
            out[qid] = 1.0
    return out


def carried_term_row(table: NodeTable, eids) -> np.ndarray:
    """f32[UE]: carried-term multiplicity row for one pod."""
    out = np.zeros((table.caps.term_universe,), np.float32)
    for e in eids:
        out[e] += 1.0
    return out


def add_pod_to_state(state: ClusterState, table: NodeTable, pod: Pod, row: int,
                     ctx=None) -> None:
    """Account an assigned pod against a node row (the analog of
    NodeInfo.addPod, node_info.go:171).

    NOTE on ordering: this matches the pod against the selector universe as
    interned *now* — when batch-encoding fixtures, intern every pod's terms
    (intern_pod_affinity_terms) before accounting any pod, or counts for
    later-interned selectors will miss earlier pods. Incremental flows
    (StateDB) refill via pending_podsel_refresh instead."""
    from kubernetes_tpu.state.volumes import EMPTY_CONTEXT

    state.requested[row] += pod_requests(pod)
    state.nonzero_requested[row] += pod_nonzero_requests(pod)
    state.port_count[row] += table.port_onehot(pod.host_ports())
    eids, _ = intern_pod_affinity_terms(table, pod)
    state.term_count[row] += carried_term_row(table, eids)
    state.podsel_count[row] += pod_match_row(table, pod)
    if pod.spec.volumes:
        any_row, rw_row = table.vol_rows(pod)
        state.vol_any[row] += any_row
        state.vol_rw[row] += rw_row
        # permissive: a bound pod's broken claim skips only that volume (the
        # reference would error the whole scheduling attempt for every
        # incoming pod, predicates.go:302 — a poisoned-node state not worth
        # reproducing)
        state.attach_count[row] += table.attach_row(
            pod, ctx or EMPTY_CONTEXT, permissive=True)
    table.bump(row)


def encode_nodes(
    nodes: Iterable[Node],
    caps: Capacities,
    assigned_pods: Sequence[Pod] = (),
    table: NodeTable | None = None,
    ctx=None,
) -> tuple[ClusterState, NodeTable]:
    """Full (re-)encode: the List half of list+watch. Incremental updates go
    through `statedb.StateDB` which touches only changed rows/columns.

    Pass an existing `table` to keep universe ids stable across re-encodes
    (so previously encoded pod batches stay valid)."""
    state = empty_state(caps)
    if table is not None:
        # relist semantics: rows for departed nodes are released
        node_list = list(nodes)
        names = {n.metadata.name for n in node_list}
        for gone in [n for n in table.row_of if n not in names]:
            table.release_row(gone)
        nodes = node_list
    table = table or NodeTable(caps)
    # re-materialize universe taint and term attributes when reusing a table
    for (key, value, effect), tid in table.taints.items():
        state.taint_u_key[tid] = hash32(key)
        val_lo, val_hi = hash_lanes(value)
        state.taint_u_val_lo[tid] = val_lo
        state.taint_u_val_hi[tid] = val_hi
        state.taint_u_effect[tid] = Effect.NAMES.get(effect, Effect.NONE)
    if table.term_attrs:
        table.dirty_term_attrs = True
    for aid, vtype in table.attach_types.items():
        state.attach_type[aid] = vtype
    for node in nodes:
        row = table.assign_row(node.metadata.name)
        _fill_node_row(state, table, row, node)
        table.bump(row)
    # intern every assigned pod's terms before accounting any, so selector
    # counts are complete regardless of order (see add_pod_to_state)
    bound = [p for p in assigned_pods if p.spec.node_name]
    for pod in bound:
        intern_pod_affinity_terms(table, pod)
    for pod in bound:
        row = table.row_of.get(pod.spec.node_name)
        if row is None:
            continue  # pod bound to an unknown node: ignored, like cache misses
        add_pod_to_state(state, table, pod, row, ctx=ctx)
    return state, table
