"""Host-side volume analysis: pod volumes -> interned device atoms.

The reference's volume predicates walk per-pod volume lists with string
comparisons (NoDiskConflict predicates.go:100-195, MaxPDVolumeCountChecker
:215-320, VolumeZoneChecker :395-470, VolumeNodeChecker :1345). Here every
distinct conflict identity becomes an integer "atom" in a small universe, the
node side carries per-atom usage counts, the pod side carries one-hot want
rows, and the predicates in ops/predicates.py reduce to one masked matmul
each.

Atom grammars:
- **Conflict atoms** (NoDiskConflict): GCE PD -> ("gce", pdName); AWS EBS ->
  ("aws", volumeID); ISCSI -> ("iscsi", iqn); RBD -> one atom per monitor
  ("rbd", monitor, pool, image) — set-overlap of monitors (haveSame,
  predicates.go:887) is exactly one-hot overlap of per-monitor atoms. Each
  atom carries a read-only flag; a conflict needs a read-write party except
  for AWS EBS, which conflicts regardless (predicates.go:121-128), so EBS
  atoms are always read-write.
- **Attach atoms** (MaxPDVolumeCount): (VolType, cloud volume id), resolved
  through PVC -> PV when the volume is claim-backed. Lookup misses produce a
  deterministic synthetic atom unique per (pod, claim) with VolType.ANY
  (the reference generates a random ID and counts it toward the limit,
  predicates.go:240-268).
- **Zone terms** (VolumeZone): bound PV labels for zone/region become
  (key, value) selector-universe terms the node must carry — the reference
  compares raw label strings (predicates.go:461-470).
- **Volume node selectors** (VolumeNode): a bound PV's node-affinity
  annotation becomes one interned selector whose per-node membership is
  evaluated host-side (volume.alpha.kubernetes.io/node-affinity, mirrors
  pkg/volume/util.go CheckNodeAffinity).

Resolution errors (unnamed claim, unbound claim, missing PV for zone checks)
mirror the reference's error returns: the predicate fails for the pod
everywhere, surfaced as per-pod fail bits.
"""

from __future__ import annotations

from kubernetes_tpu.api.objects import PersistentVolume, Pod
from kubernetes_tpu.state.layout import TOPOLOGY_KEYS, VolType

ZONE_LABELS = (TOPOLOGY_KEYS[1], TOPOLOGY_KEYS[2])  # zone, region

# PV annotation carrying alpha node affinity (reference
# v1.AlphaStorageNodeAffinityAnnotation, pkg/api/v1/types.go).
NODE_AFFINITY_ANNOTATION = "volume.alpha.kubernetes.io/node-affinity"


class VolumeError(Exception):
    """Unresolvable volume reference — the analog of a predicate returning a
    non-nil error (fails the pod's scheduling attempt)."""


# The claim-resolution half of the shared encode context (state/context.py).
from kubernetes_tpu.state.context import EMPTY_CONTEXT, EncodeContext  # noqa: E402,F401

VolumeContext = EncodeContext


def conflict_atoms(volume: dict) -> list[tuple[tuple, bool]]:
    """[(atom, read_only)] for one raw v1 Volume (isVolumeConflict,
    predicates.go:100-147)."""
    gce = volume.get("gcePersistentDisk")
    if gce is not None:
        return [(("gce", gce.get("pdName", "")), bool(gce.get("readOnly")))]
    aws = volume.get("awsElasticBlockStore")
    if aws is not None:
        # EBS conflicts regardless of read-only (predicates.go:121-125)
        return [(("aws", aws.get("volumeID", "")), False)]
    iscsi = volume.get("iscsi")
    if iscsi is not None:
        return [(("iscsi", iscsi.get("iqn", "")), bool(iscsi.get("readOnly")))]
    rbd = volume.get("rbd")
    if rbd is not None:
        pool = rbd.get("pool") or "rbd"
        image = rbd.get("image", "")
        ro = bool(rbd.get("readOnly"))
        return [(("rbd", mon, pool, image), ro)
                for mon in rbd.get("monitors") or []]
    return []


def pod_conflict_atoms(pod: Pod) -> list[tuple[tuple, bool]]:
    out = []
    for vol in pod.spec.volumes:
        out.extend(conflict_atoms(vol))
    return out


_FILTERS = (
    (VolType.EBS, "awsElasticBlockStore", "volumeID"),
    (VolType.GCE, "gcePersistentDisk", "pdName"),
    (VolType.AZURE, "azureDisk", "diskName"),
)


def _direct_attach_atom(volume: dict) -> tuple[int, tuple] | None:
    for vtype, key, id_field in _FILTERS:
        src = volume.get(key)
        if src is not None:
            return (vtype, (key, src.get(id_field, "")))
    return None


def _resolve_pvc(namespace: str, volume: dict, ctx: VolumeContext):
    """Resolve a claim-backed volume to its PV. Returns (pv | None, claim).
    Raises VolumeError for the reference's hard-error cases; returns
    pv=None for lookup misses (the permissive paths)."""
    claim = volume["persistentVolumeClaim"]
    pvc_name = claim.get("claimName", "")
    if not pvc_name:
        raise VolumeError("PersistentVolumeClaim had no name")
    pvc = ctx.get_pvc(namespace, pvc_name)
    if pvc is None:
        return None, pvc_name  # not found: permissive for attach counting
    pv_name = pvc.volume_name
    if not pv_name:
        raise VolumeError(f"PersistentVolumeClaim is not bound: {pvc_name!r}")
    return ctx.get_pv(pv_name), pvc_name


def pod_attach_atoms(pod: Pod, ctx: VolumeContext,
                     permissive: bool = False) -> list[tuple[int, tuple]]:
    """Unique (VolType, atom) list for MaxPDVolumeCount. Raises VolumeError
    on the reference's error paths (unnamed/unbound claims); with
    `permissive`, erroring volumes are skipped instead — used when counting
    already-bound pods, where a broken claim must not zero the node's whole
    attach row."""
    atoms: dict[tuple, int] = {}
    for idx, vol in enumerate(pod.spec.volumes):
        direct = _direct_attach_atom(vol)
        if direct is not None:
            vtype, atom = direct
            atoms[atom] = vtype
            continue
        if "persistentVolumeClaim" not in vol:
            continue
        try:
            pv, pvc_name = _resolve_pvc(pod.metadata.namespace, vol, ctx)
        except VolumeError:
            if permissive:
                continue
            raise
        if pv is None:
            # PVC not found: one synthetic atom per (pod, volume slot) that
            # counts toward every filter (predicates.go:240-268 random IDs —
            # deterministic here, same multiplicity)
            atoms[("missing", pod.metadata.namespace, pvc_name,
                   pod.metadata.uid, idx)] = VolType.ANY
            continue
        direct = _direct_attach_atom(pv.spec)
        if direct is not None:
            vtype, atom = direct
            atoms[atom] = vtype
    return [(vtype, atom) for atom, vtype in atoms.items()]


def pod_zone_terms(pod: Pod, ctx: VolumeContext) -> list[tuple[str, str]]:
    """(label key, value) constraints from bound PVs' zone/region labels
    (VolumeZoneChecker predicate body, predicates.go:430-470). Raises
    VolumeError when a claim chain cannot be resolved — VolumeZone treats
    every miss as a hard error (predicates.go:440-458)."""
    terms: list[tuple[str, str]] = []
    for vol in pod.spec.volumes:
        if "persistentVolumeClaim" not in vol:
            continue
        pv, pvc_name = _resolve_pvc(pod.metadata.namespace, vol, ctx)
        if pv is None:
            raise VolumeError(
                f"PersistentVolumeClaim or PV not found: {pvc_name!r}")
        for k, v in pv.metadata.labels.items():
            if k in ZONE_LABELS:
                terms.append((k, v))
    return terms


def parse_volume_node_selector(pv: PersistentVolume) -> list | None:
    """NodeSelectorTerms from the PV's alpha node-affinity annotation, or
    None when absent (mirrors GetStorageNodeAffinityFromAnnotation +
    CheckNodeAffinity, pkg/volume/util.go)."""
    import json

    raw = pv.metadata.annotations.get(NODE_AFFINITY_ANNOTATION)
    if not raw:
        return None
    try:
        affinity = json.loads(raw)
    except ValueError as exc:
        raise VolumeError(f"bad node-affinity annotation on PV "
                          f"{pv.metadata.name!r}: {exc}")
    required = (affinity or {}).get(
        "requiredDuringSchedulingIgnoredDuringExecution")
    if not required:
        return None
    return [t.get("matchExpressions") or []
            for t in required.get("nodeSelectorTerms") or []]


def node_selector_canon(terms: list) -> str:
    """Stable interning key for a NodeSelector (list of OR-terms)."""
    import json

    return json.dumps(terms, sort_keys=True, separators=(",", ":"))


def node_selector_matches(terms: list, labels: dict[str, str]) -> bool:
    """OR over terms, AND over each term's requirements; invalid
    requirements make their term match nothing (nodeMatchesNodeSelectorTerms
    semantics, predicates.go:625-660)."""
    from kubernetes_tpu.state.cluster_state import match_requirement
    from kubernetes_tpu.state.pod_batch import _valid_requirement

    for exprs in terms:
        if not exprs:
            continue  # empty term matches nothing
        if any(not _valid_requirement(e) for e in exprs):
            continue
        if all(match_requirement(labels, e.get("key", ""), e["operator"],
                                 tuple(e.get("values") or ())) for e in exprs):
            return True
    return False


def pod_volume_node_selectors(pod: Pod, ctx: VolumeContext) -> list[list]:
    """NodeSelector term-lists the node must satisfy, one per constrained
    bound PV (NoVolumeNodeConflict; empty when the feature gate is off,
    predicates.go:1355-1357)."""
    if not ctx.local_volumes_enabled:
        return []
    selectors: list[list] = []
    for vol in pod.spec.volumes:
        if "persistentVolumeClaim" not in vol:
            continue
        pv, pvc_name = _resolve_pvc(pod.metadata.namespace, vol, ctx)
        if pv is None:
            raise VolumeError(
                f"PersistentVolumeClaim or PV not found: {pvc_name!r}")
        terms = parse_volume_node_selector(pv)
        if terms is not None:
            selectors.append(terms)
    return selectors


# ---- preferAvoidPods (NodePreferAvoidPodsPriority, used in M1b) ----

AVOID_PODS_ANNOTATION = "scheduler.alpha.kubernetes.io/preferAvoidPods"


def parse_avoid_signatures(annotations: dict[str, str]) -> list[tuple[str, str]]:
    """[(kind, uid)] signatures from the node's preferAvoidPods annotation
    (GetAvoidPodsFromNodeAnnotations, pkg/api/v1/helper/helpers.go); parse
    failures yield no signatures (the priority treats them as schedulable,
    node_prefer_avoid_pods.go:47-50)."""
    import json

    raw = annotations.get(AVOID_PODS_ANNOTATION)
    if not raw:
        return []
    try:
        parsed = json.loads(raw)
    except ValueError:
        return []
    out = []
    for entry in (parsed or {}).get("preferAvoidPods") or []:
        ctrl = ((entry.get("podSignature") or {}).get("podController") or {})
        kind = ctrl.get("kind", "")
        uid = ctrl.get("uid", "")
        if kind and uid:
            out.append((kind, uid))
    return out


def pod_controller_ref(pod: Pod) -> tuple[str, str] | None:
    """(kind, uid) of the pod's controller owner if it is an RC or RS
    (GetControllerRef + kind filter, node_prefer_avoid_pods.go:35-43)."""
    for ref in pod.metadata.owner_references:
        if ref.get("controller"):
            kind = ref.get("kind", "")
            if kind in ("ReplicationController", "ReplicaSet"):
                return (kind, ref.get("uid", ""))
            return None
    return None
