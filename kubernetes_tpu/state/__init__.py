from kubernetes_tpu.state.layout import Capacities, Resource  # noqa: F401
from kubernetes_tpu.state.cluster_state import (  # noqa: F401
    ClusterState,
    NodeTable,
    encode_nodes,
)
from kubernetes_tpu.state.pod_batch import (  # noqa: F401
    PodBatch,
    encode_cluster,
    encode_pods,
)
