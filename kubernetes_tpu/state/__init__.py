from kubernetes_tpu.state.layout import Capacities, Resource  # noqa: F401
from kubernetes_tpu.state.cluster_state import ClusterState, encode_nodes  # noqa: F401
from kubernetes_tpu.state.pod_batch import PodBatch, encode_pods  # noqa: F401
