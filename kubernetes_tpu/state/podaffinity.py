"""Host-side pod-affinity term parsing, canonicalization and matching.

The irregular half of inter-pod (anti-)affinity (reference
plugin/pkg/scheduler/algorithm/predicates/predicates.go:982-1240 and
priorities/interpod_affinity.go): v1 `PodAffinityTerm`s carry a
`metav1.LabelSelector` plus a namespace list plus a topology key. All string
work happens here on the host — selectors are canonicalized and interned into
the pod-selector universe (cluster_state.NodeTable), pods are matched against
universe entries when encoded or accounted, and the device only ever sees
integer ids, one-hot match rows and per-node/per-domain counts.

Semantics mirrored:
- `metav1.LabelSelectorAsSelector`: nil selector -> labels.Nothing (matches
  no pods); empty selector -> labels.Everything; matchLabels entries become
  In requirements; only In/NotIn/Exists/DoesNotExist are legal operators.
- `priorityutil.GetNamespacesFromPodAffinityTerm`: an empty namespace list
  means the namespace of the pod *carrying* the term.
- `priorityutil.PodMatchesTermsNamespaceAndSelector`: namespace membership
  AND selector match.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from kubernetes_tpu.api.objects import Pod

# Canonical selector forms:
#   NOTHING          — nil selector, matches no pods
#   PARSE_ERROR      — invalid selector, poisons the carrying term
#   ()               — empty selector, matches everything
#   ((key, op, values), ...) — conjunction of requirements
#   (UNION, (canon, ...))    — disjunction (SelectorSpread's match-any over
#                              controller selectors, selector_spreading.go:123)
NOTHING = "<nothing>"
PARSE_ERROR = "<error>"
UNION = "<union>"

_SEL_OPS = ("In", "NotIn", "Exists", "DoesNotExist")


def canonical_selector(selector: dict | None):
    """Canonicalize a metav1.LabelSelector dict."""
    if selector is None:
        return NOTHING
    reqs = []
    for k in sorted(selector.get("matchLabels") or {}):
        reqs.append((k, "In", (selector["matchLabels"][k],)))
    for e in selector.get("matchExpressions") or []:
        op = e.get("operator", "")
        values = tuple(sorted(e.get("values") or ()))
        if op not in _SEL_OPS:
            return PARSE_ERROR
        if op in ("In", "NotIn") and not values:
            return PARSE_ERROR
        if op in ("Exists", "DoesNotExist") and values:
            return PARSE_ERROR
        reqs.append((e.get("key", ""), op, values))
    return tuple(sorted(reqs))


def union_selector(canons) -> tuple:
    """Canonical match-any disjunction over selector canons."""
    return (UNION, tuple(sorted(set(canons), key=repr)))


def map_selector(selector: dict) -> tuple:
    """Canonicalize a map-style selector (labels.SelectorFromSet — Service
    and RC spec.selector)."""
    return tuple(sorted((k, "In", (v,)) for k, v in selector.items()))


def selector_matches(canon, labels: dict[str, str]) -> bool:
    if canon == NOTHING or canon == PARSE_ERROR:
        return False
    if len(canon) == 2 and canon[0] == UNION:
        return any(selector_matches(c, labels) for c in canon[1])
    from kubernetes_tpu.state.cluster_state import match_requirement

    return all(match_requirement(labels, k, op, values)
               for k, op, values in canon)


@dataclass(frozen=True)
class ParsedTerm:
    """One PodAffinityTerm with namespaces resolved against its carrier."""

    selector: Any                 # canonical selector form
    namespaces: frozenset[str]
    topology_key: str             # "" = empty (meaning depends on term kind)
    weight: int = 0               # preferred terms only

    @property
    def universe_key(self):
        return (self.namespaces, self.selector)

    def matches_pod(self, pod: Pod) -> bool:
        return (pod.metadata.namespace in self.namespaces
                and selector_matches(self.selector, pod.metadata.labels))


def _parse_term(term: dict, carrier_namespace: str, weight: int = 0) -> ParsedTerm:
    namespaces = frozenset(term.get("namespaces") or [carrier_namespace])
    return ParsedTerm(
        selector=canonical_selector(term.get("labelSelector")),
        namespaces=namespaces,
        topology_key=term.get("topologyKey", "") or "",
        weight=weight,
    )


@dataclass
class PodAffinityTerms:
    """All four term lists of one pod, parsed."""

    aff_req: list[ParsedTerm]
    anti_req: list[ParsedTerm]
    aff_pref: list[ParsedTerm]
    anti_pref: list[ParsedTerm]

    @property
    def empty(self) -> bool:
        return not (self.aff_req or self.anti_req or self.aff_pref
                    or self.anti_pref)

    @property
    def has_required(self) -> bool:
        return bool(self.aff_req or self.anti_req)


def parse_pod_affinity(affinity: dict | None, carrier_namespace: str) -> PodAffinityTerms:
    """Extract the four PodAffinityTerm lists from a raw v1 Affinity dict
    (getPodAffinityTerms/getPodAntiAffinityTerms, predicates.go:1039-1063)."""
    aff = (affinity or {}).get("podAffinity") or {}
    anti = (affinity or {}).get("podAntiAffinity") or {}

    def required(src):
        return [_parse_term(t, carrier_namespace)
                for t in src.get("requiredDuringSchedulingIgnoredDuringExecution") or []]

    def preferred(src):
        return [_parse_term(p.get("podAffinityTerm") or {}, carrier_namespace,
                            weight=int(p.get("weight", 0)))
                for p in src.get("preferredDuringSchedulingIgnoredDuringExecution") or []]

    return PodAffinityTerms(
        aff_req=required(aff),
        anti_req=required(anti),
        aff_pref=preferred(aff),
        anti_pref=preferred(anti),
    )


def pod_matches_entry(pod: Pod, ns_key: frozenset, canon) -> bool:
    """PodMatchesTermsNamespaceAndSelector for a universe entry."""
    return (pod.metadata.namespace in ns_key
            and selector_matches(canon, pod.metadata.labels))
