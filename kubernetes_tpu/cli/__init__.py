# kubectl lives in kubernetes_tpu.cli.kubectl (no eager re-export: importing
# it here would shadow `python -m kubernetes_tpu.cli.kubectl` via runpy)
