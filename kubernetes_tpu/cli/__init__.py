from kubernetes_tpu.cli.kubectl import main  # noqa: F401
