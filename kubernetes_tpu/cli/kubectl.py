"""kubectl-style CLI over the HTTP apiserver.

The pkg/kubectl analog (reference cmd structure pkg/kubectl/cmd/cmd.go;
resource Builder/Visitor pipeline resource/builder.go:109; printers
pkg/printers) scoped to the verbs the framework's objects support:

    get  <resource> [name] [-n ns] [-o json|wide|name] [--all-namespaces]
    describe <resource> <name> [-n ns]
    create -f file.json|yaml  (or - for stdin)
    apply  -f file.json|yaml  (create-or-update by name)
    delete <resource> <name> [-n ns]
    scale  <workload> <name> --replicas=N
    bind   <pod> <node>          (the pods/binding subresource)
    logs/exec are runtime verbs: not applicable to a hollow runtime

Server address from --server or $KUBECTL_SERVER (default
http://127.0.0.1:8080). YAML input is accepted when PyYAML is available;
JSON always works (the reference's own wire format here).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from urllib.parse import urlsplit

from kubernetes_tpu.api.objects import Binding
from kubernetes_tpu.apiserver.http import RESOURCES, RemoteStore, decode_object
from kubernetes_tpu.apiserver.store import (
    AlreadyExists,
    Conflict,
    Expired,
    NotFound,
    TooManyRequests,
)
from kubernetes_tpu.apiserver.validation import ValidationError

# singular/short aliases -> plural resource (kubectl's RESTMapper role)
ALIASES = {
    "po": "pods", "pod": "pods",
    "no": "nodes", "node": "nodes",
    "svc": "services", "service": "services",
    "ep": "endpoints",
    "ev": "events", "event": "events",
    "pv": "persistentvolumes", "persistentvolume": "persistentvolumes",
    "pvc": "persistentvolumeclaims",
    "persistentvolumeclaim": "persistentvolumeclaims",
    "rc": "replicationcontrollers",
    "replicationcontroller": "replicationcontrollers",
    "rs": "replicasets", "replicaset": "replicasets",
    "sts": "statefulsets", "statefulset": "statefulsets",
    "deploy": "deployments", "deployment": "deployments",
    "job": "jobs",
    "ds": "daemonsets", "daemonset": "daemonsets",
    "cj": "cronjobs", "cronjob": "cronjobs",
    "sa": "serviceaccounts", "serviceaccount": "serviceaccounts",
    "cm": "configmaps", "configmap": "configmaps",
    "secret": "secrets",
    "ns": "namespaces", "namespace": "namespaces",
    "hpa": "horizontalpodautoscalers",
    "horizontalpodautoscaler": "horizontalpodautoscalers",
    "pdb": "poddisruptionbudgets",
    "poddisruptionbudget": "poddisruptionbudgets",
    "pg": "podgroups", "podgroup": "podgroups",
    "ng": "nodegroups", "nodegroup": "nodegroups",
    "dsp": "deschedulepolicies", "deschedulepolicy": "deschedulepolicies",
    "pc": "priorityclasses", "priorityclass": "priorityclasses",
    "quota": "resourcequotas", "resourcequota": "resourcequotas",
    "limits": "limitranges", "limitrange": "limitranges",
    "crd": "customresourcedefinitions",
    "customresourcedefinition": "customresourcedefinitions",
    "apiservice": "apiservices",
    "csr": "certificatesigningrequests",
    "certificatesigningrequest": "certificatesigningrequests",
    "role": "roles",
    "clusterrole": "clusterroles",
    "rolebinding": "rolebindings",
    "clusterrolebinding": "clusterrolebindings",
    "alertrule": "alertrules",
    "cluster": "clusters",
}


def resolve_resource(word: str) -> str:
    plural = ALIASES.get(word.lower(), word.lower())
    if plural not in RESOURCES:
        raise SystemExit(f"error: unknown resource type {word!r}")
    return plural


def load_manifest(path: str) -> list[dict]:
    raw = sys.stdin.read() if path == "-" else open(path).read()
    try:
        doc = json.loads(raw)
        return doc if isinstance(doc, list) else [doc]
    except json.JSONDecodeError:
        pass
    try:
        import yaml  # optional; baked into most images
    except ImportError:
        raise SystemExit("error: manifest is not JSON and PyYAML is "
                         "unavailable")
    try:
        return [d for d in yaml.safe_load_all(raw) if d]
    except yaml.YAMLError as e:
        raise SystemExit(f"error: cannot parse manifest: {e}")


def _age(obj) -> str:
    ts = obj.metadata.creation_timestamp
    if not ts:
        return "<unknown>"
    secs = max(0, int(time.time() - ts))
    if secs < 120:
        return f"{secs}s"
    if secs < 7200:
        return f"{secs // 60}m"
    return f"{secs // 3600}h"


def _row(kind: str, obj, wide: bool) -> list[str]:
    if kind == "Pod":
        row = [obj.metadata.name, obj.status.phase or "Pending", _age(obj)]
        if wide:
            row.append(obj.spec.node_name or "<none>")
            row.append(obj.status.nominated_node_name or "<none>")
        return row
    if kind == "Node":
        ready = next((c.status for c in obj.status.conditions
                      if c.type == "Ready"), "Unknown")
        status = {"True": "Ready", "False": "NotReady"}.get(
            ready, "NotReady" if obj.status.conditions else "Unknown")
        return [obj.metadata.name, status, _age(obj)]
    if kind in ("ReplicaSet", "ReplicationController", "StatefulSet",
                "Deployment"):
        status = obj.status or {}
        return [obj.metadata.name,
                f"{status.get('replicas', 0)}/{obj.replicas}",
                str(status.get("readyReplicas", 0)), _age(obj)]
    if kind == "Job":
        status = obj.status or {}
        return [obj.metadata.name,
                f"{status.get('succeeded', 0)}/{obj.completions}", _age(obj)]
    if kind == "Service":
        return [obj.metadata.name, _age(obj)]
    if kind == "Endpoints":
        addrs = [a.get("targetRef", {}).get("name", "?")
                 for s in obj.subsets for a in s.get("addresses", [])]
        return [obj.metadata.name, ",".join(addrs[:4])
                + ("..." if len(addrs) > 4 else ""), _age(obj)]
    if kind == "Event":
        return [obj.metadata.name, obj.type, obj.reason,
                str(getattr(obj, "count", 1)), obj.message[:60]]
    if kind == "PodGroup":
        status = obj.status or {}
        return [obj.metadata.name, obj.phase,
                f"{status.get('placed', 0)}/{obj.min_member}", _age(obj)]
    if kind == "PriorityClass":
        return [obj.metadata.name, str(obj.value),
                str(bool(obj.global_default)).lower(), _age(obj)]
    if kind == "NodeGroup":
        return [obj.metadata.name, str(obj.min_size), str(obj.max_size),
                str(obj.target_size), str(obj.ready_nodes), _age(obj)]
    if kind == "DeschedulePolicy":
        return [obj.metadata.name, str(obj.dry_run).lower(),
                str(obj.max_moves_per_cycle), str(obj.priority_cutoff),
                _age(obj)]
    if kind == "AlertRule":
        expr = obj.expr if len(obj.expr) <= 44 else obj.expr[:41] + "..."
        return [obj.metadata.name,
                "alert" if obj.alert else "record", expr,
                f"{obj.for_s:g}s" if obj.alert else "-", _age(obj)]
    if kind == "Cluster":
        alloc = obj.allocatable_capacity
        capacity = ",".join(alloc[r] for r in ("cpu", "memory")
                            if r in alloc) or "<unknown>"
        return [obj.metadata.name, str(obj.ready), capacity,
                _cluster_allocated(alloc, obj.free_capacity),
                ",".join(obj.zones) or "<none>", _age(obj)]
    return [obj.metadata.name, _age(obj)]


def _cluster_allocated(alloc: dict, free: dict) -> str:
    """allocatable minus free = what the member's bound pods hold."""
    from kubernetes_tpu.api.quantity import parse_quantity

    out = []
    for res, fmt in (("cpu", lambda f: f"{int(f * 1000)}m"),
                     ("memory", lambda f: f"{int(f / (1 << 20))}Mi")):
        if res not in alloc:
            continue
        try:
            used = parse_quantity(alloc[res]) - parse_quantity(
                free.get(res, "0"))
        except ValueError:
            continue
        out.append(fmt(max(0, used)))
    return ",".join(out) or "<unknown>"


HEADERS = {
    "Pod": ["NAME", "STATUS", "AGE"],
    "Pod-wide": ["NAME", "STATUS", "AGE", "NODE", "NOMINATED NODE"],
    "Node": ["NAME", "STATUS", "AGE"],
    "ReplicaSet": ["NAME", "REPLICAS", "READY", "AGE"],
    "ReplicationController": ["NAME", "REPLICAS", "READY", "AGE"],
    "StatefulSet": ["NAME", "REPLICAS", "READY", "AGE"],
    "Deployment": ["NAME", "REPLICAS", "READY", "AGE"],
    "Job": ["NAME", "COMPLETIONS", "AGE"],
    "Service": ["NAME", "AGE"],
    "Endpoints": ["NAME", "ADDRESSES", "AGE"],
    "Event": ["NAME", "TYPE", "REASON", "COUNT", "MESSAGE"],
    "PodGroup": ["NAME", "PHASE", "PLACED", "AGE"],
    "PriorityClass": ["NAME", "VALUE", "GLOBAL-DEFAULT", "AGE"],
    "NodeGroup": ["NAME", "MIN", "MAX", "TARGET", "READY", "AGE"],
    "DeschedulePolicy": ["NAME", "DRY-RUN", "MAX-MOVES", "CUTOFF", "AGE"],
    "AlertRule": ["NAME", "TYPE", "EXPR", "FOR", "AGE"],
    "Cluster": ["NAME", "READY", "CAPACITY", "ALLOCATED", "ZONES", "AGE"],
}


def print_table(rows: list[list[str]], headers: list[str]) -> None:
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(headers)]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    print(fmt.format(*headers))
    for r in rows:
        print(fmt.format(*r))


def _monitor_url(client) -> str | None:
    from kubernetes_tpu.obs.monitor import find_monitor_url

    return find_monitor_url(client)


def _monitor_get(url: str, path: str) -> dict | None:
    """GET {url}{path} from the published monitor; parsed JSON, or None
    when the monitor is unreachable / answers non-200."""
    import http.client

    u = urlsplit(url)
    try:
        conn = http.client.HTTPConnection(u.hostname, u.port or 80,
                                          timeout=5.0)
        conn.request("GET", path)
        resp = conn.getresponse()
        body = resp.read()
        conn.close()
        if resp.status != 200:
            return None
        return json.loads(body)
    except (OSError, ValueError):
        return None


def _monitor_query(url: str, expr: str) -> list[tuple[dict, float]] | None:
    """Instant-vector query against the monitor's /query endpoint."""
    from urllib.parse import quote

    doc = _monitor_get(url, f"/query?query={quote(expr)}")
    if not doc or doc.get("status") != "success":
        return None
    return [(d.get("labels", {}), d.get("value", 0.0))
            for d in doc.get("data", [])]


def _cmd_get_alerts(client, args) -> int:
    """`kubectl get alerts` — live alert state from the running monitor
    (not a store resource; the store holds AlertRule specs, the monitor
    holds which ones currently fire)."""
    url = _monitor_url(client)
    if url is None:
        print("error: no monitor is running (kube-system/monitor "
              "Endpoints not published); alert state lives in the "
              "monitor, not the store", file=sys.stderr)
        return 1
    doc = _monitor_get(url, "/alerts")
    if doc is None:
        print(f"error: monitor at {url} did not answer /alerts",
              file=sys.stderr)
        return 1
    if args.output == "json":
        print(json.dumps(doc, indent=2))
        return 0
    rows, now = [], time.time()
    for a in doc.get("alerts", []):
        labels = ",".join(f"{k}={v}"
                          for k, v in sorted(a.get("labels", {}).items()))
        since = a.get("since")
        value = a.get("value")
        rows.append([a.get("alert", "?"), a.get("state", "?"),
                     "<none>" if value is None else f"{value:g}",
                     labels or "<none>",
                     f"{max(0, int(now - since))}s" if since
                     else "<unknown>"])
    print_table(rows, ["NAME", "STATE", "VALUE", "LABELS", "SINCE"])
    return 0


def cmd_get(client, args) -> int:
    if getattr(args, "raw", ""):
        # `kubectl get --raw /metrics` (kubectl get flags.go RawURI):
        # print the body verbatim, non-2xx is an error
        status, text = client.raw("GET", args.raw)
        if status >= 400:
            print(f"error: the server returned HTTP {status} for "
                  f"{args.raw}", file=sys.stderr)
            return 1
        sys.stdout.write(text if text.endswith("\n") or not text
                         else text + "\n")
        return 0
    if not args.resource:
        print("error: resource type required (or use --raw)",
              file=sys.stderr)
        return 1
    if args.resource.lower() in ("alert", "alerts"):
        return _cmd_get_alerts(client, args)
    plural = resolve_resource(args.resource)
    kind = RESOURCES[plural]
    ns = None if args.all_namespaces else args.namespace
    if args.name:
        if getattr(args, "selector", ""):
            print("error: a resource name cannot be combined with "
                  "--selector", file=sys.stderr)
            return 1
        objs = [client.get(kind, args.name, args.namespace)]
    else:
        selector = None
        if getattr(args, "selector", ""):
            from kubernetes_tpu.apiserver.http import parse_label_selector

            try:
                selector = parse_label_selector(args.selector)
            except ValueError as e:
                print(f"error: {e}", file=sys.stderr)
                return 1
        objs = client.list(kind, namespace=ns, label_selector=selector)
        objs.sort(key=lambda o: (o.metadata.namespace, o.metadata.name))
    if args.output == "json":
        docs = [o.to_dict() for o in objs]
        print(json.dumps(docs[0] if args.name else
                         {"kind": f"{kind}List", "items": docs}, indent=2))
        return 0
    if args.output == "name":
        for o in objs:
            print(f"{plural}/{o.metadata.name}")
        return 0
    wide = args.output == "wide"
    headers = HEADERS.get(f"{kind}-wide" if wide and
                          f"{kind}-wide" in HEADERS else kind,
                          ["NAME", "AGE"])
    print_table([_row(kind, o, wide) for o in objs], headers)
    return 0


def cmd_describe(client, args) -> int:
    kind = RESOURCES[resolve_resource(args.resource)]
    obj = client.get(kind, args.name, args.namespace)
    print(json.dumps(obj.to_dict(), indent=2))
    if kind == "Cluster" and obj.planner_status:
        planner = obj.planner_status
        print("\nPlanner:")
        print(f"  Placements:\t{planner.get('placements', 0)}")
        print(f"  Spillovers:\t{planner.get('spillovers', 0)}")
        print(f"  Masked:\t{planner.get('masked', False)}")
        for workload, count in sorted(
                (planner.get("lastDecision") or {}).items()):
            print(f"  Decision:\t{workload} -> {count} replicas")
    # related events, the describe signature feature
    events = [e for e in client.list("Event", namespace=args.namespace)
              if e.involved_object.get("name") == args.name]
    if events:
        print("\nEvents:")
        for e in sorted(events, key=lambda e: e.metadata.creation_timestamp):
            print(f"  {e.type}\t{e.reason}\t{e.message}")
    return 0


def cmd_create(client, args) -> int:
    for doc in load_manifest(args.filename):
        obj = decode_object(doc.get("kind", ""), doc)
        created = client.create(obj)
        print(f"{created.kind.lower()}/{created.metadata.name} created")
    return 0


LAST_APPLIED = "kubectl.kubernetes.io/last-applied-configuration"


def cmd_apply(client, args) -> int:
    """Declarative apply via the three-way strategic merge the reference
    kubectl performs (pkg/kubectl/cmd/apply.go + strategicpatch
    CreateThreeWayMergePatch): deletions come from comparing the
    last-applied annotation to the manifest, updates from comparing the
    manifest to the live object — fields written by controllers (status,
    allocated clusterIP, scale changes the manifest doesn't pin) survive."""
    import copy as _copy

    from kubernetes_tpu.apiserver.strategicpatch import (
        STRATEGIC,
        create_three_way_patch,
    )

    for doc in load_manifest(args.filename):
        kind = doc.get("kind", "")
        name = (doc.get("metadata") or {}).get("name", "")
        ns = (doc.get("metadata") or {}).get("namespace",
                                             args.namespace or "default")
        modified = _copy.deepcopy(doc)
        # -n applies to manifests that don't pin a namespace — on BOTH the
        # create and patch paths, or the first apply would land the object
        # somewhere later applies don't look
        modified.setdefault("metadata", {})["namespace"] = ns
        modified["metadata"].setdefault(
            "annotations", {})[LAST_APPLIED] = json.dumps(
                doc, sort_keys=True, separators=(",", ":"))
        try:
            client.create(decode_object(kind, modified))
            print(f"{kind.lower()}/{name} created")
            continue
        except AlreadyExists:
            pass
        live = client.get(kind, name, ns)
        live_dict = live.to_dict()
        last = (live.metadata.annotations or {}).get(LAST_APPLIED)
        original = json.loads(last) if last else {}
        patch = create_three_way_patch(original, modified, live_dict)
        patch.get("metadata", {}).pop("resourceVersion", None)
        if not any(k for k in patch if k != "apiVersion"):
            print(f"{kind.lower()}/{name} unchanged")
            continue
        client.patch(kind, name, ns, patch, STRATEGIC)
        print(f"{kind.lower()}/{name} configured")
    return 0


def cmd_delete(client, args) -> int:
    kind = RESOURCES[resolve_resource(args.resource)]
    client.delete(kind, args.name, args.namespace)
    print(f"{kind.lower()}/{args.name} deleted")
    return 0


def cmd_explain(client, args) -> int:
    """kubectl explain resource[.field...]: field docs read from the
    server's swagger (pkg/kubectl/explain over routes/openapi.go)."""
    from kubernetes_tpu.apiserver.openapi import explain

    dotted = args.resource.split(".")
    kind = RESOURCES[resolve_resource(dotted[0])]
    status, body = client.raw("GET", "/swagger.json")
    if status != 200:
        print(f"error: server returned {status} for /swagger.json",
              file=sys.stderr)
        return 1
    out = explain(json.loads(body), kind, dotted[1:])
    print(out)
    return 0 if not out.startswith("error:") else 1


def cmd_explain_pending(client, args) -> int:
    """kubectl explain-pending pod: why is this pod not scheduled? Prints
    the pod's latest FailedScheduling message — with KTPU_EXPLAIN (or
    Scheduler(explain=True)) that is the per-predicate breakdown the
    device solver emitted ("0/N nodes available: k Insufficient
    resources, ..."), the reference's findNodesThatFit failure summary."""
    pod = client.get("Pod", args.name, args.namespace)
    if pod.spec.node_name:
        print(f"pod {args.name} is scheduled to {pod.spec.node_name}")
        return 0
    events = [e for e in client.list("Event", namespace=args.namespace)
              if e.involved_object.get("name") == args.name
              and e.reason == "FailedScheduling"]
    if not events:
        print(f"pod {args.name} is pending; no FailedScheduling event "
              f"recorded yet (still queued, or the scheduler has not "
              f"retried it)")
        return 0
    latest = max(events, key=lambda e: e.metadata.creation_timestamp)
    print(latest.message)
    return 0


def cmd_patch(client, args) -> int:
    """kubectl patch -p '...' --type strategic|merge|json
    (pkg/kubectl/cmd/patch.go)."""
    from kubernetes_tpu.apiserver import strategicpatch as sp

    kind = RESOURCES[resolve_resource(args.resource)]
    content_type = {"strategic": sp.STRATEGIC, "merge": sp.MERGE,
                    "json": sp.JSONPATCH}[args.type]
    client.patch(kind, args.name, args.namespace, json.loads(args.patch),
                 content_type)
    print(f"{kind.lower()}/{args.name} patched")
    return 0


def _pairs_patch(pairs: list[str], field: str) -> dict:
    values: dict = {}
    for pair in pairs:
        if pair.endswith("-") and "=" not in pair:
            values[pair[:-1]] = None  # strategic null deletes the key
        else:
            k, _, v = pair.partition("=")
            values[k] = v
    return {"metadata": {field: values}}


def cmd_label(client, args) -> int:
    """kubectl label: a strategic merge patch on metadata.labels
    (pkg/kubectl/cmd/label.go)."""
    from kubernetes_tpu.apiserver import strategicpatch as sp

    kind = RESOURCES[resolve_resource(args.resource)]
    client.patch(kind, args.name, args.namespace,
                 _pairs_patch(args.pairs, "labels"), sp.STRATEGIC)
    print(f"{kind.lower()}/{args.name} labeled")
    return 0


def cmd_annotate(client, args) -> int:
    """kubectl annotate (pkg/kubectl/cmd/annotate.go)."""
    from kubernetes_tpu.apiserver import strategicpatch as sp

    kind = RESOURCES[resolve_resource(args.resource)]
    client.patch(kind, args.name, args.namespace,
                 _pairs_patch(args.pairs, "annotations"), sp.STRATEGIC)
    print(f"{kind.lower()}/{args.name} annotated")
    return 0


def cmd_scale(client, args) -> int:
    kind = RESOURCES[resolve_resource(args.resource)]

    def mutate(obj):
        obj.spec["replicas"] = args.replicas
        return obj

    client.guaranteed_update(kind, args.name, args.namespace, mutate)
    print(f"{kind.lower()}/{args.name} scaled to {args.replicas}")
    return 0


def cmd_bind(client, args) -> int:
    client.bind(Binding(pod_name=args.pod, namespace=args.namespace,
                        target_node=args.node))
    print(f"pod/{args.pod} bound to {args.node}")
    return 0


def _node_proxy_path(client, args) -> tuple[str, str]:
    """(node-proxy path prefix, container name) for the pod, via its
    spec.nodeName (the apiserver -> kubelet hop kubectl logs/exec ride)."""
    pod = client.get("Pod", args.name, args.namespace)
    node = pod.spec.node_name
    if not node:
        raise NotFound(f"pod {args.name} is not scheduled yet")
    container = getattr(args, "container", "") or (
        pod.spec.containers[0].name if pod.spec.containers else "c")
    return f"/api/v1/nodes/{node}/proxy", container


def cmd_logs(client, args) -> int:
    prefix, container = _node_proxy_path(client, args)
    status, body = client.raw(
        "GET", f"{prefix}/containerLogs/{args.namespace}/{args.name}/"
               f"{container}")
    if status != 200:
        print(f"Error from server: {body.strip()}", file=sys.stderr)
        return 1
    sys.stdout.write(body)
    return 0


def cmd_exec(client, args) -> int:
    from urllib.parse import quote

    prefix, container = _node_proxy_path(client, args)
    if not args.stdin and not args.command:
        print("error: you must specify a command (or -i for interactive)",
              file=sys.stderr)
        return 1
    if args.stdin:
        # interactive: channel-framed stream through the apiserver's
        # bidirectional node proxy (remotecommand.go:27 topology)
        import shlex

        from kubernetes_tpu.client.remotecommand import exec_stream

        import itertools

        # quote argv so the server-side shlex re-split preserves the
        # argument boundaries the non-interactive JSON path keeps; stdin
        # streams LAZILY so the session is actually interactive (and a
        # piped gigabyte doesn't buffer in memory)
        initial = [(" ".join(shlex.quote(c) for c in args.command)
                    + "\n").encode()] if args.command else []
        lines = itertools.chain(
            initial, (line.encode() for line in sys.stdin))
        code, out, err = exec_stream(
            client.host, client.port,
            f"{prefix}/exec/{args.namespace}/{args.name}/{container}",
            lines, token=client.token)
        sys.stdout.write(out)
        sys.stderr.write(err)
        return code
    status, body = client.raw(
        "POST", f"{prefix}/exec/{args.namespace}/{args.name}/{container}"
                f"?command={quote(json.dumps(args.command))}")
    if status != 200:
        print(f"Error from server: {body.strip()}", file=sys.stderr)
        return 1
    result = json.loads(body)
    sys.stdout.write(result.get("output", ""))
    return int(result.get("exitCode", 0))


def cmd_port_forward(client, args) -> int:
    """kubectl port-forward pod LOCAL:REMOTE — a local listener whose
    connections tunnel through apiserver -> node proxy -> kubelet ->
    pod port backend (client-go/tools/portforward topology over the
    channel framing)."""
    import asyncio

    from kubernetes_tpu.client.remotecommand import (
        open_upgraded,
        pump_socket_frames,
    )

    local, _, remote = args.ports.partition(":")
    remote = remote or local
    args.name = args.pod
    prefix, _container = _node_proxy_path(client, args)
    path = (f"{prefix}/portForward/{args.namespace}/{args.pod}"
            f"?port={int(remote)}")

    async def serve():
        async def handle(reader, writer):
            try:
                # the blocking connect+handshake must not stall the loop
                # (other tunnels keep pumping while this one dials)
                sock = await asyncio.to_thread(
                    open_upgraded, client.host, client.port, path,
                    token=client.token)
            except (OSError, ConnectionError) as e:
                print(f"error: {e}", file=sys.stderr)
                writer.close()
                return
            try:
                await pump_socket_frames(sock, reader, writer)
            finally:
                sock.close()

        server = await asyncio.start_server(handle, "127.0.0.1",
                                            int(local))
        bound = server.sockets[0].getsockname()[1]
        print(f"Forwarding from 127.0.0.1:{bound} -> {remote}",
              flush=True)
        async with server:
            await server.serve_forever()

    try:
        asyncio.run(serve())
    except KeyboardInterrupt:
        pass
    return 0


def cmd_run(client, args) -> int:
    """kubectl run (pkg/kubectl/cmd/run.go): --restart picks the
    generator — Always -> Deployment (the 1.8 default), OnFailure -> Job,
    Never -> bare Pod (run.go:253 generator selection)."""
    labels = dict(kv.split("=", 1) for kv in args.labels.split(",")
                  if "=" in kv) if args.labels else {"run": args.name}
    container = {"name": args.name, "image": args.image}
    if args.command:
        container["command"] = list(args.command)
    pod_spec = {"containers": [container]}
    template = {"metadata": {"labels": labels}, "spec": dict(pod_spec)}
    if args.restart == "Never":
        obj = decode_object("Pod", {
            "kind": "Pod",
            "metadata": {"name": args.name, "namespace": args.namespace,
                         "labels": labels},
            "spec": pod_spec})
        created_kind = "pod"
    elif args.restart == "OnFailure":
        template["spec"]["restartPolicy"] = "OnFailure"
        obj = decode_object("Job", {
            "kind": "Job",
            "metadata": {"name": args.name, "namespace": args.namespace},
            "spec": {"template": template}})
        created_kind = "job"
    else:
        obj = decode_object("Deployment", {
            "kind": "Deployment",
            "metadata": {"name": args.name, "namespace": args.namespace},
            "spec": {"replicas": args.replicas,
                     "selector": {"matchLabels": labels},
                     "template": template}})
        created_kind = "deployment"
    client.create(obj)
    print(f"{created_kind}/{args.name} created")
    return 0


def cmd_expose(client, args) -> int:
    """kubectl expose (pkg/kubectl/cmd/expose.go): derive a Service
    selector from the exposed workload (its spec.selector, or a pod's
    labels) and create the Service."""
    kind = RESOURCES[resolve_resource(args.resource)]
    obj = client.get(kind, args.name, args.namespace)
    if kind == "Pod":
        selector = dict(obj.metadata.labels)
    else:
        sel = (obj.spec.get("selector") or {})
        selector = dict(sel.get("matchLabels") or sel or {})
    if not selector:
        print(f"error: couldn't find a selector on {kind}/{args.name}",
              file=sys.stderr)
        return 1
    port = {"port": args.port}
    if args.target_port:
        port["targetPort"] = args.target_port
    svc = decode_object("Service", {
        "kind": "Service",
        "metadata": {"name": args.service_name or args.name,
                     "namespace": args.namespace},
        "spec": {"selector": selector, "ports": [port],
                 "type": args.type}})
    client.create(svc)
    print(f"service/{svc.metadata.name} exposed")
    return 0


def cmd_set(client, args) -> int:
    """kubectl set image (pkg/kubectl/cmd/set/set_image.go): patch the
    named containers' images through the workload's pod template."""
    if args.what != "image":
        print(f"error: unknown set subcommand {args.what!r}",
              file=sys.stderr)
        return 1
    kind = RESOURCES[resolve_resource(args.resource)]
    updates = dict(kv.split("=", 1) for kv in args.pairs)

    def mutate(obj):
        containers = (obj.spec.get("template") or {}).get(
            "spec", {}).get("containers", []) if kind != "Pod" \
            else [c.to_dict() for c in obj.spec.containers]
        hit = False
        for c in containers:
            if c.get("name") in updates or "*" in updates:
                c["image"] = updates.get(c.get("name"), updates.get("*"))
                hit = True
        if not hit:
            raise NotFound(
                f"container(s) {sorted(updates)} not found in "
                f"{kind}/{args.name}")
        if kind == "Pod":
            from kubernetes_tpu.api.objects import Container

            obj.spec.containers = [Container.from_dict(c)
                                   for c in containers]
        return obj

    client.guaranteed_update(kind, args.name, args.namespace, mutate)
    print(f"{kind.lower()}/{args.name} image updated")
    return 0


def cmd_edit(client, args) -> int:
    """kubectl edit (pkg/kubectl/cmd/editor/editoptions.go): fetch, open
    $EDITOR on the JSON, PUT the result back; an unchanged buffer is a
    no-op ('Edit cancelled')."""
    import os
    import subprocess
    import tempfile

    kind = RESOURCES[resolve_resource(args.resource)]
    obj = client.get(kind, args.name, args.namespace)
    doc = obj.to_dict()
    doc.setdefault("kind", kind)
    before = json.dumps(doc, indent=2, sort_keys=True)
    editor = os.environ.get("EDITOR", "vi")
    with tempfile.NamedTemporaryFile(
            "w+", suffix=".json", delete=False) as f:
        f.write(before)
        path = f.name
    try:
        try:
            subprocess.run(f"{editor} {path}", shell=True, check=True)
        except subprocess.CalledProcessError:
            # vim :cq / any editor abort: cancel, don't traceback
            print("Edit cancelled (editor exited nonzero).")
            return 0
        with open(path) as f:
            after = f.read()
    finally:
        os.unlink(path)
    if after.strip() == before.strip():
        print("Edit cancelled, no changes made.")
        return 0
    edited = decode_object(kind, json.loads(after))
    edited.metadata.namespace = obj.metadata.namespace
    # pin the PUT to the version the editor buffer was rendered from: a
    # write that landed while the editor was open must surface as a
    # conflict, not be silently overwritten (the reference's edit loop
    # re-opens the editor on exactly this error)
    edited.metadata.resource_version = obj.metadata.resource_version
    try:
        client.update(edited)
    except Conflict:
        print(f"Error: {kind.lower()}/{args.name} changed while editing; "
              f"re-run edit against the new version")
        return 1
    print(f"{kind.lower()}/{args.name} edited")
    return 0


def cmd_top(client, args) -> int:
    """kubectl top node|pod. With a monitor running (its URL published on
    the kube-system/monitor Endpoints), usage is live: the kubelet
    /stats/summary -> Monitor TSDB pipeline queried over /query — the
    metrics-server path of the reference (top_node.go). Without one, fall
    back to the hollow stand-in: summed pod requests per node (plus the
    eviction manager's usage annotations for pods that carry them)."""
    from kubernetes_tpu.agent.eviction import pod_memory_usage_mib
    from kubernetes_tpu.api.quantity import parse_quantity

    what = resolve_resource(args.resource)
    url = _monitor_url(client)
    if what == "nodes":
        cpu = mem = None
        if url is not None:
            vec = _monitor_query(url, "node_cpu_usage_cores")
            if vec:
                cpu = {lbl.get("node", ""): v for lbl, v in vec}
                mem = {lbl.get("node", ""): v for lbl, v in
                       _monitor_query(url, "node_memory_usage_mib") or []}
        if cpu is None:
            cpu, mem = {}, {}
            for pod in client.list("Pod"):
                if not pod.spec.node_name \
                        or pod.status.phase in ("Succeeded", "Failed"):
                    continue
                name = pod.spec.node_name
                # parse_quantity returns Fraction; keep the aggregate float
                cpu[name] = cpu.get(name, 0.0) + float(sum(
                    parse_quantity(c.requests["cpu"])
                    for c in pod.spec.containers if "cpu" in c.requests))
                mem[name] = mem.get(name, 0.0) + float(sum(
                    parse_quantity(c.requests["memory"])
                    for c in pod.spec.containers
                    if "memory" in c.requests)) / (1 << 20)
        print(f"{'NAME':24} {'CPU(cores)':>12} {'CPU%':>6} "
              f"{'MEMORY(Mi)':>12} {'MEM%':>6}")
        for node in client.list("Node"):
            name = node.metadata.name
            cap_cpu = parse_quantity(
                str(node.status.allocatable.get("cpu", "0")))
            cap_mem = parse_quantity(
                str(node.status.allocatable.get("memory", "0")))
            used_cpu = cpu.get(name, 0.0)
            used_mib = mem.get(name, 0.0)
            cpu_pct = 100 * used_cpu / cap_cpu if cap_cpu else 0
            mem_pct = 100 * used_mib * (1 << 20) / cap_mem if cap_mem else 0
            print(f"{name:24} {used_cpu:>11.2f} {cpu_pct:>5.0f}% "
                  f"{used_mib:>12.0f} {mem_pct:>5.0f}%")
        return 0
    if what == "pods":
        cpu = mem = None
        if url is not None:
            vec = _monitor_query(
                url, f'pod_cpu_usage_cores{{namespace="{args.namespace}"}}')
            if vec:
                cpu = {lbl.get("pod", ""): v for lbl, v in vec}
                mem = {lbl.get("pod", ""): v for lbl, v in _monitor_query(
                    url, f'pod_memory_usage_mib'
                         f'{{namespace="{args.namespace}"}}') or []}
        print(f"{'NAME':32} {'CPU(cores)':>12} {'MEMORY(Mi)':>12}")
        for pod in client.list("Pod", namespace=args.namespace):
            if pod.status.phase in ("Succeeded", "Failed"):
                continue
            name = pod.metadata.name
            if cpu is not None and name in cpu:
                used_cpu, used_mib = cpu[name], mem.get(name, 0.0)
            else:
                # parse_quantity returns Fraction, which float-format
                # rejects
                used_cpu = float(sum(parse_quantity(c.requests["cpu"])
                                     for c in pod.spec.containers
                                     if "cpu" in c.requests))
                used_mib = pod_memory_usage_mib(pod)
            print(f"{name:32} {used_cpu:>11.2f} {used_mib:>12.0f}")
        return 0
    print("error: top supports nodes|pods", file=sys.stderr)
    return 1


def cmd_autoscale(client, args) -> int:
    """kubectl autoscale (pkg/kubectl/cmd/autoscale.go): create an HPA
    targeting the workload."""
    kind = RESOURCES[resolve_resource(args.resource)]
    hpa = decode_object("HorizontalPodAutoscaler", {
        "kind": "HorizontalPodAutoscaler",
        "metadata": {"name": args.name, "namespace": args.namespace},
        "spec": {"scaleTargetRef": {"kind": kind, "name": args.name},
                 "minReplicas": args.min,
                 "maxReplicas": args.max,
                 "targetCPUUtilizationPercentage": args.cpu_percent}})
    client.create(hpa)
    print(f"horizontalpodautoscaler/{args.name} autoscaled")
    return 0


def cmd_attach(client, args) -> int:
    """kubectl attach (pkg/kubectl/cmd/attach.go): join the running
    container's streams — at hollow fidelity the output stream is the
    container log buffer; -i additionally opens the interactive exec
    channel (the same SPDY-analog transport kubectl exec -i uses)."""
    prefix, container = _node_proxy_path(client, args)
    status, body = client.raw(
        "GET", f"{prefix}/containerLogs/{args.namespace}/{args.name}/"
               f"{container}")
    if status != 200:
        print(f"Error from server: {body.strip()}", file=sys.stderr)
        return 1
    sys.stdout.write(body)
    if not args.stdin:
        return 0
    from kubernetes_tpu.client.remotecommand import exec_stream

    lines = (line.encode() for line in sys.stdin)
    code, out, err = exec_stream(
        client.host, client.port,
        f"{prefix}/exec/{args.namespace}/{args.name}/{container}",
        lines, token=client.token)
    sys.stdout.write(out)
    sys.stderr.write(err)
    return code


def cmd_api_resources(client, args) -> int:
    """Discovery walk: /api/v1 + every /apis group version
    (pkg/kubectl/cmd/apiresources analog)."""
    rows = []
    core = client._request("GET", "/api/v1")
    for r in core.get("resources", []):
        rows.append((r["name"], "v1", r["namespaced"], r["kind"]))
    groups = client._request("GET", "/apis")
    for g in groups.get("groups", []):
        for v in g.get("versions", []):
            gv = v["groupVersion"]
            try:
                listing = client._request("GET", f"/apis/{gv}")
            except Exception:  # noqa: BLE001 — unreachable aggregated group
                continue
            for r in listing.get("resources", []):
                rows.append((r["name"], gv, r["namespaced"], r["kind"]))
    print(f"{'NAME':<32} {'APIVERSION':<34} {'NAMESPACED':<11} KIND")
    for name, gv, namespaced, kind in sorted(rows):
        print(f"{name:<32} {gv:<34} {str(namespaced).lower():<11} {kind}")
    return 0


def cmd_rollout(client, args) -> int:
    """rollout status|history|undo deployment/<name> (pkg/kubectl/cmd/
    rollout + rollback semantics through spec.rollbackTo)."""
    kind = RESOURCES[resolve_resource(args.resource)]
    if kind != "Deployment":
        print(f"error: rollout is only supported for deployments, "
              f"got {kind}", file=sys.stderr)
        return 1
    from kubernetes_tpu.controllers.deployment import REVISION_ANNOTATION

    deploy = client.get(kind, args.name, args.namespace)
    if args.action == "status":
        status = deploy.status or {}
        desired = deploy.replicas
        updated = int(status.get("updatedReplicas", 0))
        available = int(status.get("availableReplicas", 0))
        if updated >= desired and available >= desired:
            print(f"deployment \"{args.name}\" successfully rolled out")
            return 0
        print(f"Waiting for rollout to finish: {updated} out of "
              f"{desired} new replicas have been updated "
              f"({available} available)...")
        return 1
    owned = [rs for rs in client.list("ReplicaSet", args.namespace)
             if any(r.get("uid") == deploy.metadata.uid
                    for r in rs.metadata.owner_references)]
    owned.sort(key=lambda r: int(
        r.metadata.annotations.get(REVISION_ANNOTATION, 0) or 0))
    if args.action == "history":
        print("REVISION  REPLICASET")
        for rs in owned:
            rev = rs.metadata.annotations.get(REVISION_ANNOTATION, "?")
            print(f"{rev:<9} {rs.metadata.name}")
        return 0
    if args.action == "undo":
        def mutate(obj):
            obj.spec["rollbackTo"] = (
                {"revision": args.to_revision} if args.to_revision else {})
            return obj

        client.guaranteed_update(kind, args.name, args.namespace, mutate)
        print(f"deployment/{args.name} rolled back")
        return 0
    print(f"error: unknown rollout action {args.action!r}",
          file=sys.stderr)
    return 1


def _set_unschedulable(client, node: str, value: bool) -> None:
    def mutate(obj):
        obj.spec.unschedulable = value
        return obj

    client.guaranteed_update("Node", node, "default", mutate)


def cmd_cordon(client, args) -> int:
    _set_unschedulable(client, args.name, True)
    print(f"node/{args.name} cordoned")
    return 0


def cmd_uncordon(client, args) -> int:
    _set_unschedulable(client, args.name, False)
    print(f"node/{args.name} uncordoned")
    return 0


def cmd_drain(client, args) -> int:
    """Cordon, then evict every pod on the node through the eviction
    subresource — PodDisruptionBudgets gate each eviction (429 retries),
    DaemonSet pods are skipped because their controller would immediately
    re-place them (pkg/kubectl/cmd/drain.go semantics)."""
    import time as _time

    _set_unschedulable(client, args.name, True)
    deadline = _time.monotonic() + args.timeout
    pending = None
    while pending is None or pending:
        pending = []
        for pod in client.list("Pod"):
            if pod.spec.node_name != args.name:
                continue
            owner = next((r for r in pod.metadata.owner_references
                          if r.get("controller")), {})
            if owner.get("kind") == "DaemonSet":
                continue
            try:
                evicted = client.evict(pod.metadata.name,
                                       pod.metadata.namespace)
            except NotFound:
                continue  # went away on its own mid-drain: success
            except TooManyRequests:
                # load-shed 429 (not a PDB answer): server pressure —
                # retry on the next pass like real drain does
                evicted = False
            if evicted:
                print(f"pod/{pod.metadata.name} evicted")
            else:
                pending.append(pod.metadata.name)
        if pending:
            if _time.monotonic() > deadline:
                print(f"error: pods not evictable within budget: "
                      f"{', '.join(sorted(pending))}", file=sys.stderr)
                return 1
            # kubectl is a synchronous CLI process: no event loop to block
            _time.sleep(0.5)  # ktpu: allow[blocking-in-async]
    print(f"node/{args.name} drained")
    return 0


def build_parser() -> argparse.ArgumentParser:
    import os

    p = argparse.ArgumentParser(prog="kubectl",
                                description="CLI over the HTTP apiserver")
    p.add_argument("--server", "-s",
                   default=os.environ.get("KUBECTL_SERVER",
                                          "http://127.0.0.1:8080"))
    p.add_argument("--token",
                   default=os.environ.get("KUBECTL_TOKEN", ""),
                   help="bearer token for an authn-enabled apiserver "
                        "(env KUBECTL_TOKEN)")
    p.add_argument("--certificate-authority", default="",
                   help="CA bundle for an https server")
    p.add_argument("--insecure-skip-tls-verify", action="store_true")
    sub = p.add_subparsers(dest="verb", required=True)

    def common(sp, name=True):
        sp.add_argument("resource")
        if name:
            sp.add_argument("name")
        sp.add_argument("-n", "--namespace", default="default")

    g = sub.add_parser("get")
    g.add_argument("resource", nargs="?", default="")
    g.add_argument("name", nargs="?")
    g.add_argument("--raw", default="",
                   help="request a raw server path and print the body, "
                        "e.g. --raw /metrics or --raw /healthz")
    g.add_argument("-n", "--namespace", default="default")
    g.add_argument("--all-namespaces", action="store_true")
    g.add_argument("-l", "--selector", default="",
                   help="label selector, e.g. app=web,tier=frontend")
    g.add_argument("-o", "--output", default="",
                   choices=["", "json", "wide", "name"])
    g.set_defaults(fn=cmd_get)
    d = sub.add_parser("describe")
    common(d)
    d.set_defaults(fn=cmd_describe)
    for verb, fn in (("create", cmd_create), ("apply", cmd_apply)):
        c = sub.add_parser(verb)
        c.add_argument("-f", "--filename", required=True)
        c.add_argument("-n", "--namespace", default="default")
        c.set_defaults(fn=fn)
    de = sub.add_parser("delete")
    common(de)
    de.set_defaults(fn=cmd_delete)
    pa = sub.add_parser("patch")
    common(pa)
    pa.add_argument("-p", "--patch", required=True,
                    help="patch document (JSON)")
    pa.add_argument("--type", default="strategic",
                    choices=["strategic", "merge", "json"])
    pa.set_defaults(fn=cmd_patch)
    lb = sub.add_parser("label")
    common(lb)
    lb.add_argument("pairs", nargs="+",
                    help="key=value to set, key- to remove")
    lb.set_defaults(fn=cmd_label)
    an = sub.add_parser("annotate")
    common(an)
    an.add_argument("pairs", nargs="+",
                    help="key=value to set, key- to remove")
    an.set_defaults(fn=cmd_annotate)
    sc = sub.add_parser("scale")
    common(sc)
    sc.add_argument("--replicas", type=int, required=True)
    sc.set_defaults(fn=cmd_scale)
    b = sub.add_parser("bind")
    b.add_argument("pod")
    b.add_argument("node")
    b.add_argument("-n", "--namespace", default="default")
    b.set_defaults(fn=cmd_bind)
    for verb, fn in (("cordon", cmd_cordon), ("uncordon", cmd_uncordon)):
        c = sub.add_parser(verb)
        c.add_argument("name")
        c.set_defaults(fn=fn)
    dr = sub.add_parser("drain")
    dr.add_argument("name")
    dr.add_argument("--timeout", type=float, default=30.0)
    dr.set_defaults(fn=cmd_drain)
    ar = sub.add_parser("api-resources")
    ar.set_defaults(fn=cmd_api_resources)
    rn = sub.add_parser("run")
    rn.add_argument("name")
    rn.add_argument("--image", required=True)
    rn.add_argument("--replicas", type=int, default=1)
    rn.add_argument("--restart", default="Always",
                    choices=["Always", "OnFailure", "Never"])
    rn.add_argument("--labels", default="",
                    help="comma list of key=value")
    rn.add_argument("-n", "--namespace", default="default")
    rn.add_argument("command", nargs="*", default=[])
    rn.set_defaults(fn=cmd_run)
    xp = sub.add_parser("expose")
    common(xp)
    xp.add_argument("--port", type=int, required=True)
    xp.add_argument("--target-port", type=int, default=0)
    xp.add_argument("--name", dest="service_name", default="")
    xp.add_argument("--type", default="ClusterIP")
    xp.set_defaults(fn=cmd_expose)
    st = sub.add_parser("set")
    st.add_argument("what", help="set subcommand (image)")
    st.add_argument("resource")
    st.add_argument("name")
    st.add_argument("pairs", nargs="+",
                    help="container=image (or *=image)")
    st.add_argument("-n", "--namespace", default="default")
    st.set_defaults(fn=cmd_set)
    ed = sub.add_parser("edit")
    common(ed)
    ed.set_defaults(fn=cmd_edit)
    tp = sub.add_parser("top")
    tp.add_argument("resource", help="nodes|pods")
    tp.add_argument("-n", "--namespace", default="default")
    tp.set_defaults(fn=cmd_top)
    asc = sub.add_parser("autoscale")
    common(asc)
    asc.add_argument("--min", type=int, default=1)
    asc.add_argument("--max", type=int, required=True)
    asc.add_argument("--cpu-percent", type=int, default=80)
    asc.set_defaults(fn=cmd_autoscale)
    at = sub.add_parser("attach")
    at.add_argument("name")
    at.add_argument("-n", "--namespace", default="default")
    at.add_argument("-c", "--container", default="")
    at.add_argument("-i", "--stdin", action="store_true")
    at.set_defaults(fn=cmd_attach)
    ex2 = sub.add_parser("explain")
    ex2.add_argument("resource",
                     help="resource[.field...], e.g. pods.spec.containers")
    ex2.set_defaults(fn=cmd_explain)
    ep = sub.add_parser("explain-pending")
    ep.add_argument("name", help="pending pod name")
    ep.add_argument("-n", "--namespace", default="default")
    ep.set_defaults(fn=cmd_explain_pending)
    ro = sub.add_parser("rollout")
    ro.add_argument("action", choices=["status", "history", "undo"])
    ro.add_argument("resource")
    ro.add_argument("name")
    ro.add_argument("-n", "--namespace", default="default")
    ro.add_argument("--to-revision", type=int, default=0)
    ro.set_defaults(fn=cmd_rollout)
    lg = sub.add_parser("logs")
    lg.add_argument("name")
    lg.add_argument("-n", "--namespace", default="default")
    lg.add_argument("-c", "--container", default="")
    lg.set_defaults(fn=cmd_logs)
    ex = sub.add_parser("exec")
    ex.add_argument("name")
    ex.add_argument("-n", "--namespace", default="default")
    ex.add_argument("-c", "--container", default="")
    ex.add_argument("-i", "--stdin", action="store_true",
                    help="stream stdin lines through the interactive "
                         "exec channel")
    ex.add_argument("command", nargs="*", default=[])
    ex.set_defaults(fn=cmd_exec)
    pf = sub.add_parser("port-forward")
    pf.add_argument("pod")
    pf.add_argument("ports", help="LOCAL:REMOTE (or PORT for both)")
    pf.add_argument("-n", "--namespace", default="default")
    pf.set_defaults(fn=cmd_port_forward)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    url = urlsplit(args.server)
    tls = url.scheme == "https"
    client = RemoteStore(
        url.hostname, url.port or (443 if tls else 80), token=args.token,
        tls=tls, ca_file=args.certificate_authority or None,
        insecure_skip_verify=args.insecure_skip_tls_verify)
    try:
        return args.fn(client, args)
    except NotFound as e:
        print(f"Error from server (NotFound): {e}", file=sys.stderr)
        return 1
    except (Conflict, AlreadyExists) as e:
        print(f"Error from server (Conflict): {e}", file=sys.stderr)
        return 1
    except PermissionError as e:
        print(f"Error from server (Forbidden): {e}", file=sys.stderr)
        return 1
    except ConnectionError as e:
        print(f"Unable to connect to the server: {e}", file=sys.stderr)
        return 1
    except json.JSONDecodeError as e:
        print(f"error: invalid JSON: {e}", file=sys.stderr)
        return 1
    except ValidationError as e:
        print(f"Error from server (Invalid): {e}", file=sys.stderr)
        return 1
    except TooManyRequests as e:
        print(f"Error from server (TooManyRequests): {e}", file=sys.stderr)
        return 1
    except Expired as e:
        print(f"Error from server (Gone): {e}", file=sys.stderr)
        return 1
    except ValueError as e:
        # remaining server-side rejections (400 BadRequest) surface as
        # ValueError from the client; a traceback is not a CLI answer
        print(f"Error from server (BadRequest): {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
