"""ktpu-lint: AST invariant checks for the control plane and solver.

`python -m kubernetes_tpu.analysis --strict` is the CI gate
(tests/test_lint.py runs it over the whole tree in tier-1); see
analysis/lint.py for the engine and analysis/rules.py for the catalog.
"""

from kubernetes_tpu.analysis.lint import (  # noqa: F401
    AnalysisResult,
    Finding,
    lint_source,
    load_baseline,
    run_analysis,
)
from kubernetes_tpu.analysis.rules import RULE_NAMES, RULES  # noqa: F401
