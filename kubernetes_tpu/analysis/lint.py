"""ktpu-lint: the AST invariant engine.

The hack/verify-* position of the reference build (golint/go-vet gates
that run before any test does), turned inward: the invariants this
codebase actually rests on — event-loop purity, trace purity of the
jit-compiled solver kernels, BatchFlags gate discipline, seeded
determinism, store write discipline — encoded as AST rules over every
first-party module, so the Round-6 driver refactors land against a
machine-checked contract instead of reviewer memory.

Mechanics:

- `run_analysis()` walks `kubernetes_tpu/` (skipping __pycache__ and
  generated trees), parses each module once, and runs every registered
  rule over it.
- A finding on line L is suppressed by ``# ktpu: allow[rule]`` on line L
  or L-1 (``allow[all]`` silences every rule). Suppressions are the
  reviewed escape hatch: the comment sits next to the code it excuses.
- `analysis/baseline.txt` grandfathers pre-existing findings as
  ``rule<SP>path<SP>count`` ratchet lines: strict mode fails only when a
  (rule, path) pair exceeds its baselined count, so new code adds zero
  findings while old debt is paid down file by file.

Rules live in `analysis/rules.py`; the CLI in `analysis/__main__.py`.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPO_ROOT = os.path.dirname(PKG_DIR)
BASELINE_PATH = os.path.join(PKG_DIR, "analysis", "baseline.txt")

# trees never linted: bytecode caches, generated wire code, C build output
SKIP_DIRS = {"__pycache__", "_wiregen", "_build"}

_ALLOW_RE = re.compile(r"ktpu:\s*allow\[([A-Za-z0-9_,\- ]+)\]")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str      # repo-relative, forward slashes
    line: int
    col: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] " \
               f"{self.message}"

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message}


class Module:
    """One parsed first-party module plus the name-resolution maps the
    rules share (import aliases, so `_time.sleep` and `from time import
    sleep as zzz` both resolve to `time.sleep`)."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)
        self.module_aliases: dict[str, str] = {}
        self.name_imports: dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    self.module_aliases[local] = (
                        alias.name if alias.asname else alias.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    self.name_imports[alias.asname or alias.name] = \
                        f"{node.module}.{alias.name}"

    # ---- name resolution ----

    def dotted(self, node: ast.expr) -> list[str] | None:
        """['a', 'b', 'c'] for the expression a.b.c, else None."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
            parts.reverse()
            return parts
        return None

    def resolve(self, func: ast.expr) -> str | None:
        """Dotted name of a call target with import aliases unwound:
        `_time.sleep` -> 'time.sleep', bare `sleep` imported from time ->
        'time.sleep'. Attribute chains rooted in non-names (e.g.
        `self._rng.random`) resolve to their literal spelling."""
        parts = self.dotted(func)
        if not parts:
            return None
        head = parts[0]
        if head in self.module_aliases:
            return ".".join([self.module_aliases[head]] + parts[1:])
        if head in self.name_imports:
            return ".".join([self.name_imports[head]] + parts[1:])
        return ".".join(parts)

    def allowed(self, rule: str, line: int) -> bool:
        """True when line `line` (1-based) or the line above carries a
        `# ktpu: allow[rule]` suppression for this rule."""
        for idx in (line - 1, line - 2):
            if 0 <= idx < len(self.lines):
                m = _ALLOW_RE.search(self.lines[idx])
                if m:
                    rules = {r.strip() for r in m.group(1).split(",")}
                    if rule in rules or "all" in rules:
                        return True
        return False


@dataclass
class AnalysisResult:
    findings: list[Finding] = field(default_factory=list)   # new (gating)
    baselined: list[Finding] = field(default_factory=list)
    suppressed: int = 0       # inline allow[...] count
    modules: int = 0
    stale_baseline: list[str] = field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.findings


def iter_module_paths(root: str | None = None) -> list[tuple[str, str]]:
    """(abspath, repo-relative path) for every first-party module under
    `root` (default: the kubernetes_tpu package)."""
    root = root or PKG_DIR
    root = os.path.abspath(root)
    if os.path.isfile(root):
        return [(root, os.path.relpath(root, REPO_ROOT))]
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in SKIP_DIRS)
        for name in sorted(filenames):
            if name.endswith(".py"):
                path = os.path.join(dirpath, name)
                out.append((path, os.path.relpath(path, REPO_ROOT)))
    return out


def load_baseline(path: str | None = None) -> dict[tuple[str, str], int]:
    """`rule path count` ratchet lines -> {(rule, path): count}."""
    path = path or BASELINE_PATH
    baseline: dict[tuple[str, str], int] = {}
    if not os.path.exists(path):
        return baseline
    with open(path, encoding="utf-8") as f:
        for raw in f:
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            if len(parts) != 3:
                raise ValueError(f"baseline.txt: bad line {raw!r} "
                                 "(want: rule path count)")
            baseline[(parts[0], parts[1])] = int(parts[2])
    return baseline


def lint_module(mod: Module, rules=None) -> tuple[list[Finding], int]:
    """All unsuppressed findings for one module + the inline-suppressed
    count. Rule exceptions become findings themselves (a broken rule must
    fail loudly in CI, not silently stop checking)."""
    from kubernetes_tpu.analysis.rules import RULES

    findings: list[Finding] = []
    suppressed = 0
    for rule in (rules if rules is not None else RULES):
        try:
            produced = list(rule.check(mod))
        except Exception as exc:  # pragma: no cover - rule bug surface
            findings.append(Finding(rule.name, mod.relpath, 1, 0,
                                    f"rule crashed: {exc!r}"))
            continue
        for f in produced:
            if mod.allowed(f.rule, f.line):
                suppressed += 1
            else:
                findings.append(f)
    return findings, suppressed


def lint_source(source: str, relpath: str = "fixture.py",
                rules=None) -> list[Finding]:
    """Lint an in-memory snippet (the fixture-test entry point)."""
    mod = Module(relpath, relpath, source)
    findings, _ = lint_module(mod, rules=rules)
    return findings


def run_analysis(paths: list[str] | None = None, *,
                 rules=None,
                 baseline: dict | None = None,
                 use_baseline: bool = True) -> AnalysisResult:
    """Lint every module under `paths` (default: the whole package) and
    split findings into new-vs-baselined. The ratchet: per (rule, path),
    the first `count` findings ride the baseline, any excess is new."""
    if baseline is None:
        baseline = load_baseline() if use_baseline else {}
    result = AnalysisResult()
    module_paths: list[tuple[str, str]] = []
    for p in (paths or [PKG_DIR]):
        module_paths.extend(iter_module_paths(p))
    seen_counts: dict[tuple[str, str], int] = {}
    for path, relpath in module_paths:
        with open(path, encoding="utf-8") as f:
            source = f.read()
        mod = Module(path, relpath.replace(os.sep, "/"), source)
        result.modules += 1
        findings, suppressed = lint_module(mod, rules=rules)
        result.suppressed += suppressed
        for f in sorted(findings, key=lambda f: (f.line, f.col)):
            key = (f.rule, f.path)
            seen_counts[key] = seen_counts.get(key, 0) + 1
            if seen_counts[key] <= baseline.get(key, 0):
                result.baselined.append(f)
            else:
                result.findings.append(f)
    for (rule, path), count in sorted(baseline.items()):
        if seen_counts.get((rule, path), 0) < count:
            result.stale_baseline.append(
                f"{rule} {path}: baseline grants {count}, found "
                f"{seen_counts.get((rule, path), 0)} — ratchet it down")
    return result
