"""CLI: python -m kubernetes_tpu.analysis [--strict] [--json] [paths...]

Exit codes: 0 clean (or informational run), 1 new findings under
--strict, 2 usage error. `--no-baseline` shows the whole debt;
`--rules r1,r2` narrows the catalog (names as in rules.py).
"""

from __future__ import annotations

import argparse
import json
import sys

from kubernetes_tpu.analysis.lint import run_analysis
from kubernetes_tpu.analysis.rules import RULE_NAMES, RULES


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m kubernetes_tpu.analysis",
        description="ktpu-lint: AST invariant checks (event-loop purity, "
                    "trace purity, BatchFlags discipline, determinism, "
                    "store write discipline)")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to lint (default: the whole package)")
    p.add_argument("--strict", action="store_true",
                   help="exit 1 on any new (non-baselined) finding")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore analysis/baseline.txt (show all findings)")
    p.add_argument("--rules", default="",
                   help="comma list of rule names to run (default: all)")
    args = p.parse_args(argv)

    rules = RULES
    if args.rules:
        wanted = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = wanted - RULE_NAMES
        if unknown:
            print(f"unknown rules: {sorted(unknown)} "
                  f"(have: {sorted(RULE_NAMES)})", file=sys.stderr)
            return 2
        rules = [r for r in RULES if r.name in wanted]

    result = run_analysis(args.paths or None, rules=rules,
                          use_baseline=not args.no_baseline)

    if args.as_json:
        print(json.dumps({
            "findings": [f.as_dict() for f in result.findings],
            "baselined": [f.as_dict() for f in result.baselined],
            "suppressed_inline": result.suppressed,
            "modules": result.modules,
            "stale_baseline": result.stale_baseline,
        }, indent=2))
    else:
        for f in result.findings:
            print(f.render())
        for note in result.stale_baseline:
            print(f"stale baseline: {note}", file=sys.stderr)
        print(f"ktpu-lint: {result.modules} modules, "
              f"{len(result.findings)} new finding(s), "
              f"{len(result.baselined)} baselined, "
              f"{result.suppressed} suppressed inline", file=sys.stderr)
    return 1 if (args.strict and result.findings) else 0


if __name__ == "__main__":
    sys.exit(main())
