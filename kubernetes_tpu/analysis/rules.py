"""The ktpu-lint rule catalog: six invariants the codebase rests on.

R1 blocking-in-async   — event-loop purity: no blocking call reachable on
                         the asyncio loop (the PR-2 webhook-SAR bug class).
R2 trace-impure        — jit-kernel purity: no host sync / wall clock /
                         Python control flow on traced values inside the
                         solver kernels (guards the HLO-pin invariant).
R3 batchflags-gate     — BatchFlags discipline: every flag pinned by a
                         gating-parity test, and no flag computed from
                         batch content outside the sanctioned gate fns.
R4 nondeterminism      — seeded replay: no ambient RNG / wall clock in the
                         solve path (the FaultPlane seed-replay contract).
R5 store-rmw           — write discipline: read-modify-write must carry a
                         resourceVersion precondition or ride the
                         sanctioned CAS helpers (the lost-update class).
R6 span-discipline     — observability hygiene: scoped span acquisitions
                         (start_span) ride `with`/try-finally so no code
                         path leaks an open span; counter/histogram
                         family names carry the Prometheus suffix
                         conventions (_total, _seconds/...).
R7 multiproc-handles   — process-boundary hygiene: no live handle
                         (socket, loop, store, shm, jax array holder)
                         captured by a multiprocessing spawn target or
                         passed in its args, and no raw SharedMemory
                         access outside the event-ring API.

Each rule is a small class with a `name` and `check(Module) -> [Finding]`.
Heuristics err toward precision: a rule that cries wolf gets suppressed
wholesale and protects nothing. The runtime complement (what static
analysis cannot see: actual interleavings, actual stalls) lives in
`kubernetes_tpu/testing/races.py`.
"""

from __future__ import annotations

import ast
import os
import re

from kubernetes_tpu.analysis.lint import (
    Finding,
    Module,
    PKG_DIR,
    REPO_ROOT,
)

# ---------------------------------------------------------------------------
# R1: event-loop purity


# Calls that park the calling thread. Inside `async def` that thread owns
# the event loop: every timer, watch stream and server on it freezes (the
# webhook-SAR bug PR 2 fixed by hand — now a machine-checked class).
BLOCKING_CALLS = {
    "time.sleep",
    "os.system",
    "os.wait",
    "socket.create_connection",
    "select.select",
    "urllib.request.urlopen",
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "subprocess.Popen",
}


def _walk_own_body(fn: ast.AST):
    """Yield nodes of a function body WITHOUT descending into nested
    function/lambda definitions (their bodies execute elsewhere — e.g. a
    worker passed to asyncio.to_thread — and are judged separately)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


# loop/asyncio surface that is NOT thread-safe: touching any of it from a
# stage worker thread corrupts or races the loop. The one sanctioned
# crossing is call_soon_threadsafe (scheduler/pipeline.py LoopCalls).
LOOP_ONLY_METHODS = {
    "call_soon", "call_later", "call_at", "create_task", "ensure_future",
    "run_until_complete",
}
# run_coroutine_threadsafe crosses into a foreign loop safely; asyncio.run
# (with new/set_event_loop) is a thread OWNING a private loop — the
# harness's in-process APIServer pattern — not a crossing at all
THREADSAFE_ASYNCIO = {"asyncio.run_coroutine_threadsafe", "asyncio.run",
                      "asyncio.new_event_loop", "asyncio.set_event_loop"}


def _thread_target_names(mod: Module) -> set[str]:
    """Function names passed as threading.Thread(target=...) anywhere in
    the module — the bodies that execute OFF the loop."""
    targets: set[str] = set()
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call)
                and mod.resolve(node.func) == "threading.Thread"):
            continue
        for kw in node.keywords:
            if kw.arg != "target":
                continue
            if isinstance(kw.value, ast.Attribute):
                targets.add(kw.value.attr)
            elif isinstance(kw.value, ast.Name):
                targets.add(kw.value.id)
    return targets


class EventLoopPurity:
    name = "blocking-in-async"

    def check(self, mod: Module):
        reported: set[int] = set()
        # tier 3: the inverse direction — a function handed to
        # threading.Thread(target=...) runs OFF the loop, so asyncio/loop
        # calls from it race loop internals (the staged-pipeline bug
        # class); only call_soon_threadsafe (and run_coroutine_threadsafe)
        # legally cross the thread->loop boundary
        thread_targets = _thread_target_names(mod)
        if thread_targets:
            for fn in ast.walk(mod.tree):
                if not isinstance(fn, ast.FunctionDef) \
                        or fn.name not in thread_targets:
                    continue
                for node in _walk_own_body(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    target = mod.resolve(node.func)
                    if target and target.startswith("asyncio.") \
                            and target not in THREADSAFE_ASYNCIO:
                        yield Finding(
                            self.name, mod.relpath, node.lineno,
                            node.col_offset,
                            f"{target}() inside thread target "
                            f"'{fn.name}' races the event loop from a "
                            "worker thread — marshal through "
                            "loop.call_soon_threadsafe")
                    elif isinstance(node.func, ast.Attribute) \
                            and node.func.attr in LOOP_ONLY_METHODS:
                        yield Finding(
                            self.name, mod.relpath, node.lineno,
                            node.col_offset,
                            f".{node.func.attr}() inside thread target "
                            f"'{fn.name}' is an event-loop method — not "
                            "thread-safe off the loop; marshal through "
                            "loop.call_soon_threadsafe")
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for node in _walk_own_body(fn):
                if not isinstance(node, ast.Call):
                    continue
                target = mod.resolve(node.func)
                if target in BLOCKING_CALLS:
                    reported.add(id(node))
                    yield Finding(
                        self.name, mod.relpath, node.lineno, node.col_offset,
                        f"blocking call {target}() inside "
                        f"'async def {fn.name}' parks the event loop — "
                        "use the await equivalent or asyncio.to_thread")
                elif (isinstance(node.func, ast.Attribute)
                      and node.func.attr == "accept"
                      and "limiter" in (mod.dotted(node.func.value)
                                        or ["?"])[-1]):
                    # the flowcontrol token bucket: sync accept() sleeps
                    reported.add(id(node))
                    yield Finding(
                        self.name, mod.relpath, node.lineno, node.col_offset,
                        f"sync rate-limiter accept() inside "
                        f"'async def {fn.name}' sleeps on the loop — "
                        "await accept_async() instead")
        # tier 2: a bare time.sleep anywhere in control-plane code is an
        # event-loop hazard the moment a coroutine reaches it (most of
        # this codebase runs on one loop). Legitimately-threaded sites
        # carry an explicit `# ktpu: allow[blocking-in-async]` so the
        # audit stays honest.
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and id(node) not in reported \
                    and mod.resolve(node.func) == "time.sleep":
                yield Finding(
                    self.name, mod.relpath, node.lineno, node.col_offset,
                    "time.sleep in control-plane code blocks any event "
                    "loop that reaches it — asyncio.sleep / to_thread it, "
                    "or annotate the thread-only path with "
                    "`# ktpu: allow[blocking-in-async]`")


# ---------------------------------------------------------------------------
# R2: trace purity of jit-compiled kernels


# modules holding jit-compiled kernels (the HLO-pinned surface)
KERNEL_MODULES = (
    "kubernetes_tpu/ops/solver.py",
    "kubernetes_tpu/ops/pallas_kernels.py",
    "kubernetes_tpu/autoscaler/simulator.py",
    "kubernetes_tpu/parallel/mesh.py",
    "kubernetes_tpu/state/pod_batch.py",
)

# kernel entry points jitted from OTHER modules (the driver wraps
# schedule_batch in jax.jit at its call site, so decorator detection
# alone cannot see these roots)
EXTRA_KERNEL_ROOTS = {
    "kubernetes_tpu/ops/solver.py": {"schedule_batch", "evaluate_pod"},
    "kubernetes_tpu/state/pod_batch.py": {"unpack_batch"},
}

# parameters that are static under jit (part of the compile key), so
# Python control flow on them is trace-time program selection, not a
# data-dependent branch
STATIC_PARAM_NAMES = {
    "self", "policy", "flags", "caps", "prows", "g", "gates", "table",
    "mesh", "interpret", "axis_name", "n", "num", "allow_fused",
}

TRACE_CLOCKS = {"time.time", "time.monotonic", "time.perf_counter",
                "time.process_time"}


def _is_jit_expr(mod: Module, node: ast.expr) -> bool:
    """True for `jax.jit`, bare `jit`, and partial(jax.jit, ...)."""
    target = mod.resolve(node)
    if target in ("jax.jit", "jit"):
        return True
    if isinstance(node, ast.Call) and \
            mod.resolve(node.func) in ("functools.partial", "partial"):
        return bool(node.args) and _is_jit_expr(mod, node.args[0])
    return False


class TracePurity:
    name = "trace-impure"

    def check(self, mod: Module):
        if not any(mod.relpath.endswith(k) for k in KERNEL_MODULES):
            return
        # module function table (top-level and nested, by bare name)
        fns: dict[str, list[ast.AST]] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fns.setdefault(node.name, []).append(node)

        roots: set[str] = set(EXTRA_KERNEL_ROOTS.get(mod.relpath, set()))
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(_is_jit_expr(mod, d) for d in node.decorator_list):
                    roots.add(node.name)
            elif isinstance(node, ast.Call) and \
                    mod.resolve(node.func) in ("jax.jit", "jit"):
                for arg in node.args[:1]:
                    if isinstance(arg, ast.Name):
                        roots.add(arg.id)
                    elif isinstance(arg, ast.Lambda):
                        for sub in ast.walk(arg.body):
                            if isinstance(sub, ast.Call) and \
                                    isinstance(sub.func, ast.Name):
                                roots.add(sub.func.id)

        # transitive closure over same-module bare-name calls
        kernel_names: set[str] = set()
        frontier = [r for r in roots if r in fns]
        while frontier:
            name = frontier.pop()
            if name in kernel_names:
                continue
            kernel_names.add(name)
            for fn in fns[name]:
                for node in ast.walk(fn):
                    if isinstance(node, ast.Call) and \
                            isinstance(node.func, ast.Name) and \
                            node.func.id in fns:
                        frontier.append(node.func.id)

        visited: set[int] = set()
        for name in sorted(kernel_names):
            for fn in fns[name]:
                if id(fn) in visited:
                    continue
                visited.add(id(fn))
                yield from self._check_kernel(mod, fn)

    def _check_kernel(self, mod: Module, fn: ast.AST):
        traced = {a.arg for a in (fn.args.posonlyargs + fn.args.args
                                  + fn.args.kwonlyargs)
                  if a.arg not in STATIC_PARAM_NAMES
                  and not self._static_annotation(a)}
        where = f"jit kernel '{fn.name}'"
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                target = mod.resolve(node.func)
                if target in TRACE_CLOCKS:
                    yield Finding(
                        self.name, mod.relpath, node.lineno, node.col_offset,
                        f"{target}() inside {where} is evaluated once at "
                        "trace time and baked into the compiled program")
                elif target and (target.startswith("random.")
                                 or target.startswith("numpy.random.")):
                    yield Finding(
                        self.name, mod.relpath, node.lineno, node.col_offset,
                        f"{target}() inside {where}: host RNG burns into "
                        "the trace — thread a jax PRNG key instead")
                elif target in ("numpy.asarray", "numpy.array"):
                    yield Finding(
                        self.name, mod.relpath, node.lineno, node.col_offset,
                        f"{target}() inside {where} forces a host sync on "
                        "traced values — use jnp inside the kernel")
                elif isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "item":
                    yield Finding(
                        self.name, mod.relpath, node.lineno, node.col_offset,
                        f".item() inside {where} forces a device->host "
                        "readback at trace time")
                elif isinstance(node.func, ast.Name) and \
                        node.func.id in ("float", "int", "bool") and \
                        any(self._direct_traced(a, traced)
                            for a in node.args):
                    yield Finding(
                        self.name, mod.relpath, node.lineno, node.col_offset,
                        f"{node.func.id}() on traced value inside {where} "
                        "concretizes the tracer (breaks under jit)")
            elif isinstance(node, (ast.If, ast.While)) and \
                    not self._is_structure_test(node.test) and \
                    self._traced_outside_calls(node.test, traced):
                yield Finding(
                    self.name, mod.relpath, node.lineno, node.col_offset,
                    f"Python `{'if' if isinstance(node, ast.If) else 'while'}`"
                    f" on traced value inside {where} — data-dependent "
                    "control flow must be lax.cond/jnp.where")

    @staticmethod
    def _static_annotation(arg: ast.arg) -> bool:
        ann = arg.annotation
        return isinstance(ann, ast.Name) and \
            ann.id in ("int", "bool", "str", "float", "Policy",
                       "PolicyGates", "BatchFlags", "Capacities")

    @classmethod
    def _direct_traced(cls, expr: ast.expr, traced: set[str]) -> bool:
        """Name / attribute / subscript chain rooted at a traced param
        (batch, state.requested, carry.rr[0] — a raw traced value, not an
        expression that merely mentions one)."""
        while isinstance(expr, (ast.Attribute, ast.Subscript)):
            expr = expr.value
        return isinstance(expr, ast.Name) and expr.id in traced

    @classmethod
    def _traced_outside_calls(cls, test: ast.expr, traced: set[str]) -> bool:
        """A traced value used directly in a branch test. Values passed as
        CALL ARGUMENTS are skipped: a helper that worked at first trace is
        trace-time-static by construction (a data read inside it would
        have raised TracerBoolConversionError already), while direct uses
        (`if batch.gang_id`, `if x.any()`) are data-dependent branches."""
        if cls._direct_traced(test, traced):
            return True
        if isinstance(test, ast.Call):
            # receiver chain of a method call is a direct use (.any());
            # arguments are the helper's problem
            return isinstance(test.func, ast.Attribute) and \
                cls._direct_traced(test.func.value, traced)
        if isinstance(test, ast.BoolOp):
            return any(cls._traced_outside_calls(v, traced)
                       for v in test.values)
        if isinstance(test, ast.UnaryOp):
            return cls._traced_outside_calls(test.operand, traced)
        if isinstance(test, ast.Compare):
            return any(cls._traced_outside_calls(e, traced)
                       for e in (test.left, *test.comparators))
        if isinstance(test, ast.BinOp):
            return any(cls._traced_outside_calls(e, traced)
                       for e in (test.left, test.right))
        return False

    @staticmethod
    def _is_structure_test(test: ast.expr) -> bool:
        """`x is None` / `x is not None` picks the traced pytree
        STRUCTURE (part of the jit key), not a data value — legal."""
        return isinstance(test, ast.Compare) and \
            all(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops)


# ---------------------------------------------------------------------------
# R3: BatchFlags discipline


# the only functions allowed to derive flag values from batch content
# (the driver/encoder gate fns whose outputs the parity tests pin)
SANCTIONED_GATES = {
    ("kubernetes_tpu/ops/solver.py", "batch_flags"),
    ("kubernetes_tpu/state/pod_batch.py", "packed_batch_flags"),
}

_SOLVER_RELPATH = "kubernetes_tpu/ops/solver.py"
_PIN_TEST_RELPATH = "tests/test_batch_flags.py"


def _batchflags_fields() -> dict[str, int]:
    """{field: lineno} of the BatchFlags dataclass, parsed from source so
    the rule needs no jax import."""
    path = os.path.join(REPO_ROOT, _SOLVER_RELPATH)
    if not os.path.exists(path):  # pragma: no cover - repo layout moved
        return {}
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read())
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "BatchFlags":
            return {stmt.target.id: stmt.lineno for stmt in node.body
                    if isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)}
    return {}


def _pin_coverage_map() -> dict[str, str] | None:
    """{flag: pin-test relpath} from the PIN_COVERAGE map in
    tests/test_batch_flags.py, or None when the map (or file) is missing."""
    path = os.path.join(REPO_ROOT, _PIN_TEST_RELPATH)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read())
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "PIN_COVERAGE"
                for t in node.targets) and \
                isinstance(node.value, ast.Dict):
            return {k.value: (v.value if isinstance(v, ast.Constant) else "")
                    for k, v in zip(node.value.keys, node.value.values)
                    if isinstance(k, ast.Constant)}
    return None


def _pinned_flags() -> set[str] | None:
    """Keys of the PIN_COVERAGE map, or None when it is missing."""
    cov = _pin_coverage_map()
    return None if cov is None else set(cov)


# BatchFlags fields whose gate changes the PARTITIONED program (mesh/
# sharding related): a gating-parity pin is not enough — the named pin test
# must also hold an HLO pin (a .lower(...)...as_text() comparison), because
# GSPMD can move collectives without changing single-device results.
_MESH_FIELD_RE = re.compile(r"(shard|mesh|spmd|device_axis)", re.IGNORECASE)


def _has_hlo_pin(relpath: str) -> bool:
    path = os.path.join(REPO_ROOT, relpath)
    if not os.path.exists(path):
        return False
    with open(path, encoding="utf-8") as f:
        src = f.read()
    return ".lower(" in src and "as_text" in src


def _const(expr: ast.expr) -> bool:
    return isinstance(expr, ast.Constant)


class BatchFlagsDiscipline:
    name = "batchflags-gate"

    def check(self, mod: Module):
        if mod.relpath == _SOLVER_RELPATH:
            yield from self._check_pin_coverage(mod)
        yield from self._check_gate_sites(mod)

    def _check_pin_coverage(self, mod: Module):
        fields = _batchflags_fields()
        pinned = _pinned_flags()
        if pinned is None:
            if fields:
                line = min(fields.values())
                yield Finding(
                    self.name, mod.relpath, line, 0,
                    f"no PIN_COVERAGE map in {_PIN_TEST_RELPATH}: every "
                    "BatchFlags field needs a named gating-parity pin")
            return
        coverage = _pin_coverage_map() or {}
        for name, line in sorted(fields.items(), key=lambda kv: kv[1]):
            if name not in pinned:
                yield Finding(
                    self.name, mod.relpath, line, 0,
                    f"BatchFlags.{name} is not listed in PIN_COVERAGE "
                    f"({_PIN_TEST_RELPATH}) — a flag without a "
                    "gating-parity pin can silently change the compiled "
                    "program")
            elif _MESH_FIELD_RE.search(name) and \
                    not _has_hlo_pin(coverage.get(name, "")):
                yield Finding(
                    self.name, mod.relpath, line, 0,
                    f"BatchFlags.{name} is mesh-related but its pin test "
                    f"({coverage.get(name) or 'unset'}) carries no HLO pin "
                    "(.lower()/as_text comparison) — GSPMD partitioning "
                    "changes are invisible to value-level parity pins")

    def _check_gate_sites(self, mod: Module):
        fields = set(_batchflags_fields())
        # enclosing-function map for sanction checks
        enclosing: dict[int, str] = {}
        for fn in ast.walk(mod.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for node in ast.walk(fn):
                    enclosing.setdefault(id(node), fn.name)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            target = mod.resolve(node.func)
            if target and target.split(".")[-1] == "BatchFlags":
                derived = bool(node.args) or any(
                    not _const(kw.value) for kw in node.keywords)
                sanctioned = (mod.relpath,
                              enclosing.get(id(node), "")) in SANCTIONED_GATES
                if derived and not sanctioned:
                    yield Finding(
                        self.name, mod.relpath, node.lineno, node.col_offset,
                        "BatchFlags derived from batch content outside the "
                        "sanctioned gate functions (solver.batch_flags / "
                        "pod_batch.packed_batch_flags) — ad-hoc gates skip "
                        "the parity pins")
            elif target and target.split(".")[-1] == "replace" and fields \
                    and self._flags_receiver(mod, node):
                hit = [kw.arg for kw in node.keywords
                       if kw.arg in fields and not _const(kw.value)]
                if hit:
                    yield Finding(
                        self.name, mod.relpath, node.lineno, node.col_offset,
                        f"replace({', '.join(hit)}=...) derives a "
                        "BatchFlags field from a non-constant outside the "
                        "sanctioned gate functions")

    @staticmethod
    def _flags_receiver(mod: Module, node: ast.Call) -> bool:
        """Is this replace() plausibly operating on a BatchFlags value?
        Method style: receiver named *flag*; dataclasses.replace style:
        first arg named *flag* or built by a sanctioned gate fn. Keeps
        Carry.replace(ipa=...) and other field-name collisions out."""
        if isinstance(node.func, ast.Attribute):
            d = mod.dotted(node.func.value)
            return bool(d) and "flag" in d[-1].lower()
        arg = node.args[0] if node.args else None
        if isinstance(arg, ast.Call):
            inner = mod.resolve(arg.func) or ""
            return inner.split(".")[-1] in ("batch_flags",
                                            "packed_batch_flags",
                                            "BatchFlags")
        d = mod.dotted(arg) if arg is not None else None
        return bool(d) and "flag" in d[-1].lower()


# ---------------------------------------------------------------------------
# R4: seeded determinism of the solve path


R4_SCOPES = ("kubernetes_tpu/ops/", "kubernetes_tpu/state/",
             "kubernetes_tpu/scheduler/", "kubernetes_tpu/descheduler/",
             "kubernetes_tpu/solversvc/", "kubernetes_tpu/scenario/")
R4_FILES = ("kubernetes_tpu/autoscaler/simulator.py",)

AMBIENT_ENTROPY = {"uuid.uuid4", "uuid.uuid1", "os.urandom",
                   "numpy.random.seed"}


class Determinism:
    name = "nondeterminism"

    def check(self, mod: Module):
        if not (mod.relpath.startswith(R4_SCOPES)
                or mod.relpath in R4_FILES):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            target = mod.resolve(node.func)
            if target is None:
                continue
            # module-level random.* only: a seeded random.Random instance
            # (self._rng.random()) is the injected, replayable source —
            # and constructing one IS the sanctioned injection move
            if target in ("random.Random", "numpy.random.default_rng"):
                continue
            if target.startswith(("random.", "numpy.random.",
                                  "secrets.")) or \
                    target in AMBIENT_ENTROPY:
                base = target.split(".")[0]
                head = (mod.dotted(node.func) or ["?"])[0]
                if base in ("random", "numpy", "secrets", "uuid", "os") \
                        and (head in mod.module_aliases
                             or head in mod.name_imports
                             or head in ("random", "np", "numpy", "uuid",
                                         "os", "secrets")):
                    yield Finding(
                        self.name, mod.relpath, node.lineno, node.col_offset,
                        f"ambient {target}() in the solve path breaks "
                        "seed-replay (FaultPlane contract) — inject a "
                        "random.Random(seed) / jax PRNG key")
            elif target == "time.time":
                yield Finding(
                    self.name, mod.relpath, node.lineno, node.col_offset,
                    "wall-clock time.time() in the solve path breaks "
                    "seed-replay — inject utils.clock.Clock (tests warp "
                    "it; perf_counter is fine for metrics)")


# ---------------------------------------------------------------------------
# R5: store write discipline


class StoreWriteDiscipline:
    name = "store-rmw"

    # the store itself defines the checked/unchecked semantics
    EXEMPT = ("kubernetes_tpu/apiserver/store.py",)

    def check(self, mod: Module):
        if mod.relpath in self.EXEMPT:
            return
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "update":
                for kw in node.keywords:
                    if kw.arg == "check_version" and \
                            isinstance(kw.value, ast.Constant) and \
                            kw.value.value is False:
                        yield Finding(
                            self.name, mod.relpath, node.lineno,
                            node.col_offset,
                            "update(check_version=False) discards the "
                            "resourceVersion precondition: a concurrent "
                            "writer's change is silently lost — use "
                            "guaranteed_update/patch, or carry the read "
                            "version")
            elif isinstance(node, ast.Assign):
                for tgt in node.targets:
                    d = mod.dotted(tgt)
                    if d and d[-2:] == ["metadata", "resource_version"] \
                            and isinstance(node.value, ast.Constant) \
                            and not node.value.value:
                        yield Finding(
                            self.name, mod.relpath, node.lineno,
                            node.col_offset,
                            "stripping metadata.resource_version before a "
                            "write defeats optimistic concurrency (the "
                            "lost-update race class)")


# ---------------------------------------------------------------------------
# R6: span lifecycle + metric naming discipline


COUNTER_SUFFIXES = ("_total", "_count")
HISTOGRAM_SUFFIXES = ("_seconds", "_ms", "_microseconds")
# a recording rule's output is itself a family: unit/shape suffixes only
# (rates and ratios get their own, on top of the counter/histogram set)
RECORDING_SUFFIXES = COUNTER_SUFFIXES + HISTOGRAM_SUFFIXES + (
    "_ratio", "_frac", "_per_second", "_bytes", "_mib", "_cores")
# Prometheus alertname convention: CamelCase, e.g. SchedulerDown
ALERT_NAME_RE = re.compile(r"^[A-Z][a-zA-Z0-9]*$")
# profiling endpoints live in the pprof-style debug namespace
# (obs/profiling.py): /debug/pprof/<what> or /debug/profile/<what>
PROFILE_PATH_RE = re.compile(r"^/debug/(pprof|profile)(/[a-z_]+)+$")


class SpanDiscipline:
    """start_span is the SCOPED acquisition API (obs/tracing.py): its
    return value must be a `with` context (Span.__exit__ ends it and
    stamps error status on exceptions) or be .end()ed in a try/finally —
    otherwise a raised exception leaks an open span, which the orphan
    check (/debug/traces open_spans) then reports forever. begin_span is
    the EXPLICIT-handoff API for cross-thread spans (the staged
    pipeline's batch spans) and is exempt by design: its callers own the
    end on every path.

    Second check: Prometheus naming. Counter families end in _total (or
    the reference's legacy _count), histogram families in a unit suffix
    (_seconds/_ms/_microseconds) — a family without one renders
    dashboards unit-blind.

    Third check: monitoring-rule naming. A RecordingRule's output series
    is a family like any other, so its name must carry a unit/shape
    suffix (the counter/histogram set plus _ratio/_frac/_per_second/
    _bytes/_mib/_cores); an AlertingRule's name must be CamelCase (the
    Prometheus alertname convention — `kubectl get alerts` and the Event
    reason both render it).

    Fourth check: profiling-plane naming. Profiling sample families
    carry the `profiling_` prefix (one namespace for the sampler /
    compile-introspection metrics), and any `*_PATH` endpoint constant
    whose value mentions profiling lives under the pprof-style debug
    namespace (`/debug/pprof/*` or `/debug/profile/*`) — ad-hoc
    profile routes fragment the obs mux surface.

    Fifth check: solver-service naming. Every metric family DEFINED in
    `kubernetes_tpu/solversvc/` carries the `solversvc_` prefix — the
    multi-tenant serving plane is one dashboard namespace, and a bare
    `requests_total` from the service would collide with (or hide
    behind) the apiserver's families on every federated scrape.

    Sixth check: replication-plane naming. Metric families DEFINED in
    `kubernetes_tpu/apiserver/replication.py` carry the registered
    `store_replication_` family prefix — failover dashboards and the
    bench[store-ha] gate select on that namespace, and a bare
    `promotions_total` would alias leader-election families from the
    client package on the same scrape.

    Seventh check: federation-plane naming. Metric families DEFINED in
    `kubernetes_tpu/federation/` carry the `federation_` prefix (the
    GlobalPlanner's `federation_planner_{cycles,placements,spillovers}
    _total` / `federation_planner_solve_seconds` set the pattern) — the
    hub scrapes its own apiserver AND every member's, so a bare
    `placements_total` from the planner would shadow member scheduler
    families on the federated dashboard."""

    name = "span-discipline"

    def check(self, mod: Module):
        yield from self._check_span_lifecycle(mod)
        yield from self._check_metric_names(mod)
        yield from self._check_rule_names(mod)
        yield from self._check_profiling_names(mod)
        yield from self._check_solversvc_names(mod)
        yield from self._check_replication_names(mod)
        yield from self._check_federation_names(mod)

    def _check_span_lifecycle(self, mod: Module):
        sanctioned: set[int] = set()
        finally_ended: set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    sanctioned.add(id(item.context_expr))
            elif isinstance(node, ast.Try) and node.finalbody:
                for stmt in node.finalbody:
                    for call in ast.walk(stmt):
                        if isinstance(call, ast.Call) and \
                                isinstance(call.func, ast.Attribute) and \
                                call.func.attr == "end":
                            d = mod.dotted(call.func.value)
                            if d:
                                finally_ended.add(d[-1])
        # an assignment whose NAME is .end()ed inside some finally in this
        # module counts as try/finally discipline
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                names = {t.id for t in node.targets
                         if isinstance(t, ast.Name)}
                names |= {t.attr for t in node.targets
                          if isinstance(t, ast.Attribute)}
                if names & finally_ended:
                    sanctioned.add(id(node.value))
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "start_span"):
                continue
            if id(node) in sanctioned:
                continue
            yield Finding(
                self.name, mod.relpath, node.lineno, node.col_offset,
                "start_span() outside a `with` block or try/finally "
                "that .end()s it: an exception leaks an open span "
                "(orphan in /debug/traces) — use `with ...start_span(...)"
                "` , end it in a finally, or switch to begin_span() and "
                "own the end on every path")

    def _check_metric_names(self, mod: Module):
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("counter", "histogram")):
                continue
            arg = node.args[0] if node.args else None
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                continue
            kind, fam = node.func.attr, arg.value
            if kind == "counter" and not fam.endswith(COUNTER_SUFFIXES):
                yield Finding(
                    self.name, mod.relpath, node.lineno, node.col_offset,
                    f"counter family {fam!r} must end in "
                    f"{'/'.join(COUNTER_SUFFIXES)} (Prometheus counter "
                    "naming; renderers and recording rules key on it)")
            elif kind == "histogram" and \
                    not fam.endswith(HISTOGRAM_SUFFIXES):
                yield Finding(
                    self.name, mod.relpath, node.lineno, node.col_offset,
                    f"histogram family {fam!r} must carry a unit suffix "
                    f"({'/'.join(HISTOGRAM_SUFFIXES)}) — unit-blind "
                    "duration families misread as counts on dashboards")

    @staticmethod
    def _rule_name_arg(node: ast.Call, kw_name: str):
        """First positional arg or the named keyword, when a constant
        string (dynamic names are a runtime-validation concern)."""
        arg = node.args[0] if node.args else next(
            (kw.value for kw in node.keywords if kw.arg == kw_name), None)
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg
        return None

    def _check_rule_names(self, mod: Module):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            ctor = func.id if isinstance(func, ast.Name) else (
                func.attr if isinstance(func, ast.Attribute) else "")
            if ctor == "RecordingRule":
                arg = self._rule_name_arg(node, "record")
                if arg is not None and \
                        not arg.value.endswith(RECORDING_SUFFIXES):
                    yield Finding(
                        self.name, mod.relpath, node.lineno,
                        node.col_offset,
                        f"recording rule output {arg.value!r} must end in "
                        f"a unit/shape suffix "
                        f"({'/'.join(RECORDING_SUFFIXES)}) — the recorded "
                        "series is a metric family like any other")
            elif ctor == "AlertingRule":
                arg = self._rule_name_arg(node, "alert")
                if arg is not None and not ALERT_NAME_RE.match(arg.value):
                    yield Finding(
                        self.name, mod.relpath, node.lineno,
                        node.col_offset,
                        f"alert name {arg.value!r} must be CamelCase "
                        "(^[A-Z][a-zA-Z0-9]*$, the Prometheus alertname "
                        "convention — kubectl and Event reasons render "
                        "it)")

    def _check_profiling_names(self, mod: Module):
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("counter", "gauge",
                                           "histogram"):
                arg = node.args[0] if node.args else None
                if isinstance(arg, ast.Constant) \
                        and isinstance(arg.value, str) \
                        and "profil" in arg.value \
                        and not arg.value.startswith("profiling_"):
                    yield Finding(
                        self.name, mod.relpath, node.lineno,
                        node.col_offset,
                        f"profiling-plane family {arg.value!r} must carry "
                        "the profiling_ prefix — one namespace for the "
                        "sampler/compile introspection families")
            elif isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if not (isinstance(tgt, ast.Name)
                            and tgt.id.endswith("_PATH")):
                        continue
                    v = node.value
                    if isinstance(v, ast.Constant) \
                            and isinstance(v.value, str) \
                            and "prof" in v.value \
                            and not PROFILE_PATH_RE.match(v.value):
                        yield Finding(
                            self.name, mod.relpath, tgt.lineno,
                            tgt.col_offset,
                            f"profiling endpoint {v.value!r} must live "
                            "under /debug/pprof/* or /debug/profile/* "
                            "(the pprof-style debug namespace the obs "
                            "mux routes)")

    def _check_solversvc_names(self, mod: Module):
        if not mod.relpath.startswith("kubernetes_tpu/solversvc/"):
            return
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("counter", "gauge",
                                           "histogram")):
                continue
            arg = node.args[0] if node.args else None
            if isinstance(arg, ast.Constant) \
                    and isinstance(arg.value, str) \
                    and not arg.value.startswith("solversvc_"):
                yield Finding(
                    self.name, mod.relpath, node.lineno, node.col_offset,
                    f"solve-service family {arg.value!r} must carry the "
                    "solversvc_ prefix — the multi-tenant serving plane "
                    "is one dashboard namespace and bare names collide "
                    "with the apiserver's families on federated scrapes")

    def _check_replication_names(self, mod: Module):
        if mod.relpath != "kubernetes_tpu/apiserver/replication.py":
            return
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("counter", "gauge",
                                           "histogram")):
                continue
            arg = node.args[0] if node.args else None
            if isinstance(arg, ast.Constant) \
                    and isinstance(arg.value, str) \
                    and not arg.value.startswith("store_replication_"):
                yield Finding(
                    self.name, mod.relpath, node.lineno, node.col_offset,
                    f"replication family {arg.value!r} must carry the "
                    "registered store_replication_ prefix — failover "
                    "dashboards and the bench[store-ha] gate select on "
                    "that namespace, and bare names alias the client "
                    "package's leader-election families")

    def _check_federation_names(self, mod: Module):
        if not mod.relpath.startswith("kubernetes_tpu/federation/"):
            return
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("counter", "gauge",
                                           "histogram")):
                continue
            arg = node.args[0] if node.args else None
            if isinstance(arg, ast.Constant) \
                    and isinstance(arg.value, str) \
                    and not arg.value.startswith("federation_"):
                yield Finding(
                    self.name, mod.relpath, node.lineno, node.col_offset,
                    f"federation family {arg.value!r} must carry the "
                    "federation_ prefix — the hub scrapes its own and "
                    "every member's apiserver, and a bare planner family "
                    "would shadow member scheduler families on the "
                    "federated dashboard")


# ---------------------------------------------------------------------------
# R7: multiprocessing handle discipline


class MultiprocDiscipline:
    """A child process gets a COPY (fork) or a re-pickle (spawn) of
    whatever the target captures — a socket fd pointing at the parent's
    connection state, an event loop that was never running there, an
    ObjectStore whose mutations silently diverge from the parent's, a
    jax array whose device buffer does not follow. Every one of these is
    a works-on-the-happy-path bug that only detonates under load or
    respawn. The discipline (apiserver/multiproc.py's WorkerSpec shape):
    a spawn target is a MODULE-LEVEL function taking only names and
    numbers; the child constructs its own handles.

    Three checks:
      1. `*.Process(target=...)` with a lambda, a bound method
         (Attribute), or a function defined nested in another function —
         all three capture enclosing live state.
      2. `Process(args=/kwargs=)` entries whose terminal identifier names
         a live handle (store/loop/sock/ring/shm/...).
      3. Raw `SharedMemory(...)` construction outside the event-ring
         module: the ring API owns segment naming, tracker discipline
         and lifetime; ad-hoc segments leak on crash."""

    name = "multiproc-handles"

    # terminal identifiers that name live handles (matched after
    # stripping leading underscores)
    LIVE_HANDLES = {
        "store", "loop", "sock", "socket", "server", "conn", "writer",
        "reader", "shm", "ring", "cache", "client", "session", "arr",
        "array",
    }
    # the ring module owns the raw segment; everyone else rides its API
    SHM_EXEMPT = ("kubernetes_tpu/apiserver/multiproc.py",)

    def check(self, mod: Module):
        nested = self._nested_function_names(mod)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = mod.resolve(node.func) or ""
            if resolved == "Process" or resolved.endswith(".Process"):
                yield from self._check_process_call(mod, node, nested)
            if resolved == "SharedMemory" \
                    or resolved.endswith(".SharedMemory"):
                if mod.relpath not in self.SHM_EXEMPT:
                    yield Finding(
                        self.name, mod.relpath, node.lineno,
                        node.col_offset,
                        "raw SharedMemory() outside the event-ring API "
                        "(apiserver/multiproc.py): the ring owns segment "
                        "naming, resource-tracker discipline and unlink "
                        "lifetime — ad-hoc segments leak on crash")

    @staticmethod
    def _nested_function_names(mod: Module) -> set[str]:
        """Names of functions defined INSIDE another function/method —
        passing one as a spawn target captures the enclosing frame."""
        top = {n.name for n in mod.tree.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        nested: set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for child in ast.walk(node):
                    if child is not node and isinstance(
                            child,
                            (ast.FunctionDef, ast.AsyncFunctionDef)):
                        if child.name not in top:
                            nested.add(child.name)
        return nested

    def _is_live_handle(self, expr: ast.expr) -> str | None:
        """The offending terminal identifier when `expr` names a live
        handle, else None."""
        if isinstance(expr, ast.Attribute):
            terminal = expr.attr
        elif isinstance(expr, ast.Name):
            terminal = expr.id
        else:
            return None
        stripped = terminal.lstrip("_").lower()
        return terminal if stripped in self.LIVE_HANDLES else None

    def _check_process_call(self, mod: Module, node: ast.Call,
                            nested: set[str]):
        for kw in node.keywords:
            if kw.arg == "target":
                v = kw.value
                if isinstance(v, ast.Lambda):
                    yield Finding(
                        self.name, mod.relpath, v.lineno, v.col_offset,
                        "lambda as a Process target captures its "
                        "enclosing frame (sockets, loops, stores ride "
                        "along) — use a module-level function taking a "
                        "picklable spec")
                elif isinstance(v, ast.Attribute):
                    yield Finding(
                        self.name, mod.relpath, v.lineno, v.col_offset,
                        f"bound method {ast.unparse(v)!r} as a Process "
                        "target pickles/forks its whole instance — every "
                        "live handle on it crosses the process boundary; "
                        "use a module-level function taking a picklable "
                        "spec")
                elif isinstance(v, ast.Name) and v.id in nested:
                    yield Finding(
                        self.name, mod.relpath, v.lineno, v.col_offset,
                        f"nested function {v.id!r} as a Process target "
                        "captures its enclosing frame — hoist it to "
                        "module level and pass state through args")
            elif kw.arg in ("args", "kwargs"):
                elements: list[ast.expr] = []
                if isinstance(kw.value, (ast.Tuple, ast.List)):
                    elements = list(kw.value.elts)
                elif isinstance(kw.value, ast.Dict):
                    elements = [v for v in kw.value.values
                                if v is not None]
                for el in elements:
                    offender = self._is_live_handle(el)
                    if offender:
                        yield Finding(
                            self.name, mod.relpath, el.lineno,
                            el.col_offset,
                            f"live handle {offender!r} passed to a child "
                            "process: the child gets a copy/re-pickle "
                            "whose state silently diverges (fds, loops, "
                            "stores, device arrays don't cross) — pass "
                            "names/numbers and reconstruct inside the "
                            "child")


RULES = [EventLoopPurity(), TracePurity(), BatchFlagsDiscipline(),
         Determinism(), StoreWriteDiscipline(), SpanDiscipline(),
         MultiprocDiscipline()]

RULE_NAMES = {r.name for r in RULES}
