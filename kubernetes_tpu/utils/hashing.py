"""Stable string hashing for device-side label/taint/selector matching.

The reference matches labels, selectors, taints and node names as Go strings
(e.g. plugin/pkg/scheduler/algorithm/predicates/predicates.go:686
`PodMatchNodeSelector`). On TPU, strings become fixed-width integer hashes
computed once on the host at encode time; all device-side comparisons are
integer equality. We use FNV-1a 64-bit split into two uint32 lanes (TPU int64
support is emulated, uint32 compares are native), giving an effective 64-bit
match space: collisions require both lanes to collide simultaneously
(~2^-64 per pair; at 15k nodes x 32 labels the birthday bound is ~1e-8).

Hash value 0 is reserved as the "empty slot" sentinel; real hashes that land
on 0 are remapped to 1.
"""

from __future__ import annotations

from kubernetes_tpu import native as _native

_FNV64_OFFSET = 0xCBF29CE484222325
_FNV64_PRIME = 0x100000001B3
_MASK64 = 0xFFFFFFFFFFFFFFFF


def _fnv1a64_py(data: bytes) -> int:
    h = _FNV64_OFFSET
    for b in data:
        h = ((h ^ b) * _FNV64_PRIME) & _MASK64
    return h


def fnv1a64(data: str | bytes) -> int:
    """FNV-1a 64-bit hash of a string (utf-8) or bytes. Computed by the
    native kernel (kubernetes_tpu/native/fnv.c) when the build-on-import
    succeeded; bit-identical pure-Python fallback otherwise."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    if _native.fnv1a64 is not None:
        return _native.fnv1a64(data)
    return _fnv1a64_py(data)


def hash_lanes(data: str | bytes) -> tuple[int, int]:
    """Return (lo32, hi32) uint32 lanes of fnv1a64, each remapped 0 -> 1."""
    h = fnv1a64(data)
    lo = h & 0xFFFFFFFF
    hi = (h >> 32) & 0xFFFFFFFF
    return (lo or 1, hi or 1)


def hash32(data: str | bytes) -> int:
    """Single uint32 hash lane (lo lane), 0 remapped to 1.

    Used where one lane suffices (small universes such as topology-domain
    interning where exactness is enforced by a host-side intern table).
    """
    return hash_lanes(data)[0]


def hash_kv(key: str, value: str) -> tuple[int, int]:
    """Hash lanes for a key=value pair (labels, selector terms, taints)."""
    return hash_lanes(key + "\x00" + value)


def hash_lanes_many(items: list[str | bytes]) -> list[tuple[int, int]]:
    """Lanes for a batch of strings in ONE native call when the kernel is
    available (encode paths hash several strings per object); scalar
    fallback is bit-identical."""
    if _native.lanes_batch is not None and items:
        encoded = [i.encode("utf-8") if isinstance(i, str) else i
                   for i in items]
        lo, hi = _native.lanes_batch(encoded)
        return [(int(lo[k]), int(hi[k])) for k in range(len(items))]
    return [hash_lanes(i) for i in items]
