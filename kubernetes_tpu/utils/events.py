"""Event recording with spam aggregation.

The EventBroadcaster/EventRecorder analog (reference
client-go/tools/record/event.go:78,114 and events_cache aggregation): repeated
(object, reason, message) events bump a count on one stored Event instead of
creating new objects.
"""

from __future__ import annotations

import time

from kubernetes_tpu.api.objects import Event, ObjectMeta
from kubernetes_tpu.apiserver.store import NotFound, ObjectStore


class EventRecorder:
    def __init__(self, store: ObjectStore, component: str = "default-scheduler"):
        self.store = store
        self.component = component

    def record(self, obj, event_type: str, reason: str, message: str) -> Event:
        name = f"{obj.metadata.name}.{reason.lower()}"
        namespace = obj.metadata.namespace
        try:
            existing = self.store.get("Event", name, namespace)
            existing.count += 1
            existing.message = message
            return self.store.update(existing, check_version=False)
        except NotFound:
            event = Event(
                metadata=ObjectMeta(name=name, namespace=namespace),
                involved_object={
                    "kind": obj.kind,
                    "name": obj.metadata.name,
                    "namespace": namespace,
                    "uid": obj.metadata.uid,
                },
                reason=reason,
                message=message,
                type=event_type,
                source_component=self.component,
            )
            return self.store.create(event)
