"""Event recording with spam aggregation.

The EventBroadcaster/EventRecorder analog (reference
client-go/tools/record/event.go:78,114 and events_cache aggregation): repeated
(object, reason, message) events bump a count on one stored Event instead of
creating new objects.
"""

from __future__ import annotations

import time
from collections import OrderedDict

from kubernetes_tpu.api.objects import Event, ObjectMeta
from kubernetes_tpu.apiserver.store import (
    AlreadyExists,
    Conflict,
    NotFound,
    ObjectStore,
    TooManyRequests,
)

_KNOWN_MAX = 65536


def _group_entries(entries: list[tuple]):
    """Merge (obj, type, reason, message) 4-tuples by (type, reason),
    groups ordered by first appearance: [(type, reason, [(obj, message),
    ...]), ...]. Within a solved batch no object appears under two
    reasons (a pod either bound or failed), so merging runs is safe."""
    groups: dict[tuple[str, str], list[tuple]] = {}
    for obj, event_type, reason, message in entries:
        groups.setdefault((event_type, reason), []).append((obj, message))
    return [(t, r, sub) for (t, r), sub in groups.items()]


class EventRecorder:
    def __init__(self, store: ObjectStore, component: str = "default-scheduler"):
        self.store = store
        self.component = component
        # LRU aggregation index: most events are first-time names (per-pod),
        # and raising NotFound per recorded event dominates the recorder
        # under load; bounded so a long-lived process cannot grow it forever
        self._known: OrderedDict[tuple[str, str], None] = OrderedDict()

    def build_many(self, entries: list[tuple], event_type: str,
                   reason: str) -> tuple[list[Event], list[tuple[str, str]]]:
        """Construct (but do not store) the Event objects for a batch of
        (obj, message) pairs. Pure object construction — no store access, no
        recorder state — so an event worker shard can run it OFF the event
        loop while the driver keeps scheduling; install_many() publishes the
        result on the loop."""
        built: list[Event] = []
        keys: list[tuple[str, str]] = []
        reason_suffix = f".{reason.lower()}"
        for obj, message in entries:
            name = obj.metadata.name + reason_suffix
            namespace = obj.metadata.namespace
            keys.append((namespace, name))
            built.append(Event(
                metadata=ObjectMeta(name=name, namespace=namespace),
                involved_object={
                    "kind": obj.kind,
                    "name": obj.metadata.name,
                    "namespace": namespace,
                    "uid": obj.metadata.uid,
                },
                reason=reason,
                message=message,
                type=event_type,
                source_component=self.component,
            ))
        return built, keys

    def record_many(
            self, entries: list[tuple], event_type: str, reason: str) -> None:
        """Batched recording of one (type, reason) across many objects — the
        scheduler's per-batch `Scheduled` burst. entries = (obj, message)
        pairs. First-time names (the overwhelming case: event names embed
        the per-pod object name) go through the store's bulk-create path in
        one pass; repeats fall back to the aggregating record()."""
        built, keys = self.build_many(entries, event_type, reason)
        self.install_many(entries, built, keys, event_type, reason)

    def record_grouped(self, entries: list[tuple]) -> None:
        """Record (obj, event_type, reason, message) 4-tuples, coalescing
        runs that share (type, reason) into one batched store write each —
        a solved batch's Scheduled burst plus its FailedScheduling tail
        lands in two bulk creates instead of thousands of singles."""
        for event_type, reason, sub in _group_entries(entries):
            self.record_many(sub, event_type, reason)

    def install_many(self, entries: list[tuple], built: list[Event],
                     keys: list[tuple[str, str]], event_type: str,
                     reason: str) -> None:
        """Publish pre-built events (build_many) to the store — the
        loop-side half of record_many. Names already in the aggregation
        index fall back to the bumping record() path."""
        fresh: list[Event] = []
        fresh_keys: list[tuple[str, str]] = []
        for (obj, message), event, key in zip(entries, built, keys):
            if key in self._known:
                self.record(obj, event_type, reason, message)
            else:
                fresh.append(event)
                fresh_keys.append(key)
        if not fresh:
            return
        create_many = getattr(self.store, "create_many", None)
        if create_many is None:
            for event in fresh:
                try:
                    self.store.create(event, copy=False)
                except AlreadyExists:
                    # aggregate like record(): the name exists, bump count
                    self._bump(event.metadata.name, event.metadata.namespace,
                               event.message)
        else:
            try:
                create_many(fresh)
            except AlreadyExists:
                # a name existed that _known had forgotten: replay per-event,
                # aggregating onto existing objects (count += 1); an existing
                # object carrying OUR uid was the batch's own committed
                # prefix and is left alone
                for event in fresh:
                    try:
                        self.store.create(event, copy=False)
                    except AlreadyExists:
                        existing = self.store.get(
                            "Event", event.metadata.name,
                            event.metadata.namespace)
                        if existing.metadata.uid == event.metadata.uid:
                            continue
                        self._bump(event.metadata.name,
                                   event.metadata.namespace, event.message)
        for key in fresh_keys:
            self._known[key] = None
        while len(self._known) > _KNOWN_MAX:
            self._known.popitem(last=False)

    def _bump(self, name: str, namespace: str, message: str) -> Event:
        """Aggregate onto the stored Event through the CAS retry loop — a
        concurrent recorder's bump is retried against, never overwritten
        (the unversioned read-modify-write this replaced could lose
        counts; ktpu-lint store-rmw flagged every such site)."""

        def mutate(ev):
            ev.count += 1
            ev.message = message

        return self.store.guaranteed_update("Event", name, namespace, mutate)

    def record(self, obj, event_type: str, reason: str,
               message: str) -> Event | None:
        """Best-effort: a throttled or conflicted store drops the event
        (the broadcaster's lossy contract — events are observability, and
        losing one must never fail the component's control flow)."""
        try:
            return self._record(obj, event_type, reason, message)
        except (TooManyRequests, Conflict):
            return None

    def _record(self, obj, event_type: str, reason: str, message: str) -> Event:
        name = f"{obj.metadata.name}.{reason.lower()}"
        namespace = obj.metadata.namespace
        key = (namespace, name)
        if key in self._known:
            self._known.move_to_end(key)
            try:
                return self._bump(name, namespace, message)
            except NotFound:
                self._known.pop(key, None)  # deleted externally: recreate
        event = Event(
            metadata=ObjectMeta(name=name, namespace=namespace),
            involved_object={
                "kind": obj.kind,
                "name": obj.metadata.name,
                "namespace": namespace,
                "uid": obj.metadata.uid,
            },
            reason=reason,
            message=message,
            type=event_type,
            source_component=self.component,
        )
        try:
            created = self.store.create(event, copy=False)
        except AlreadyExists:
            # raced with an earlier instance of this event name
            created = self._bump(name, namespace, message)
        self._known[key] = None
        if len(self._known) > _KNOWN_MAX:
            self._known.popitem(last=False)
        return created
