"""Event recording with spam aggregation.

The EventBroadcaster/EventRecorder analog (reference
client-go/tools/record/event.go:78,114 and events_cache aggregation): repeated
(object, reason, message) events bump a count on one stored Event instead of
creating new objects.
"""

from __future__ import annotations

import time
from collections import OrderedDict

from kubernetes_tpu.api.objects import Event, ObjectMeta
from kubernetes_tpu.apiserver.store import AlreadyExists, NotFound, ObjectStore

_KNOWN_MAX = 65536


class EventRecorder:
    def __init__(self, store: ObjectStore, component: str = "default-scheduler"):
        self.store = store
        self.component = component
        # LRU aggregation index: most events are first-time names (per-pod),
        # and raising NotFound per recorded event dominates the recorder
        # under load; bounded so a long-lived process cannot grow it forever
        self._known: OrderedDict[tuple[str, str], None] = OrderedDict()

    def record(self, obj, event_type: str, reason: str, message: str) -> Event:
        name = f"{obj.metadata.name}.{reason.lower()}"
        namespace = obj.metadata.namespace
        key = (namespace, name)
        if key in self._known:
            self._known.move_to_end(key)
            try:
                existing = self.store.get("Event", name, namespace)
                existing.count += 1
                existing.message = message
                return self.store.update(existing, check_version=False)
            except NotFound:
                self._known.pop(key, None)  # deleted externally: recreate
        event = Event(
            metadata=ObjectMeta(name=name, namespace=namespace),
            involved_object={
                "kind": obj.kind,
                "name": obj.metadata.name,
                "namespace": namespace,
                "uid": obj.metadata.uid,
            },
            reason=reason,
            message=message,
            type=event_type,
            source_component=self.component,
        )
        try:
            created = self.store.create(event, copy=False)
        except AlreadyExists:
            # raced with an earlier instance of this event name
            existing = self.store.get("Event", name, namespace)
            existing.count += 1
            existing.message = message
            created = self.store.update(existing, check_version=False)
        self._known[key] = None
        if len(self._known) > _KNOWN_MAX:
            self._known.popitem(last=False)
        return created
