"""In-request step timing: over-threshold logging plus exportable spans.

The util/trace.Trace analog (reference apiserver/pkg/util/trace/trace.go:28-90;
the scheduler wraps Schedule with trace.Step(...) + LogIfLong(100ms),
core/generic_scheduler.go:89-126), extended into a span tracer: every
finished trace can feed a registry histogram family (per-step durations)
and a structured-JSON sink, while the log line stays thresholded.

The sink is process-global: `set_trace_sink(callable | path | None)`, or
the KTPU_TRACE_FILE environment variable (one JSON object per line,
append mode) read at import. File sinks are serialized by a module lock
(multiple pipeline threads finish traces concurrently) and closed at
interpreter exit.

A StepTimer may additionally carry a distributed-tracing span
(obs/tracing.py): pass `trace_span=`, and `export()` folds the step
marks into that trace as retroactive child spans and ends the batch
span — so the legacy (non-staged) scheduling path produces the same
stitched trace shape as the staged pipeline.
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import threading
import time
from typing import Callable

log = logging.getLogger("kubernetes_tpu.trace")

_sink: Callable[[dict], None] | None = None
_sink_lock = threading.Lock()
_sink_file = None


def _close_sink_file() -> None:
    global _sink_file
    with _sink_lock:
        if _sink_file is not None:
            try:
                _sink_file.close()
            except OSError:
                pass
            _sink_file = None


atexit.register(_close_sink_file)


def set_trace_sink(sink) -> None:
    """Install the structured trace sink: a callable(dict), a file path
    (JSON lines, appended), or None to disable. Replacing a file sink
    closes the previous handle."""
    global _sink, _sink_file
    if sink is None or callable(sink):
        _close_sink_file()
        _sink = sink
        return
    f = open(sink, "a", encoding="utf-8")

    def write(record: dict) -> None:
        # one lock around write+flush: records from concurrent pipeline
        # threads stay line-atomic, and writes cannot race the atexit
        # close
        with _sink_lock:
            if f.closed:
                return
            f.write(json.dumps(record) + "\n")
            f.flush()

    _close_sink_file()
    with _sink_lock:
        _sink_file = f
    _sink = write


def trace_sink() -> Callable[[dict], None] | None:
    return _sink


if os.environ.get("KTPU_TRACE_FILE"):
    set_trace_sink(os.environ["KTPU_TRACE_FILE"])


class StepTimer:
    """Named step spans off one start point. `step_hist`, when given, is a
    histogram family labeled by step name; each finished trace observes
    its per-step durations there (log_if_long is the finish point).
    `trace_span`, when given, is an obs/tracing.py Span owned by this
    timer: export() records each step as a child span and ends it."""

    def __init__(self, name: str, step_hist=None, trace_span=None):
        self.name = name
        self.start = time.monotonic()
        self.start_wall = time.time()
        self.steps: list[tuple[str, float]] = []
        self.step_hist = step_hist
        self.trace_span = trace_span

    def step(self, label: str) -> None:
        self.steps.append((label, time.monotonic()))

    def total(self) -> float:
        return time.monotonic() - self.start

    def spans(self) -> list[tuple[str, float]]:
        """-> [(step label, duration seconds)] between consecutive marks."""
        prev = self.start
        out = []
        for label, t in self.steps:
            out.append((label, t - prev))
            prev = t
        return out

    def export(self, total: float | None = None) -> None:
        """Feed the step histogram, the JSON sink, and the distributed
        trace (no-ops when none is configured)."""
        spans = None
        if self.step_hist is not None:
            spans = self.spans()
            for label, dur in spans:
                self.step_hist.labels(label).observe(dur)
        if _sink is not None:
            spans = spans if spans is not None else self.spans()
            _sink({"ts": time.time(), "name": self.name,
                   "total_ms": round(1e3 * (total if total is not None
                                            else self.total()), 3),
                   "steps": [{"step": label, "ms": round(1e3 * dur, 3)}
                             for label, dur in spans]})
        if self.trace_span is not None:
            span = self.trace_span
            self.trace_span = None  # export finishes the trace exactly once
            if span.sampled:
                from kubernetes_tpu.obs.tracing import TRACER
                spans = spans if spans is not None else self.spans()
                wall = self.start_wall
                for label, dur in spans:
                    TRACER.record_span(label, span.context, wall, dur,
                                       tid="loop")
                    wall += dur
            span.end("ok")

    def log_if_long(self, threshold: float) -> bool:
        """Finish the trace: always export spans; log only when the total
        exceeds `threshold` (the reference's LogIfLong contract)."""
        total = self.total()
        self.export(total=total)
        if total < threshold:
            return False
        parts = [f"{label}: {1e3 * dur:.1f}ms"
                 for label, dur in self.spans()]
        log.warning("trace %s (total %.1fms): %s",
                    self.name, 1e3 * total, "; ".join(parts))
        return True
