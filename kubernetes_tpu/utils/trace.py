"""In-request step timing, logged only when over threshold.

The util/trace.Trace analog (reference apiserver/pkg/util/trace/trace.go:28-90;
the scheduler wraps Schedule with trace.Step(...) + LogIfLong(100ms),
core/generic_scheduler.go:89-126).
"""

from __future__ import annotations

import logging
import time

log = logging.getLogger("kubernetes_tpu.trace")


class StepTimer:
    def __init__(self, name: str):
        self.name = name
        self.start = time.monotonic()
        self.steps: list[tuple[str, float]] = []

    def step(self, label: str) -> None:
        self.steps.append((label, time.monotonic()))

    def total(self) -> float:
        return time.monotonic() - self.start

    def log_if_long(self, threshold: float) -> bool:
        total = self.total()
        if total < threshold:
            return False
        prev = self.start
        parts = []
        for label, t in self.steps:
            parts.append(f"{label}: {1e3 * (t - prev):.1f}ms")
            prev = t
        log.warning("trace %s (total %.1fms): %s",
                    self.name, 1e3 * total, "; ".join(parts))
        return True
