"""Injectable wall clock: the utils/clock position of the reference
(k8s.io/utils/clock) that lets controllers stamp real time in production
and warped time in tests.

Two needs meet here: lint rule R4 (nondeterminism) bans ambient
`time.time()` from the solve path because the FaultPlane's seed-replay
contract requires a schedule to be a pure function of (seed, workload) —
and the fault plane wants to WARP time in tests (cooldowns, deadlines,
dwell windows) without sleeping through them. Components take a `Clock`
and call `.now()`; tests hand them a `ManualClock` and advance it.
"""

from __future__ import annotations

import time


class Clock:
    """Wall clock with an injectable source. `now()` returns POSIX
    seconds (float), same contract as time.time."""

    __slots__ = ("_now",)

    def __init__(self, now=time.time):
        self._now = now

    def now(self) -> float:
        return self._now()


class ManualClock(Clock):
    """Test clock: starts at `start`, moves only when told to."""

    __slots__ = ("_t",)

    def __init__(self, start: float = 0.0):
        self._t = float(start)
        super().__init__(lambda: self._t)

    def set(self, t: float) -> None:
        self._t = float(t)

    def advance(self, seconds: float) -> None:
        self._t += seconds


# the process default: real wall time (components default to this so
# construction sites don't change; tests override per instance)
SYSTEM_CLOCK = Clock()
