"""Persistent XLA compilation cache.

The reference pays no compile cost (Go is AOT); our analog of its instant
cold start is XLA program persistence: first-ever compile of each
(policy, capacities, flags) solver variant lands on disk, later processes
load it in well under a second. The scheduler enables this at construction
(plugin/cmd/kube-scheduler self-configures its runtime the same way).
"""

from __future__ import annotations

import os

_DEFAULT_DIR = os.path.expanduser("~/.cache/kubernetes_tpu/xla")
_enabled = False


def enable(cache_dir: str | None = None) -> bool:
    """Idempotent, best-effort: point JAX's persistent compilation cache at
    `cache_dir` (env KUBERNETES_TPU_XLA_CACHE overrides the default).
    Returns True when active."""
    global _enabled
    if _enabled:
        return True
    try:
        import jax

        path = (cache_dir or os.environ.get("KUBERNETES_TPU_XLA_CACHE")
                or _DEFAULT_DIR)
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        _enabled = True
    except Exception:  # noqa: BLE001 - cache is an optimization, never fatal
        _enabled = False
    return _enabled
