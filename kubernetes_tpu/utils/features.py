"""Feature gates: the --feature-gates registry.

Analog of pkg/features/kube_features.go + apimachinery util/feature: a
process-wide registry of named boolean gates with defaults, settable from
a `--feature-gates=A=true,B=false` flag or the KUBERNETES_TPU_FEATURE_GATES
env var, queried at decision points. Unknown gates are an error at parse
time (the reference fails fast on typos too).
"""

from __future__ import annotations

import os

# gate -> default (the registry; kube_features.go:139 registers defaults)
_DEFAULTS: dict[str, bool] = {
    # fused Pallas scoring kernel (opt-in; parity-pinned but single-chip)
    "PallasFusedScoring": False,
    # device-side assignment-ledger chaining across batches
    "ChainedLedgers": True,
    # batch-content gating (skip provably-neutral kernels per batch)
    "BatchContentGating": True,
    # equivalence-class packed-row encode cache
    "EncodeCache": True,
    # rate-limited node eviction in the node lifecycle controller
    "RateLimitedEviction": True,
}


class FeatureGateError(ValueError):
    pass


class FeatureGate:
    def __init__(self, defaults: dict[str, bool] | None = None):
        self._defaults = dict(defaults if defaults is not None
                              else _DEFAULTS)
        self._overrides: dict[str, bool] = {}

    def enabled(self, name: str) -> bool:
        if name not in self._defaults:
            raise FeatureGateError(f"unknown feature gate {name!r}")
        return self._overrides.get(name, self._defaults[name])

    def set_from_map(self, overrides: dict[str, bool]) -> None:
        unknown = [k for k in overrides if k not in self._defaults]
        if unknown:
            raise FeatureGateError(
                f"unknown feature gate(s): {sorted(unknown)}; "
                f"known: {sorted(self._defaults)}")
        self._overrides.update(overrides)

    def set_from_string(self, spec: str) -> None:
        """Parse 'A=true,B=false' (the --feature-gates flag grammar)."""
        overrides: dict[str, bool] = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            name, eq, value = part.partition("=")
            if not eq or value.lower() not in ("true", "false"):
                raise FeatureGateError(
                    f"bad --feature-gates entry {part!r} "
                    f"(want Name=true|false)")
            overrides[name.strip()] = value.lower() == "true"
        self.set_from_map(overrides)

    def known(self) -> dict[str, bool]:
        return {k: self.enabled(k) for k in sorted(self._defaults)}


# the process-default gate (utilfeature.DefaultFeatureGate)
DEFAULT_FEATURE_GATE = FeatureGate()
_env = os.environ.get("KUBERNETES_TPU_FEATURE_GATES", "")
if _env:
    try:
        DEFAULT_FEATURE_GATE.set_from_string(_env)
    except FeatureGateError as e:
        # the module imports lazily from hot paths — a typo'd env var must
        # not crash the first scheduling batch; warn loudly and run with
        # defaults (binaries that pass --feature-gates still fail fast in
        # their flag parsing)
        import logging

        logging.getLogger(__name__).error(
            "ignoring KUBERNETES_TPU_FEATURE_GATES: %s", e)


def enabled(name: str) -> bool:
    return DEFAULT_FEATURE_GATE.enabled(name)
