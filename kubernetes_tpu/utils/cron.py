"""5-field cron schedule evaluation for the CronJob controller.

The reference vendors robfig/cron (used by pkg/controller/cronjob/utils.go
getRecentUnmetScheduleTimes). This is an independent minimal evaluator for
the standard 5-field form (minute hour day-of-month month day-of-week)
supporting '*', '*/n', 'a-b', 'a-b/n' and comma lists — the subset cluster
operators actually write. Fire times are minute-aligned.
"""

from __future__ import annotations

import time

_FIELD_RANGES = ((0, 59), (0, 23), (1, 31), (1, 12), (0, 6))


class CronError(ValueError):
    pass


def _parse_field(text: str, lo: int, hi: int) -> frozenset[int]:
    out: set[int] = set()
    for part in text.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            try:
                step = int(step_s)
            except ValueError:
                raise CronError(f"bad step {step_s!r}") from None
            if step <= 0:
                raise CronError(f"bad step {step}")
        if part == "*":
            lo_p, hi_p = lo, hi
        elif "-" in part:
            try:
                a, b = part.split("-", 1)
                lo_p, hi_p = int(a), int(b)
            except ValueError:
                raise CronError(f"bad range {part!r}") from None
        else:
            try:
                lo_p = hi_p = int(part)
            except ValueError:
                raise CronError(f"bad value {part!r}") from None
        if not (lo <= lo_p <= hi and lo <= hi_p <= hi and lo_p <= hi_p):
            raise CronError(f"{part!r} outside [{lo},{hi}]")
        out.update(range(lo_p, hi_p + 1, step))
    return frozenset(out)


class CronSchedule:
    def __init__(self, spec: str):
        fields = spec.split()
        if len(fields) != 5:
            raise CronError(f"want 5 fields, got {len(fields)}: {spec!r}")
        self.minute, self.hour, self.dom, self.month, self.dow = (
            _parse_field(f, lo, hi)
            for f, (lo, hi) in zip(fields, _FIELD_RANGES))
        # standard cron: if BOTH dom and dow are restricted, either may match
        self._dom_star = fields[2] == "*"
        self._dow_star = fields[4] == "*"

    def matches(self, epoch: float) -> bool:
        t = time.localtime(epoch)
        if t.tm_min not in self.minute or t.tm_hour not in self.hour \
                or t.tm_mon not in self.month:
            return False
        dom_ok = t.tm_mday in self.dom
        dow_ok = (t.tm_wday + 1) % 7 in self.dow  # cron: 0=Sunday
        if self._dom_star or self._dow_star:
            return dom_ok and dow_ok
        return dom_ok or dow_ok

    def fire_times(self, start: float, end: float,
                   limit: int = 1000) -> list[float]:
        """Minute-aligned fire times in (start, end]. Bounded by `limit`
        (the reference errors past 100 unmet times, utils.go:94 — a
        too-long-dead cronjob must not replay unbounded)."""
        out: list[float] = []
        t = (int(start) // 60 + 1) * 60
        scanned = 0
        while t <= end:
            if self.matches(t):
                out.append(float(t))
                if len(out) >= limit:
                    break
            t += 60
            scanned += 1
            if scanned > 366 * 24 * 60:  # one year of minutes: give up
                break
        return out
