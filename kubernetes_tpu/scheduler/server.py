"""Scheduler observability endpoints: /healthz + Prometheus /metrics.

The plugin/cmd/kube-scheduler server surface (app/server.go:151 installs
healthz and the Prometheus handler): text exposition of the reference's
scheduler histograms (metrics/metrics.go:31-50 —
e2e_scheduling_latency_microseconds, scheduling_algorithm_latency_
microseconds, binding_latency_microseconds with ExponentialBuckets(1000, 2,
15)) plus the framework's counters. Latency windows are converted to
cumulative histogram buckets at scrape time.
"""

from __future__ import annotations

import asyncio
from typing import Iterable

from kubernetes_tpu.scheduler.driver import Scheduler

# ExponentialBuckets(1000, 2, 15) in microseconds (metrics.go:36)
BUCKETS_US = [1000.0 * (2 ** i) for i in range(15)]


def _histogram(name: str, help_text: str,
               samples_seconds: Iterable[float]) -> str:
    samples = [1e6 * s for s in samples_seconds]  # seconds -> microseconds
    lines = [f"# HELP {name} {help_text}", f"# TYPE {name} histogram"]
    cumulative = 0
    remaining = sorted(samples)
    idx = 0
    for bound in BUCKETS_US:
        while idx < len(remaining) and remaining[idx] <= bound:
            idx += 1
        cumulative = idx
        lines.append(f'{name}_bucket{{le="{bound:g}"}} {cumulative}')
    lines.append(f'{name}_bucket{{le="+Inf"}} {len(remaining)}')
    lines.append(f"{name}_sum {sum(remaining):g}")
    lines.append(f"{name}_count {len(remaining)}")
    return "\n".join(lines)


def render_metrics(sched: Scheduler) -> str:
    m = sched.metrics
    parts = [
        "# HELP scheduler_pods_scheduled_total Pods successfully bound.",
        "# TYPE scheduler_pods_scheduled_total counter",
        f"scheduler_pods_scheduled_total {m.scheduled}",
        "# HELP scheduler_pods_failed_total Scheduling attempts that failed.",
        "# TYPE scheduler_pods_failed_total counter",
        f"scheduler_pods_failed_total {m.failed}",
        "# HELP scheduler_binding_errors_total Bind writes rejected.",
        "# TYPE scheduler_binding_errors_total counter",
        f"scheduler_binding_errors_total {m.binding_errors}",
        "# HELP scheduler_batches_total Solver batches dispatched.",
        "# TYPE scheduler_batches_total counter",
        f"scheduler_batches_total {m.batches}",
        _histogram("e2e_scheduling_latency_microseconds",
                   "E2e scheduling latency (queue arrival to bind).",
                   m.e2e_latency),
        _histogram("scheduling_algorithm_latency_microseconds",
                   "Scheduling algorithm (device solve) latency.",
                   m.algorithm_latency),
        _histogram("binding_latency_microseconds",
                   "Binding latency per pod.",
                   m.binding_latency),
    ]
    return "\n".join(parts) + "\n"


class SchedulerServer:
    """Asyncio HTTP server for /healthz and /metrics."""

    def __init__(self, sched: Scheduler, host: str = "127.0.0.1",
                 port: int = 0):
        self.sched = sched
        self.host = host
        self.port = port
        self._server: asyncio.AbstractServer | None = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            request_line = await reader.readline()
            if not request_line:
                return
            try:
                method, path, _ = request_line.decode().split(None, 2)
            except ValueError:
                return
            while True:  # drain headers
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
            path = path.split("?", 1)[0].rstrip("/") or "/"
            if method != "GET":
                body, status, ctype = b"method not allowed", 405, "text/plain"
            elif path in ("/", "/healthz"):
                body, status, ctype = b"ok", 200, "text/plain"
            elif path == "/metrics":
                body = render_metrics(self.sched).encode()
                status, ctype = 200, "text/plain; version=0.0.4"
            else:
                body, status, ctype = b"not found", 404, "text/plain"
            reason = {200: "OK", 404: "Not Found",
                      405: "Method Not Allowed"}.get(status, "Error")
            writer.write(
                f"HTTP/1.1 {status} {reason}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(body)}\r\n"
                f"Connection: close\r\n\r\n".encode() + body)
            await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            writer.close()
