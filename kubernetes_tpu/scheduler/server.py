"""Scheduler observability endpoints: /metrics + /healthz + /readyz.

The plugin/cmd/kube-scheduler server surface (app/server.go:151 installs
healthz and the Prometheus handler). Metrics are the driver's registry —
the reference's scheduler histograms (metrics/metrics.go:31-50 —
e2e_scheduling_latency_microseconds, scheduling_algorithm_latency_
microseconds, binding_latency_microseconds with ExponentialBuckets(1000, 2,
15)) plus phase/trace/jit families — rendered together with the
process-global registry (workqueue, informer families).
"""

from __future__ import annotations

import asyncio

from kubernetes_tpu.obs import metrics as obs_metrics
from kubernetes_tpu.obs.http import (
    METRICS_CONTENT_TYPE,
    http_head,
    obs_response,
)
from kubernetes_tpu.obs.profiling import PROFILER
from kubernetes_tpu.scheduler.driver import Scheduler

# ExponentialBuckets(1000, 2, 15) in microseconds (metrics.go:36);
# kept as the canonical bucket list for the latency families
BUCKETS_US = [1000.0 * (2 ** i) for i in range(15)]


def render_metrics(sched: Scheduler) -> str:
    """The driver's (usually private) registry plus the process-global
    one. Family names don't overlap: scheduler families live on the
    driver's registry, workqueue/informer families on the global one.
    Scrape-time refresh: pipeline saturation gauges mirror the live
    StagedPipeline.snapshot(), device-memory gauges re-read
    memory_stats() (CPU fallback: StateDB blob accounting)."""
    if sched._staged is not None:
        sched.metrics.export_pipeline(sched._staged.snapshot())
    PROFILER.memory.collect([sched.statedb])
    text = sched.metrics.registry.render()
    if sched.metrics.registry is not obs_metrics.REGISTRY:
        text += obs_metrics.REGISTRY.render()
    return text


class SchedulerServer:
    """Asyncio HTTP server for /metrics, /healthz and /readyz."""

    def __init__(self, sched: Scheduler, host: str = "127.0.0.1",
                 port: int = 0):
        self.sched = sched
        self.host = host
        self.port = port
        self._server: asyncio.AbstractServer | None = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            request_line = await reader.readline()
            if not request_line:
                return
            try:
                method, path, _ = request_line.decode().split(None, 2)
            except ValueError:
                return
            while True:  # drain headers
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
            query = path.split("?", 1)[1] if "?" in path else ""
            path = path.split("?", 1)[0].rstrip("/") or "/"
            if path == "/":  # healthz alias, kube-scheduler's root ping
                path = "/healthz"
            if method == "GET" and path == "/metrics":
                status, body, ctype = (
                    200, render_metrics(self.sched).encode(),
                    METRICS_CONTENT_TYPE)
            else:
                resp = obs_response(
                    method, path + ("?" + query if query else ""),
                    ready_checks={
                        "informers-synced": lambda: self.sched.synced},
                    degraded_checks={
                        "device-solver":
                            lambda: not self.sched.solver_degraded},
                    profiler=PROFILER)
                if resp is None:
                    status, body, ctype = 404, b"not found", "text/plain"
                else:
                    status, body, ctype = resp
            writer.write(http_head(status, body, ctype))
            await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            writer.close()
