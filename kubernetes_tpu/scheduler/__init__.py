from kubernetes_tpu.scheduler.driver import Scheduler  # noqa: F401
