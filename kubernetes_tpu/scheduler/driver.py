"""Scheduler driver: informers -> batch solver -> bindings.

The host loop replacing the reference's `Scheduler.Run`/`scheduleOne`
(plugin/pkg/scheduler/scheduler.go:149,253) and its factory wiring
(factory/factory.go:118 NewConfigFactory: informers feeding a FIFO of
unscheduled pods, error path with exponential backoff :897). Differences are
the point of the re-design:

- pods are popped in FIFO order but scheduled as a *batch* in one device
  program (ops/solver.py) instead of one blocking scheduleOne per pod;
- assume + bind: each assignment is accounted optimistically in StateDB
  (cache.AssumePod analog), then bound through the store; a failed bind
  rolls the assumption back (ForgetPod, scheduler.go:224) and requeues with
  backoff;
- unschedulable pods requeue with exponential backoff and emit
  FailedScheduling events (scheduler.go:174,248 event parity).
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import os
import threading
import time
from collections import deque

import numpy as np

from kubernetes_tpu.api.objects import Binding, Pod
from kubernetes_tpu.apiserver.store import (
    Conflict,
    NotFound,
    ObjectStore,
    TooManyRequests,
    WatchEvent,
)
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.client.workqueue import Backoff, BackoffQueue
from kubernetes_tpu.gang import (
    DEFAULT_SCHEDULE_TIMEOUT_S,
    annotation_min,
    pod_group_key,
)
from kubernetes_tpu.models.policy import DEFAULT_POLICY, Policy
from kubernetes_tpu.obs import metrics as obs_metrics
from kubernetes_tpu.obs.profiling import COMPILES, record_readback
from kubernetes_tpu.obs.tracing import (
    TRACE_ANNOTATION,
    TRACER,
    pod_trace_context,
    wall_now,
)
from kubernetes_tpu.ops.solver import EXPLAIN_STAGES, schedule_batch
from kubernetes_tpu.state import Capacities
from kubernetes_tpu.state.encode_cache import EncodeCache
from kubernetes_tpu.state.layout import CapacityError
from kubernetes_tpu.state.statedb import StateDB
from kubernetes_tpu.utils.events import EventRecorder
from kubernetes_tpu.utils.trace import StepTimer

log = logging.getLogger(__name__)


# queue-key namespace for gang groups: pod keys are "ns/name" (DNS-1123
# names cannot contain ":"), so the prefix cannot collide
_GANG_KEY_PREFIX = "gang:"

# requeue delay for a quarantined poison pod: long enough that one bad pod
# cannot re-poison every batch, short enough that a transient cause clears
QUARANTINE_BACKOFF_S = 30.0


class _SolveFailed(RuntimeError):
    """The device solve failed twice for one batch (raised internally to
    route schedule_pending into bisect/quarantine recovery)."""

# FailedScheduling reason per EXPLAIN_STAGES column — the reference's
# predicate names as they appear in failedPredicateMap events
# (findNodesThatFit, core/generic_scheduler.go:163)
EXPLAIN_REASONS = ("MatchNodeSelector", "Insufficient resources",
                   "PodFitsHostPorts", "NoDiskConflict", "MaxVolumeCount",
                   "MatchInterPodAffinity")


def render_unschedulable(counts, total_nodes: int) -> str | None:
    """Render one pod's explain breakdown (cumulative survivor counts
    down EXPLAIN_STAGES) into the reference's FailedScheduling message
    shape — "0/15000 nodes available: 11992 Insufficient tpu, 8
    PodFitsHostPorts". Returns None unless the final survivor count is
    zero (a schedulable pod is not a render candidate)."""
    counts = [int(c) for c in counts]
    if counts[-1] != 0:
        return None
    parts = []
    prev = total_nodes
    for i, stage in enumerate(EXPLAIN_STAGES):
        rejected = prev - counts[i]
        if rejected > 0:
            parts.append(f"{rejected} {EXPLAIN_REASONS[i]}")
        prev = counts[i]
    msg = f"0/{total_nodes} nodes available"
    return msg + (": " + ", ".join(parts) if parts else "")


# ExponentialBuckets(1000, 2, 15) in microseconds (reference metrics.go:36)
LATENCY_BUCKETS_US = obs_metrics.exponential_buckets(1000.0, 2.0, 15)
# phase spans run ~10us (cache-hit encode) to tens of seconds (cold solve)
PHASE_BUCKETS_S = obs_metrics.exponential_buckets(1e-5, 2.0, 22)


class _LatencyWindow(deque):
    """Bounded sample window (seconds) whose append also observes a
    registry histogram in microseconds — the reference's fixed-bucket
    Prometheus histograms; the window keeps snapshot() percentiles exact.
    Call sites alias `.append`, so the mirror lives here."""

    def __init__(self, hist, extra=None):
        super().__init__(maxlen=8192)
        self._hist = hist
        self._extra = extra

    def append(self, seconds: float) -> None:
        self._hist.observe(1e6 * seconds)
        if self._extra is not None:
            self._extra(seconds)
        super().append(seconds)


class SchedulerMetrics:
    """Counters/latency mirrors of the reference's Prometheus metrics
    (plugin/pkg/scheduler/metrics/metrics.go:31-50), backed by an obs
    registry. Each instance owns a PRIVATE registry by default: tests and
    the perf harness construct many schedulers per process and assert
    exact per-instance counts, so scheduler families must not accumulate
    across instances. The scheduler's /metrics endpoint renders this
    registry plus the process-global one (workqueue/informer families)."""

    def __init__(self, registry: obs_metrics.Registry | None = None):
        self.registry = registry if registry is not None \
            else obs_metrics.Registry()
        r = self.registry
        self._c_scheduled = r.counter(
            "scheduler_pods_scheduled_total", "Pods successfully bound.")
        self._c_failed = r.counter(
            "scheduler_pods_failed_total",
            "Scheduling attempts that failed.")
        self._c_binding_errors = r.counter(
            "scheduler_binding_errors_total", "Bind writes rejected.")
        self._c_batches = r.counter(
            "scheduler_batches_total", "Solver batches dispatched.")
        self._c_jit_hits = r.counter(
            "scheduler_jit_cache_hits_total",
            "Batches served by an already-compiled solver variant.")
        self._c_jit_misses = r.counter(
            "scheduler_jit_cache_misses_total",
            "Batches that compiled a new solver variant (BatchFlags).")
        self._c_gang_placed = r.counter(
            "scheduler_gang_groups_placed_total",
            "Gangs whose quorum placed and bound atomically.")
        self._c_gang_reverted = r.counter(
            "scheduler_gang_groups_reverted_total",
            "Gangs the solver reverted below quorum (no member bound).")
        self._c_gang_timeouts = r.counter(
            "scheduler_gang_groups_timeout_total",
            "Gangs that timed out waiting for quorum; members released.")
        self._c_preempt_attempts = r.counter(
            "scheduler_preemption_attempts_total",
            "Preemption attempts (pods with a victim-set verdict).")
        self._c_preempt_victims = r.counter(
            "scheduler_preemption_victims_total",
            "Pods evicted to make room for higher-priority pods.")
        self._c_preempt_success = r.counter(
            "scheduler_preemption_success_total",
            "Preemptions that evicted their victims and nominated a node.")
        self._c_solve_failures = r.counter(
            "scheduler_solve_failures_total",
            "Device solve attempts that raised or timed out.")
        self._c_solve_retries = r.counter(
            "scheduler_solve_retries_total",
            "Batches re-dispatched after a failed device solve.")
        self._c_quarantined = r.counter(
            "scheduler_pods_quarantined_total",
            "Pods quarantined after bisection isolated them as the cause "
            "of persistent solve failures.")
        self._c_serial_fallback = r.counter(
            "scheduler_serial_fallback_pods_total",
            "Pods placed by the degraded serial host path while the "
            "device solver was failing.")
        self._h_phase = r.histogram(
            "scheduler_phase_duration_seconds",
            "Per-batch scheduling phase durations "
            "(encode/flush/dispatch/solve/settle_wait/bind/commit).",
            ("phase",), buckets=PHASE_BUCKETS_S)
        self.trace_steps = r.histogram(
            "scheduler_trace_step_duration_seconds",
            "Scheduling-batch trace spans (StepTimer steps).",
            ("step",), buckets=PHASE_BUCKETS_S)
        # pipeline saturation gauges, refreshed at scrape time from
        # StagedPipeline.snapshot() so the monitor can watch the same
        # busy fractions the bench extras report
        self._g_stage_busy = r.gauge(
            "scheduler_pipeline_stage_busy_frac",
            "Fraction of the wall each pipeline stage was busy since "
            "the last stats reset.", ("stage",))
        self._g_queue_hw = r.gauge(
            "scheduler_pipeline_queue_high_water",
            "Queue-depth high-water mark per pipeline stage queue.",
            ("stage",))
        self._g_pipe_depth = r.gauge(
            "scheduler_pipeline_depth",
            "Batches currently in flight in the staged pipeline.")
        self._scheduled = 0
        self._failed = 0
        self._binding_errors = 0
        self._batches = 0
        self.gang_placed = 0
        self.gang_reverted = 0
        self.gang_timeouts = 0
        self.preempt_attempts = 0
        self.preempt_victims = 0
        self.preempt_success = 0
        self.solve_failures = 0
        self.solve_retries = 0
        self.quarantined = 0
        self.serial_fallback = 0
        # bounded windows (the registry histograms are cumulative; the
        # windows keep the recent-sample percentiles snapshot() reports)
        self.e2e_latency = _LatencyWindow(r.histogram(
            "e2e_scheduling_latency_microseconds",
            "E2e scheduling latency (queue arrival to bind).",
            buckets=LATENCY_BUCKETS_US))
        self.algorithm_latency = _LatencyWindow(
            r.histogram("scheduling_algorithm_latency_microseconds",
                        "Scheduling algorithm (device solve) latency.",
                        buckets=LATENCY_BUCKETS_US),
            extra=lambda s: self.add_phase("solve", s))
        self.binding_latency = _LatencyWindow(r.histogram(
            "binding_latency_microseconds", "Binding latency per pod.",
            buckets=LATENCY_BUCKETS_US))
        # cumulative host-plane phase costs (seconds) — the
        # transport-independent breakdown: tunnel weather moves
        # settle_wait, not encode/bind/commit
        self.phase_s: dict = {}
        self.phase_pods = 0

    # counter attributes stay plain-int readable/writable (tests assert
    # `metrics.scheduled == 40`); writes mirror the delta to the registry
    @property
    def scheduled(self) -> int:
        return self._scheduled

    @scheduled.setter
    def scheduled(self, value: int) -> None:
        if value > self._scheduled:
            self._c_scheduled.inc(value - self._scheduled)
        self._scheduled = value

    @property
    def failed(self) -> int:
        return self._failed

    @failed.setter
    def failed(self, value: int) -> None:
        if value > self._failed:
            self._c_failed.inc(value - self._failed)
        self._failed = value

    @property
    def binding_errors(self) -> int:
        return self._binding_errors

    @binding_errors.setter
    def binding_errors(self, value: int) -> None:
        if value > self._binding_errors:
            self._c_binding_errors.inc(value - self._binding_errors)
        self._binding_errors = value

    @property
    def batches(self) -> int:
        return self._batches

    @batches.setter
    def batches(self, value: int) -> None:
        if value > self._batches:
            self._c_batches.inc(value - self._batches)
        self._batches = value

    def jit_hit(self) -> None:
        self._c_jit_hits.inc()

    def jit_miss(self) -> None:
        self._c_jit_misses.inc()

    def gang_placed_inc(self) -> None:
        self.gang_placed += 1
        self._c_gang_placed.inc()

    def gang_reverted_inc(self) -> None:
        self.gang_reverted += 1
        self._c_gang_reverted.inc()

    def gang_timeout_inc(self) -> None:
        self.gang_timeouts += 1
        self._c_gang_timeouts.inc()

    def preempt_attempt_inc(self) -> None:
        self.preempt_attempts += 1
        self._c_preempt_attempts.inc()

    def preempt_victims_add(self, n: int) -> None:
        self.preempt_victims += n
        self._c_preempt_victims.inc(n)

    def preempt_success_inc(self) -> None:
        self.preempt_success += 1
        self._c_preempt_success.inc()

    def solve_failure_inc(self) -> None:
        self.solve_failures += 1
        self._c_solve_failures.inc()

    def solve_retry_inc(self) -> None:
        self.solve_retries += 1
        self._c_solve_retries.inc()

    def quarantine_inc(self) -> None:
        self.quarantined += 1
        self._c_quarantined.inc()

    def serial_fallback_inc(self) -> None:
        self.serial_fallback += 1
        self._c_serial_fallback.inc()

    def add_phase(self, name: str, seconds: float) -> None:
        self.phase_s[name] = self.phase_s.get(name, 0.0) + seconds
        self._h_phase.labels(name).observe(seconds)

    def export_pipeline(self, snap: dict | None) -> None:
        """Mirror a StagedPipeline.snapshot() into the saturation
        gauges — called at /metrics scrape time."""
        if not snap:
            return
        for stage, frac in (snap.get("stage_busy_frac") or {}).items():
            self._g_stage_busy.labels(stage).set(float(frac))
        for stage, depth in (snap.get("queue_depth_max") or {}).items():
            self._g_queue_hw.labels(stage).set(float(depth))
        self._g_pipe_depth.set(float(snap.get("depth", 0)))

    def phase_histograms(self) -> dict:
        """Per-phase histogram snapshot {phase: {count, sum_ms, p50_ms,
        p99_ms}} — the bench.py --metrics-snapshot payload, quantiles
        estimated from the registry buckets (histogram_quantile shape)."""
        out: dict = {}
        for (phase,), child in self._h_phase.children():
            out[phase] = {
                "count": child.count,
                "sum_ms": round(1e3 * child.sum, 3),
                "p50_ms": round(1e3 * child.quantile(0.5), 3),
                "p99_ms": round(1e3 * child.quantile(0.99), 3),
            }
        return out

    def snapshot(self) -> dict:
        lat = sorted(self.e2e_latency) or [0.0]
        out = {
            "scheduled": self.scheduled,
            "failed": self.failed,
            "binding_errors": self.binding_errors,
            "batches": self.batches,
            "e2e_p50_ms": 1e3 * lat[len(lat) // 2],
            "e2e_p99_ms": 1e3 * lat[min(len(lat) - 1, int(len(lat) * 0.99))],
        }
        if self.phase_pods:
            out["phase_us_per_pod"] = {
                k: round(1e6 * v / self.phase_pods, 2)
                for k, v in sorted(self.phase_s.items())}
        if self.gang_placed or self.gang_reverted or self.gang_timeouts:
            out["gang"] = {"placed": self.gang_placed,
                           "reverted": self.gang_reverted,
                           "timeouts": self.gang_timeouts}
        if self.preempt_attempts:
            out["preemption"] = {"attempts": self.preempt_attempts,
                                 "victims": self.preempt_victims,
                                 "success": self.preempt_success}
        if self.solve_failures or self.quarantined or self.serial_fallback:
            out["faults"] = {"solve_failures": self.solve_failures,
                             "solve_retries": self.solve_retries,
                             "quarantined": self.quarantined,
                             "serial_fallback": self.serial_fallback}
        return out


def store_encode_context(store: ObjectStore, policy: Policy = DEFAULT_POLICY,
                         local_volumes_enabled=False):
    """EncodeContext backed by the object store — the PVInfo/PVCInfo and
    Service/RC/RS/StatefulSet listers the reference's predicate/priority
    factories receive (factory/plugins.go PluginFactoryArgs)."""
    from kubernetes_tpu.state.context import EncodeContext

    def getter(kind):
        def get(name, namespace="default"):
            try:
                return store.get(kind, name, namespace)
            except NotFound:
                return None
        return get

    get_pvc_ = getter("PersistentVolumeClaim")
    get_pv_ = getter("PersistentVolume")
    get_node_ = getter("Node")
    # read-only listers: skip the defensive deep clone (the encoders never
    # mutate — at 15k nodes / 30k pods a cloning list per encode miss was
    # the single largest host cost after device transfers)
    return EncodeContext(
        get_pvc=lambda ns, name: get_pvc_(name, ns),
        get_pv=lambda name: get_pv_(name),
        local_volumes_enabled=local_volumes_enabled,
        get_services=lambda ns: store.list("Service", ns, copy_objects=False),
        get_rcs=lambda ns: store.list("ReplicationController", ns,
                                      copy_objects=False),
        get_rss=lambda ns: store.list("ReplicaSet", ns, copy_objects=False),
        get_sss=lambda ns: store.list("StatefulSet", ns, copy_objects=False),
        list_pods=lambda ns: store.list("Pod", ns, copy_objects=False),
        get_node=lambda name: get_node_(name),
        service_affinity_labels=policy.service_affinity_labels(),
        service_anti=bool(policy.service_anti_priorities),
    )


# back-compat alias (pre-spreading name)
def store_volume_context(store: ObjectStore, local_volumes_enabled=False):
    return store_encode_context(store,
                                local_volumes_enabled=local_volumes_enabled)


class Scheduler:
    def __init__(
        self,
        store: ObjectStore,
        caps: Capacities | None = None,
        policy: Policy = DEFAULT_POLICY,
        mesh=None,
        scheduler_name: str = "default-scheduler",
        batch_wait: float = 0.002,
        enable_preemption: bool = True,
        explain: bool | None = None,
    ):
        from kubernetes_tpu.utils.compilation_cache import enable

        enable()  # persistent XLA cache: cold start loads compiled variants

        self.store = store
        self.caps = caps or Capacities()
        if mesh is not None and self.caps.num_nodes % mesh.size:
            # GSPMD shards the node axis evenly: round the row budget up to
            # the next mesh multiple (the extra rows stay unassigned — same
            # sentinel shape shard_state pads direct callers with)
            import dataclasses as _dc

            from kubernetes_tpu.parallel.mesh import padded_num_nodes
            self.caps = _dc.replace(
                self.caps,
                num_nodes=padded_num_nodes(self.caps.num_nodes, mesh.size))
        policy = policy.with_env_overrides()  # KUBE_MAX_PD_VOLS (defaults.go)
        self.policy = policy
        self.scheduler_name = scheduler_name
        self.batch_wait = batch_wait

        self.volume_ctx = store_encode_context(store, policy)
        self.statedb = StateDB(self.caps, mesh=mesh, volume_ctx=self.volume_ctx)
        self.encode_cache = EncodeCache(self.caps, self.statedb.table,
                                        volume_ctx=self.volume_ctx)
        from kubernetes_tpu.models.policy import build_policy_rows

        self._prows = build_policy_rows(policy, self.statedb.table, self.caps)
        self.queue = BackoffQueue(name="scheduler")
        self.backoff = Backoff(initial=0.05, max_duration=5.0)
        self.metrics = SchedulerMetrics()
        self.events = EventRecorder(store)
        self._assumed: set[str] = set()
        self._enqueue_time: dict[str, float] = {}
        self._rr = np.uint32(0)
        # packed transport blob free-list: acquired at batch assembly,
        # released once the batch's ledger commits (in-flight batches'
        # blobs stay referenced — commit reads accounting rows from them)
        self._blob_pool: deque = deque()
        # host StateDB/EncodeCache guard: the loop mutates them from
        # informer handlers and encode, the staged dispatch thread reads
        # them in flush(), the commit thread scatters in commit_batch()
        self._state_lock = threading.RLock()
        # informer events waiting for _state_lock: handlers run on the
        # event loop, but the staged commit thread can hold the lock for
        # the length of a ledger commit — a blocking acquire there would
        # stall the whole loop (heartbeats, other informers, watchdogs)
        # for that window. Contended events park here and replay in FIFO
        # order once the lock frees (per-object ordering preserved: once
        # anything is queued, everything queues behind it).
        self._deferred_events: deque = deque()
        self._drain_handle: asyncio.TimerHandle | None = None
        # deferred event buffer, (obj, type, reason, message): recording
        # is off the batch-critical path, coalesced per solved batch and
        # flushed when the loop next idles (the EventBroadcaster's
        # buffered-channel shape, record/event.go:78); stop() flushes
        # synchronously so no event is ever dropped
        self._pending_events: list[tuple[Pod, str, str, str]] = []
        self._event_flush_scheduled = False
        # node name -> keys of bound pods seen on it (indexed even before
        # the node itself is known, so a late node event re-accounts them);
        # replaces the O(nodes x pods) informer sweep per node event
        self._pods_by_node: dict[str, set[str]] = {}
        self._pod_node: dict[str, str] = {}
        # gang staging: annotated members wait here until their group
        # reaches quorum, then the whole group enters ONE batch (never
        # split — the solver's revert window is a contiguous in-batch run)
        self._gang_members: dict[str, set[str]] = {}
        self._gang_of_pod: dict[str, str] = {}
        self._gang_first_seen: dict[str, float] = {}
        self._gang_min_hint: dict[str, int] = {}
        # priority preemption: nominated-node capacity holds + the flag
        # (BatchFlags.preempt additionally gates the pass per batch, so a
        # priority-free workload never compiles the preemption program)
        from kubernetes_tpu.preemption import NominatedNodes

        self.enable_preemption = enable_preemption
        self.nominated = NominatedNodes()

        self.node_informer = Informer(store, "Node")
        self.pod_informer = Informer(store, "Pod")
        self.podgroup_informer = Informer(store, "PodGroup")
        self.node_informer.add_handler(self._on_node_event)
        self.pod_informer.add_handler(self._on_pod_event)
        self.podgroup_informer.add_handler(self._on_podgroup_event)
        # workload objects feed cached pod encodings (spreading entries):
        # any change invalidates the encode cache (the reference invalidates
        # its equivalence cache from the same informers, factory.go:160-250)
        self.workload_informers = [
            Informer(store, kind)
            for kind in ("Service", "ReplicationController", "ReplicaSet",
                         "StatefulSet")]
        for informer in self.workload_informers:
            informer.add_handler(self._on_workload_event)

        self.mesh = mesh
        self._schedule_fns: dict = {}
        # policy-configured external extenders (core/extender.go:40): when
        # present, scheduling runs per pod — device evaluation first, then
        # each extender's Filter/Prioritize (the reference's composition
        # points, generic_scheduler.go:211-228,381-401)
        from kubernetes_tpu.extender.client import HTTPExtender

        self._extenders = [HTTPExtender(c) for c in policy.extenders]
        self._pod_eval_fn = None
        self._stopped = False
        # Pipelining: dispatch batch k+1 while batch k's result is still in
        # flight on the device, hiding dispatch/readback round-trip latency
        # (substantial over remote-device transports). Safe only when pod
        # encoding is placement-independent: ServiceAffinity backfills and
        # ServiceAntiAffinity totals read current placements at encode time,
        # so those policies force the synchronous path.
        self._pipeline = not (policy.service_affinity_labels()
                              or policy.service_anti_priorities)
        # in-flight batches, oldest first; depth >1 hides the per-batch
        # dispatch/readback round trip (dominant on remote-device
        # transports: ~120ms RTT vs ~10ms of device compute per batch)
        import os

        self.pipeline_depth = int(
            os.environ.get("KTPU_PIPELINE_DEPTH", "4") or 4)
        self._inflight_q: deque = deque()
        # staged stage-per-thread pipeline (scheduler/pipeline.py):
        # encode on the loop | dispatch | settle | commit+bind in worker
        # threads. The default batch path when encoding is
        # placement-independent; KTPU_STAGED_PIPELINE=0 falls back to the
        # single-loop pipelined driver
        from kubernetes_tpu.scheduler.pipeline import (
            EventShard,
            LoopCalls,
            StagedPipeline,
        )

        self._loop_calls = LoopCalls()
        staged_on = self._pipeline and (
            os.environ.get("KTPU_STAGED_PIPELINE", "1") != "0")
        self._staged = StagedPipeline(self, self.pipeline_depth) \
            if staged_on else None
        self._event_shard = EventShard(self.events, self._loop_calls) \
            if staged_on else None
        if self._event_shard is not None:
            self._event_shard._recorder_metrics_hook = \
                lambda s: self.metrics.add_phase("events_async", s)
        # settled-count accumulator + failed-batch payloads filled by the
        # staged pipeline's loop-side closures; schedule_pending drains
        self._staged_settled = 0
        self._staged_failures: list = []
        # solve-failure hardening (the batched analog of the reference's
        # MakeDefaultErrorFunc: an algorithm error must never kill the
        # scheduling loop). With a timeout set, each dispatch+readback runs
        # in a worker thread under a watchdog deadline — trading pipelined
        # dispatch for boundedness against a wedged device
        self.solve_timeout_s = float(
            os.environ.get("KTPU_SOLVE_TIMEOUT_S", "0") or 0) or None
        # testing seam: called with the batch's pod keys right before every
        # dispatch (FaultPlane.solve_hook injects failures through it)
        self.solve_fault_hook = None
        self.quarantine_backoff_s = QUARANTINE_BACKOFF_S
        self._quarantined: set[str] = set()
        # "why pending" explainability: compile the explain variant and
        # render per-predicate FailedScheduling reasons for every
        # unschedulable pod. An operator switch (KTPU_EXPLAIN / ctor arg),
        # NEVER batch-content derived — see BatchFlags.explain
        self.explain = explain if explain is not None \
            else os.environ.get("KTPU_EXPLAIN", "") in ("1", "true")

    @staticmethod
    def _variant_key(flags) -> str:
        """Human-readable jit-variant label for the compile registry:
        the active BatchFlags gates joined, 'baseline' when none."""
        on = [f.name for f in dataclasses.fields(flags)
              if getattr(flags, f.name)]
        return "+".join(on) or "baseline"

    def _get_schedule_fn(self, flags):
        """Compiled solver variant for this batch's content gates — a
        handful of variants in practice (jit caches per BatchFlags)."""
        import jax

        fn = self._schedule_fns.get(flags)
        if fn is not None:
            self.metrics.jit_hit()
        else:
            self.metrics.jit_miss()
            from kubernetes_tpu.state.pod_batch import unpack_batch

            caps, policy, prows = self.caps, self.policy, self._prows
            if self.mesh is not None:
                from kubernetes_tpu.parallel.mesh import make_sharded_scheduler
                fn = make_sharded_scheduler(self.mesh, policy, caps=caps,
                                            prows=prows, flags=flags,
                                            packed=True)
            else:
                fn = jax.jit(
                    lambda s, fb, ib, rr, v=None: schedule_batch(
                        s, unpack_batch(fb, ib, caps), rr, policy,
                        caps=caps, prows=prows, flags=flags, victims=v))
            # compile registry (obs/profiling.py): first-call compile
            # seconds + cost_analysis per variant ride the cache entry
            fn = COMPILES.instrument(self._variant_key(flags), fn)
            self._schedule_fns[flags] = fn
        return fn

    def _on_workload_event(self, event: WatchEvent) -> None:
        self.encode_cache.generation += 1

    # ---- informer handlers ----

    # retry cadence for the deferred-event drain; short enough that a
    # parked event lands within a tick of the commit thread releasing
    # the lock, long enough not to busy-spin the loop against it
    _DRAIN_RETRY_S = 0.002

    def _on_node_event(self, event: WatchEvent) -> None:
        self._handle_locked(self._apply_node_event, event)

    def _on_pod_event(self, event: WatchEvent) -> None:
        self._handle_locked(self._apply_pod_event, event)

    def _handle_locked(self, apply, event: WatchEvent) -> None:
        """Run an informer-event application under _state_lock without
        ever blocking the event loop on it: if the lock is contended
        (commit thread mid-ledger-commit) or earlier events are already
        parked, defer and drain in order once it frees."""
        if self._deferred_events \
                or not self._state_lock.acquire(blocking=False):
            self._deferred_events.append((apply, event))
            self._schedule_drain()
            return
        try:
            apply(event)
        finally:
            self._state_lock.release()

    def _schedule_drain(self) -> None:
        if self._drain_handle is None:
            self._drain_handle = asyncio.get_running_loop().call_later(
                self._DRAIN_RETRY_S, self._drain_deferred)

    def _drain_deferred(self) -> None:
        self._drain_handle = None
        # re-acquire per event so the commit/dispatch threads can
        # interleave, and bound the work per loop callback — a long
        # backlog drains across several callbacks instead of recreating
        # the stall this path exists to avoid
        budget = 256
        while self._deferred_events and budget > 0:
            if not self._state_lock.acquire(blocking=False):
                break
            try:
                apply, event = self._deferred_events.popleft()
                apply(event)
            except Exception:  # noqa: BLE001 — parity with Informer._dispatch
                log.exception("deferred informer event failed")
            finally:
                self._state_lock.release()
            budget -= 1
        if self._deferred_events:
            self._schedule_drain()

    def _apply_node_event(self, event: WatchEvent) -> None:
        node = event.obj
        with self._state_lock:
            if event.type == "DELETED":
                self.statedb.remove_node(node.metadata.name)
                return
            self.statedb.upsert_node(node)
            # re-account bound pods the state missed: pods whose
            # MODIFIED/ADDED event raced ahead of this node's, or whose
            # accounting was dropped by a node delete+recreate — via the
            # node->pods index, not an O(all pods) informer sweep
            for key in self._pods_by_node.get(node.metadata.name, ()):
                if self.statedb.is_accounted(key) or key in self._assumed:
                    continue
                ns, name = key.split("/", 1)
                pod = self.pod_informer.get(name, ns)
                if pod is not None \
                        and pod.spec.node_name == node.metadata.name:
                    self.statedb.add_pod(pod)

    def _wants(self, pod: Pod) -> bool:
        return pod.spec.scheduler_name == self.scheduler_name

    @property
    def inflight_batches(self) -> int:
        """Dispatched-but-unsettled batches (pipeline depth in use)."""
        staged = self._staged.inflight if self._staged is not None else 0
        return len(self._inflight_q) + staged

    def _unindex_pod(self, key: str) -> None:
        prev = self._pod_node.pop(key, None)
        if prev is not None:
            pods = self._pods_by_node.get(prev)
            if pods is not None:
                pods.discard(key)
                if not pods:
                    del self._pods_by_node[prev]

    def _apply_pod_event(self, event: WatchEvent) -> None:
        pod: Pod = event.obj
        key = pod.key
        if event.type == "DELETED":
            self._assumed.discard(key)
            self._quarantined.discard(key)
            self._enqueue_time.pop(key, None)
            self._unindex_pod(key)
            self._gang_forget(key)
            with self._state_lock:
                self.statedb.remove_pod(key)
                self.encode_cache.forget(key)
            return
        if pod.spec.node_name:
            if self._pod_node.get(key) != pod.spec.node_name:
                self._unindex_pod(key)
                self._pod_node[key] = pod.spec.node_name
                self._pods_by_node.setdefault(
                    pod.spec.node_name, set()).add(key)
            self._enqueue_time.pop(key, None)
            self._quarantined.discard(key)  # bound after all: not poison
            self._gang_forget(key)
            with self._state_lock:
                self.encode_cache.forget(key)
                if key in self._assumed:
                    # our own binding confirmed by the watch
                    self._assumed.discard(key)
                else:
                    # bound elsewhere; if the node is unknown the
                    # node-event handler re-accounts it once it appears
                    self.statedb.add_pod(pod)
        elif self._wants(pod):
            self._enqueue_time.setdefault(key, time.monotonic())
            # encode-on-watch: fingerprint + class encode now, while the
            # previous batch is on the wire/device, so batch assembly on
            # the critical path is a key lookup + two row memcpys
            try:
                with self._state_lock:
                    self.encode_cache.premake(pod)
            except CapacityError:
                # over-capacity pods still enqueue: batch assembly re-raises
                # and its per-pod failure path records the FailedScheduling
                # event instead of wedging the informer handler
                pass
            except (Conflict, TooManyRequests):
                # transient apiserver fault inside encode-on-watch (the
                # encode context lists Services/workloads through the
                # store): premake is only a cache warmer — the pod MUST
                # still enqueue, or a throttled list silently drops it
                # from scheduling forever; batch assembly re-encodes
                pass
            # gang members wait in staging until their group reaches
            # quorum — the extender path is per-pod and cannot place a
            # group atomically, so it schedules them individually
            if not self._extenders and self._stage_gang_member(key, pod):
                return
            self.queue.add(key)

    # ---- gang scheduling (all-or-nothing groups) ----

    def _on_podgroup_event(self, event: WatchEvent) -> None:
        """A PodGroup write can change a group's quorum: re-check whether
        the staged members now satisfy it."""
        group = event.obj
        gkey = f"{group.metadata.namespace}/{group.metadata.name}"
        members = self._gang_members.get(gkey)
        if members and len(members) >= self._gang_quorum(gkey):
            self.queue.add(_GANG_KEY_PREFIX + gkey)

    def _gang_quorum(self, gkey: str) -> int:
        """minMember for a group: the PodGroup object when it exists, else
        the largest group-min annotation seen on a member, else 1."""
        ns, name = gkey.split("/", 1)
        group = self.podgroup_informer.get(name, ns)
        if group is not None:
            return max(1, group.min_member)
        return max(1, self._gang_min_hint.get(gkey, 1))

    def _gang_timeout(self, gkey: str) -> float:
        ns, name = gkey.split("/", 1)
        group = self.podgroup_informer.get(name, ns)
        if group is not None and group.schedule_timeout_seconds:
            return float(group.schedule_timeout_seconds)
        return DEFAULT_SCHEDULE_TIMEOUT_S

    def _gang_forget(self, key: str) -> None:
        """Drop one pod from gang staging (deleted, bound, or released)."""
        gkey = self._gang_of_pod.pop(key, None)
        if gkey is None:
            return
        members = self._gang_members.get(gkey)
        if members is not None:
            members.discard(key)
            if not members:
                del self._gang_members[gkey]
                self._gang_first_seen.pop(gkey, None)
                self._gang_min_hint.pop(gkey, None)

    def _stage_gang_member(self, key: str, pod: Pod) -> bool:
        """Stage a gang-annotated pending pod; enqueue its GROUP (not the
        pod) once quorum is staged. Returns False for non-gang pods."""
        gkey = pod_group_key(pod)
        if gkey is None:
            return False
        prev = self._gang_of_pod.get(key)
        if prev is not None and prev != gkey:
            self._gang_forget(key)  # annotation changed: move groups
        self._gang_of_pod[key] = gkey
        members = self._gang_members.setdefault(gkey, set())
        members.add(key)
        self._gang_first_seen.setdefault(gkey, time.monotonic())
        hint = annotation_min(pod)
        if hint is not None:
            self._gang_min_hint[gkey] = max(
                self._gang_min_hint.get(gkey, 1), hint)
        if len(members) >= self._gang_quorum(gkey):
            self.queue.add(_GANG_KEY_PREFIX + gkey)
        return True

    def _check_gang_timeouts(self) -> None:
        """Release groups that never reached quorum within their schedule
        timeout: members go back to the queue as individual pods (the
        PodGroup's phase flips to Timeout via gang/controller.py)."""
        if not self._gang_first_seen:
            return
        now = time.monotonic()
        for gkey in list(self._gang_first_seen):
            timeout = self._gang_timeout(gkey)
            if now - self._gang_first_seen[gkey] < timeout:
                continue
            members = self._gang_members.get(gkey, set())
            if len(members) >= self._gang_quorum(gkey):
                continue  # at quorum: the group key is queued, not stuck
            self.metrics.gang_timeout_inc()
            for key in sorted(members):
                ns, name = key.split("/", 1)
                pod = self.pod_informer.get(name, ns)
                if pod is not None:
                    self.events.record(
                        pod, "Warning", "FailedScheduling",
                        f"pod group {gkey} did not reach quorum within "
                        f"{timeout:.0f}s; scheduling individually")
                self.queue.add(key)
            for key in list(members):
                self._gang_forget(key)
            self._gang_first_seen.pop(gkey, None)

    def _admit_gang(self, qkey: str, fblob, iblob, pods: list[Pod],
                    live_keys: list[str], gang_cols: list[tuple[int, int]],
                    gang_groups: dict) -> None:
        """Admit a quorate group into the current batch — whole or not at
        all (the solver's revert window is a contiguous in-batch run, so a
        group is never split across batches)."""
        gkey = qkey[len(_GANG_KEY_PREFIX):]
        self.queue.done(qkey)
        members: list[tuple[str, Pod]] = []
        for key in sorted(self._gang_members.get(gkey, ())):
            ns, name = key.split("/", 1)
            pod = self.pod_informer.get(name, ns)
            if pod is None or pod.spec.node_name:
                self._gang_forget(key)  # deleted or bound since staging
                self._enqueue_time.pop(key, None)
                continue
            members.append((key, pod))
        quorum = self._gang_quorum(gkey)
        if len(members) < quorum:
            return  # wait for more members (or the timeout sweep)
        if len(members) > self.caps.batch_pods:
            # can never fit one batch: release the members individually
            # rather than stalling the group forever
            for key, pod in members:
                self._gang_forget(key)
                self.metrics.failed += 1
                self.events.record(
                    pod, "Warning", "FailedScheduling",
                    f"pod group {gkey} has {len(members)} members but "
                    f"batch capacity is {self.caps.batch_pods}; a group "
                    f"cannot be split across solver batches")
                self.queue.add(key)
            return
        if len(pods) + len(members) > self.caps.batch_pods:
            self.queue.add(qkey)  # whole group in the NEXT batch
            return
        start = len(pods)
        seq = len(gang_groups) + 1  # batch-local id; 0 = non-gang
        positions: list[int] = []
        for key, pod in members:
            try:
                self.encode_cache.encode_packed_into(fblob, iblob,
                                                     len(pods), pod)
            except CapacityError as e:
                # un-admit the group (rows past len(pods) are re-zeroed by
                # the caller's tail wipe) and release its members — the
                # oversized member can never encode
                del pods[start:]
                del live_keys[start:]
                del gang_cols[start:]
                for mkey, mpod in members:
                    self._gang_forget(mkey)
                    self.queue.add(mkey)
                self.metrics.failed += 1
                self.events.record(
                    pod, "Warning", "FailedScheduling",
                    f"pod group {gkey}: member exceeds scheduler "
                    f"capacities: {e}")
                return
            positions.append(len(pods))
            pods.append(pod)
            live_keys.append(key)
            gang_cols.append((seq, quorum))
        gang_groups[seq] = (gkey, quorum, positions)

    # ---- lifecycle ----

    @property
    def synced(self) -> bool:
        """Both core informers completed their initial list — the
        scheduler's /readyz signal."""
        return (self.node_informer._synced.is_set()
                and self.pod_informer._synced.is_set())

    @property
    def solver_degraded(self) -> bool:
        """True while any pod is quarantined for poisoning device solves —
        the /healthz degraded signal (alive and scheduling, but some work
        is parked; liveness must NOT fail, a restart wouldn't help)."""
        return bool(self._quarantined)

    async def start(self) -> None:
        self.node_informer.start()
        self.pod_informer.start()
        self.podgroup_informer.start()
        for informer in self.workload_informers:
            informer.start()
        await self.node_informer.wait_for_sync()
        await self.pod_informer.wait_for_sync()
        await self.podgroup_informer.wait_for_sync()

    def _flush_events(self) -> None:
        """Record buffered per-batch events — Scheduled bursts plus the
        batch path's FailedScheduling tail — coalesced into one bulk
        store write per (type, reason) group (runs when the event loop
        next idles, or synchronously from stop()). In staged mode the
        event shard builds the objects off-loop first; otherwise a
        failing store keeps the entries for the next flush (bounded
        retries) instead of silently dropping them."""
        self._event_flush_scheduled = False
        if not self._pending_events:
            return
        entries, self._pending_events = self._pending_events, []
        shard = self._event_shard
        if shard is not None and not shard._stopped:
            shard.submit(entries)
            return
        t0 = time.monotonic()
        try:
            self.events.record_grouped(entries)
            self._event_flush_failures = 0
        except Exception:  # noqa: BLE001 — events must not kill the driver
            self._event_flush_failures = getattr(
                self, "_event_flush_failures", 0) + 1
            if self._event_flush_failures <= 3:
                log.warning("event flush failed (attempt %d); retrying on "
                            "next flush", self._event_flush_failures,
                            exc_info=True)
                self._pending_events = entries + self._pending_events
            else:
                log.error("event flush failed %d times; dropping %d events",
                          self._event_flush_failures, len(entries))
        self.metrics.add_phase("events_async", time.monotonic() - t0)

    async def _drain_events_async(self) -> None:
        """Make every buffered/sharded event visible (request-response
        seam: runs only when the pipeline is drained, so tests observe
        events as soon as schedule_pending returns idle)."""
        if self._pending_events:
            self._flush_events()
        shard = self._event_shard
        if shard is not None and shard.outstanding \
                and (self._staged is None or self._staged.inflight == 0):
            await shard.drain()

    def stop(self) -> None:
        self._stopped = True
        if self._drain_handle is not None:
            self._drain_handle.cancel()
            self._drain_handle = None
        # flush parked informer events with a blocking acquire — stop()
        # may block, and state must reflect every delivered event
        while self._deferred_events:
            apply, event = self._deferred_events.popleft()
            with self._state_lock:
                try:
                    apply(event)
                except Exception:  # noqa: BLE001
                    log.exception("deferred informer event failed in stop")
        if self._staged is not None:
            self._staged.drain_sync()
        self._settle_inflight()
        if self._event_shard is not None:
            self._flush_events()  # routes the buffer through the shard
            self._event_shard.stop()
            self._event_shard.drain_sync()
        self._flush_events()
        if self._staged is not None:
            self._staged.shutdown()
        self.queue.close()
        self.node_informer.stop()
        self.pod_informer.stop()
        self.podgroup_informer.stop()
        for informer in self.workload_informers:
            informer.stop()

    def kill(self) -> None:
        """Hard abort — the chaos drill's crash simulation. Every stage
        drops its in-flight work unapplied: batches that never bound are
        simply rescheduled by the restarted instance from store truth
        (crash-only contract; stop() is the graceful drain)."""
        self._stopped = True
        if self._staged is not None:
            self._staged.kill()
        if self._event_shard is not None:
            self._event_shard.kill()
        self._loop_calls.clear()
        self._pending_events = []
        if self._drain_handle is not None:
            self._drain_handle.cancel()
            self._drain_handle = None
        self._deferred_events.clear()
        for entry in self._inflight_q:
            timer = entry[6]
            if getattr(timer, "trace_span", None) is not None:
                timer.trace_span.end("aborted")
        self._inflight_q.clear()
        self.queue.close()
        self.node_informer.stop()
        self.pod_informer.stop()
        self.podgroup_informer.stop()
        for informer in self.workload_informers:
            informer.stop()

    async def run(self) -> None:
        """Schedule until stopped (wait.Until(scheduleOne) analog). A
        scheduling pass that raises (store 429s, transport failure) is
        logged and retried with backoff — the loop itself is crash-only
        state, so surviving beats dying and losing the queue."""
        await self.start()
        run_key = "__run_loop__"
        while not self._stopped:
            try:
                await self.schedule_pending(wait=0.5)
                self.backoff.reset(run_key)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — the loop survives anything
                log.exception("scheduling pass failed; backing off")
                await asyncio.sleep(self.backoff.next_delay(run_key))

    # ---- one batch ----

    def _acquire_blobs(self):
        """Packed transport blob pair from the free-list (allocates when
        empty — in-flight gating bounds steady-state allocation to
        depth+2 pairs; a leak on an exception path just reallocates)."""
        try:
            return self._blob_pool.popleft()
        except IndexError:
            from kubernetes_tpu.state.pod_batch import _layout

            _lay, f_width, i_width = _layout(self.caps)
            p = self.caps.batch_pods
            return (np.zeros((p, f_width), np.float32),
                    np.zeros((p, i_width), np.int32))

    def _release_blobs(self, blobs) -> None:
        """Return a blob pair once its batch's ledger commit has read the
        accounting rows (callable from the commit stage thread — deque
        append is atomic)."""
        if len(self._blob_pool) < self.pipeline_depth + 2:
            self._blob_pool.append(blobs)

    def _next_blobs(self):
        """Back-compat acquire-without-release (tests' scratch blobs):
        the pair stays pooled, so sequential callers may see the same
        arrays."""
        blobs = self._acquire_blobs()
        self._release_blobs(blobs)
        return blobs

    async def schedule_pending(self, wait: float | None = None) -> int:
        """Pop up to a batch of pending pods, schedule, bind. Returns the
        number of pods scheduled (in pipeline mode: settled this call)."""
        self._check_gang_timeouts()
        if len(self.nominated):
            self.nominated.expire(time.monotonic())
        settled = 0
        if self._staged is not None:
            self._loop_calls.bind(asyncio.get_running_loop())
            self._loop_calls.drain()
            settled = self._take_staged_settled()
            if self._staged_failures:
                settled += await self._drain_staged_failures()
        inflight = self._inflight_q or (
            self._staged is not None and self._staged.inflight)
        effective_wait = 0 if inflight else wait
        keys = await self.queue.get_batch(self.caps.batch_pods,
                                          wait=effective_wait)
        if not keys:
            settled += await self._asettle_inflight()
            if self._staged is not None and self._staged.inflight:
                # yield so marshalled apply closures make progress, then
                # collect whatever settled meanwhile
                await asyncio.sleep(0.001)
                self._loop_calls.drain()
                settled += self._take_staged_settled()
            return settled
        try:
            return settled + await self._schedule_batch(keys)
        except asyncio.CancelledError:
            raise
        except Exception:
            # level-triggered hardening: a popped key must never be lost to
            # an exception — the informer won't re-announce an unchanged
            # pending pod, so re-add every key before propagating (done()
            # first: add() on a processing key only marks it dirty)
            self._requeue_keys(keys)
            raise

    async def _schedule_batch(self, keys: list[str]) -> int:
        t_phase = time.thread_time()
        t_enc_wall = wall_now()
        fblob, iblob = self._acquire_blobs()
        pods: list[Pod] = []
        live_keys: list[str] = []
        # per-row (gang_id, gang_min) parallel to pods, (0, 0) = non-gang;
        # gang_groups: batch-local id -> (group key, quorum, row positions)
        gang_cols: list[tuple[int, int]] = []
        gang_groups: dict[int, tuple[str, int, list[int]]] = {}
        # the lock serializes encode-side interning against the staged
        # dispatch thread's flush (which applies pending refreshes)
        with self._state_lock:
            epoch_before = self.statedb.table.pod_row_epoch
            for key in keys:
                if key.startswith(_GANG_KEY_PREFIX):
                    self._admit_gang(key, fblob, iblob, pods, live_keys,
                                     gang_cols, gang_groups)
                    continue
                ns, name = key.split("/", 1)
                pod = self.pod_informer.get(name, ns)
                if pod is None or pod.spec.node_name:
                    self._enqueue_time.pop(key, None)
                    self.queue.done(key)  # deleted or already bound: drop
                    continue
                try:
                    self.encode_cache.encode_packed_into(fblob, iblob,
                                                         len(pods), pod)
                except CapacityError as e:
                    # per-pod failure must not wedge the batch
                    # (MakeDefaultErrorFunc parity, factory.go:897)
                    self._fail(key, pod,
                               f"pod exceeds scheduler capacities: {e}")
                    continue
                pods.append(pod)
                live_keys.append(key)
                gang_cols.append((0, 0))
            if pods and self.statedb.table.pod_row_epoch != epoch_before:
                # a later pod in this batch interned new podsel/avoid
                # entries: earlier pods' match/carry rows (encoded,
                # possibly cached, against the smaller universe) miss
                # them — re-encode every row against the final universes
                # (epoch is in the cache key, so stale cached rows cannot
                # be served)
                for i, pod in enumerate(pods):
                    self.encode_cache.encode_packed_into(fblob, iblob, i,
                                                         pod)
        if not pods:
            self._release_blobs((fblob, iblob))
            return await self._asettle_inflight()
        # unused tail rows of a reused blob must not leak the previous
        # batch's encodings (valid flags in particular)
        if len(pods) < self.caps.batch_pods:
            fblob[len(pods):] = 0.0
            iblob[len(pods):] = 0
        if gang_groups:
            # gang columns go in AFTER encoding: cached packed rows carry
            # zeros (a batch-local group id cannot be cached), and the
            # epoch re-encode above would have reset earlier writes
            from kubernetes_tpu.state.pod_batch import blob_col

            gid_col = blob_col(fblob, iblob, "gang_id", self.caps)
            gmin_col = blob_col(fblob, iblob, "gang_min", self.caps)
            for i, (gid, gmin) in enumerate(gang_cols):
                if gid:
                    gid_col[i] = gid
                    gmin_col[i] = gmin
        # host-phase costs accrue THREAD CPU time (see _apply_batch): wall
        # time on the loop includes GIL waits on concurrent stage threads
        self.metrics.add_phase("encode", time.thread_time() - t_phase)
        self.metrics.phase_pods += len(pods)

        if self._extenders:
            try:
                return await self._schedule_with_extenders(pods, live_keys,
                                                           fblob, iblob)
            finally:
                self._release_blobs((fblob, iblob))
        # the batch span: adopted from the first pod that carries a sampled
        # trace.ktpu.io/context annotation (stitching the client/apiserver
        # spans), else a rate-sampled root. Explicit handoff — the span
        # rides the queue item across stage threads and ends at commit.
        batch_span = self._begin_batch_span(pods)
        if batch_span.sampled:
            TRACER.record_span("encode", batch_span.context, t_enc_wall,
                               wall_now() - t_enc_wall, tid="encode",
                               attrs={"pods": len(pods)})
        if self._staged is not None and not self._stopped:
            return await self._schedule_batch_staged(
                pods, live_keys, fblob, iblob, gang_groups, batch_span)

        timer = StepTimer(f"scheduling batch of {len(pods)}",
                          step_hist=self.metrics.trace_steps,
                          trace_span=batch_span)
        from kubernetes_tpu.state.pod_batch import packed_batch_flags

        flags = packed_batch_flags(fblob, iblob, len(pods),
                                   self.statedb.table, self.caps)
        if self.explain:
            flags = dataclasses.replace(flags, explain=True)
        schedule_fn = self._get_schedule_fn(flags)
        victims, vslots = self._build_victims(flags)
        settled = 0
        if self._inflight_q and (not self._pipeline
                                 or self.statedb.ledger_dirty):
            # a dirty flush would re-upload host truth that misses the
            # in-flight batches' charges: settle them first
            settled += await self._asettle_inflight()
        t_phase = time.thread_time()
        state = self.statedb.flush()
        self.metrics.add_phase("flush", time.thread_time() - t_phase)
        timer.step("encode + flush")

        t0 = time.monotonic()
        try:
            result = await self._dispatch_guarded(schedule_fn, state, fblob,
                                                  iblob, victims, live_keys)
        except _SolveFailed as e:
            self.metrics.add_phase("dispatch", time.monotonic() - t0)
            self._release_blobs((fblob, iblob))
            batch_span.end("error")
            return settled + await self._recover_solve_failure(
                pods, live_keys, gang_groups, e)
        self._rr = result.rr_end
        try:
            # start the device->host copy now; by settle time (after the
            # next dispatch) it is usually already on the host
            result.assignments.copy_to_host_async()
        except AttributeError:
            pass
        self.metrics.add_phase("dispatch", time.monotonic() - t0)
        timer.step("device dispatch")
        # start the blocking host fetch NOW in a worker thread: by settle
        # time (up to pipeline_depth dispatches later) the round trip has
        # already been paid in the background — profiling showed the event
        # loop idling ~0.3s per batch in select() when the fetch thread
        # only started at settle
        fetch = asyncio.get_running_loop().create_task(
            asyncio.to_thread(np.asarray, result.assignments))
        # retrieve (and discard) failures so an entry popped by the sync
        # stop() path can't leave an un-retrieved task exception behind;
        # the settle path handles the error itself via a fresh fetch
        fetch.add_done_callback(
            lambda t: None if t.cancelled() else t.exception())
        # pipeline only under sustained load (more pods already queued →
        # another call is imminent); a drained queue settles synchronously
        # so small/interactive workloads keep request-response semantics
        if self._pipeline and len(self.queue) > 0:
            # adopt the (lazy, device-side) output ledger now so the next
            # batch chains on it without a synchronization; settle the
            # oldest batches while this one computes
            self.statedb.adopt_result(result)
            self._inflight_q.append((result, pods, live_keys, (fblob, iblob),
                                     flags, t0, timer, True, fetch,
                                     gang_groups, vslots))
            while len(self._inflight_q) > self.pipeline_depth:
                settled += await self._asettle_one()
            return settled
        self._inflight_q.append((result, pods, live_keys, (fblob, iblob),
                                 flags, t0, timer, False, fetch, gang_groups,
                                 vslots))
        return settled + await self._asettle_inflight()

    # ---- staged stage-per-thread path (scheduler/pipeline.py) ----

    def _begin_batch_span(self, pods: list[Pod]):
        """Begin the batch's root/joined span. Explicit handoff (the span
        crosses the dispatch/settle/commit threads on the queue item), so
        ownership of end() is the commit/error/drop path's — tracked in
        the tracer's open-span table meanwhile."""
        parent = None
        for pod in pods:
            ctx = pod_trace_context(pod)
            if ctx is not None:
                parent = ctx
                break
        span = TRACER.begin_span("schedule.batch", parent=parent,
                                 tid="scheduler")
        span.set_attr("pods", len(pods))
        return span

    async def _schedule_batch_staged(self, pods: list[Pod],
                                     live_keys: list[str], fblob, iblob,
                                     gang_groups: dict,
                                     batch_span=None) -> int:
        """Hand one encoded batch to the staged pipeline: flush + solve +
        readback + ledger commit run in stage threads while this loop
        encodes the next batch (unconditional prefetch — the overlap the
        single-loop path only got under queue pressure). With the queue
        drained the call degrades to request-response: it awaits the
        pipeline so callers observe their pods bound on return."""
        from kubernetes_tpu.state.pod_batch import packed_batch_flags

        from kubernetes_tpu.scheduler.pipeline import _BatchWork

        flags = packed_batch_flags(fblob, iblob, len(pods),
                                   self.statedb.table, self.caps)
        if self.explain:
            flags = dataclasses.replace(flags, explain=True)
        schedule_fn = self._get_schedule_fn(flags)
        with self._state_lock:
            victims, vslots = self._build_victims(flags)
        work = _BatchWork(pods, live_keys, (fblob, iblob), flags,
                          schedule_fn, victims, vslots, gang_groups)
        work.span = batch_span
        self._loop_calls.bind(asyncio.get_running_loop())
        await self._staged.wait_capacity()
        self._staged.submit(work)
        settled = self._take_staged_settled()
        if len(self.queue) == 0:
            await self._staged.drain()
            settled += self._take_staged_settled()
            if self._staged_failures:
                settled += await self._drain_staged_failures()
            await self._drain_events_async()
        return settled

    def _take_staged_settled(self) -> int:
        n, self._staged_settled = self._staged_settled, 0
        return n

    def _requeue_keys(self, keys: list[str]) -> None:
        """Level-triggered hardening for a batch whose apply failed: no
        popped key may be lost (done() first — add() on a processing key
        only marks it dirty)."""
        for key in keys:
            self.queue.done(key)
            self.queue.add(key)

    def _on_staged_solve_failure(self, work) -> None:
        """Loop-side landing for a batch whose solve failed twice in the
        dispatch stage: park the payload; the next schedule_pending
        drains the pipeline and runs the bisect/quarantine/serial-host
        recovery ladder on it."""
        self.statedb.mark_ledger_dirty()
        self._release_blobs(work.blobs)
        if work.span is not None:
            work.span.end("error")
        self._staged_failures.append(
            (work.pods, work.live_keys, work.gang_groups, work.error))

    async def _drain_staged_failures(self) -> int:
        await self._staged.drain()
        settled = self._take_staged_settled()
        payloads, self._staged_failures = self._staged_failures, []
        for pods, live_keys, gang_groups, error in payloads:
            settled += await self._recover_solve_failure(
                pods, live_keys, gang_groups, error)
        return settled

    async def _schedule_with_extenders(self, pods: list[Pod],
                                       live_keys: list[str],
                                       fblob, iblob) -> int:
        """Serial per-pod scheduling with extender composition: device
        evaluation (the full policy's predicates+priorities, the same
        _pod_eval the batch solver scans) -> each extender's Filter veto ->
        Prioritize scores added to the device's weighted sum ->
        round-robin selectHost -> bind. Later pods see earlier assumptions
        (scheduleOne's serial contract) because the ledger re-flushes per
        pod. Extender errors fail the pod's attempt and requeue with
        backoff (generic_scheduler.go:211-228)."""
        import jax

        from kubernetes_tpu.extender.client import ExtenderError
        from kubernetes_tpu.ops.solver import evaluate_pod
        from kubernetes_tpu.state.pod_batch import unpack_batch

        if self._pod_eval_fn is None:
            caps, policy, prows = self.caps, self.policy, self._prows

            def _eval(state, fb, ib, i):
                batch = unpack_batch(fb, ib, caps)
                row = jax.tree.map(lambda a: a[i], batch)
                return evaluate_pod(state, row, policy, caps=caps,
                                    prows=prows)

            self._pod_eval_fn = jax.jit(_eval)
        scheduled = 0
        full_mode = any(not e.config.node_cache_capable
                        for e in self._extenders)
        for i, (key, pod) in enumerate(zip(live_keys, pods)):
            # per-pod flush: pod k+1 must see pod k's assumption
            state = self.statedb.flush()
            feasible, score = self._pod_eval_fn(state, fblob, iblob, i)
            feasible = np.asarray(feasible)
            score = np.asarray(score)
            name_of = self.statedb.table.name_of
            rows: dict[str, int] = {}
            names: list[str] = []
            for row in np.flatnonzero(feasible):
                node_name = name_of[int(row)]
                if node_name is not None:
                    names.append(node_name)
                    rows[node_name] = int(row)
            if not names:
                # nothing feasible device-side: FitError before any
                # extender round trip (findNodesThatFit returns early)
                self._fail(key, pod, "no nodes available to schedule pods")
                continue
            nodes_by_name = None
            if full_mode:
                nodes_by_name = {n: obj for n in names
                                 if (obj := self.node_informer.get(n))
                                 is not None}
            try:
                ext_scores: dict[str, float] = {}
                for ext in self._extenders:
                    names = (await asyncio.to_thread(
                        ext.filter, pod, names, nodes_by_name))[0]
                    if not names:
                        break
                if names:
                    for ext in self._extenders:
                        for node_name, sc in (await asyncio.to_thread(
                                ext.prioritize, pod, names,
                                nodes_by_name)).items():
                            ext_scores[node_name] = \
                                ext_scores.get(node_name, 0.0) + sc
            except ExtenderError as e:
                self._fail(key, pod, f"extender error: {e}")
                continue
            names = [n for n in names if n in rows]
            if not names:
                self._fail(key, pod, "no nodes available to schedule pods")
                continue
            totals = [(float(score[rows[n]]) + ext_scores.get(n, 0.0), n)
                      for n in names]
            best = max(total for total, _ in totals)
            ties = [n for total, n in totals if total == best]
            choice = ties[int(self._rr) % len(ties)]
            self._rr = np.uint32(int(self._rr) + 1)
            try:
                self.store.bind(Binding(pod_name=pod.metadata.name,
                                        namespace=pod.metadata.namespace,
                                        target_node=choice))
            except (Conflict, NotFound, TooManyRequests) as e:
                self.metrics.binding_errors += 1
                self._fail(key, pod, f"binding rejected: {e}")
                continue
            self._assumed.add(key)
            self.statedb.add_pod(pod, choice)
            scheduled += 1
            self.queue.done(key)
            self.backoff.reset(key)
            enqueued = self._enqueue_time.pop(key, None)
            if enqueued is not None:
                self.metrics.e2e_latency.append(
                    time.monotonic() - enqueued)
            self.events.record(pod, "Normal", "Scheduled",
                               f"Successfully assigned {key} to {choice}")
        self.metrics.scheduled += scheduled
        self.metrics.batches += 1
        return scheduled

    # ---- solve-failure hardening ----
    #
    # The degradation ladder for a failing device solve:
    #   1. retry the dispatch once (transient transport/compiler faults);
    #   2. on a second failure, settle the pipeline, then BISECT the batch
    #      with probe solves to isolate pods whose presence fails the
    #      solve — those are quarantined (event + long unschedulable
    #      requeue);
    #   3. the healthy remainder degrades to the serial HOST placement
    #      path (capacity-only greedy fit over the StateDB ledger) so the
    #      cluster keeps making progress while the device path is down;
    #   4. if bisection finds no poison (the fault cleared), everything
    #      requeues for a normal batch.
    # All of it is host-side: the compiled solver program is untouched
    # (the HLO pin test in tests/test_faults.py proves bit-identity).

    async def _call_solve(self, schedule_fn, state, fblob, iblob, victims,
                          live_keys: list[str]):
        """One dispatch. With solve_timeout_s set, dispatch AND readback
        complete inside the deadline in a worker thread (a wedged device
        otherwise hangs the readback forever); the event loop keeps
        serving informers during the solve either way."""
        if self.solve_timeout_s:
            hook = self.solve_fault_hook

            def call():
                if hook is not None:
                    hook(list(live_keys))
                result = schedule_fn(state, fblob, iblob, self._rr, victims)
                np.asarray(result.assignments)  # force completion in-deadline
                return result

            # NOTE: on timeout the worker thread is abandoned, not killed —
            # a truly wedged dispatch leaks one thread (the watchdog's cost)
            return await asyncio.wait_for(asyncio.to_thread(call),
                                          self.solve_timeout_s)
        if self.solve_fault_hook is not None:
            self.solve_fault_hook(list(live_keys))
        # dispatch in a worker thread: tracing/compiling a new BatchFlags
        # variant (and the whole solve on CPU backends) is synchronous in
        # the runtime and would hold the event loop for the duration —
        # informers/heartbeats stall, which the LoopStallWatchdog flags.
        # The device result stays lazy; readback still overlaps via the
        # fetch task downstream.
        return await asyncio.to_thread(
            schedule_fn, state, fblob, iblob, self._rr, victims)

    async def _dispatch_guarded(self, schedule_fn, state, fblob, iblob,
                                victims, live_keys: list[str]):
        """Dispatch with one retry; raises _SolveFailed after the second
        failure (scheduleOne survives algorithm errors through
        MakeDefaultErrorFunc — the batched analog must survive a failing
        or wedged device solve)."""
        last: Exception | None = None
        for attempt in (1, 2):
            try:
                return await self._call_solve(schedule_fn, state, fblob,
                                              iblob, victims, live_keys)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — incl. TimeoutError
                last = e
                self.metrics.solve_failure_inc()
                if attempt == 1:
                    self.metrics.solve_retry_inc()
                    log.warning("device solve failed (attempt 1/2): %s; "
                                "retrying", e)
        raise _SolveFailed(str(last)) from last

    async def _recover_solve_failure(self, pods: list[Pod],
                                     live_keys: list[str],
                                     gang_groups: dict,
                                     error: Exception) -> int:
        """Persistent solve failure for one batch: drain the pipeline,
        requeue gang groups whole (all-or-nothing survives degradation),
        bisect the rest for poison pods, and place the healthy remainder
        via the serial host path."""
        log.error("device solve failed after retry for a %d-pod batch "
                  "(%s); bisecting", len(pods), error)
        settled = await self._asettle_inflight()
        # the failed dispatch may have half-consumed device state: force
        # the next flush to re-upload host truth
        self.statedb.mark_ledger_dirty()
        gang_rows: set[int] = set()
        for gkey, _quorum, positions in gang_groups.values():
            gang_rows.update(positions)
            # a gang is never split or serial-bound: the whole group
            # requeues with backoff and re-enters a future batch
            qkey = _GANG_KEY_PREFIX + gkey
            self.queue.add_after(qkey, self.backoff.next_delay(qkey))
        items = [(k, p) for i, (k, p) in enumerate(zip(live_keys, pods))
                 if i not in gang_rows]
        poison = await self._bisect_poison(items)
        if not poison:
            # probes pass now: the failure was transient after all —
            # requeue everything for a normal batched retry
            for key, _pod in items:
                self.queue.done(key)
                self.queue.add_after(key, self.backoff.next_delay(key))
            return settled
        poison_keys = {k for k, _ in poison}
        for key, pod in poison:
            self._quarantine(key, pod)
        survivors = [(k, p) for k, p in items if k not in poison_keys]
        return settled + self._schedule_serial_host(survivors)

    async def _bisect_poison(
            self, items: list[tuple[str, Pod]]) -> list[tuple[str, Pod]]:
        """Pods whose presence makes the solve fail, found by recursive
        probe solves — O(k log n) probes for k poison pods."""
        if not items:
            return []
        if await self._probe_solve(items):
            return []
        if len(items) == 1:
            return list(items)
        mid = len(items) // 2
        return (await self._bisect_poison(items[:mid])
                + await self._bisect_poison(items[mid:]))

    async def _probe_solve(self, items: list[tuple[str, Pod]]) -> bool:
        """True when a device solve over exactly these pods completes.
        Reuses the compiled variant cache; the probe's output ledger is
        never adopted (the ledger was already marked dirty), so results
        are discarded without side effects."""
        from kubernetes_tpu.state.pod_batch import packed_batch_flags

        blobs = self._acquire_blobs()
        try:
            keys = [k for k, _ in items]
            fblob, iblob = blobs
            with self._state_lock:
                for i, (_key, pod) in enumerate(items):
                    self.encode_cache.encode_packed_into(fblob, iblob, i, pod)
                if len(items) < self.caps.batch_pods:
                    fblob[len(items):] = 0.0
                    iblob[len(items):] = 0
                flags = packed_batch_flags(fblob, iblob, len(items),
                                           self.statedb.table, self.caps)
                schedule_fn = self._get_schedule_fn(flags)
                state = self.statedb.flush()
            result = await self._call_solve(schedule_fn, state, fblob,
                                            iblob, None, keys)
            await asyncio.to_thread(np.asarray, result.assignments)
            self.statedb.mark_ledger_dirty()  # never adopt probe output
            return True
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — a failed probe is an answer
            self.metrics.solve_failure_inc()
            return False
        finally:
            self._release_blobs(blobs)

    def _quarantine(self, key: str, pod: Pod) -> None:
        """Poison pod: surface the verdict as an event and park it with a
        long unschedulable requeue so one bad pod cannot re-poison every
        batch; a later delete/bind clears the quarantine."""
        self.metrics.quarantine_inc()
        self._quarantined.add(key)
        self.metrics.failed += 1
        self.queue.done(key)
        self.queue.add_after(key, self.quarantine_backoff_s)
        log.error("pod %s quarantined: device solve fails whenever it is "
                  "in the batch", key)
        self.events.record(
            pod, "Warning", "FailedScheduling",
            f"pod quarantined: device solve fails whenever this pod is in "
            f"the batch; retrying in {self.quarantine_backoff_s:.0f}s")

    def _schedule_serial_host(self, items: list[tuple[str, Pod]]) -> int:
        """Degraded placement: greedy first-fit over the StateDB host
        ledger (capacity predicate only — no device program involved).
        Keeps the healthy remainder of a poisoned batch moving while the
        device path is down; pods that don't fit requeue with normal
        backoff and re-enter the full solver once it recovers."""
        if not items:
            return 0
        from kubernetes_tpu.state.cluster_state import pod_requests

        host = self.statedb.host
        name_of = self.statedb.table.name_of
        scheduled = 0
        for key, pod in items:
            req = pod_requests(pod)
            free = host.allocatable - host.requested
            fits = np.flatnonzero(host.valid & np.all(free >= req, axis=1))
            choice = None
            n = len(fits)
            start = int(self._rr) % n if n else 0
            for off in range(n):
                row = int(fits[(start + off) % n])
                node_name = name_of[row]
                if node_name is not None:
                    choice = node_name
                    break
            self._rr = np.uint32(int(self._rr) + 1)
            if choice is None:
                self._fail(key, pod, "no nodes available to schedule pods "
                                     "(degraded host path)")
                continue
            try:
                self.store.bind(Binding(pod_name=pod.metadata.name,
                                        namespace=pod.metadata.namespace,
                                        target_node=choice))
            except (Conflict, NotFound, TooManyRequests) as e:
                self.metrics.binding_errors += 1
                self._fail(key, pod, f"binding rejected: {e}")
                continue
            self._assumed.add(key)
            self.statedb.add_pod(pod, choice)
            self.metrics.serial_fallback_inc()
            scheduled += 1
            self.queue.done(key)
            self.backoff.reset(key)
            enqueued = self._enqueue_time.pop(key, None)
            if enqueued is not None:
                self.metrics.e2e_latency.append(time.monotonic() - enqueued)
            self.events.record(
                pod, "Normal", "Scheduled",
                f"Successfully assigned {key} to {choice} "
                f"(degraded host path)")
        self.metrics.scheduled += scheduled
        self.metrics.batches += 1
        return scheduled

    def _settle_inflight(self) -> int:
        """Settle every in-flight batch, oldest first (synchronous —
        the stop() path)."""
        settled = 0
        while self._inflight_q:
            settled += self._settle_one()
        return settled

    async def _asettle_inflight(self) -> int:
        settled = 0
        while self._inflight_q:
            settled += await self._asettle_one()
        # fully drained: make deferred events visible before returning, so
        # non-pipelined callers keep request-response semantics (under
        # sustained pipelined load the call_soon flush runs instead)
        await self._drain_events_async()
        return settled

    async def _asettle_one(self) -> int:
        """Async settle: the readback was started in a worker thread AT
        DISPATCH, so by now (up to pipeline_depth dispatches later) the
        transport round trip has usually already completed — this await
        is a cache hit in steady state; the event loop keeps running
        informers / encoding during any residual wait."""
        if not self._inflight_q:
            return 0
        entry = self._inflight_q[0]
        t0 = time.monotonic()
        try:
            assignments = await entry[8]
        except asyncio.CancelledError:
            if not entry[8].cancelled():
                raise  # WE were cancelled, not the fetch task
            assignments = None  # fetch cancelled: re-read below
        except Exception:  # noqa: BLE001 — transient transport failure
            # a poisoned prefetch must not wedge the queue forever: the
            # old per-settle fetch retried fresh every attempt; do the same
            log.warning("prefetch failed; re-reading assignments",
                        exc_info=True)
            assignments = None
        if assignments is None:
            assignments = await asyncio.to_thread(
                np.asarray, entry[0].assignments)
        waited = time.monotonic() - t0
        self.metrics.add_phase("settle_wait", waited)
        if not self._inflight_q or self._inflight_q[0] is not entry:
            return 0  # settled by stop() while we waited
        return self._settle_one(assignments, waited=waited)

    def _settle_one(self, assignments: np.ndarray | None = None,
                    waited: float | None = None) -> int:
        """Read back the oldest in-flight solve, bind its assignments, and
        commit the ledger (the synchronous tail of schedule_pending)."""
        if not self._inflight_q:
            return 0
        (result, pods, live_keys, blobs, flags, t0, timer,
         adopted, fetch, gang_groups, vslots) = self._inflight_q.popleft()
        if assignments is None and fetch.done() \
                and not fetch.cancelled() and fetch.exception() is None:
            assignments = fetch.result()  # prefetch already landed
        # NOTE: never cancel() an unfinished fetch here — a concurrently
        # suspended _asettle_one is awaiting it, and cancellation would
        # propagate into that coroutine; the duplicate synchronous read
        # below is harmless
        t_wait = time.monotonic()
        if assignments is None:
            assignments = np.asarray(result.assignments)
            record_readback(assignments)
            self.metrics.add_phase("settle_wait",
                                   time.monotonic() - t_wait)
        # synchronous batches observe the true dispatch-to-ready span; for a
        # pipelined batch only the readback wait is observable (the full
        # span would count the successor's host work as algorithm time) —
        # when the readback ran in _asettle_one's thread, `waited` carries
        # that span here
        if adopted:
            residual = waited if waited is not None \
                else time.monotonic() - t_wait
        else:
            residual = time.monotonic() - t0
        self.metrics.algorithm_latency.append(residual)
        timer.step("device solve")

        rows = assignments[:len(pods)].tolist()
        # preemption verdicts ride the same result; resolve them only when
        # this batch actually carried a victim table
        preempt_rows = victim_counts = None
        if vslots is not None:
            preempt = np.asarray(result.preempt_node)
            victims = np.asarray(result.victim_count)
            record_readback(preempt, victims)
            preempt_rows = preempt[:len(pods)].tolist()
            victim_counts = victims[:len(pods)].tolist()
        explain_rows = None
        if flags.explain and result.explain_counts is not None:
            explain = np.asarray(result.explain_counts)
            record_readback(explain)
            explain_rows = explain[:len(pods)].tolist()
        scheduled, committed, any_rejected = self._apply_batch(
            result, pods, live_keys, blobs, flags, rows, preempt_rows,
            victim_counts, gang_groups, vslots, timer,
            explain_rows=explain_rows, span=timer.trace_span)
        self._commit_ledger(result, blobs[0], committed, any_rejected,
                            flags, adopted)
        self._release_blobs(blobs)
        timer.step("bind + commit")
        timer.log_if_long(0.1 * len(pods))
        return scheduled

    def _apply_batch(self, result, pods: list[Pod], live_keys: list[str],
                     blobs, flags, rows: list[int],
                     preempt_rows: list[int] | None,
                     victim_counts: list[int] | None, gang_groups: dict,
                     vslots, timer=None, explain_rows=None,
                     span=None) -> tuple[int, list, bool]:
        """Act on one solved batch's host-side verdicts: settle gangs,
        partition assigned rows from rejections, bulk-bind through the
        store, and buffer the per-pod events. Runs ON the event loop (in
        staged mode the commit thread marshals it here via LoopCalls) so
        every store write stays loop-serialized. Returns (scheduled,
        committed, any_rejected) for _commit_ledger."""
        scheduled = 0
        committed: list[tuple[Pod, str, int]] = []
        any_rejected = False
        t_bind = time.monotonic()
        t_bind_cpu = time.thread_time()
        # partition the batch: assigned rows to bind vs solver rejections
        name_of = self.statedb.table.name_of
        event_entries: list[tuple[Pod, str, str, str]] = []
        taken_victims: set[str] = set()
        # settle gangs at the GROUP level first: a reverted group requeues
        # as one unit with group backoff (its members' -1 rows are the
        # solver's revert, not individual rejections); a placed group's
        # below-quorum stragglers fall through to individual failure
        gang_handled: set[str] = set()
        for _seq, (gkey, quorum, positions) in gang_groups.items():
            placed = sum(1 for p in positions if rows[p] >= 0)
            if placed >= quorum:
                self.metrics.gang_placed_inc()
                qkey = _GANG_KEY_PREFIX + gkey
                self.backoff.reset(qkey)
                self._gang_first_seen.pop(gkey, None)
                for p in positions:
                    if rows[p] < 0:
                        # straggler past quorum: the gang is satisfied, the
                        # leftover member schedules (and fails) on its own
                        self._gang_forget(live_keys[p])
                continue
            self.metrics.gang_reverted_inc()
            qkey = _GANG_KEY_PREFIX + gkey
            for p in positions:
                gang_handled.add(live_keys[p])
                self.metrics.failed += 1
                event_entries.append(
                    (pods[p], "Warning", "FailedScheduling",
                     f"pod group {gkey} placed {placed}/{quorum} members; "
                     f"group reverted (all-or-nothing)"))
            # gang preemption composes all-or-nothing: the solver emits
            # verdicts only when EVERY unplaced member found a victim set,
            # so either the whole group's victims are evicted or none are
            if preempt_rows is not None:
                unplaced = [p for p in positions if rows[p] < 0]
                if unplaced and all(preempt_rows[p] >= 0 for p in unplaced):
                    for p in unplaced:
                        if not self._preempt_one(
                                live_keys[p], pods[p], preempt_rows[p],
                                victim_counts[p], vslots, taken_victims):
                            break
            self.queue.add_after(qkey, self.backoff.next_delay(qkey))
        to_bind: list[tuple[int, str, Pod, str]] = []
        now_mono = time.monotonic()
        holds_active = len(self.nominated) > 0
        total_nodes = (sum(1 for n in name_of if n is not None)
                       if explain_rows is not None else 0)
        for i, (key, pod) in enumerate(zip(live_keys, pods)):
            row = rows[i]
            if row < 0:
                if key in gang_handled:
                    continue  # group-level requeue already recorded
                if preempt_rows is not None and preempt_rows[i] >= 0 \
                        and self._preempt_one(key, pod, preempt_rows[i],
                                              victim_counts[i], vslots,
                                              taken_victims):
                    # nominated + victims evicted: retry once they vanish
                    self.queue.done(key)
                    self.queue.add_after(key, 0.05)
                    continue
                message = "no nodes available to schedule pods"
                if explain_rows is not None:
                    rendered = render_unschedulable(explain_rows[i],
                                                    total_nodes)
                    if rendered is not None:
                        message = rendered
                self._fail_batch(key, pod, message, event_entries)
                continue
            node_name = name_of[row]
            if node_name is None:
                any_rejected = True  # the vanished node left a ledger charge
                self._fail_batch(key, pod, "assigned node vanished",
                                 event_entries)
                continue
            if holds_active and self.nominated.blocks(
                    node_name, int(pod.spec.priority), now_mono):
                # the solver saw the victims' freed room, but it is being
                # held for a nominated higher-priority preemptor — backing
                # off here is what makes the eviction actually pay off
                any_rejected = True
                self._fail_batch(key, pod,
                                 f"node {node_name} capacity is held for a "
                                 f"nominated higher-priority pod",
                                 event_entries)
                continue
            to_bind.append((i, key, pod, node_name))

        # one bulk store transaction for the whole batch's bindings (the
        # serial per-pod path was the measured e2e wall, PERF.md); stores
        # without the bulk verb (RemoteStore) fall back per pod
        bind_many = getattr(self.store, "bind_many", None)
        if to_bind and bind_many is not None:
            try:
                errs = bind_many(
                    [Binding(pod_name=pod.metadata.name,
                             namespace=pod.metadata.namespace,
                             target_node=node_name)
                     for _i, _k, pod, node_name in to_bind])[1]
            except Exception as e:  # noqa: BLE001 — e.g. a store 429
                # the whole transaction failed before any per-pod verdicts:
                # every pod takes the bind-rejected path (requeue + event)
                log.warning("bulk bind failed: %s", e)
                errs = [e] * len(to_bind)
        elif to_bind:
            errs = []
            for _i, _key, pod, node_name in to_bind:
                try:
                    self.store.bind(Binding(pod_name=pod.metadata.name,
                                            namespace=pod.metadata.namespace,
                                            target_node=node_name))
                    errs.append(None)
                except (Conflict, NotFound, TooManyRequests) as e:
                    errs.append(e)
        else:
            errs = []

        now = time.monotonic()
        assumed_add = self._assumed.add
        queue_done = self.queue.done
        backoff_reset = self.backoff.reset
        enq_pop = self._enqueue_time.pop
        e2e_append = self.metrics.e2e_latency.append
        for (i, key, pod, node_name), err in zip(to_bind, errs):
            if gang_groups:
                # settled either way: eagerly unstage (the watch event
                # confirming the bind would do it too, but later)
                self._gang_forget(key)
            if err is not None:
                # the solver's ledger charged this pod; drop that ledger below
                any_rejected = True
                self.metrics.binding_errors += 1
                self._fail_batch(key, pod, f"binding rejected: {err}",
                                 event_entries)
                continue
            assumed_add(key)
            committed.append((pod, node_name, i))
            scheduled += 1
            queue_done(key)
            backoff_reset(key)
            self.nominated.release(key)
            enq = enq_pop(key, None)
            if enq is not None:
                e2e_append(now - enq)
            event_entries.append(
                (pod, "Normal", "Scheduled",
                 f"Successfully assigned {key} to {node_name}"))
        if span is not None and span.sampled and committed:
            # sampled batch: pods created without a client traceparent get
            # the batch's context stamped at bind time, so the kubelet's
            # sync span still joins the stitched trace (1% of batches —
            # off the headline path)
            self._stamp_trace_annotations(committed, span)
        if event_entries:
            self._pending_events.extend(event_entries)
            if not self._event_flush_scheduled:
                try:
                    asyncio.get_running_loop().call_soon(self._flush_events)
                    self._event_flush_scheduled = True
                except RuntimeError:   # sync stop() path: no running loop
                    self._flush_events()
        dt_bind = time.monotonic() - t_bind
        # phase cost in THREAD CPU time: with stage threads overlapping the
        # loop, wall time here includes GIL waits on a concurrent solve's
        # trace/compile — CPU time is the stable drift signal the phase
        # gates watch (wall == cpu when uncontended)
        self.metrics.add_phase("bind", time.thread_time() - t_bind_cpu)
        if scheduled:
            # per-pod binding latency (the batch amortizes one write loop)
            self.metrics.binding_latency.append(dt_bind / scheduled)
        self.metrics.scheduled += scheduled
        self.metrics.batches += 1
        if self.metrics.batches % 128 == 0:
            self.backoff.gc()
        return scheduled, committed, any_rejected

    def _stamp_trace_annotations(self, committed: list, span) -> None:
        """Stamp the batch's trace context onto just-bound pods that lack
        one (annotation trace.ktpu.io/context — the kubelet joins it)."""
        tp = span.context.to_traceparent()
        for pod, _node_name, _i in committed:
            ann = pod.metadata.annotations or {}
            if TRACE_ANNOTATION in ann:
                continue

            def _mutate(obj):
                new = dict(obj.metadata.annotations or {})
                new.setdefault(TRACE_ANNOTATION, tp)
                obj.metadata.annotations = new

            try:
                self.store.guaranteed_update(
                    "Pod", pod.metadata.name, pod.metadata.namespace,
                    _mutate, retries=4)
            except Exception:  # noqa: BLE001 — tracing must never fail a bind
                log.debug("trace annotation stamp failed for %s/%s",
                          pod.metadata.namespace, pod.metadata.name,
                          exc_info=True)

    def _commit_ledger(self, result, fblob, committed: list,
                       any_rejected: bool, flags, adopted: bool) -> None:
        """Fold one applied batch into the StateDB ledgers. Safe OFF the
        loop (the staged commit thread calls it directly): everything runs
        under the host-state lock, against host/device arrays the loop
        never mutates mid-batch."""
        t_commit = time.thread_time()
        with self._state_lock:
            if any_rejected:
                # the solver output charges pods whose binding failed: keep
                # the host truth (accounting only bound pods) and force a
                # re-upload instead of adopting the device ledger
                # (ForgetPod analog)
                self.statedb.commit_batch(result, fblob, committed,
                                          replace_device=False)
                self.statedb.mark_ledger_dirty()
            else:
                # clean batch: adopt the full device ledger, no transfer
                # either way (a pipelined batch already adopted at dispatch —
                # replacing now would regress the device ledger past its
                # successor)
                from kubernetes_tpu.ops.solver import ledger_coverage

                self.statedb.commit_batch(
                    result, fblob, committed, replace_device=not adopted,
                    coverage=ledger_coverage(self.policy, flags))
        # CPU time, not wall: under the staged pipeline this runs in the
        # commit thread while the dispatch thread may be tracing a new
        # solver variant (GIL-heavy) — see _apply_batch's bind phase note
        self.metrics.add_phase("commit", time.thread_time() - t_commit)

    def _build_victims(self, flags):
        """Victim-candidate table for this batch: the StateDB's accounted
        pods joined with informer priorities, PDB-evictable bits read from
        the store. Returns (None, None) when the pass is off — preemption
        disabled, no priority spread in the batch (flags.preempt), or no
        evictable candidate anywhere — so the pre-preemption program runs
        unchanged."""
        if not (self.enable_preemption and flags.preempt):
            return None, None
        from kubernetes_tpu.preemption import build_victim_table

        pods_by_key: dict[str, Pod] = {}
        for key in self.statedb._accounted:
            ns, name = key.split("/", 1)
            victim = self.pod_informer.get(name, ns)
            if victim is not None:
                pods_by_key[key] = victim
        victims, vslots = build_victim_table(
            self.statedb, pods_by_key, store=self.store)
        if victims is None:
            return None, None
        return victims, vslots

    def _preempt_one(self, key: str, pod: Pod, node_row: int, k: int,
                     vslots: dict, taken: set) -> bool:
        """Act on one preemption verdict: evict the victim set through the
        PDB-checked eviction path, record status.nominatedNodeName on the
        preemptor, and hold the freed capacity. Returns True when the
        nomination stands. Already-evicted victims are never rolled back
        on a later refusal (the reference evicts asynchronously too) — the
        preemptor just retries against the partially-freed node."""
        from kubernetes_tpu.controllers.disruption import can_evict
        from kubernetes_tpu.preemption import resolve_victims

        self.metrics.preempt_attempt_inc()
        node_name = self.statedb.table.name_of[node_row]
        if node_name is None:
            return False  # verdict node vanished since the solve
        vkeys = resolve_victims(vslots, node_row, int(k),
                                int(pod.spec.priority), taken)
        if vkeys is None:
            return False  # table went stale: retry next batch
        evicted = 0
        for vkey in vkeys:
            vns, vname = vkey.split("/", 1)
            victim = self.pod_informer.get(vname, vns)
            if victim is None:
                continue  # already gone; its capacity is already free
            if not can_evict(self.store, victim):
                # a budget drained between table assembly and now: refuse
                # the rest (eviction-subresource 429 semantics)
                self.events.record(
                    pod, "Warning", "FailedPreemption",
                    f"eviction of {vkey} refused by disruption budget")
                return False
            try:
                self.store.delete("Pod", vname, vns)
            except (NotFound, Conflict):
                continue
            evicted += 1
            self.events.record(
                victim, "Normal", "Preempted",
                f"Preempted by {key} to make room on node {node_name}")

        def set_nominated(obj):
            obj.status.nominated_node_name = node_name
            return obj

        try:
            self.store.guaranteed_update(
                "Pod", pod.metadata.name, pod.metadata.namespace,
                set_nominated)
        except (NotFound, Conflict):
            return False  # preemptor vanished mid-preemption
        self.nominated.nominate(key, node_name, int(pod.spec.priority),
                                time.monotonic())
        self.metrics.preempt_victims_add(evicted)
        self.metrics.preempt_success_inc()
        self.events.record(
            pod, "Normal", "Preempting",
            f"Evicted {evicted} lower-priority pod(s) on {node_name}; "
            f"nominated")
        return True

    def _fail(self, key: str, pod: Pod, message: str) -> None:
        self.metrics.failed += 1
        self.queue.done(key)
        self.queue.add_after(key, self.backoff.next_delay(key))
        self.events.record(pod, "Warning", "FailedScheduling", message)

    def _fail_batch(self, key: str, pod: Pod, message: str,
                    buf: list) -> None:
        """_fail for the batch apply path: the event rides the batch's
        coalesced buffer (one bulk store write per (type, reason)) instead
        of a per-pod synchronous record."""
        self.metrics.failed += 1
        self.queue.done(key)
        self.queue.add_after(key, self.backoff.next_delay(key))
        buf.append((pod, "Warning", "FailedScheduling", message))
