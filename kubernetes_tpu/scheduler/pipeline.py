"""Staged scheduler pipeline: stage-per-thread batch processing.

The input-pipeline treatment from accelerator training stacks applied to
the scheduling loop: the compiled solver sustains ~50k pods/s on-device,
but one asyncio loop serially encoding, dispatching, settling, binding and
committing every batch caps e2e throughput at a fraction of that. Here the
batch loop is split into stages connected by queues:

    encode (event loop) | dispatch | settle | commit+bind

- **encode** stays on the event loop: informers, the EncodeCache and the
  workqueue are loop-owned, and encoding batch k+1 overlaps batch k's
  solve (which runs in the dispatch thread) by construction.
- **dispatch** (thread): ledger flush -> compiled solve -> adopt the
  output ledger for chaining -> start the async device->host copy. Runs
  FIFO in one thread so round-robin/ledger chaining stays serial.
- **settle** (thread): the blocking device->host readback plus row-list
  conversion — the per-batch transport wait leaves the loop entirely.
- **commit** (thread): marshals ONE apply closure back onto the event
  loop (bind + queue/backoff/event bookkeeping must run where the store
  and workqueue live), then mirrors the ledger into host numpy off-loop.

Thread discipline (ktpu-lint R1 extends to these workers): stage threads
never touch the asyncio loop except through `call_soon_threadsafe`
(wrapped by LoopCalls), and never block on `time.sleep` — all waits are
`threading.Event.wait`/condition timeouts, so shutdown is prompt.

Host StateDB/EncodeCache arrays are guarded by the scheduler's
`_state_lock` (an RLock): the loop mutates them from informer handlers
and encode, the dispatch thread reads them in flush(), and the commit
thread scatters into them in commit_batch().
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque

import numpy as np

from kubernetes_tpu.obs.profiling import record_readback
from kubernetes_tpu.obs.tracing import TRACER, wall_now

log = logging.getLogger(__name__)

# stage workers park on their wake event at most this long between
# shutdown-flag checks; bounds stop() latency without polling hot
_IDLE_WAIT_S = 0.2


class LoopCalls:
    """Thread-safe closure marshalling onto the asyncio event loop.

    Stage threads push loop-affine work (store writes, workqueue ops)
    here; the loop runs it via `call_soon_threadsafe`. The pending deque
    is also drainable DIRECTLY by the loop thread (`drain()`), which is
    what makes the synchronous stop() path and mid-coroutine progress
    forcing possible without deadlocking on a busy loop.
    """

    def __init__(self):
        self._calls: deque = deque()
        self._loop = None

    def bind(self, loop) -> None:
        self._loop = loop

    def push(self, fn) -> None:
        """Enqueue `fn` to run on the loop thread (callable from any
        thread). If the loop is gone (teardown), the closure waits in the
        deque for a direct drain()."""
        self._calls.append(fn)
        loop = self._loop
        if loop is not None:
            try:
                loop.call_soon_threadsafe(self.drain)
            except RuntimeError:
                pass  # loop closed: drained directly by the stop() path

    def drain(self) -> None:
        """Run every pending closure (loop thread only)."""
        while True:
            try:
                fn = self._calls.popleft()
            except IndexError:
                return
            try:
                fn()
            except Exception:  # noqa: BLE001 — one closure must not
                log.exception("marshalled loop call failed")  # kill the rest

    def clear(self) -> None:
        self._calls.clear()


class EventShard:
    """Worker shard that coalesces per-batch event bursts off the loop.

    The driver buffers (obj, type, reason, message) entries per solved
    batch; this shard builds the Event objects (name formatting, metadata
    construction — the measured bulk of the 27-43 us/pod events cost) in
    a worker thread, then installs each (type, reason) group through ONE
    bulk store create marshalled back onto the loop, where the store and
    its watchers live. Recorder state (`_known`) is only ever touched on
    the loop (install path), so no locking is added to the recorder.
    """

    def __init__(self, recorder, calls: LoopCalls):
        self._recorder = recorder
        self._calls = calls
        self._pending: deque = deque()   # (entries, attempt) batches
        self._wake = threading.Event()
        self._progress = threading.Event()
        self._stopped = False
        self._thread: threading.Thread | None = None
        # loop-owned counters (submit and install both run on the loop)
        self.outstanding = 0
        self.installed_batches = 0
        self.built_entries = 0

    # ---- loop side ----

    def submit(self, entries: list[tuple], attempt: int = 0) -> None:
        """Hand one batch of (obj, type, reason, message) entries to the
        shard (loop thread)."""
        self.outstanding += 1
        self._pending.append((entries, attempt))
        self._ensure_thread()
        self._wake.set()

    def _install(self, built_groups, entries, attempt) -> None:
        """Publish pre-built event groups (runs on the loop)."""
        t0 = time.monotonic()
        try:
            for sub, built, keys, event_type, reason in built_groups:
                self._recorder.install_many(sub, built, keys, event_type,
                                            reason)
            self.installed_batches += 1
        except Exception:  # noqa: BLE001 — events are best-effort
            self.outstanding -= 1
            if attempt < 3:
                log.warning("event install failed (attempt %d); retrying",
                            attempt + 1, exc_info=True)
                self.submit(entries, attempt + 1)
            else:
                log.error("event install failed %d times; dropping %d "
                          "events", attempt + 1, len(entries))
            return
        finally:
            self._recorder_metrics_hook(time.monotonic() - t0)
        self.outstanding -= 1

    # overridable seam: the scheduler points this at
    # metrics.add_phase("events_async", ...) so the loop-side install cost
    # stays visible in the phase breakdown
    def _recorder_metrics_hook(self, seconds: float) -> None:
        pass

    async def drain(self, timeout: float = 5.0) -> None:
        """Await until every submitted batch is installed (loop thread,
        loop running) — the request-response seam for tests and the
        pipeline-drained path."""
        import asyncio

        deadline = time.monotonic() + timeout
        while self.outstanding and time.monotonic() < deadline:
            self._calls.drain()
            if self.outstanding:
                await asyncio.sleep(0.001)
        self._calls.drain()

    def drain_sync(self, timeout: float = 5.0) -> None:
        """Force every outstanding batch through (stop() path; the loop
        may be busy inside stop() or already closed, so marshalled
        installs are executed directly and not-yet-built batches are
        recorded inline)."""
        deadline = time.monotonic() + timeout
        while self.outstanding and time.monotonic() < deadline:
            self._calls.drain()
            if not self.outstanding:
                break
            try:
                entries, _attempt = self._pending.popleft()
            except IndexError:
                # the worker holds a batch: wait for it to marshal
                self._progress.wait(0.002)
                self._progress.clear()
                continue
            try:
                self._recorder.record_grouped(entries)
            except Exception:  # noqa: BLE001 — best-effort at teardown
                log.warning("event drain failed; dropping %d events",
                            len(entries), exc_info=True)
            finally:
                self.outstanding -= 1
        self._calls.drain()

    def stop(self) -> None:
        self._stopped = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)

    def kill(self) -> None:
        """Hard abort (crash simulation): drop queued batches."""
        self._stopped = True
        self._pending.clear()
        self._wake.set()

    # ---- worker thread ----

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._events_stage, name="ktpu-events-stage",
                daemon=True)
            self._thread.start()

    def _events_stage(self) -> None:
        """Stage worker: pure Event construction off the loop. Touches
        the loop only via LoopCalls (call_soon_threadsafe)."""
        from kubernetes_tpu.utils.events import _group_entries

        while not self._stopped:
            if not self._wake.wait(timeout=_IDLE_WAIT_S):
                continue
            self._wake.clear()
            while not self._stopped:
                try:
                    entries, attempt = self._pending.popleft()
                except IndexError:
                    break
                built_groups = []
                for event_type, reason, sub in _group_entries(entries):
                    built, keys = self._recorder.build_many(
                        sub, event_type, reason)
                    built_groups.append((sub, built, keys, event_type,
                                         reason))
                self.built_entries += len(entries)
                self._calls.push(
                    lambda g=built_groups, e=entries, a=attempt:
                    self._install(g, e, a))
                self._progress.set()


class _BatchWork:
    """One batch's state as it moves through the stages.

    `span` is the batch's trace span, carried EXPLICITLY on the queue
    item (contextvars do not cross the stage-thread boundaries): stage
    work is recorded retroactively against it, and whichever path
    finishes the batch — commit, solve-failure landing, or a kill-path
    _drop — owns ending it."""

    __slots__ = ("pods", "live_keys", "blobs", "flags", "schedule_fn",
                 "victims", "vslots", "gang_groups", "result",
                 "assignments", "rows", "preempt_rows", "victim_counts",
                 "error", "solve_span", "active_counted", "span",
                 "explain_rows")

    def __init__(self, pods, live_keys, blobs, flags, schedule_fn,
                 victims, vslots, gang_groups):
        self.pods = pods
        self.live_keys = live_keys
        self.blobs = blobs
        self.flags = flags
        self.schedule_fn = schedule_fn
        self.victims = victims
        self.vslots = vslots
        self.gang_groups = gang_groups
        self.result = None
        self.assignments = None
        self.rows = None
        self.preempt_rows = None
        self.victim_counts = None
        self.error = None
        self.solve_span = 0.0
        self.active_counted = False
        self.span = None
        self.explain_rows = None


class StagedPipeline:
    """dispatch | settle | commit stage threads behind the loop's encode.

    Bounded by `depth` (in-flight batches gated at submit via
    wait_capacity) and by the scheduler's blob free-list. FIFO end to
    end: each stage is a single thread draining its own deque.
    """

    def __init__(self, sched, depth: int):
        self.sched = sched
        self.depth = depth
        self._calls: LoopCalls = sched._loop_calls
        self._dispatch_q: deque = deque()
        self._settle_q: deque = deque()
        self._commit_q: deque = deque()
        self._dispatch_wake = threading.Event()
        self._settle_wake = threading.Event()
        self._commit_wake = threading.Event()
        self._progress = threading.Event()
        # dispatched-but-uncommitted count: the ledger-dirty barrier (a
        # dirty flush would re-upload host truth missing in-flight
        # charges, so dispatch waits for downstream to empty first)
        self._active = 0
        self._dcond = threading.Condition()
        self._threads: list[threading.Thread] = []
        self._stopped = False
        self.killed = False
        # loop-owned accounting
        self.inflight = 0
        self.submitted = 0
        self.completed = 0
        self.dropped = 0
        # occupancy instrumentation (satellite: bench `extras`)
        self.busy = {"dispatch": 0.0, "settle": 0.0, "commit": 0.0,
                     "apply": 0.0}
        self._qmax = {"dispatch": 0, "settle": 0, "commit": 0}
        self._started: float | None = None

    # ---- loop side ----

    def submit(self, work: _BatchWork) -> None:
        if self.killed:
            self._drop(work)  # a submitter that raced the kill
            return
        if self._started is None:
            self._started = time.perf_counter()
        self.submitted += 1
        self.inflight += 1
        self._dispatch_q.append(work)
        self._qmax["dispatch"] = max(self._qmax["dispatch"],
                                     len(self._dispatch_q))
        self._ensure_threads()
        self._dispatch_wake.set()

    async def wait_capacity(self) -> None:
        """Block (yielding to the loop) until a pipeline slot frees up.
        The yields are what let marshalled apply closures run, so waiting
        here IS making progress."""
        import asyncio

        while self.inflight >= self.depth:
            self._calls.drain()
            if self.inflight >= self.depth:
                await asyncio.sleep(0.0005)

    async def drain(self, timeout: float = 60.0) -> None:
        """Await until every submitted batch fully commits (loop
        running)."""
        import asyncio

        deadline = time.monotonic() + timeout
        while self.inflight > 0 and time.monotonic() < deadline:
            self._calls.drain()
            if self.inflight > 0:
                await asyncio.sleep(0.0005)
        self._calls.drain()

    def drain_sync(self, timeout: float = 30.0) -> None:
        """Drain from the loop thread without a running loop (stop()
        path): executes marshalled closures directly while the stage
        threads finish their in-flight work."""
        deadline = time.monotonic() + timeout
        while self.inflight > 0 and time.monotonic() < deadline:
            self._calls.drain()
            if self.inflight > 0:
                self._progress.wait(0.002)
                self._progress.clear()
        self._calls.drain()
        if self.inflight > 0:
            log.error("staged pipeline drain timed out with %d batches "
                      "in flight", self.inflight)

    def _finish(self, work: _BatchWork, scheduled: int) -> None:
        """Last hop, on the loop: close out one batch's accounting."""
        self.inflight -= 1
        self.completed += 1
        self.sched._staged_settled += scheduled

    def shutdown(self) -> None:
        self._stopped = True
        for ev in (self._dispatch_wake, self._settle_wake,
                   self._commit_wake):
            ev.set()
        with self._dcond:
            self._dcond.notify_all()
        for t in self._threads:
            t.join(timeout=1.0)

    def kill(self) -> None:
        """Hard abort (crash simulation): every stage drops its in-flight
        work on the floor — unapplied batches simply never bind, which is
        the crash-consistency contract (a restarted scheduler re-schedules
        them from the store's truth)."""
        self.killed = True
        self._stopped = True
        # drain-and-drop every queued batch HERE: a stage thread parked on
        # its wake event exits without another queue pass, which would
        # strand queued work (and leak its batch span as a forever-open
        # orphan in /debug/traces). popleft is safe against a concurrently
        # draining stage thread — each item is popped exactly once.
        for q in (self._dispatch_q, self._settle_q, self._commit_q):
            while True:
                try:
                    work = q.popleft()
                except IndexError:
                    break
                self._drop(work)
        for ev in (self._dispatch_wake, self._settle_wake,
                   self._commit_wake):
            ev.set()
        with self._dcond:
            self._dcond.notify_all()

    def snapshot(self) -> dict:
        """Per-stage occupancy + queue-depth high-water marks for bench
        `extras` — what fraction of the wall each stage was busy, i.e.
        where the next wall is."""
        wall = (time.perf_counter() - self._started) \
            if self._started is not None else 0.0
        return {
            "depth": self.depth,
            "submitted": self.submitted,
            "completed": self.completed,
            "dropped": self.dropped,
            "wall_s": round(wall, 3),
            "stage_busy_frac": {
                k: (round(v / wall, 4) if wall > 0 else 0.0)
                for k, v in sorted(self.busy.items())},
            "queue_depth_max": dict(self._qmax),
        }

    def reset_stats(self) -> None:
        """Start a fresh occupancy window (harness warmup boundary)."""
        self._started = time.perf_counter()
        for k in self.busy:
            self.busy[k] = 0.0
        for k in self._qmax:
            self._qmax[k] = 0
        self.submitted = self.completed = self.dropped = 0

    # ---- worker threads ----

    def _ensure_threads(self) -> None:
        if self._threads and all(t.is_alive() for t in self._threads):
            return
        self._threads = [
            threading.Thread(target=self._dispatch_stage,
                             name="ktpu-dispatch-stage", daemon=True),
            threading.Thread(target=self._settle_stage,
                             name="ktpu-settle-stage", daemon=True),
            threading.Thread(target=self._commit_stage,
                             name="ktpu-commit-stage", daemon=True),
        ]
        for t in self._threads:
            t.start()

    def _drop(self, work: _BatchWork) -> None:
        self.dropped += 1
        if work.span is not None:
            work.span.end("aborted")  # no orphan spans on the kill path
        if work.active_counted:
            with self._dcond:
                self._active -= 1
                self._dcond.notify_all()

    def _dispatch_stage(self) -> None:
        """Stage worker: ledger flush + compiled solve + output-ledger
        adoption, FIFO. Loop access only through LoopCalls."""
        sched = self.sched
        while not self._stopped:
            if not self._dispatch_wake.wait(timeout=_IDLE_WAIT_S):
                continue
            self._dispatch_wake.clear()
            while True:
                try:
                    work = self._dispatch_q.popleft()
                except IndexError:
                    break
                if self.killed:
                    self._drop(work)
                    continue
                # ledger-dirty barrier: host truth changed (external bind,
                # rejected binding rollback) — the re-upload must not
                # overwrite charges still in flight downstream
                with self._dcond:
                    while (self._active > 0 and not self.killed
                           and sched.statedb.ledger_dirty):
                        self._dcond.wait(0.05)
                    if self.killed:
                        self.dropped += 1
                        if work.span is not None:
                            work.span.end("aborted")
                        continue
                    self._active += 1
                    work.active_counted = True
                t0 = time.perf_counter()
                t0_wall = wall_now()
                t0_cpu = time.thread_time()
                try:
                    with sched._state_lock:
                        state = sched.statedb.flush()
                    sched.metrics.add_phase("flush",
                                            time.thread_time() - t0_cpu)
                    result = None
                    last: Exception | None = None
                    for attempt in (1, 2):
                        try:
                            result = self._solve(work, state)
                            break
                        except Exception as e:  # noqa: BLE001
                            last = e
                            sched.metrics.solve_failure_inc()
                            if attempt == 1:
                                sched.metrics.solve_retry_inc()
                                log.warning(
                                    "device solve failed (attempt 1/2): "
                                    "%s; retrying", e)
                    if result is None:
                        work.error = last
                    else:
                        sched._rr = result.rr_end
                        with sched._state_lock:
                            sched.statedb.adopt_result(result)
                        try:
                            result.assignments.copy_to_host_async()
                        except AttributeError:
                            pass
                        work.result = result
                except Exception as e:  # noqa: BLE001 — flush/adopt
                    work.error = e
                span = time.perf_counter() - t0
                work.solve_span = span
                self.busy["dispatch"] += span
                if work.span is not None and work.span.sampled:
                    # retroactive child: flush + solve + adopt on this row
                    TRACER.record_span(
                        "dispatch", work.span.context, t0_wall, span,
                        tid="dispatch",
                        status="error" if work.error is not None else "ok")
                sched.metrics.add_phase("dispatch", span)
                if work.error is None:
                    sched.metrics.algorithm_latency.append(span)
                if self.killed:
                    # the settle thread may already be gone: an append now
                    # would strand the batch (and orphan its span)
                    self._drop(work)
                    continue
                self._settle_q.append(work)
                self._qmax["settle"] = max(self._qmax["settle"],
                                           len(self._settle_q))
                self._settle_wake.set()
                self._progress.set()

    def _solve(self, work: _BatchWork, state):
        """One compiled solve in this (dispatch) thread. With
        solve_timeout_s set, the call runs under a watchdog deadline in a
        helper thread — a wedged device costs one abandoned thread, not a
        wedged pipeline."""
        sched = self.sched
        fblob, iblob = work.blobs
        hook = sched.solve_fault_hook
        if not sched.solve_timeout_s:
            if hook is not None:
                hook(list(work.live_keys))
            return work.schedule_fn(state, fblob, iblob, sched._rr,
                                    work.victims)
        box: dict = {}

        def call():
            # the fault hook runs INSIDE the deadline (a hook-simulated
            # wedged device must trip the watchdog, not stall the stage)
            try:
                if hook is not None:
                    hook(list(work.live_keys))
                r = work.schedule_fn(state, fblob, iblob, sched._rr,
                                     work.victims)
                np.asarray(r.assignments)  # force completion in-deadline
                box["r"] = r
            except Exception as e:  # noqa: BLE001
                box["e"] = e

        t = threading.Thread(target=call, daemon=True,
                             name="ktpu-solve-watchdog")
        t.start()
        t.join(sched.solve_timeout_s)
        if "r" in box:
            return box["r"]
        if "e" in box:
            raise box["e"]
        raise TimeoutError(
            f"device solve exceeded {sched.solve_timeout_s}s deadline")

    def _settle_stage(self) -> None:
        """Stage worker: blocking device->host readback + row-list
        conversion — the transport wait the loop used to eat."""
        sched = self.sched
        while not self._stopped:
            if not self._settle_wake.wait(timeout=_IDLE_WAIT_S):
                continue
            self._settle_wake.clear()
            while True:
                try:
                    work = self._settle_q.popleft()
                except IndexError:
                    break
                if self.killed:
                    self._drop(work)
                    continue
                if work.error is None:
                    t0 = time.perf_counter()
                    t0_wall = wall_now()
                    try:
                        n = len(work.pods)
                        work.assignments = np.asarray(
                            work.result.assignments)
                        work.rows = work.assignments[:n].tolist()
                        read = [work.assignments]
                        if work.vslots is not None:
                            preempt = np.asarray(work.result.preempt_node)
                            victims = np.asarray(work.result.victim_count)
                            work.preempt_rows = preempt[:n].tolist()
                            work.victim_counts = victims[:n].tolist()
                            read += [preempt, victims]
                        if (work.flags.explain
                                and work.result.explain_counts is not None):
                            explain = np.asarray(
                                work.result.explain_counts)
                            work.explain_rows = explain[:n].tolist()
                            read.append(explain)
                        record_readback(*read)
                    except Exception as e:  # noqa: BLE001 — transport
                        work.error = e  # routed into solve-failure recovery
                    dt = time.perf_counter() - t0
                    self.busy["settle"] += dt
                    if work.span is not None and work.span.sampled:
                        TRACER.record_span(
                            "settle", work.span.context, t0_wall, dt,
                            tid="settle",
                            status="error" if work.error is not None
                            else "ok")
                    sched.metrics.add_phase("settle_wait", dt)
                if self.killed:
                    self._drop(work)  # commit thread may already be gone
                    continue
                self._commit_q.append(work)
                self._qmax["commit"] = max(self._qmax["commit"],
                                           len(self._commit_q))
                self._commit_wake.set()
                self._progress.set()

    def _commit_stage(self) -> None:
        """Stage worker: marshal the loop-affine apply (bind + queue +
        event bookkeeping) onto the loop, wait for its verdicts, then
        mirror the ledger into host numpy here, off the loop."""
        sched = self.sched
        while not self._stopped:
            if not self._commit_wake.wait(timeout=_IDLE_WAIT_S):
                continue
            self._commit_wake.clear()
            while True:
                try:
                    work = self._commit_q.popleft()
                except IndexError:
                    break
                if self.killed:
                    self._drop(work)
                    continue
                if work.error is not None:
                    # solve failed twice: hand the batch to the loop's
                    # recovery path (bisect/quarantine/serial fallback)
                    self._calls.push(
                        lambda w=work: sched._on_staged_solve_failure(w))
                    with self._dcond:
                        self._active -= 1
                        self._dcond.notify_all()
                    self._calls.push(
                        lambda w=work: self._finish(w, 0))
                    self._progress.set()
                    continue
                done = threading.Event()
                box: dict = {}

                def apply(work=work, done=done, box=box):
                    t0 = time.perf_counter()
                    try:
                        box["out"] = sched._apply_batch(
                            work.result, work.pods, work.live_keys,
                            work.blobs, work.flags, work.rows,
                            work.preempt_rows, work.victim_counts,
                            work.gang_groups, work.vslots, None,
                            explain_rows=work.explain_rows,
                            span=work.span)
                    except Exception:  # noqa: BLE001
                        log.exception("staged apply failed; requeueing "
                                      "the batch")
                        sched._requeue_keys(work.live_keys)
                        sched.statedb.mark_ledger_dirty()
                    finally:
                        self.busy["apply"] += time.perf_counter() - t0
                        done.set()

                self._calls.push(apply)
                while not done.wait(timeout=0.1):
                    if self.killed:
                        break
                if not done.is_set():
                    self._drop(work)
                    continue
                scheduled = 0
                out = box.get("out")
                t0 = time.perf_counter()
                t0_wall = wall_now()
                if out is not None:
                    scheduled, committed, any_rejected = out
                    try:
                        sched._commit_ledger(work.result, work.blobs[0],
                                             committed, any_rejected,
                                             work.flags, adopted=True)
                    except Exception:  # noqa: BLE001
                        log.exception("staged ledger commit failed; "
                                      "marking dirty")
                        sched.statedb.mark_ledger_dirty()
                sched._release_blobs(work.blobs)
                dt = time.perf_counter() - t0
                self.busy["commit"] += dt
                if work.span is not None and work.span.sampled:
                    TRACER.record_span("commit", work.span.context,
                                       t0_wall, dt, tid="commit")
                if work.span is not None:
                    work.span.end("ok")
                with self._dcond:
                    self._active -= 1
                    self._dcond.notify_all()
                self._calls.push(
                    lambda w=work, n=scheduled: self._finish(w, n))
                self._progress.set()
