"""Gang scheduling: all-or-nothing placement of pod groups.

The TPU-first capability the batched solver exists for: a multi-host slice
workload (a 256-host pjit job) is useless at 255 placements, so its pods
must place atomically or not at all. The subsystem spans four layers:

- `api/objects.py PodGroup` — the coscheduling group object (minMember
  quorum, schedule timeout, phase);
- `state/pod_batch.py` — per-pod gang_id/gang_min columns, groups
  contiguous in the batch;
- `ops/solver.py` — the group-revert scan carry (BatchFlags.gang): a group
  that exits the scan below quorum restores its entry ledger snapshot so no
  partial gang ever reaches bind;
- `scheduler/driver.py` — stages annotated pods per group, admits a group
  into a batch only whole and only at quorum, requeues reverted groups with
  group-level backoff, and releases members for individual scheduling when
  quorum never arrives within the timeout;
- `gang/controller.py` — materializes PodGroups from gang-annotated
  parallel workloads and reconciles their phase from observed bindings.

Pods opt in with the `scheduling.ktpu.io/group-name` annotation (the
pod-group label convention of kube-batch / scheduler-plugins coscheduling,
as an annotation so plain v1 pods carry it).
"""

from __future__ import annotations

# group membership: pods carrying the same group-name annotation in one
# namespace form a gang
GROUP_NAME_ANNOTATION = "scheduling.ktpu.io/group-name"
# quorum override carried on pods or workloads when no PodGroup exists yet
GROUP_MIN_ANNOTATION = "scheduling.ktpu.io/group-min"
# quorum-wait override (seconds) on workloads the controller materializes
GROUP_TIMEOUT_ANNOTATION = "scheduling.ktpu.io/group-timeout-seconds"

DEFAULT_SCHEDULE_TIMEOUT_S = 30.0


def pod_group_key(pod) -> str | None:
    """\"namespace/groupname\" for a gang-annotated pod, else None."""
    name = pod.metadata.annotations.get(GROUP_NAME_ANNOTATION)
    if not name:
        return None
    return f"{pod.metadata.namespace}/{name}"


def annotation_min(obj) -> int | None:
    """The group-min annotation as an int, None when absent/invalid."""
    raw = obj.metadata.annotations.get(GROUP_MIN_ANNOTATION)
    if raw is None:
        return None
    try:
        value = int(raw)
    except (TypeError, ValueError):
        return None
    return value if value >= 1 else None
