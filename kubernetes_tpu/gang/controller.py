"""Gang controller: PodGroups materialized from parallel workloads, phase
reconciled from observed bindings.

The controller side of gang scheduling (the scheduler driver does the
actual atomic admission; see gang/__init__.py for the layer map):

- a Job or ReplicaSet carrying the group-name annotation (on its own
  metadata or its pod template) declares a gang — the controller creates
  the matching PodGroup with minMember defaulted from the workload's
  parallelism/replicas, so workload authors never hand-write group objects
  (the kube-batch/scheduler-plugins shape, where a PodGroup CRD fronts the
  coscheduling plugin);
- the PodGroup's status tracks what the cluster actually shows: member and
  placed counts from the pod informer, and a phase ladder
  Pending -> Placing -> Placed, with Timeout when quorum has not arrived
  within spec.scheduleTimeoutSeconds. Members of a timed-out group are
  released atomically — one event per group, and the scheduler's own
  timeout release (scheduler/driver.py) requeues them for individual
  scheduling — rather than leaking a forever-Pending gang.
"""

from __future__ import annotations

import time

from kubernetes_tpu.api.objects import PodGroup
from kubernetes_tpu.apiserver.store import Conflict, NotFound, ObjectStore
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.controllers.base import ReconcileController
from kubernetes_tpu.gang import (
    DEFAULT_SCHEDULE_TIMEOUT_S,
    GROUP_NAME_ANNOTATION,
    GROUP_TIMEOUT_ANNOTATION,
    annotation_min,
    pod_group_key,
)
from kubernetes_tpu.utils.events import EventRecorder

GANG_WORKLOAD_KINDS = ("Job", "ReplicaSet")


def workload_group_name(obj) -> str | None:
    """The gang a workload declares: its own annotation, else its pod
    template's (workload authors usually annotate the template so the
    created pods inherit membership)."""
    name = obj.metadata.annotations.get(GROUP_NAME_ANNOTATION)
    if name:
        return name
    template = (obj.spec.get("template") or {})
    annotations = ((template.get("metadata") or {})
                   .get("annotations") or {})
    return annotations.get(GROUP_NAME_ANNOTATION) or None


def workload_min_member(obj) -> int:
    """Quorum a workload implies: explicit group-min annotation, else the
    whole parallel width (a pjit job needs every host)."""
    explicit = annotation_min(obj)
    if explicit is not None:
        return explicit
    if obj.kind == "Job":
        return max(1, obj.parallelism)
    return max(1, obj.replicas)


class GangController(ReconcileController):
    """Reconciles one key per gang: \"namespace/groupname\"."""

    workers = 2

    def __init__(self, store: ObjectStore):
        super().__init__()
        self.name = "gang-controller"
        self.store = store
        self.events = EventRecorder(store)
        self.podgroups = Informer(store, "PodGroup")
        self.pods = Informer(store, "Pod")
        self.workloads = [Informer(store, kind)
                          for kind in GANG_WORKLOAD_KINDS]
        self.podgroups.add_handler(self._on_podgroup)
        self.pods.add_handler(self._on_pod)
        for informer in self.workloads:
            informer.add_handler(self._on_workload)

    async def start(self) -> None:
        await super().start()
        self.podgroups.start()
        self.pods.start()
        for informer in self.workloads:
            informer.start()
        await self.podgroups.wait_for_sync()
        await self.pods.wait_for_sync()
        for informer in self.workloads:
            await informer.wait_for_sync()

    def stop(self) -> None:
        super().stop()
        self.podgroups.stop()
        self.pods.stop()
        for informer in self.workloads:
            informer.stop()

    # ---- informer handlers ----

    def _on_podgroup(self, event) -> None:
        obj = event.obj
        self.enqueue(f"{obj.metadata.namespace}/{obj.metadata.name}")

    def _on_pod(self, event) -> None:
        key = pod_group_key(event.obj)
        if key is not None:
            self.enqueue(key)

    def _on_workload(self, event) -> None:
        name = workload_group_name(event.obj)
        if name is not None:
            self.enqueue(f"{event.obj.metadata.namespace}/{name}")

    # ---- reconcile ----

    def _declaring_workloads(self, namespace: str, name: str) -> list:
        return [obj for informer in self.workloads
                for obj in informer.items()
                if obj.metadata.namespace == namespace
                and workload_group_name(obj) == name]

    def _members(self, namespace: str, name: str) -> tuple[int, int, float]:
        """(members, placed, oldest_pending_age_s) from the pod cache."""
        members = placed = 0
        oldest = None
        now = time.time()
        for pod in self.pods.items():
            if pod.metadata.namespace != namespace:
                continue
            if pod.metadata.annotations.get(GROUP_NAME_ANNOTATION) != name:
                continue
            members += 1
            if pod.spec.node_name:
                placed += 1
            else:
                created = getattr(pod.metadata, "creation_timestamp", None)
                if created:
                    age = max(0.0, now - created)
                    oldest = age if oldest is None else max(oldest, age)
        return members, placed, oldest or 0.0

    async def sync(self, key: str) -> None:
        namespace, name = key.split("/", 1)
        group = self.podgroups.get(name, namespace)
        declaring = self._declaring_workloads(namespace, name)

        if group is None:
            if not declaring:
                return  # nothing declares this gang anymore
            # materialize the PodGroup from the widest declaring workload
            # (two workloads sharing a group name pool their quorum needs)
            min_member = max(workload_min_member(w) for w in declaring)
            spec: dict = {"minMember": min_member}
            for w in declaring:
                raw = w.metadata.annotations.get(GROUP_TIMEOUT_ANNOTATION)
                if raw:
                    try:
                        spec["scheduleTimeoutSeconds"] = float(raw)
                    except (TypeError, ValueError):
                        pass
                    break
            group = PodGroup.from_dict({
                "metadata": {"name": name, "namespace": namespace},
                "spec": spec,
                "status": {"phase": "Pending"},
            })
            try:
                self.store.create(group)
            except Conflict:
                pass  # another worker won the race; resync picks it up
            return

        members, placed, oldest_age = self._members(namespace, name)
        min_member = group.min_member
        timeout = group.schedule_timeout_seconds or DEFAULT_SCHEDULE_TIMEOUT_S
        if placed >= min_member:
            phase = "Placed"
        elif placed > 0:
            phase = "Placing"
        elif members > 0 and oldest_age > timeout:
            phase = "Timeout"
        else:
            phase = "Pending"

        status = {"phase": phase, "placed": placed, "members": members}
        if all(group.status.get(k) == v for k, v in status.items()):
            if phase in ("Pending", "Placing") and members > 0:
                # come back to flip to Timeout even with no further events
                self.enqueue_after(key, timeout)
            return

        def mutate(obj):
            obj.status.update(status)
            return obj

        try:
            self.store.guaranteed_update("PodGroup", name, namespace, mutate)
        except (NotFound, Conflict):
            return
        if phase == "Timeout" and group.status.get("phase") != "Timeout":
            # one group-level event; the scheduler's quorum-timeout release
            # requeues the members themselves
            self.events.record(
                group, "Warning", "GangTimeout",
                f"pod group {key} waited {timeout:.0f}s without reaching "
                f"quorum ({placed}/{min_member} placed, {members} members); "
                f"members released for individual scheduling")
        if phase in ("Pending", "Placing") and members > 0:
            self.enqueue_after(key, timeout)
