"""SolverFrontend: the multi-tenant wire surface over one SolverService.

Two protocols on one port:

- **Stock extender wire protocol, per tenant** — an unmodified Go
  kube-scheduler configured with ``urlPrefix:
  http://svc:port/tenants/<name>`` POSTs the usual ExtenderArgs to
  ``/tenants/<name>/{filter,prioritize,bind}`` and gets the usual
  ExtenderFilterResult / HostPriorityList back. Payload shaping is the
  SAME helpers the per-cluster `ExtenderServer` uses
  (extender.server.filter_payload / priority_payload): one evaluation
  path, one protocol rendering.
- **Native batch-solve endpoint** — ``POST /tenants/<name>/solve``
  with ``{"pods": [...], "bind": bool}``: the gang/preemption-capable
  superset, plus ``/tenants/<name>/state`` for node/pod sync
  (cache-capable tenants) and ``/tenants/<name>/register``.

HTTP mechanics (deadline, 429 + Retry-After on FlowRejected, obs
endpoints) are inherited from ExtenderServer — overload shed by the
service's fair queues surfaces to stock HTTPExtender retry semantics
unchanged.
"""

from __future__ import annotations

import json
import logging
from typing import Any

from kubernetes_tpu.extender.server import (
    ExtenderServer,
    filter_payload,
    priority_payload,
)
from kubernetes_tpu.solversvc.core import SolverService

log = logging.getLogger(__name__)


class SolverFrontend(ExtenderServer):
    """Asyncio HTTP front end for a SolverService (see module docstring)."""

    def __init__(self, svc: SolverService, host: str = "127.0.0.1",
                 port: int = 0, deadline_s: float = 5.0,
                 warmup_buckets: tuple = (), auto_register: bool = False):
        super().__init__(service=None, host=host, port=port,
                         deadline_s=deadline_s)
        self.svc = svc
        self.warmup_buckets = tuple(warmup_buckets)
        self.auto_register = auto_register

    def _warm(self) -> None:
        self.svc.warmup(self.warmup_buckets)

    async def start(self) -> None:
        await self.svc.start()
        await super().start()

    async def stop(self) -> None:
        await super().stop()
        await self.svc.stop()

    async def _route(self, method: str, path: str, body: bytes):
        path = path.rstrip("/")
        if method == "GET" and path in ("", "/healthz"):
            return 200, {"ok": True, "tenants": len(self.svc.tenants)}
        parts = [p for p in path.split("/") if p]
        if len(parts) != 3 or parts[0] != "tenants":
            return 404, {"error": f"unknown path {path!r}"}
        _, tenant, verb = parts
        if method != "POST":
            return 405, {"error": f"method {method} not allowed"}
        try:
            args = json.loads(body or b"{}")
        except json.JSONDecodeError as e:
            return 400, {"error": f"bad JSON: {e}"}
        if not isinstance(args, dict):
            return 400, {"error": "request body must be a JSON object"}
        try:
            if verb == "register":
                self.svc.register_tenant(tenant)
                return 200, {"ok": True}
            if tenant not in self.svc.tenants:
                if not self.auto_register:
                    return 404, {"error": f"unknown tenant {tenant!r}"}
                self.svc.register_tenant(tenant)
            return await self._tenant_verb(tenant, verb, args)
        except (ValueError, KeyError) as e:  # malformed args / bad tenant
            return 400, {"error": f"{type(e).__name__}: {e}"}

    async def _tenant_verb(self, tenant: str, verb: str,
                           args: dict[str, Any]):
        if verb in ("filter", "prioritize"):
            pod = args.get("pod") or {}
            node_items = None
            if args.get("nodes") is not None:
                items = args["nodes"].get("items") or []
                node_items = {
                    ((d.get("metadata") or {}).get("name", "")): d
                    for d in items}
                verdict = await self.svc.evaluate(tenant, pod, nodes=items)
            else:
                verdict = await self.svc.evaluate(
                    tenant, pod, node_names=args.get("nodenames") or [])
            if verb == "filter":
                return 200, filter_payload(
                    verdict.names,
                    lambda n: verdict.feasible.get(n, False), node_items)
            return 200, priority_payload(
                verdict.names, lambda n: verdict.score.get(n, 0))
        if verb == "bind":
            err = self.svc.bind(tenant, args.get("PodName", ""),
                                args.get("PodNamespace", "default"),
                                args.get("Node", ""))
            return 200, {"Error": err}
        if verb == "solve":
            verdict = await self.svc.solve(tenant, args.get("pods") or [],
                                           bind=bool(args.get("bind")))
            return 200, {"assignments": verdict.assignments,
                         "bound": verdict.bound, "errors": verdict.errors}
        if verb == "state":
            synced = {"nodes": 0, "pods": 0, "removed": 0}
            for nd in args.get("nodes") or []:
                self.svc.upsert_node(tenant, nd)
                synced["nodes"] += 1
            for pd in args.get("pods") or []:
                if self.svc.account_pod(tenant, pd):
                    synced["pods"] += 1
            for name in args.get("removeNodes") or []:
                self.svc.remove_node(tenant, name)
                synced["removed"] += 1
            for ref in args.get("removePods") or []:
                self.svc.forget_pod(tenant, ref.get("namespace", "default"),
                                    ref.get("name", ""))
                synced["removed"] += 1
            return 200, synced
        return 404, {"error": f"unknown verb {verb!r}"}
