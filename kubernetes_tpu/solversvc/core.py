"""SolverService: continuous batching of N tenants' solves into one program.

The inference-serving pattern applied to scheduling: tenant control
planes submit solve requests (single-pod extender verbs or native
multi-pod batch solves), a micro-batch window coalesces whatever
arrived into ONE padded device batch per step, and two persistent
program families answer them:

- ``evaluate``: `ops.solver.evaluate_pod` vmapped over the pod axis —
  per-node (feasible, score) vectors for filter/prioritize verbs;
- ``solve``: `ops.solver.schedule_batch` — assignments with gang
  all-or-nothing and preemption semantics for the native endpoint.

Shapes are pow-2 pod buckets over ONE shared StateDB (pow-2 node
growth by rebuild), so the jit cache is keyed by (bucket, flags) and a
shifting tenant mix never recompiles; every variant is registered with
the compile registry under a ``solversvc[...]`` name so `bench
--profile` attributes recompiles to the exact bucket.

Fairness is APF itself: a dedicated `solversvc` priority level in a
`FlowController` (apiserver/flowcontrol.py), one flow per tenant,
seat width from `solve_seats` — overload sheds with FlowRejected,
which the front end surfaces as an honest 429 + Retry-After.

Isolation is by construction (tenancy.py): everything in the shared
StateDB is tenant-namespaced at ingestion, and the step additionally
refuses (and counts) any assignment row whose node is not the
requesting tenant's — a counter that must read 0 forever.

Determinism seam (R4): the micro-batch window is driven by an injected
`utils.clock.Clock` — tests warp a ManualClock instead of sleeping;
`time.perf_counter` appears only in latency metrics.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import dataclasses
import logging
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from kubernetes_tpu.api.objects import Node, Pod
from kubernetes_tpu.apiserver.flowcontrol import (
    FlowController,
    FlowRejected,
    solve_seats,
)
from kubernetes_tpu.gang import annotation_min, pod_group_key
from kubernetes_tpu.models.policy import (
    DEFAULT_POLICY,
    Policy,
    build_policy_rows,
)
from kubernetes_tpu.obs.tracing import TRACER
from kubernetes_tpu.solversvc.tenancy import (
    check_tenant_name,
    namespace_node,
    namespace_pod,
    split_tenant,
    tenant_prefix,
)
from kubernetes_tpu.state.encode_cache import EncodeCache
from kubernetes_tpu.state.layout import Capacities
from kubernetes_tpu.state.pod_batch import (
    _layout,
    blob_col,
    packed_batch_flags,
    unpack_batch,
)
from kubernetes_tpu.state.statedb import StateDB
from kubernetes_tpu.utils.clock import SYSTEM_CLOCK, Clock

log = logging.getLogger(__name__)

_mx: dict | None = None


def _svc_metrics() -> dict:
    """solversvc_* families, registered on first use (all families created
    in this package carry the solversvc_ prefix — R6-lint enforced)."""
    global _mx
    if _mx is None:
        from kubernetes_tpu.obs import metrics as m

        _mx = {
            "requests": m.REGISTRY.counter(
                "solversvc_requests_total",
                "Solve-service requests, by tenant and verb.",
                ("tenant", "verb")),
            "rejected": m.REGISTRY.counter(
                "solversvc_rejected_total",
                "Requests shed by the fair queues (429), by tenant.",
                ("tenant",)),
            "steps": m.REGISTRY.counter(
                "solversvc_steps_total",
                "Continuous-batch steps executed."),
            "batched": m.REGISTRY.counter(
                "solversvc_batched_pods_total",
                "Pod rows coalesced into device batches, by program kind.",
                ("kind",)),
            "occupancy": m.REGISTRY.gauge(
                "solversvc_batch_occupancy",
                "Pod rows in the most recent batch step."),
            "tenants": m.REGISTRY.gauge(
                "solversvc_tenants", "Registered tenants."),
            "solve_seconds": m.REGISTRY.histogram(
                "solversvc_solve_seconds",
                "Device dispatch+readback per batch step, by program kind.",
                ("kind",)),
            "window_wait_seconds": m.REGISTRY.histogram(
                "solversvc_window_wait_seconds",
                "Submit-to-step wait (micro-batch window + queue)."),
            "isolation": m.REGISTRY.counter(
                "solversvc_isolation_violations_total",
                "Assignments refused because the node row belonged to "
                "another tenant (must stay 0)."),
            "jit_miss": m.REGISTRY.counter(
                "solversvc_jit_miss_total",
                "Fresh program compiles, by kind (bucket+flags misses).",
                ("kind",)),
        }
    return _mx


class _TenantUser:
    """Flow-control identity for a tenant (classify reads .name/.groups)."""

    __slots__ = ("name", "groups")

    def __init__(self, name: str):
        self.name = name
        self.groups = ("system:authenticated",)


@dataclass
class Tenant:
    """Per-tenant bookkeeping. All names here are NAMESPACED (prefixed)
    except the latency/bind mirrors the drill reads."""

    name: str
    store: Any = None
    nodes: set[str] = field(default_factory=set)
    node_objs: dict[str, Node] = field(default_factory=dict)
    node_fprint: dict[str, int] = field(default_factory=dict)
    # namespaced pod name -> namespaced Pod, from evaluate requests, so a
    # later extender bind can account usage (bounded: oldest dropped)
    recent_pods: dict[str, Pod] = field(default_factory=dict)
    # accounted (pod, node) pairs — replayed on node-bucket rebuild
    accounted: dict[str, tuple[Pod, str]] = field(default_factory=dict)
    assignments: dict[str, str] = field(default_factory=dict)  # original names
    bind_counts: dict[str, int] = field(default_factory=dict)  # original names
    latency: deque = field(default_factory=lambda: deque(maxlen=8192))
    requests: int = 0
    rejected: int = 0

    RECENT_MAX = 4096

    def remember(self, pod: Pod) -> None:
        self.recent_pods[pod.metadata.name] = pod
        while len(self.recent_pods) > self.RECENT_MAX:
            self.recent_pods.pop(next(iter(self.recent_pods)))


@dataclass
class EvalVerdict:
    """Per-node verdict for one pod — the extender Filter/Prioritize
    answer, in ORIGINAL (tenant-local) node names."""

    names: list[str]
    feasible: dict[str, bool]
    score: dict[str, int]


@dataclass
class SolveVerdict:
    """Native batch-solve answer, in ORIGINAL (tenant-local) names."""

    assignments: list[str | None]   # per pod, input order; None = unplaced
    bound: list[bool]
    errors: list[str]


@dataclass
class _Request:
    tenant: Tenant
    kind: str                       # "evaluate" | "solve"
    pods: list[Pod]                 # namespaced
    future: asyncio.Future
    seat: Any
    t_perf: float                   # perf_counter at submit (latency metrics)
    orig_names: list[str] | None = None    # evaluate: original candidates
    candidates: list[str] | None = None    # evaluate: namespaced candidates
    bind: bool = False              # solve: bind through the tenant store


def _variant_key(flags) -> str:
    on = [f.name for f in dataclasses.fields(flags) if getattr(flags, f.name)]
    return "+".join(on) or "baseline"


class SolverService:
    """The standing multi-tenant solve service (HTTP-free core; the wire
    front end is solversvc/server.py, the binary cmd/solversvc.py)."""

    def __init__(self, caps: Capacities | None = None,
                 policy: Policy = DEFAULT_POLICY, *,
                 clock: Clock = SYSTEM_CLOCK, window_s: float = 0.005,
                 flow: FlowController | None = None, total_seats: int = 32,
                 queue_wait_s: float = 2.0, min_bucket: int = 4):
        self.caps = caps or Capacities(num_nodes=256, batch_pods=64)
        self.policy = policy.with_env_overrides()
        self.clock = clock
        self.window_s = window_s
        self.min_bucket = max(1, min_bucket)
        self.tenants: dict[str, Tenant] = {}
        self.flow = flow or FlowController(total_seats,
                                           queue_wait_s=queue_wait_s)
        # a dedicated priority level: tenant solve traffic gets its own
        # seat budget and shuffle-sharded queues (one flow per tenant)
        self.flow.configure(
            levels={"solversvc": {"shares": 40, "queues": 16,
                                  "queueLengthLimit": 64, "handSize": 4}},
            schemas=[{"name": "solversvc", "priorityLevel": "solversvc",
                      "matchingPrecedence": 500,
                      "rules": [{"verbs": ["solve"],
                                 "resources": ["solves"]}]}])
        self._build_state(self.caps)
        self._pending: deque[_Request] = deque()
        self._arrival: asyncio.Event | None = None
        self._runner: asyncio.Task | None = None
        self._poll_s = max(window_s / 8, 0.0005)
        # dedicated single worker for device dispatch+readback: the
        # default executor is shared process-wide and can be saturated by
        # unrelated blocking work, which would wedge the serving loop
        # behind its own clients (observed on 1-vCPU CI)
        self._exec = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="solversvc-step")

    # ---- device state (rebuilt on node-bucket growth) ----

    def _build_state(self, caps: Capacities) -> None:
        self.caps = caps
        self.statedb = StateDB(caps)
        self.encode_cache = EncodeCache(caps, self.statedb.table)
        self._prows = build_policy_rows(self.policy, self.statedb.table,
                                        caps)
        self._eval_fns: dict[int, Any] = {}
        self._solve_fns: dict[tuple, Any] = {}
        _map, f_width, i_width = _layout(caps)
        self._fblob = np.zeros((caps.batch_pods, f_width), np.float32)
        self._iblob = np.zeros((caps.batch_pods, i_width), np.int32)

    def _ensure_node_capacity(self, extra: int) -> None:
        need = len(self.statedb.table.row_of) + extra
        if need <= self.caps.num_nodes:
            return
        new_n = 1 << (need - 1).bit_length()
        log.info("solversvc: growing node bucket %d -> %d rows",
                 self.caps.num_nodes, new_n)
        self._build_state(dataclasses.replace(self.caps, num_nodes=new_n))
        for t in self.tenants.values():
            for node in t.node_objs.values():
                self.statedb.upsert_node(node)
            for pod, node_name in t.accounted.values():
                self.statedb.add_pod(pod, node_name)

    # ---- tenants & state sync ----

    def _tenant(self, name: str) -> Tenant:
        t = self.tenants.get(name)
        if t is None:
            raise KeyError(f"unknown tenant {name!r}")
        return t

    def register_tenant(self, name: str, store: Any = None) -> Tenant:
        check_tenant_name(name)
        t = self.tenants.get(name)
        if t is None:
            t = self.tenants[name] = Tenant(name=name, store=store)
            _svc_metrics()["tenants"].set(len(self.tenants))
        elif store is not None:
            t.store = store
        return t

    def drop_tenant(self, name: str) -> None:
        t = self.tenants.pop(name, None)
        if t is None:
            return
        for node in list(t.nodes):
            self.statedb.remove_node(node)  # drops its accounted pods too
        _svc_metrics()["tenants"].set(len(self.tenants))

    def upsert_node(self, tenant: str, node: dict | Node) -> None:
        t = self._tenant(tenant)
        nsd = namespace_node(t.name, node)
        name = nsd.metadata.name
        fprint = hash(repr(sorted((nsd.to_dict() or {}).items())))
        if t.node_fprint.get(name) == fprint and name in t.nodes:
            return  # unchanged full-object resend (stock extender mode)
        self._ensure_node_capacity(0 if name in t.nodes else 1)
        self.statedb.upsert_node(nsd)
        t.nodes.add(name)
        t.node_objs[name] = nsd
        t.node_fprint[name] = fprint

    def remove_node(self, tenant: str, node_name: str) -> None:
        t = self._tenant(tenant)
        name = tenant_prefix(t.name, node_name)
        self.statedb.remove_node(name)
        t.nodes.discard(name)
        t.node_objs.pop(name, None)
        t.node_fprint.pop(name, None)
        for key in [k for k, (_, nn) in t.accounted.items() if nn == name]:
            del t.accounted[key]

    def account_pod(self, tenant: str, pod: dict | Pod,
                    node_name: str | None = None) -> bool:
        """Account a bound tenant pod against its node (usage sync)."""
        t = self._tenant(tenant)
        nsp = namespace_pod(t.name, pod)
        nn = tenant_prefix(t.name, node_name) if node_name \
            else nsp.spec.node_name
        if not nn:
            return False
        ok = self.statedb.add_pod(nsp, nn)
        if ok:
            t.accounted[nsp.key] = (nsp, nn)
        return ok

    def forget_pod(self, tenant: str, namespace: str, pod_name: str) -> None:
        t = self._tenant(tenant)
        key = (f"{tenant_prefix(t.name, namespace or 'default')}/"
               f"{tenant_prefix(t.name, pod_name)}")
        self.statedb.remove_pod(key)
        t.accounted.pop(key, None)

    # ---- request surfaces ----

    async def evaluate(self, tenant: str, pod: dict | Pod, *,
                       nodes: list | None = None,
                       node_names: list[str] | None = None) -> EvalVerdict:
        """Filter/Prioritize verdict for one pod. `nodes` (full objects,
        stock non-cache-capable mode) are synced into the tenant's state
        first; `node_names` resolve against already-synced state."""
        t = self._tenant(tenant)
        if nodes is not None:
            names = []
            for nd in nodes:
                self.upsert_node(t.name, nd)
                names.append(nd.metadata.name if isinstance(nd, Node)
                             else (nd.get("metadata") or {}).get("name", ""))
        else:
            names = list(node_names or [])
        nsp = namespace_pod(t.name, pod)
        t.remember(nsp)
        req = await self._submit(
            t, "evaluate", [nsp],
            orig_names=names,
            candidates=[tenant_prefix(t.name, n) for n in names])
        return req

    async def solve(self, tenant: str, pods: list, *,
                    bind: bool = False) -> SolveVerdict:
        """Native batch solve: gang/preemption-capable superset of the
        extender verbs. With bind=True, successful assignments bind
        through the tenant's store and are accounted."""
        t = self._tenant(tenant)
        if len(pods) > self.caps.batch_pods:
            raise ValueError(
                f"solve request of {len(pods)} pods exceeds the service "
                f"batch capacity {self.caps.batch_pods}")
        if not pods:
            return SolveVerdict([], [], [])
        nspods = [namespace_pod(t.name, p) for p in pods]
        for p in nspods:
            t.remember(p)
        return await self._submit(t, "solve", nspods, bind=bind)

    def bind(self, tenant: str, pod_name: str, namespace: str,
             node: str) -> str:
        """Extender bind verb. Returns "" or an error string. A bind
        routed to the wrong tenant — a node the tenant never registered —
        is REJECTED before touching any store (isolation invariant)."""
        t = self._tenant(tenant)
        _svc_metrics()["requests"].labels(t.name, "bind").inc()
        ns_node = tenant_prefix(t.name, node)
        if ns_node not in t.nodes:
            return (f"bind rejected: node {node!r} is not registered to "
                    f"tenant {t.name!r}")
        if t.store is not None:
            from kubernetes_tpu.api.objects import Binding
            from kubernetes_tpu.apiserver.store import Conflict, NotFound
            try:
                t.store.bind(Binding(pod_name=pod_name,
                                     namespace=namespace or "default",
                                     target_node=node))
            except (Conflict, NotFound) as e:
                return str(e)
        t.bind_counts[pod_name] = t.bind_counts.get(pod_name, 0) + 1
        t.assignments[pod_name] = node
        nsp = t.recent_pods.get(tenant_prefix(t.name, pod_name))
        if nsp is not None:
            if self.statedb.add_pod(nsp, ns_node):
                t.accounted[nsp.key] = (nsp, ns_node)
        return ""

    async def _submit(self, t: Tenant, kind: str, pods: list[Pod],
                      **extra) -> Any:
        mx = _svc_metrics()
        t.requests += 1
        mx["requests"].labels(t.name, kind).inc()
        try:
            seat = await self.flow.acquire(_TenantUser(t.name), "solve",
                                           "solves",
                                           width=solve_seats(len(pods)))
        except FlowRejected:
            t.rejected += 1
            mx["rejected"].labels(t.name).inc()
            raise
        if self._runner is None:
            self.flow.release(seat)
            raise RuntimeError("solversvc not started (call start())")
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        req = _Request(tenant=t, kind=kind, pods=pods, future=fut,
                       seat=seat, t_perf=time.perf_counter(), **extra)
        self._pending.append(req)
        self._arrival.set()
        return await fut

    # ---- the continuous batcher ----

    async def start(self) -> None:
        if self._runner is not None:
            return
        self._arrival = asyncio.Event()
        self._runner = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        if self._runner is not None:
            self._runner.cancel()
            try:
                await self._runner
            except asyncio.CancelledError:
                pass
            self._runner = None
        while self._pending:
            req = self._pending.popleft()
            self._finish(req, exc=RuntimeError("solversvc stopped"))

    async def _run(self) -> None:
        while True:
            await self._arrival.wait()
            if not self._pending:
                self._arrival.clear()
                continue
            # the micro-batch window: wait out `window_s` on the INJECTED
            # clock (ManualClock in tests — no wall-clock in the decision)
            # unless the pod budget fills first
            deadline = self.clock.now() + self.window_s
            while (self.clock.now() < deadline
                   and sum(len(r.pods) for r in self._pending)
                   < self.caps.batch_pods):
                await asyncio.sleep(self._poll_s)
            batch: list[_Request] = []
            taken = 0
            while self._pending:
                req = self._pending[0]
                if batch and taken + len(req.pods) > self.caps.batch_pods:
                    break
                self._pending.popleft()
                batch.append(req)
                taken += len(req.pods)
            if not self._pending:
                self._arrival.clear()
            try:
                await self._step(batch)
            except Exception as e:  # noqa: BLE001 — the batcher must
                # survive any one batch's failure; its requests error out
                log.exception("solversvc step failed")
                for r in batch:
                    self._finish(r, exc=e)

    def _finish(self, r: _Request, result: Any = None,
                exc: Exception | None = None) -> None:
        if r.seat is not None:
            elapsed = time.perf_counter() - r.t_perf
            self.flow.note_latency(r.seat, elapsed)
            self.flow.release(r.seat)
            r.tenant.latency.append(elapsed)
            r.seat = None
        if not r.future.done():
            if exc is not None:
                r.future.set_exception(exc)
            else:
                r.future.set_result(result)

    async def _step(self, batch: list[_Request]) -> None:
        mx = _svc_metrics()
        mx["steps"].inc()
        mx["occupancy"].set(sum(len(r.pods) for r in batch))
        now = time.perf_counter()
        for r in batch:
            mx["window_wait_seconds"].observe(max(0.0, now - r.t_perf))
        evals = [r for r in batch if r.kind == "evaluate"]
        solves = [r for r in batch if r.kind == "solve"]
        with TRACER.start_span("solversvc.step", attrs={
                "requests": len(batch),
                "tenants": len({r.tenant.name for r in batch}),
                "evaluate_pods": sum(len(r.pods) for r in evals),
                "solve_pods": sum(len(r.pods) for r in solves)}):
            if evals:
                try:
                    await self._step_evaluate(evals)
                except Exception as e:  # noqa: BLE001 — fail only this group
                    log.exception("solversvc evaluate step failed")
                    for r in evals:
                        self._finish(r, exc=e)
            if solves:
                try:
                    await self._step_solve(solves)
                except Exception as e:  # noqa: BLE001 — fail only this group
                    log.exception("solversvc solve step failed")
                    for r in solves:
                        self._finish(r, exc=e)

    # ---- shape buckets & programs ----

    def _bucket(self, n: int) -> int:
        b = max(self.min_bucket, 1 << max(0, int(n) - 1).bit_length())
        return min(b, self.caps.batch_pods)

    def _eval_fn(self, bucket: int):
        fn = self._eval_fns.get(bucket)
        if fn is None:
            import jax

            from kubernetes_tpu.obs.profiling import COMPILES
            from kubernetes_tpu.ops.solver import evaluate_pod

            caps, policy, prows = self.caps, self.policy, self._prows

            def program(state, fb, ib):
                rows = unpack_batch(fb, ib, caps)
                return jax.vmap(
                    lambda row: evaluate_pod(state, row, policy, caps=caps,
                                             prows=prows))(rows)

            fn = COMPILES.instrument(
                f"solversvc[evaluate,p{bucket}]", jax.jit(program))
            self._eval_fns[bucket] = fn
            _svc_metrics()["jit_miss"].labels("evaluate").inc()
        return fn

    def _solve_fn(self, bucket: int, flags):
        key = (bucket, flags)
        fn = self._solve_fns.get(key)
        if fn is None:
            import jax

            from kubernetes_tpu.obs.profiling import COMPILES
            from kubernetes_tpu.ops.solver import schedule_batch

            caps, policy, prows = self.caps, self.policy, self._prows
            fn = COMPILES.instrument(
                f"solversvc[solve,p{bucket}]+{_variant_key(flags)}",
                jax.jit(lambda s, fb, ib, rr: schedule_batch(
                    s, unpack_batch(fb, ib, caps), rr, policy, caps=caps,
                    prows=prows, flags=flags)))
            self._solve_fns[key] = fn
            _svc_metrics()["jit_miss"].labels("solve").inc()
        return fn

    def warmup(self, buckets: tuple[int, ...] = ()) -> None:
        """Pre-compile the evaluate+solve programs for the given pod
        buckets (default: the smallest) so first tenant traffic never
        waits out a compile — the extender-client 5s timeout story."""
        try:
            pod = Pod.from_dict({"metadata": {"name": "warmup",
                                              "namespace": "default"}})
            for want in tuple(buckets) or (self.min_bucket,):
                b = self._bucket(want)
                fblob, iblob = self._fblob[:b], self._iblob[:b]
                fblob[:] = 0.0
                iblob[:] = 0
                self.encode_cache.encode_packed_into(fblob, iblob, 0, pod)
                flags = packed_batch_flags(fblob, iblob, 1,
                                           self.statedb.table, self.caps)
                state = self.statedb.flush()
                np.asarray(self._eval_fn(b)(state, fblob, iblob)[0])
                np.asarray(self._solve_fn(b, flags)(
                    state, fblob, iblob, np.uint32(0)).assignments)
        except Exception:  # pragma: no cover — never block serving
            log.exception("solversvc warmup failed")

    # ---- device steps ----

    def _encode(self, reqs: list[_Request]) -> tuple:
        """(bucket, fblob view, iblob view, n, per-request offsets)."""
        n = sum(len(r.pods) for r in reqs)
        bucket = self._bucket(n)
        fblob, iblob = self._fblob[:bucket], self._iblob[:bucket]
        fblob[:] = 0.0
        iblob[:] = 0
        offsets, i = [], 0
        for r in reqs:
            offsets.append(i)
            for pod in r.pods:
                self.encode_cache.encode_packed_into(fblob, iblob, i, pod)
                i += 1
        return bucket, fblob, iblob, n, offsets

    async def _step_evaluate(self, reqs: list[_Request]) -> None:
        mx = _svc_metrics()
        bucket, fblob, iblob, n, offsets = self._encode(reqs)
        mx["batched"].labels("evaluate").inc(n)
        fn = self._eval_fn(bucket)
        state = self.statedb.flush()

        def run() -> tuple[np.ndarray, np.ndarray]:
            # dispatch AND read back off the event loop: the readback
            # blocks until the device finishes, and that wait must not
            # stall the serving loop (LoopStallWatchdog contract)
            out = fn(state, fblob, iblob)
            return np.asarray(out[0]), np.asarray(out[1])

        t0 = time.perf_counter()
        feasible, score = await asyncio.get_running_loop().run_in_executor(self._exec, run)
        mx["solve_seconds"].labels("evaluate").observe(
            time.perf_counter() - t0)
        row_of = self.statedb.table.row_of
        for r, off in zip(reqs, offsets):
            frow, srow = feasible[off], score[off]
            fmap: dict[str, bool] = {}
            smap: dict[str, int] = {}
            for orig, cand in zip(r.orig_names, r.candidates):
                row = row_of.get(cand)
                if row is None:
                    fmap[orig], smap[orig] = False, 0
                else:
                    fmap[orig] = bool(frow[row])
                    smap[orig] = int(srow[row])
            self._finish(r, EvalVerdict(names=list(r.orig_names),
                                        feasible=fmap, score=smap))

    async def _step_solve(self, reqs: list[_Request]) -> None:
        mx = _svc_metrics()
        bucket, fblob, iblob, n, offsets = self._encode(reqs)
        mx["batched"].labels("solve").inc(n)
        # gang columns per REQUEST (a gang can never span tenants or
        # requests): contiguous runs of one group key, quorum from the
        # annotation — the same all-or-nothing shape the driver admits
        gid_col = blob_col(fblob, iblob, "gang_id", self.caps)
        gmin_col = blob_col(fblob, iblob, "gang_min", self.caps)
        gid = 0
        for r, off in zip(reqs, offsets):
            i, pods = 0, r.pods
            while i < len(pods):
                gkey = pod_group_key(pods[i])
                if gkey is None:
                    i += 1
                    continue
                j = i
                while j < len(pods) and pod_group_key(pods[j]) == gkey:
                    j += 1
                gid += 1
                quorum = annotation_min(pods[i]) or (j - i)
                for row in range(i, j):
                    gid_col[off + row] = gid
                    gmin_col[off + row] = quorum
                i = j
        flags = packed_batch_flags(fblob, iblob, n, self.statedb.table,
                                   self.caps)
        fn = self._solve_fn(bucket, flags)
        state = self.statedb.flush()

        def run() -> np.ndarray:
            # dispatch + readback off the event loop (see _step_evaluate)
            result = fn(state, fblob, iblob, np.uint32(0))
            return np.asarray(result.assignments)[:n]

        t0 = time.perf_counter()
        assignments = await asyncio.get_running_loop().run_in_executor(self._exec, run)
        mx["solve_seconds"].labels("solve").observe(time.perf_counter() - t0)
        row_name = {row: name
                    for name, row in self.statedb.table.row_of.items()}
        for r, off in zip(reqs, offsets):
            self._resolve_solve(r, assignments, off, row_name)

    def _resolve_solve(self, r: _Request, assignments: np.ndarray,
                       off: int, row_name: dict[int, str]) -> None:
        t = r.tenant
        out: list[str | None] = []
        errors: list[str] = []
        bound: list[bool] = []
        for k, pod in enumerate(r.pods):
            row = int(assignments[off + k])
            node = row_name.get(row) if row >= 0 else None
            if node is None:
                out.append(None)
                errors.append("" if row < 0 else f"unknown node row {row}")
                bound.append(False)
                continue
            owner, orig_node = split_tenant(node)
            if owner != t.name:
                # impossible by construction (tenancy.py); refuse + count
                _svc_metrics()["isolation"].inc()
                out.append(None)
                errors.append(f"isolation violation: row {row} belongs to "
                              f"{owner!r}")
                bound.append(False)
                continue
            _, orig_pod = split_tenant(pod.metadata.name)
            _, orig_ns = split_tenant(pod.metadata.namespace)
            out.append(orig_node)
            err, did_bind = "", False
            if r.bind:
                err = self.bind(t.name, orig_pod, orig_ns, orig_node)
                did_bind = not err
            else:
                t.assignments[orig_pod] = orig_node
            errors.append(err)
            bound.append(did_bind)
        self._finish(r, SolveVerdict(assignments=out, bound=bound,
                                     errors=errors))
