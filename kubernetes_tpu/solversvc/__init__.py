"""Solver-as-a-service: one device program serving many control planes.

The extender surface promoted from a per-cluster callout to a standing
multi-tenant service (ROADMAP open item 2): N tenant control planes —
stock Go kube-schedulers speaking the extender wire protocol, or native
clients speaking the batch-solve endpoint — submit solve requests that a
continuous batcher coalesces into ONE padded device batch per step,
inference-serving style. Tenancy is enforced at ingestion
(`tenancy.py`: tenant-prefixed names/label keys/universe ids + an
injected tenant marker selector), fairness by APF seats
(`apiserver/flowcontrol.py` reused with a dedicated solversvc priority
level), and shapes by pow-2 pod buckets over persistent jit caches so a
shifting tenant mix never recompiles.
"""

from kubernetes_tpu.solversvc.core import (
    EvalVerdict,
    SolverService,
    SolveVerdict,
    Tenant,
)
from kubernetes_tpu.solversvc.tenancy import (
    TENANT_MARKER_LABEL,
    namespace_node,
    namespace_pod,
    split_tenant,
    tenant_prefix,
)

__all__ = [
    "EvalVerdict",
    "SolverService",
    "SolveVerdict",
    "Tenant",
    "TENANT_MARKER_LABEL",
    "namespace_node",
    "namespace_pod",
    "split_tenant",
    "tenant_prefix",
]
