"""Tenant namespacing: isolation by construction, not by filtering.

All tenants share ONE StateDB so the batcher can put every tenant's
requests in the same padded device batch — so the isolation invariant
("tenant A's nodes can never satisfy tenant B's pods") must hold at the
*encoding* layer, where nodes and pods meet. Every identifier a
predicate can match on is rewritten at ingestion to live in a
per-tenant namespace:

- object names and pod namespaces get a ``<tenant>/`` prefix ("/" is
  illegal in DNS-1123 names, the same trick as the autoscaler's
  ``~sim~`` rows — a prefixed name can never collide with any real
  object, and tenant names reject "/" so the split is unambiguous);
- label KEYS on nodes and pods, selector/affinity-expression keys,
  taint and toleration keys are prefixed, so the interned universe ids
  (selector_universe, req_universe, ...) are disjoint per tenant — two
  tenants both saying ``disk=ssd`` intern different entries;
- the WELL-KNOWN topology keys (hostname/zone/region) keep their key —
  the zone slot and default spreading semantics must survive — and get
  the prefix on the VALUE instead, so spread domains stay per-tenant;
- pod namespaces (interpod-affinity scoping) and PVC claim names are
  prefixed.

Defense in depth: every node additionally carries the marker label
``solversvc.ktpu.io/tenant: <tenant>`` and every pod an injected
nodeSelector requiring it, so even if some future predicate matched on
an un-namespaced identifier, MatchNodeSelector — the oldest predicate
in the set — still pins assignments inside the tenant.
"""

from __future__ import annotations

import re
from typing import Any

from kubernetes_tpu.api.objects import Node, Pod

TENANT_MARKER_LABEL = "solversvc.ktpu.io/tenant"

# topology keys whose KEY must survive namespacing (zone-slot encoding and
# default spreading key off them); their VALUES are prefixed instead
TOPOLOGY_VALUE_KEYS = frozenset({
    "kubernetes.io/hostname",
    "failure-domain.beta.kubernetes.io/zone",
    "failure-domain.beta.kubernetes.io/region",
})

_TENANT_RE = re.compile(r"^[a-z0-9]([a-z0-9.-]{0,61}[a-z0-9])?$")


def check_tenant_name(tenant: str) -> str:
    """Tenant names are DNS-1123-shaped and never contain "/" — the
    prefix separator — so `split_tenant` is unambiguous."""
    if not _TENANT_RE.match(tenant):
        raise ValueError(f"invalid tenant name {tenant!r} "
                         "(want DNS-1123 label/subdomain, no '/')")
    return tenant


def tenant_prefix(tenant: str, name: str) -> str:
    return f"{tenant}/{name}"


def split_tenant(name: str) -> tuple[str | None, str]:
    """Inverse of `tenant_prefix`: (tenant, original) or (None, name)."""
    tenant, sep, rest = name.partition("/")
    return (tenant, rest) if sep else (None, name)


def _ns_labels(tenant: str, labels: dict[str, str]) -> dict[str, str]:
    out: dict[str, str] = {}
    for k, v in (labels or {}).items():
        if k in TOPOLOGY_VALUE_KEYS:
            out[k] = tenant_prefix(tenant, v)
        else:
            out[tenant_prefix(tenant, k)] = v
    return out


def _ns_match_expressions(tenant: str, exprs: list[dict]) -> list[dict]:
    out = []
    for e in exprs or []:
        e = dict(e)
        key = e.get("key", "")
        if key in TOPOLOGY_VALUE_KEYS:
            e["values"] = [tenant_prefix(tenant, v)
                           for v in e.get("values") or []]
        else:
            e["key"] = tenant_prefix(tenant, key)
        out.append(e)
    return out


def _ns_label_selector(tenant: str, sel: dict) -> dict:
    sel = dict(sel or {})
    if sel.get("matchLabels"):
        sel["matchLabels"] = {tenant_prefix(tenant, k): v
                              for k, v in sel["matchLabels"].items()}
    if sel.get("matchExpressions"):
        sel["matchExpressions"] = _ns_match_expressions(
            tenant, sel["matchExpressions"])
    return sel


def _ns_affinity(tenant: str, affinity: dict) -> dict:
    """Rewrite a raw v1 Affinity dict in place-safe copy form."""
    import copy

    aff = copy.deepcopy(affinity or {})
    na = aff.get("nodeAffinity") or {}
    req = na.get("requiredDuringSchedulingIgnoredDuringExecution")
    if req:
        for term in req.get("nodeSelectorTerms") or []:
            term["matchExpressions"] = _ns_match_expressions(
                tenant, term.get("matchExpressions"))
    for pref in na.get("preferredDuringSchedulingIgnoredDuringExecution") or []:
        pterm = pref.get("preference") or {}
        pterm["matchExpressions"] = _ns_match_expressions(
            tenant, pterm.get("matchExpressions"))
    for kind in ("podAffinity", "podAntiAffinity"):
        pa = aff.get(kind) or {}
        for term in pa.get("requiredDuringSchedulingIgnoredDuringExecution") or []:
            _ns_pod_affinity_term(tenant, term)
        for wt in pa.get("preferredDuringSchedulingIgnoredDuringExecution") or []:
            _ns_pod_affinity_term(tenant, wt.get("podAffinityTerm") or {})
    return aff


def _ns_pod_affinity_term(tenant: str, term: dict) -> None:
    if term.get("labelSelector") is not None:
        term["labelSelector"] = _ns_label_selector(tenant,
                                                   term["labelSelector"])
    tk = term.get("topologyKey", "")
    if tk and tk not in TOPOLOGY_VALUE_KEYS:
        term["topologyKey"] = tenant_prefix(tenant, tk)
    if term.get("namespaces"):
        term["namespaces"] = [tenant_prefix(tenant, n)
                              for n in term["namespaces"]]


def namespace_node(tenant: str, node: dict | Node) -> Node:
    """Rewrite one tenant node into the shared-StateDB namespace."""
    check_tenant_name(tenant)
    d = node.to_dict() if isinstance(node, Node) else dict(node)
    import copy

    d = copy.deepcopy(d)
    meta = d.setdefault("metadata", {})
    name = meta.get("name", "")
    meta["name"] = tenant_prefix(tenant, name)
    labels = _ns_labels(tenant, meta.get("labels") or {})
    # hostname label tracks the (namespaced) node name; inject if absent
    labels.setdefault("kubernetes.io/hostname", meta["name"])
    labels[TENANT_MARKER_LABEL] = tenant
    meta["labels"] = labels
    spec = d.setdefault("spec", {})
    if spec.get("taints"):
        spec["taints"] = [
            {**t, "key": tenant_prefix(tenant, t.get("key", ""))}
            for t in spec["taints"]]
    return Node.from_dict(d)


def namespace_pod(tenant: str, pod: dict | Pod) -> Pod:
    """Rewrite one tenant pod into the shared-StateDB namespace, including
    the injected tenant-marker nodeSelector (assignment isolation via
    MatchNodeSelector even if everything else failed)."""
    check_tenant_name(tenant)
    d = pod.to_dict() if isinstance(pod, Pod) else dict(pod)
    import copy

    d = copy.deepcopy(d)
    meta = d.setdefault("metadata", {})
    meta["name"] = tenant_prefix(tenant, meta.get("name", ""))
    meta["namespace"] = tenant_prefix(tenant,
                                      meta.get("namespace") or "default")
    if meta.get("labels"):
        meta["labels"] = {tenant_prefix(tenant, k): v
                          for k, v in meta["labels"].items()}
    spec = d.setdefault("spec", {})
    selector = {}
    for k, v in (spec.get("nodeSelector") or {}).items():
        if k in TOPOLOGY_VALUE_KEYS:
            selector[k] = tenant_prefix(tenant, v)
        else:
            selector[tenant_prefix(tenant, k)] = v
    selector[TENANT_MARKER_LABEL] = tenant
    spec["nodeSelector"] = selector
    if spec.get("nodeName"):
        spec["nodeName"] = tenant_prefix(tenant, spec["nodeName"])
    if spec.get("tolerations"):
        spec["tolerations"] = [
            {**t, "key": tenant_prefix(tenant, t["key"])} if t.get("key")
            else dict(t)
            for t in spec["tolerations"]]
    if spec.get("affinity"):
        spec["affinity"] = _ns_affinity(tenant, spec["affinity"])
    for vol in spec.get("volumes") or []:
        pvc = vol.get("persistentVolumeClaim")
        if pvc and pvc.get("claimName"):
            pvc["claimName"] = tenant_prefix(tenant, pvc["claimName"])
    return Pod.from_dict(d)
