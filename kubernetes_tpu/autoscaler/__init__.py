"""Cluster autoscaler: node-group SPI consumers + device-batched what-if
scale simulation. See core.ClusterAutoscaler (the loop) and
simulator.ScaleSimulator (the probe-solve engine)."""

from kubernetes_tpu.autoscaler.core import (
    DELETION_TAINT,
    ClusterAutoscaler,
)
from kubernetes_tpu.autoscaler.simulator import (
    SIM_NODE_PREFIX,
    ScaleSimulator,
    ScaleUpProbe,
)

__all__ = [
    "DELETION_TAINT",
    "SIM_NODE_PREFIX",
    "ClusterAutoscaler",
    "ScaleSimulator",
    "ScaleUpProbe",
]
