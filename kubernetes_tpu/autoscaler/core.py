"""ClusterAutoscaler: the node-group control loop.

The cluster-autoscaler analog (RunOnce in the reference autoscaler's
static_autoscaler.go), rebuilt around the batched solver: instead of a
serial bin-packing estimator per node group, every expansion candidate is
scored with one device what-if solve (ScaleSimulator.probe_scale_up) and
every drain candidate is verified the same way (probe_scale_down). One
leader-elected loop per cluster, living in the controller-manager next to
the other loops or standing alone via cmd/autoscaler.py.

Scale-up: pending (unschedulable) pods are batched; each group with
headroom is offered k hypothetical template nodes and scored by
pods-placed-per-node-added; the winner is expanded through the cloud SPI
(CloudProvider.increase_size) with per-group cooldowns and max-size caps.

Scale-down is two-phase across ticks so the what-if can genuinely go
stale and be rolled back: tick t finds a node underutilized past the
unneeded dwell, verifies drainability (PDBs via eviction_allowed, no gang
members, no pods above the priority cutoff, probe_scale_down fits), then
cordons + taints it; tick t+1 RE-verifies against fresh informer state —
stale answers uncordon and roll back, fresh ones drain through can_evict
(the spending PDB gate) and delete through the SPI.
"""

from __future__ import annotations

import logging
import time

from kubernetes_tpu.api.objects import NodeGroup, Taint
from kubernetes_tpu.api.quantity import parse_quantity
from kubernetes_tpu.apiserver.store import (
    AlreadyExists,
    Conflict,
    NotFound,
    ObjectStore,
)
from kubernetes_tpu.autoscaler.simulator import ScaleSimulator
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.cloudprovider.interface import CloudProvider
from kubernetes_tpu.utils.clock import SYSTEM_CLOCK, Clock
from kubernetes_tpu.controllers.disruption import can_evict, eviction_allowed
from kubernetes_tpu.gang import pod_group_key
from kubernetes_tpu.models.policy import DEFAULT_POLICY
from kubernetes_tpu.state.layout import Capacities

log = logging.getLogger(__name__)

# cordon marker: the reference autoscaler's deletetaint.ToBeDeletedTaint
DELETION_TAINT = "ToBeDeletedByClusterAutoscaler"

SCAN_INTERVAL = 2.0            # --scan-interval (reference: 10s)
SCALEUP_COOLDOWN = 30.0        # per-group, after an increase_size
SCALEDOWN_COOLDOWN = 60.0      # --scale-down-delay-after-add spirit
UNNEEDED_TIME = 30.0           # --scale-down-unneeded-time (ref: 10m)
UTILIZATION_THRESHOLD = 0.5    # --scale-down-utilization-threshold
MAX_EXPANSION = 8              # hypothetical rows offered per probe

_mx_cache: tuple | None = None


def _metrics() -> tuple:
    """(scaleup_total, scaledown_total, rollback_total, sim_seconds,
    backlog_gauge) — the autoscaler_* families (obs satellite)."""
    global _mx_cache
    if _mx_cache is None:
        from kubernetes_tpu.obs import metrics as m

        _mx_cache = (
            m.REGISTRY.counter("autoscaler_scaleup_total",
                               "Nodes added by scale-up, by group.",
                               ("group",)),
            m.REGISTRY.counter("autoscaler_scaledown_total",
                               "Nodes removed by scale-down, by group.",
                               ("group",)),
            m.REGISTRY.counter("autoscaler_scaledown_rollback_total",
                               "Drains aborted because the what-if went "
                               "stale between cordon and drain."),
            m.REGISTRY.histogram("autoscaler_simulation_seconds",
                                 "Wall time of one what-if probe solve."),
            m.REGISTRY.gauge("autoscaler_unschedulable_pods",
                             "Pending pods the autoscaler currently sees."),
        )
    return _mx_cache


def _pod_pending(pod) -> bool:
    return not pod.spec.node_name \
        and pod.status.phase in ("", "Pending") \
        and not pod.metadata.deletion_timestamp


def _node_ready(node) -> bool:
    ready = next((c for c in node.status.conditions if c.type == "Ready"),
                 None)
    return ready is not None and ready.status == "True"


class ClusterAutoscaler:
    """One periodic pass (`run_once`) over pending pods and node groups;
    not a keyed reconcile loop — the whole cluster is one reconciliation
    unit, exactly the reference RunOnce's shape."""

    name = "cluster-autoscaler"

    def __init__(self, store: ObjectStore, cloud: CloudProvider, *,
                 caps: Capacities | None = None,
                 policy=DEFAULT_POLICY,
                 node_informer: Informer | None = None,
                 pod_informer: Informer | None = None,
                 scan_interval: float = SCAN_INTERVAL,
                 scaleup_cooldown: float = SCALEUP_COOLDOWN,
                 scaledown_cooldown: float = SCALEDOWN_COOLDOWN,
                 unneeded_time: float = UNNEEDED_TIME,
                 utilization_threshold: float = UTILIZATION_THRESHOLD,
                 scaledown_priority_cutoff: int = 0,
                 max_expansion: int = MAX_EXPANSION,
                 register_nodes: bool = True,
                 now=time.monotonic,
                 clock: Clock = SYSTEM_CLOCK):
        self.store = store
        self.cloud = cloud
        self.scan_interval = scan_interval
        self.scaleup_cooldown = scaleup_cooldown
        self.scaledown_cooldown = scaledown_cooldown
        self.unneeded_time = unneeded_time
        self.utilization_threshold = utilization_threshold
        # pods above this spec.priority pin their node (the reference's
        # --expendable-pods-priority-cutoff, inverted to "not expendable")
        self.scaledown_priority_cutoff = scaledown_priority_cutoff
        self.max_expansion = max_expansion
        # materialize created instances as Node objects (the fake-kubelet
        # role: no agent process exists to register them in tests/bench)
        self.register_nodes = register_nodes
        self.now = now
        # wall-clock stamps (status/reporting) ride the injectable clock;
        # cooldown arithmetic stays on the monotonic `now` above
        self.clock = clock
        self._own_informers = node_informer is None or pod_informer is None
        self.nodes = node_informer or Informer(store, "Node")
        self.pods = pod_informer or Informer(store, "Pod")
        self.simulator = ScaleSimulator(caps=caps, policy=policy)
        self.nodes.add_handler(self._on_node_event)
        self.pods.add_handler(self._on_pod_event)
        # group -> monotonic deadline before which it may not scale again
        self._scaleup_after: dict[str, float] = {}
        self._scaledown_after: dict[str, float] = {}
        # node -> monotonic time it was first seen underutilized
        self._unneeded_since: dict[str, float] = {}
        # node -> group: cordoned last tick, re-verify + drain this tick
        self._draining: dict[str, str] = {}
        # wall-clock scale timestamps for NodeGroup status
        self._last_scaleup: dict[str, float] = {}
        self._last_scaledown: dict[str, float] = {}
        self._task = None
        # counters mirrored as attributes for tests/bench
        self.scaleups = 0
        self.scaledowns = 0
        self.rollbacks = 0

    # ---- informer mirror (same shape as the scheduler driver's) ----

    def _on_node_event(self, event) -> None:
        node = event.obj
        if event.type == "DELETED":
            if self.simulator.has_node(node.metadata.name):
                self.simulator.remove_node(node.metadata.name)
            return
        self.simulator.upsert_node(node)

    def _on_pod_event(self, event) -> None:
        pod = event.obj
        if event.type == "DELETED":
            self.simulator.remove_pod(pod.key)
            return
        if pod.spec.node_name:
            self.simulator.add_pod(pod)

    def _sweep_accounting(self) -> None:
        """Re-account bound pods whose events raced their node's, or whose
        accounting a node delete+recreate dropped (the driver does this via
        a node->pods index; the autoscaler's pass is already O(pods))."""
        for pod in self.pods.items():
            if pod.spec.node_name \
                    and not self.simulator.is_accounted(pod.key) \
                    and self.simulator.has_node(pod.spec.node_name):
                self.simulator.add_pod(pod)

    # ---- lifecycle ----

    async def start(self) -> None:
        import asyncio

        if self._own_informers:
            self.nodes.start()
            self.pods.start()
            await self.nodes.wait_for_sync()
            await self.pods.wait_for_sync()
        self._task = asyncio.get_running_loop().create_task(self._loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        if self._own_informers:
            self.nodes.stop()
            self.pods.stop()

    async def _loop(self) -> None:
        import asyncio

        while True:
            await asyncio.sleep(self.scan_interval)
            try:
                self.run_once()
            except Exception:  # noqa: BLE001 — the loop must not die
                log.exception("autoscaler pass failed")

    # ---- one pass ----

    def run_once(self) -> None:
        now = self.now()
        self._sweep_accounting()
        self._reconcile_nodegroups()
        pending = self._pending_pods()
        _metrics()[4].set(len(pending))
        if pending:
            self._scale_up(pending, now)
        else:
            self._scale_down(now)

    def _pending_pods(self) -> list:
        """Unschedulable pods, gang members contiguous (the simulator's
        gang columns are assigned over contiguous runs, mirroring the
        driver's admission shape)."""
        pending = [p for p in self.pods.items() if _pod_pending(p)]
        pending.sort(key=lambda p: (pod_group_key(p) or f"\x7f{p.key}",
                                    p.key))
        return pending

    # ---- NodeGroup object reconciliation ----

    def _reconcile_nodegroups(self) -> None:
        ready_by_group: dict[str, int] = {}
        for node in self.nodes.items():
            group = self.cloud.node_group_of(node.metadata.name)
            if group and _node_ready(node):
                ready_by_group[group] = ready_by_group.get(group, 0) + 1
        for group in self.cloud.node_groups():
            lo, hi = self.cloud.group_size_range(group)
            spec = {"minSize": lo, "maxSize": hi,
                    "cloudProviderGroup": group}
            status = {"targetSize": self.cloud.target_size(group),
                      "readyNodes": ready_by_group.get(group, 0),
                      "lastScaleUp": self._last_scaleup.get(group, 0),
                      "lastScaleDown": self._last_scaledown.get(group, 0)}

            def mutate(obj, spec=spec, status=status):
                obj.spec = spec
                obj.status = status
                return obj

            try:
                self.store.guaranteed_update("NodeGroup", group, "default",
                                             mutate)
            except NotFound:
                try:
                    self.store.create(NodeGroup.from_dict(
                        {"metadata": {"name": group}, "spec": spec,
                         "status": status}))
                except (AlreadyExists, Conflict):
                    pass
            except Conflict:
                pass

    # ---- scale-up ----

    def _scale_up(self, pending: list, now: float) -> None:
        baseline = self.simulator.baseline_placed(pending)
        if baseline >= min(len(pending), self.simulator.caps.batch_pods):
            return  # the head of the backlog fits as-is: scheduler's job
        best = None      # (score, group, nodes_to_add, template)
        for group in self.cloud.node_groups():
            if now < self._scaleup_after.get(group, 0.0):
                continue
            _lo, hi = self.cloud.group_size_range(group)
            headroom = hi - self.cloud.target_size(group)
            if headroom <= 0:
                continue
            k = min(headroom, self.max_expansion)
            template = self.cloud.template_node(group)
            t0 = time.perf_counter()
            probe = self.simulator.probe_scale_up(pending, template, k,
                                                  baseline=baseline)
            _metrics()[3].observe(time.perf_counter() - t0)
            if probe is None or probe.newly_placed <= 0:
                continue
            want = max(1, probe.used_nodes)
            score = probe.newly_placed / want
            if best is None or score > best[0]:
                best = (score, group, min(want, headroom), template)
        if best is None:
            return
        _score, group, count, template = best
        created = self.cloud.increase_size(group, count)
        self._scaleup_after[group] = now + self.scaleup_cooldown
        # a fresh capacity add shouldn't be immediately re-shrunk
        self._scaledown_after[group] = now + self.scaledown_cooldown
        self._last_scaleup[group] = self.clock.now()
        self.scaleups += len(created)
        _metrics()[0].labels(group).inc(len(created))
        log.info("scale-up: group %s +%d (score %.2f, baseline %d/%d)",
                 group, len(created), _score, baseline, len(pending))
        if self.register_nodes:
            for name in created:
                node = template.clone()
                node.metadata.name = name
                node.metadata.labels["kubernetes.io/hostname"] = name
                try:
                    self.store.create(node)
                except (AlreadyExists, Conflict):
                    pass

    # ---- scale-down ----

    def _utilization(self, node) -> float:
        """max(cpu, memory) requested fraction of effective allocatable —
        the reference's simulator.CalculateUtilization."""
        alloc = node.status.effective_allocatable()
        cap_cpu = float(parse_quantity(alloc.get("cpu", "0") or "0"))
        cap_mem = float(parse_quantity(alloc.get("memory", "0") or "0"))
        used_cpu = used_mem = 0.0
        for pod in self.pods.items():
            if pod.spec.node_name != node.metadata.name \
                    or pod.status.phase in ("Succeeded", "Failed"):
                continue
            for c in pod.spec.containers:
                if "cpu" in c.requests:
                    used_cpu += float(parse_quantity(c.requests["cpu"]))
                if "memory" in c.requests:
                    used_mem += float(parse_quantity(c.requests["memory"]))
        fracs = []
        if cap_cpu > 0:
            fracs.append(used_cpu / cap_cpu)
        if cap_mem > 0:
            fracs.append(used_mem / cap_mem)
        return max(fracs) if fracs else 1.0

    def _node_pods(self, name: str) -> list:
        return [p for p in self.pods.items()
                if p.spec.node_name == name
                and p.status.phase not in ("Succeeded", "Failed")]

    def _drain_blocked(self, pods) -> str | None:
        """Why this node must not be drained, or None if it may be."""
        for pod in pods:
            if pod_group_key(pod) is not None:
                return f"gang member {pod.key}"  # never split a gang
            if (pod.spec.priority or 0) > self.scaledown_priority_cutoff:
                return f"pod {pod.key} above priority cutoff"
            if not eviction_allowed(self.store, pod):
                return f"PDB forbids evicting {pod.key}"
        return None

    def _verify_scale_down(self, node) -> bool:
        pods = self._node_pods(node.metadata.name)
        if self._drain_blocked(pods) is not None:
            return False
        t0 = time.perf_counter()
        ok = self.simulator.probe_scale_down(node, pods)
        _metrics()[3].observe(time.perf_counter() - t0)
        return ok

    def _scale_down(self, now: float) -> None:
        # lazy: the descheduler imports DELETION_TAINT from this module
        from kubernetes_tpu.descheduler.core import cooldown_active

        # phase 2 first: nodes cordoned last tick drain (or roll back) now
        for name in list(self._draining):
            self._finish_drain(name)
            return  # one scale-down action per tick
        wall = self.clock.now()
        # phase 1: find a newly-unneeded node, verify, cordon + taint
        for node in self.nodes.items():
            name = node.metadata.name
            group = self.cloud.node_group_of(name)
            if group is None or name in self._draining:
                continue
            lo, _hi = self.cloud.group_size_range(group)
            if self.cloud.target_size(group) <= lo:
                continue
            if now < self._scaledown_after.get(group, 0.0):
                continue
            if node.spec.unschedulable or not _node_ready(node):
                self._unneeded_since.pop(name, None)
                continue
            if cooldown_active(node, wall):
                # the descheduler just rearranged this node: shrinking it
                # now would undo the move (evict/scale-down ping-pong)
                self._unneeded_since.pop(name, None)
                continue
            if self._utilization(node) >= self.utilization_threshold:
                self._unneeded_since.pop(name, None)
                continue
            since = self._unneeded_since.setdefault(name, now)
            if now - since < self.unneeded_time:
                continue
            if not self._verify_scale_down(node):
                continue
            if not self._cordon(name, True):
                continue
            self._draining[name] = group
            self._unneeded_since.pop(name, None)
            log.info("scale-down: cordoned %s (group %s), draining next "
                     "tick", name, group)
            return  # one scale-down action per tick

    def _cordon(self, name: str, on: bool) -> bool:
        def mutate(node):
            node.spec.unschedulable = on
            node.spec.taints = [t for t in node.spec.taints
                                if t.key != DELETION_TAINT]
            if on:
                node.spec.taints.append(
                    Taint(key=DELETION_TAINT, effect="NoSchedule"))
            return node

        try:
            self.store.guaranteed_update("Node", name, "default", mutate)
            return True
        except (NotFound, Conflict):
            return False

    def _finish_drain(self, name: str) -> None:
        """Phase 2: re-verify the cordoned node against fresh informer
        state, then evict + delete — or roll the cordon back."""
        group = self._draining.pop(name)
        node = self.nodes.get(name)
        if node is None:
            return  # already gone (lifecycle GC beat us): nothing to do
        if not self._verify_scale_down(node):
            # the what-if went stale (new pods landed, a PDB tightened,
            # the remainder shrank): give the node back
            self._cordon(name, False)
            self.rollbacks += 1
            _metrics()[2].inc()
            log.info("scale-down: what-if stale for %s, rolled back", name)
            return
        for pod in self._node_pods(name):
            if not can_evict(self.store, pod):
                self._cordon(name, False)
                self.rollbacks += 1
                _metrics()[2].inc()
                log.info("scale-down: eviction refused mid-drain on %s, "
                         "rolled back", name)
                return
            try:
                self.store.delete("Pod", pod.metadata.name,
                                  pod.metadata.namespace)
            except NotFound:
                pass
        self.cloud.delete_nodes(group, [name])
        try:
            self.store.delete("Node", name, "default")
        except NotFound:
            pass
        self._scaledown_after[group] = self.now() + self.scaledown_cooldown
        self._last_scaledown[group] = self.clock.now()
        self.scaledowns += 1
        _metrics()[1].labels(group).inc()
        log.info("scale-down: drained and deleted %s (group %s)", name,
                 group)
