"""ScaleSimulator: device-batched what-if solves over hypothetical clusters.

The autoscaler's core questions — "would these pending pods fit if the
cluster had k more nodes of shape X?" and "do this node's pods re-fit on
the remainder?" — are the scheduler's findNodesThatFit evaluated against a
cluster state that does not exist. The batched solver already answers
exactly that in one XLA program, so the simulator owns a PRIVATE
StateDB/EncodeCache twin of the scheduler's device state (fed from the same
informers, never shared — probe mutations must not race the real ledger),
mutates it with hypothetical rows (template nodes added, a candidate node
removed), and dispatches `schedule_batch` with `BatchFlags.scale_sim` set.

scale_sim is the only flag the driver never derives from batch content: it
defaults False everywhere else, so real scheduling batches compile the
bit-identical pre-autoscaler program (pinned by test) while probe programs
additionally emit `placed_per_node` — the per-row placement counts the
scale-up scorer reads off the hypothetical rows.

Like the driver, the simulator keeps ONE persistent StateDB + jit-fn cache:
rebuilding per probe would close over fresh PolicyRows constants and force
a recompile every loop iteration.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from dataclasses import dataclass

import numpy as np

from kubernetes_tpu.gang import annotation_min, pod_group_key
from kubernetes_tpu.models.policy import DEFAULT_POLICY, Policy
from kubernetes_tpu.state.encode_cache import EncodeCache
from kubernetes_tpu.state.layout import Capacities, CapacityError
from kubernetes_tpu.state.pod_batch import (
    _layout,
    blob_col,
    packed_batch_flags,
    unpack_batch,
)
from kubernetes_tpu.state.statedb import StateDB

log = logging.getLogger(__name__)

# hypothetical-row name prefix: "~" is illegal in DNS-1123 names, so a sim
# row can never collide with a real registered node
SIM_NODE_PREFIX = "~sim~"


@dataclass
class ScaleUpProbe:
    """One group's expansion what-if."""

    assignments: np.ndarray   # i32[n] per-pod node row (-1 = still unfit)
    newly_placed: int         # pods placed beyond the k=0 baseline
    used_nodes: int           # hypothetical rows that received >= 1 pod
    k: int                    # hypothetical rows offered


class ScaleSimulator:
    def __init__(self, caps: Capacities | None = None,
                 policy: Policy = DEFAULT_POLICY, volume_ctx=None,
                 mesh=None):
        from kubernetes_tpu.models.policy import build_policy_rows

        # probe fleets are small: default capacities sized for control-plane
        # what-ifs, not 50k-node scheduling batches (callers override).
        # mesh: run probe solves node-sharded like the scheduler's own
        # programs — what-ifs against 100k+-node state stay per-shard too
        self.caps = caps or Capacities(num_nodes=128, batch_pods=64)
        self.mesh = mesh
        if mesh is not None and self.caps.num_nodes % mesh.size:
            from kubernetes_tpu.parallel.mesh import padded_num_nodes
            self.caps = dataclasses.replace(
                self.caps,
                num_nodes=padded_num_nodes(self.caps.num_nodes, mesh.size))
        policy = policy.with_env_overrides()
        self.policy = policy
        self.statedb = StateDB(self.caps, mesh=mesh, volume_ctx=volume_ctx)
        self.encode_cache = EncodeCache(self.caps, self.statedb.table,
                                        volume_ctx=volume_ctx)
        self._prows = build_policy_rows(policy, self.statedb.table, self.caps)
        self._fns: dict = {}
        _layout_map, f_width, i_width = _layout(self.caps)
        self._fblob = np.zeros((self.caps.batch_pods, f_width), np.float32)
        self._iblob = np.zeros((self.caps.batch_pods, i_width), np.int32)
        # probe latency accounting (autoscaler_simulation_seconds source)
        self.solve_count = 0
        self.solve_seconds = 0.0

    # ---- real-cluster mirror (driven by the autoscaler's informers) ----

    def upsert_node(self, node) -> None:
        self.statedb.upsert_node(node)

    def remove_node(self, name: str) -> None:
        self.statedb.remove_node(name)

    def has_node(self, name: str) -> bool:
        return self.statedb.has_node(name)

    def add_pod(self, pod) -> bool:
        return self.statedb.add_pod(pod)

    def remove_pod(self, key: str) -> None:
        self.statedb.remove_pod(key)

    def is_accounted(self, key: str) -> bool:
        return self.statedb.is_accounted(key)

    # ---- probe solves ----

    def _get_fn(self, flags):
        import jax

        fn = self._fns.get(flags)
        if fn is None:
            from kubernetes_tpu.ops.solver import schedule_batch

            caps, policy, prows = self.caps, self.policy, self._prows
            if self.mesh is not None:
                from kubernetes_tpu.parallel.mesh import make_sharded_scheduler
                fn = make_sharded_scheduler(self.mesh, policy, caps=caps,
                                            prows=prows, flags=flags,
                                            packed=True)
            else:
                fn = jax.jit(
                    lambda s, fb, ib, rr: schedule_batch(
                        s, unpack_batch(fb, ib, caps), rr, policy,
                        caps=caps, prows=prows, flags=flags))
            self._fns[flags] = fn
        return fn

    def _solve(self, pods) -> tuple[np.ndarray, np.ndarray]:
        """One probe solve: (assignments i32[n], placed_per_node i32[N]).
        Pods beyond batch_pods are ignored (the probe answers for the head
        of the backlog; the loop converges over iterations)."""
        n = min(len(pods), self.caps.batch_pods)
        fblob, iblob = self._fblob, self._iblob
        fblob[:] = 0.0
        iblob[:] = 0
        for i in range(n):
            self.encode_cache.encode_packed_into(fblob, iblob, i, pods[i])
        # gang columns go in after encoding (batch-local ids are never
        # cached): contiguous runs of one group key are all-or-nothing,
        # mirroring the driver's admission shape — an oversized gang must
        # probe as a unit or the what-if would claim partial placements
        # the real scheduler will refuse
        gid_col = blob_col(fblob, iblob, "gang_id", self.caps)
        gmin_col = blob_col(fblob, iblob, "gang_min", self.caps)
        i = 0
        gid = 0
        while i < n:
            gkey = pod_group_key(pods[i])
            if gkey is None:
                i += 1
                continue
            j = i
            while j < n and pod_group_key(pods[j]) == gkey:
                j += 1
            gid += 1
            quorum = annotation_min(pods[i]) or (j - i)
            for row in range(i, j):
                gid_col[row] = gid
                gmin_col[row] = quorum
            i = j

        flags = dataclasses.replace(
            packed_batch_flags(fblob, iblob, n, self.statedb.table,
                               self.caps),
            scale_sim=True)
        fn = self._get_fn(flags)
        state = self.statedb.flush()
        t0 = time.perf_counter()
        result = fn(state, fblob, iblob, np.uint32(0))
        assignments = np.asarray(result.assignments)[:n]
        placed = np.asarray(result.placed_per_node)
        self.solve_seconds += time.perf_counter() - t0
        self.solve_count += 1
        return assignments, placed

    def solve_assignments(self, pods) -> list[str | None]:
        """One solve of the batch against the current (real + hypothetical)
        state: per-pod node NAME, None = unplaced. The federation
        GlobalPlanner's entry point — its rows are whole member clusters,
        so names (not row indices) are the meaningful unit. Pods beyond
        batch_pods are reported unplaced (callers re-batch the tail)."""
        if not pods:
            return []
        assignments, _placed = self._solve(pods)
        names: list[str | None] = [
            self.statedb.table.name_of[a] if a >= 0 else None
            for a in assignments.tolist()]
        names.extend([None] * (len(pods) - len(names)))
        return names

    def baseline_placed(self, pods) -> int:
        """k=0 probe: how many of the pending pods fit the cluster as-is."""
        if not pods:
            return 0
        assignments, _placed = self._solve(pods)
        return int((assignments >= 0).sum())

    def probe_scale_up(self, pods, template, k: int,
                       baseline: int | None = None) -> ScaleUpProbe | None:
        """What-if: add k clones of `template` and re-solve the pending
        batch. Returns None when the node table cannot host k more rows
        (capacity — the caller skips the group). State is restored before
        returning, success or not."""
        if baseline is None:
            baseline = self.baseline_placed(pods)
        sim_names = []
        sim_rows = []
        try:
            for j in range(k):
                node = template.clone()
                name = f"{SIM_NODE_PREFIX}{template.metadata.name}~{j}"
                node.metadata.name = name
                node.metadata.labels["kubernetes.io/hostname"] = name
                self.statedb.upsert_node(node)
                sim_names.append(name)
                sim_rows.append(self.statedb.table.row_of[name])
        except CapacityError:
            for name in sim_names:
                self.statedb.remove_node(name)
            return None
        try:
            assignments, placed = self._solve(pods)
        finally:
            for name in sim_names:
                self.statedb.remove_node(name)
        rows = np.asarray(sim_rows, np.int64)
        return ScaleUpProbe(
            assignments=assignments,
            newly_placed=max(0, int((assignments >= 0).sum()) - baseline),
            used_nodes=int((placed[rows] > 0).sum()),
            k=k)

    def probe_scale_down(self, node, pods) -> bool:
        """What-if: remove `node`'s rows and check every one of its pods
        re-fits on the remainder. `pods` is the node's current bound pod
        set (informer truth); their clones are encoded unbound (node_name
        stripped, or fits_host would pin them to the deleted row). State
        is restored before returning.

        Nodes holding more pods than `caps.batch_pods` are probed in
        chunks: each full chunk's placements are committed into the twin
        (so later chunks see the earlier charges) before the next solve —
        an honest multi-solve answer instead of the old blanket "not
        drainable"."""
        name = node.metadata.name
        if not self.statedb.has_node(name):
            return False
        stripped = []
        for pod in pods:
            clone = pod.clone()
            clone.spec.node_name = ""
            stripped.append(clone)
        self.statedb.remove_node(name)
        committed: list = []
        try:
            if not stripped:
                return True
            step = self.caps.batch_pods
            for start in range(0, len(stripped), step):
                chunk = stripped[start:start + step]
                assignments, _placed = self._solve(chunk)
                if not bool((assignments >= 0).all()):
                    return False
                if start + step >= len(stripped):
                    break
                # commit this chunk's placements so the next solve sees
                # the charges the re-fit just spent
                for clone, row in zip(chunk, assignments.tolist()):
                    clone.spec.node_name = self.statedb.table.name_of[row]
                    self.statedb.add_pod(clone)
                    committed.append(clone)
            return True
        finally:
            # revert: the committed clones share keys with the originals,
            # so drop them before re-adding; remove_node dropped the
            # node's accounted pods too
            for clone in committed:
                self.statedb.remove_pod(clone.key)
            self.statedb.upsert_node(node)
            for pod in pods:
                self.statedb.add_pod(pod)

    def probe_defrag(self, victims, gang_pods) -> bool:
        """What-if: evict `victims` (bound, non-gang pods) and check both
        halves of a defrag move — the pending gang reaches quorum on the
        freed space AND every victim re-fits elsewhere. One solve scores
        the joint batch: gang members first in one contiguous run (so the
        gang columns apply AND the gang claims the freed space before the
        displaced pods re-pack — batch order is placement order, and the
        whole point of the evictions is to seat the gang), victim clones
        after them with node_name stripped. State is restored before
        returning."""
        if len(victims) + len(gang_pods) > self.caps.batch_pods:
            return False
        batch = list(gang_pods)
        for pod in victims:
            clone = pod.clone()
            clone.spec.node_name = ""
            batch.append(clone)
        for pod in victims:
            self.statedb.remove_pod(pod.key)
        try:
            assignments, _placed = self._solve(batch)
            ng = len(gang_pods)
            if victims and not bool((assignments[ng:] >= 0).all()):
                return False
            if not gang_pods:
                return True
            quorum = annotation_min(gang_pods[0]) or ng
            return int((assignments[:ng] >= 0).sum()) >= quorum
        finally:
            for pod in victims:
                self.statedb.add_pod(pod)
