"""The kube-proxy binary (cmd/kube-proxy analog).

Watches Services/Endpoints on an HTTP apiserver and keeps the node's NAT
table synced (iptables mode). --fake-iptables runs against the in-memory
table (the hollow-proxy / test topology); otherwise rules go through
iptables-restore.

    python -m kubernetes_tpu.cmd.proxy \
        --apiserver http://127.0.0.1:8080 --cluster-cidr 10.244.0.0/16
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import sys
from urllib.parse import urlsplit

log = logging.getLogger(__name__)


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="kubernetes-tpu-proxy",
        description="service dataplane proxy (kube-proxy analog)")
    p.add_argument("--apiserver", required=True)
    p.add_argument("--token", default=os.environ.get("KUBE_TOKEN", ""))
    p.add_argument("--cluster-cidr", default="",
                   help="pod CIDR; off-cluster VIP clients get "
                        "masqueraded (proxier.go:1136)")
    p.add_argument("--fake-iptables", action="store_true",
                   help="in-memory table instead of iptables-restore "
                        "(hollow topology)")
    p.add_argument("--dump-rules-path", default="",
                   help="write the latest restore payload to this file "
                        "after every sync (hollow-topology observability)")
    return p.parse_args(argv)


async def run(args: argparse.Namespace) -> None:
    from kubernetes_tpu.apiserver.http import RemoteStore
    from kubernetes_tpu.proxy.proxier import (
        FakeIptables,
        Proxier,
        SystemIptables,
    )

    url = urlsplit(args.apiserver)
    store = RemoteStore(url.hostname, url.port or 80, token=args.token)
    iptables = FakeIptables() if args.fake_iptables else SystemIptables()
    if args.dump_rules_path:
        # observability wrapper over WHICHEVER backend was selected — a
        # dump request must never silently swap out the real dataplane
        base_restore = iptables.restore

        def restore(rules: str) -> None:
            base_restore(rules)
            tmp = args.dump_rules_path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(rules)
            os.replace(tmp, args.dump_rules_path)

        iptables.restore = restore
    proxier = Proxier(store, iptables=iptables,
                      cluster_cidr=args.cluster_cidr)
    await proxier.start()
    log.info("kube-proxy syncing against %s (cluster-cidr=%s)",
             args.apiserver, args.cluster_cidr or "<none>")
    try:
        await asyncio.Event().wait()
    finally:
        proxier.stop()


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s")
    try:
        asyncio.run(run(parse_args(argv)))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
