"""The scheduler server binary: flags, healthz/metrics, leader election.

The plugin/cmd/kube-scheduler analog (app/server.go:67 Run: options ->
healthz+metrics endpoints :151 -> optional leader election :111-143 ->
scheduler loop). Connects to an HTTP apiserver (apiserver.http.APIServer)
and schedules against the one TPU-backed solver.

    python -m kubernetes_tpu.cmd.scheduler \
        --apiserver http://127.0.0.1:8080 \
        --policy-config-file policy.json --leader-elect \
        --port 10251

The in-process variant (--apiserver omitted) starts its own store + HTTP
apiserver — the hollow/integration topology.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import socket
import sys
from urllib.parse import urlsplit

from kubernetes_tpu.models.policy import DEFAULT_POLICY, Policy
from kubernetes_tpu.state import Capacities

log = logging.getLogger(__name__)


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="kubernetes-tpu-scheduler",
        description="TPU-native batch scheduler (kube-scheduler analog)")
    p.add_argument("--apiserver", default="",
                   help="HTTP apiserver URL; empty starts an in-process "
                        "store + apiserver on --apiserver-port")
    p.add_argument("--apiserver-port", type=int, default=8080)
    p.add_argument("--persist-path", default="",
                   help="WAL file for the in-process store (etcd-like "
                        "durability: state survives SIGKILL + restart)")
    p.add_argument("--controllers", action="store_true",
                   help="also run the controller manager in-process (the "
                        "hyperkube-style all-in-one topology)")
    p.add_argument("--admission-control", default="",
                   help="'default' enables DefaultTolerationSeconds,"
                        "LimitRanger,ResourceQuota on the in-process store")
    p.add_argument("--token-auth-file", default="",
                   help="csv of token,user,uid,groups — enables bearer-token"
                        " authn on the in-process apiserver")
    p.add_argument("--authorization-policy-file", default="",
                   help="ABAC policy jsonl — enables authorization")
    p.add_argument("--port", type=int, default=10251,
                   help="healthz/metrics port (0 = ephemeral)")
    p.add_argument("--scheduler-name", default="default-scheduler")
    p.add_argument("--policy-config-file", default="",
                   help="scheduler Policy JSON (api/types.go:38)")
    p.add_argument("--leader-elect", action="store_true")
    p.add_argument("--feature-gates", default="",
                   help="comma-separated Name=true|false overrides "
                        "(utils.features registry)")
    p.add_argument("--lock-object-name", default="kube-scheduler")
    p.add_argument("--lock-object-namespace", default="kube-system")
    p.add_argument("--num-nodes", type=int, default=1024,
                   help="node-axis capacity (padded)")
    p.add_argument("--batch-pods", type=int, default=256,
                   help="pending pods per solver batch")
    p.add_argument("--config", default="",
                   help="KubeSchedulerConfiguration JSON (componentconfig;"
                        " explicit flags take precedence)")
    p.add_argument("--profile", action="store_true",
                   default=os.environ.get("KTPU_PROFILE", "")
                   in ("1", "true"),
                   help="start the continuous profiling plane "
                        "(obs/profiling.py): sampling host profiler + "
                        "compile cost analysis; /debug/pprof/profile and "
                        "/debug/profile/device serve on the obs port "
                        "(KTPU_PROFILE=1)")
    p.add_argument("--profile-interval", type=float,
                   default=float(os.environ.get(
                       "KTPU_PROFILE_INTERVAL_S", "0.01")),
                   help="sampling profiler interval in seconds")
    args = p.parse_args(argv)
    if args.config:
        from kubernetes_tpu.models.componentconfig import (
            KubeSchedulerConfiguration,
            apply_config_to_args,
            explicit_dests,
        )

        cfg = KubeSchedulerConfiguration.from_file(args.config)
        apply_config_to_args(cfg, args, explicit_dests(p, argv), {
            "schedulerName": "scheduler_name",
            "policyConfigFile": "policy_config_file",
            "leaderElect": "leader_elect",
            "lockObjectName": "lock_object_name",
            "lockObjectNamespace": "lock_object_namespace",
            "port": "port",
            "numNodes": "num_nodes",
            "batchPods": "batch_pods",
        })
        if cfg.featureGates:
            # config gates apply first; a --feature-gates flag re-applies
            # per-key in main(), so flags override config per gate
            from kubernetes_tpu.utils.features import DEFAULT_FEATURE_GATE

            DEFAULT_FEATURE_GATE.set_from_map(cfg.featureGates)
    return args


def load_policy(path: str) -> Policy:
    if not path:
        return DEFAULT_POLICY
    with open(path) as f:
        return Policy.from_json(f.read())


async def run(args: argparse.Namespace) -> None:
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.scheduler.server import SchedulerServer

    api_server = None
    if args.apiserver:
        from kubernetes_tpu.apiserver.http import RemoteStore

        url = urlsplit(args.apiserver)
        store = RemoteStore(url.hostname, url.port or 80)
    else:
        from kubernetes_tpu.apiserver import ObjectStore
        from kubernetes_tpu.apiserver.http import APIServer

        admission = None
        if args.admission_control:
            from kubernetes_tpu.apiserver.admission import chain_for

            admission = chain_for(args.admission_control)
        store = ObjectStore(persist_path=args.persist_path or None,
                            admission=admission)
        authenticator = authorizer = None
        if args.token_auth_file:
            from kubernetes_tpu.apiserver.auth import TokenAuthenticator

            with open(args.token_auth_file) as f:
                authenticator = TokenAuthenticator.from_csv(f.read())
        if args.authorization_policy_file:
            from kubernetes_tpu.apiserver.auth import ABACAuthorizer

            with open(args.authorization_policy_file) as f:
                authorizer = ABACAuthorizer.from_policy_file(f.read())
        api_server = APIServer(store, port=args.apiserver_port,
                               authenticator=authenticator,
                               authorizer=authorizer)
        await api_server.start()
        log.info("in-process apiserver at %s", api_server.url)

    caps = Capacities(num_nodes=args.num_nodes, batch_pods=args.batch_pods)
    sched = Scheduler(store, caps=caps, policy=load_policy(
        args.policy_config_file), scheduler_name=args.scheduler_name)
    if getattr(args, "profile", False):
        from kubernetes_tpu.obs.profiling import PROFILER

        PROFILER.sampler.interval_s = args.profile_interval
        PROFILER.start(cost_analysis=True)
        log.info("profiling plane on (interval %gs): /debug/pprof/profile"
                 " + /debug/profile/device", args.profile_interval)
    server = SchedulerServer(sched, port=args.port)
    await server.start()
    log.info("healthz/metrics at %s", server.url)

    mgr_holder: list = []

    async def lead() -> None:
        """Everything that must run on the LEADER only (controllers would
        otherwise reconcile concurrently from every standby replica)."""
        if args.controllers:
            from kubernetes_tpu.controllers import ControllerManager

            from kubernetes_tpu.controllers.hpa import AnnotationMetrics

            mgr = ControllerManager(
                store, hpa_metrics=AnnotationMetrics(store))
            mgr_holder.append(mgr)
            await mgr.start()
            log.info("in-process controller manager running")
        await sched.run()

    try:
        if args.leader_elect:
            from kubernetes_tpu.client.leaderelection import LeaderElector

            identity = f"{socket.gethostname()}_{os.getpid()}"
            elector = LeaderElector(
                store, identity,
                lock_name=args.lock_object_name,
                lock_namespace=args.lock_object_namespace,
                on_started_leading=lead)
            # returns when the lease is lost: crash-only handoff — exit and
            # let the supervisor restart us as a standby (server.go:140)
            await elector.run()
            log.warning("lost leader lease; exiting")
        else:
            await lead()
    finally:
        sched.stop()
        for mgr in mgr_holder:
            mgr.stop()
        await server.stop()
        if api_server is not None:
            await api_server.stop()


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s")
    args = parse_args(argv)
    if args.feature_gates:
        from kubernetes_tpu.utils.features import DEFAULT_FEATURE_GATE

        DEFAULT_FEATURE_GATE.set_from_string(args.feature_gates)
        log.info("feature gates: %s", DEFAULT_FEATURE_GATE.known())
    try:
        asyncio.run(run(args))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
