"""The standalone apiserver binary: the kube-apiserver analog.

The reference's primary binary (cmd/kube-apiserver/app/server.go:125
CreateServerChain wires storage + authn/authz + admission + secure
serving); this binary serves the same surface from the in-memory store:

    python -m kubernetes_tpu.cmd.apiserver --port 8080 \
        --wal /var/lib/ktpu/apiserver.wal \
        --token-auth-file tokens.csv \
        --authorization-mode ABAC,RBAC \
        --authorization-policy-file abac.jsonl \
        --admission-control NamespaceLifecycle,LimitRanger \
        --tls-cert-file tls.crt --tls-private-key-file tls.key

Flags mirror the reference's options (cmd/kube-apiserver/app/options):
the WAL path is the etcd analog (checkpoint/resume per SURVEY.md §5.4 —
kill -9 the process, restart with the same --wal, state and
resourceVersions resume), --authorization-mode chains authorizers as a
union, and the admission list picks plugins by name.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import sys

log = logging.getLogger(__name__)


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="kubernetes-tpu-apiserver",
        description="REST API server over the object store "
                    "(kube-apiserver analog)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--wal", default="",
                   help="write-ahead log path (persistence + resume); "
                        "empty = in-memory only")
    p.add_argument("--token-auth-file", default="",
                   help="csv of token,user,uid[,groups] "
                        "(--token-auth-file)")
    p.add_argument("--authorization-mode", default="AlwaysAllow",
                   help="comma list of AlwaysAllow,Node,ABAC,RBAC,Webhook "
                        "(union semantics)")
    p.add_argument("--authorization-policy-file", default="",
                   help="ABAC policy file (JSON lines)")
    p.add_argument("--authorization-webhook-url", default="",
                   help="SubjectAccessReview endpoint for the Webhook "
                        "authorization mode")
    p.add_argument("--admission-control",
                   default="NamespaceLifecycle,DefaultTolerationSeconds,"
                           "LimitRanger,ResourceQuota,ServiceAccount",
                   help="ordered comma list of admission plugins")
    p.add_argument("--tls-cert-file", default="")
    p.add_argument("--tls-private-key-file", default="")
    p.add_argument("--client-ca-file", default="",
                   help="CA bundle for client-certificate authentication "
                        "(CN=user, O=groups)")
    p.add_argument("--audit-log-path", default="")
    p.add_argument("--max-requests-inflight", type=int, default=400)
    p.add_argument("--watch-cache-size", type=int, default=1 << 16)
    # HA: N stateless replicas over one shared store (same --wal)
    p.add_argument("--replica-id", default="",
                   help="this replica's identity in an HA control plane "
                        "(defaults to host:port)")
    p.add_argument("--advertise", action="store_true",
                   help="publish this replica's host:port into the "
                        "default/kubernetes Endpoints object (the endpoint "
                        "reconciler) so replica-aware clients discover the "
                        "full set; removed again on graceful shutdown")
    p.add_argument("--shutdown-drain-seconds", type=float, default=5.0,
                   help="graceful-shutdown budget: readyz 503s, in-flight "
                        "requests finish, watchers get the terminal DRAIN "
                        "frame before the process exits")
    p.add_argument("--worker-procs", type=int, default=None,
                   help="multi-process control plane: THIS process keeps "
                        "the authoritative store (single writer, WAL, "
                        "shared-memory event ring) and N worker processes "
                        "serve HTTP on --port..--port+N-1, each pinned to "
                        "a core. Defaults to $KTPU_WORKER_PROCS; 0 = "
                        "today's single-process topology")
    return p.parse_args(argv)


def build_server(args):
    """-> (APIServer, ObjectStore). Separated for in-process reuse."""
    from kubernetes_tpu.apiserver.admission import chain_for
    from kubernetes_tpu.apiserver.auth import (
        ABACAuthorizer,
        NodeAuthorizer,
        RBACAuthorizer,
        TokenAuthenticator,
        UnionAuthenticator,
        UnionAuthorizer,
        X509Authenticator,
    )
    from kubernetes_tpu.apiserver.http import APIServer
    from kubernetes_tpu.apiserver.store import ObjectStore

    store = ObjectStore(
        watch_window=args.watch_cache_size,
        persist_path=args.wal or None,
        admission=chain_for(args.admission_control)
        if args.admission_control else None)

    authns = []
    if args.client_ca_file:
        if not (args.tls_cert_file and args.tls_private_key_file):
            # without TLS serving there is no handshake to carry the
            # client cert: the flag would be silently inert
            raise SystemExit("--client-ca-file requires --tls-cert-file "
                             "and --tls-private-key-file")
        # x509 first, like the reference's authenticator union ordering
        authns.append(X509Authenticator())
    if args.token_auth_file:
        with open(args.token_auth_file, encoding="utf-8") as f:
            authns.append(TokenAuthenticator.from_csv(f.read()))
    authenticator = UnionAuthenticator(*authns) if authns else None

    modes = [m.strip() for m in args.authorization_mode.split(",")
             if m.strip()]
    authorizers = []
    for mode in modes:
        if mode == "AlwaysAllow":
            authorizers = []  # no authorizer = open (authn-only)
            break
        if mode == "ABAC":
            if not args.authorization_policy_file:
                raise SystemExit(
                    "--authorization-mode ABAC needs "
                    "--authorization-policy-file")
            with open(args.authorization_policy_file,
                      encoding="utf-8") as f:
                authorizers.append(ABACAuthorizer.from_policy_file(
                    f.read()))
        elif mode == "RBAC":
            authorizers.append(RBACAuthorizer(store))
        elif mode == "Node":
            authorizers.append(NodeAuthorizer(store))
        elif mode == "Webhook":
            if not args.authorization_webhook_url:
                raise SystemExit("--authorization-mode Webhook needs "
                                 "--authorization-webhook-url")
            from kubernetes_tpu.apiserver.auth import WebhookAuthorizer

            authorizers.append(
                WebhookAuthorizer(args.authorization_webhook_url))
        else:
            raise SystemExit(f"unknown authorization mode {mode!r}")
    authorizer = UnionAuthorizer(*authorizers) if authorizers else None

    server = APIServer(
        store, host=args.host, port=args.port,
        authenticator=authenticator, authorizer=authorizer,
        audit_path=args.audit_log_path or None,
        max_in_flight=args.max_requests_inflight,
        tls_cert_file=args.tls_cert_file or None,
        tls_key_file=args.tls_private_key_file or None,
        client_ca_file=args.client_ca_file or None,
        replica_id=getattr(args, "replica_id", ""))
    return server, store


async def run(args) -> None:
    server, _store = build_server(args)
    await server.start()
    advertise = getattr(args, "advertise", False)
    if advertise:
        server.advertise()
    scheme = "https" if args.tls_cert_file else "http"
    log.info("apiserver serving on %s://%s:%d (wal=%s, replica=%s)",
             scheme, server.host, server.port, args.wal or "<memory>",
             server.replica_id or "-")
    print(f"READY {scheme}://{server.host}:{server.port}", flush=True)
    try:
        await asyncio.Event().wait()  # serve until killed
    finally:
        # graceful exit: deregister from discovery, then drain — readyz
        # 503s, in-flight finishes, watchers get the terminal DRAIN frame
        # telling them to resume from their last rv on another replica
        if advertise:
            server.unadvertise()
        await server.drain(getattr(args, "shutdown_drain_seconds", 5.0))


async def run_multiproc(args, n: int) -> None:
    """Owner + N worker processes (`--worker-procs N`). The owner does
    not serve HTTP: workers own ports --port..--port+N-1 and forward
    mutations back over the unix-socket RPC; watch frames reach them as
    the owner's encode-once wire bytes through the shared-memory ring."""
    import signal

    from kubernetes_tpu.apiserver.admission import chain_for
    from kubernetes_tpu.apiserver.multiproc import (
        StoreOwner,
        WorkerSpec,
        spawn_worker,
        wait_port,
    )
    from kubernetes_tpu.apiserver.store import ObjectStore

    if args.tls_cert_file or args.token_auth_file or args.client_ca_file:
        # serving-side security config lives in the worker processes;
        # plumbing it through WorkerSpec is not wired yet — refuse
        # loudly rather than serve an open surface the flags promised
        # to close
        raise SystemExit("--worker-procs does not support TLS/authn "
                         "flags yet; run single-process for a secured "
                         "surface")
    store = ObjectStore(
        watch_window=args.watch_cache_size,
        persist_path=args.wal or None,
        admission=chain_for(args.admission_control)
        if args.admission_control else None)
    owner = StoreOwner(store, n_slots=max(n, 2))
    await owner.start()
    procs = []
    try:
        for i in range(n):
            spec = WorkerSpec(
                worker_id=i, ring_name=owner.ring.name,
                rpc_path=owner.rpc_path, host=args.host,
                port=args.port + i,
                advertise=getattr(args, "advertise", False))
            procs.append(spawn_worker(spec))
        for i in range(n):
            if not await asyncio.to_thread(
                    wait_port, args.host, args.port + i, 30.0):
                raise SystemExit(
                    f"worker {i} failed to serve on "
                    f"{args.host}:{args.port + i}")
            print(f"READY http://{args.host}:{args.port + i}",
                  flush=True)
        log.info("store owner up (wal=%s); %d worker process(es) on "
                 "ports %d..%d", args.wal or "<memory>", n,
                 args.port, args.port + n - 1)
        await asyncio.Event().wait()  # serve until killed
    finally:
        for proc in procs:
            if proc.is_alive():
                try:
                    os.kill(proc.pid, signal.SIGTERM)
                except ProcessLookupError:
                    pass
        for proc in procs:
            proc.join(timeout=getattr(
                args, "shutdown_drain_seconds", 5.0) + 2.0)
            if proc.is_alive():
                proc.kill()
        await owner.aclose()


def main(argv=None) -> int:
    logging.basicConfig(
        level=os.environ.get("KUBE_LOG_LEVEL", "INFO"),
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s")
    args = parse_args(argv)
    n = args.worker_procs
    if n is None:
        from kubernetes_tpu.apiserver.multiproc import default_worker_procs

        n = default_worker_procs()
    try:
        if n > 0:
            asyncio.run(run_multiproc(args, n))
        else:
            asyncio.run(run(args))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
