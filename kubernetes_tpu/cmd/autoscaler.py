"""The standalone cluster-autoscaler binary.

The reference cluster-autoscaler runs as its own leader-elected deployment
rather than inside the controller-manager; this entrypoint mirrors that
topology (the in-manager loop is the default — use one or the other, never
both, or they will fight over cordons).

    python -m kubernetes_tpu.cmd.autoscaler \
        --apiserver http://127.0.0.1:8080 --leader-elect \
        --node-groups '{"pool-a": {"minSize": 0, "maxSize": 10,
                                   "cpu": "4", "memory": "8Gi"}}'

--node-groups configures a FakeCloud provider (the only one shipped); a
real provider would be injected the way the controller-manager takes
`cloud`.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import socket
import sys
from urllib.parse import urlsplit

log = logging.getLogger(__name__)


def build_cloud(spec: str):
    """FakeCloud from the --node-groups JSON: either a map
    {name: {minSize, maxSize, cpu, memory, pods, zone, initial}} or a
    list of the same objects carrying a "name" key ("min"/"max" are
    accepted as aliases)."""
    from kubernetes_tpu.cloudprovider import FakeCloud

    cloud = FakeCloud()
    parsed = json.loads(spec) if spec else {}
    if isinstance(parsed, list):
        parsed = {cfg["name"]: cfg for cfg in parsed}
    for name, cfg in parsed.items():
        cloud.add_node_group(
            name,
            int(cfg.get("minSize", cfg.get("min", 0))),
            int(cfg.get("maxSize", cfg.get("max", 10))),
            cpu=str(cfg.get("cpu", "4")),
            memory=str(cfg.get("memory", "8Gi")),
            pods=str(cfg.get("pods", "110")),
            zone=str(cfg.get("zone", "")),
            labels=cfg.get("labels") or {},
            initial=int(cfg.get("initial", 0)))
    return cloud


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="kubernetes-tpu-autoscaler",
        description="cluster autoscaler (node-group scale-up/scale-down)")
    p.add_argument("--apiserver", required=True,
                   help="HTTP apiserver URL (apiserver.http.APIServer)")
    p.add_argument("--token", default=os.environ.get("KUBE_TOKEN", ""),
                   help="bearer token for an authn-enabled apiserver "
                        "(env KUBE_TOKEN)")
    p.add_argument("--leader-elect", action="store_true")
    p.add_argument("--port", type=int, default=10260,
                   help="serve /metrics, /healthz and /readyz here "
                        "(0 = ephemeral)")
    p.add_argument("--lock-object-name", default="cluster-autoscaler")
    p.add_argument("--lock-object-namespace", default="kube-system")
    p.add_argument("--node-groups", default="",
                   help="JSON map of fake node groups (see module doc)")
    p.add_argument("--scan-interval", type=float, default=2.0)
    p.add_argument("--scale-down-unneeded-time", type=float, default=30.0)
    p.add_argument("--scale-down-utilization-threshold", type=float,
                   default=0.5)
    p.add_argument("--expendable-pods-priority-cutoff", type=int, default=0)
    p.add_argument("--lease-duration", type=float, default=15.0)
    p.add_argument("--renew-deadline", type=float, default=10.0)
    p.add_argument("--retry-period", type=float, default=2.0)
    return p.parse_args(argv)


async def run(args: argparse.Namespace) -> None:
    from kubernetes_tpu.apiserver.http import RemoteStore
    from kubernetes_tpu.autoscaler import ClusterAutoscaler

    url = urlsplit(args.apiserver)
    store = RemoteStore(url.hostname, url.port or 80, token=args.token)
    cloud = build_cloud(args.node_groups)
    autoscaler = ClusterAutoscaler(
        store, cloud,
        scan_interval=args.scan_interval,
        unneeded_time=args.scale_down_unneeded_time,
        utilization_threshold=args.scale_down_utilization_threshold,
        scaledown_priority_cutoff=args.expendable_pods_priority_cutoff)

    from kubernetes_tpu.obs.http import ObsServer

    obs = ObsServer(
        ready_checks={"informers-synced":
                      lambda: autoscaler.nodes._synced.is_set()
                      and autoscaler.pods._synced.is_set()},
        port=args.port)
    try:
        await obs.start()
        log.info("observability endpoints on %s", obs.url)
    except OSError as e:
        log.warning("observability endpoints disabled "
                    "(port %d unavailable: %s)", args.port, e)
        obs = None

    async def lead():
        await autoscaler.start()
        log.info("autoscaler running against %s (groups: %s)",
                 args.apiserver, ", ".join(cloud.node_groups()) or "none")
        await asyncio.Event().wait()

    try:
        if args.leader_elect:
            from kubernetes_tpu.client.leaderelection import LeaderElector

            elector = LeaderElector(
                store, f"{socket.gethostname()}_{os.getpid()}",
                lock_name=args.lock_object_name,
                lock_namespace=args.lock_object_namespace,
                lease_duration=args.lease_duration,
                renew_deadline=args.renew_deadline,
                retry_period=args.retry_period,
                on_started_leading=lead)
            await elector.run()
            log.warning("lost leader lease; exiting")
        else:
            await lead()
    finally:
        autoscaler.stop()
        if obs is not None:
            await obs.stop()


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s")
    try:
        asyncio.run(run(parse_args(argv)))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
