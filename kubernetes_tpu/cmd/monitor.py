"""The standalone monitoring-plane binary.

The metrics-server + prometheus + alertmanager trio of a reference
cluster, collapsed into one leader-electable process: discover scrape
targets (Nodes' kubelet endpoints from the store, plus any --target),
pull their 0.0.4 exposition on a jittered interval into the bounded
in-memory TSDB, evaluate recording/alerting rules (built-in SLO rules +
AlertRule objects from the store), and serve the query/alert API on the
obs mux:

    python -m kubernetes_tpu.cmd.monitor \
        --apiserver http://127.0.0.1:8080 --leader-elect \
        --target scheduler=http://127.0.0.1:10251 \
        --target apiserver=http://127.0.0.1:8080

The serving URL is published on the kube-system/monitor Endpoints
object so HPA's MonitorMetrics, `kubectl top` and `kubectl get alerts`
can find it (obs.monitor.find_monitor_url).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import socket
import sys
from urllib.parse import urlsplit

log = logging.getLogger(__name__)


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="kubernetes-tpu-monitor",
        description="monitoring plane (fleet scraper, TSDB, SLO alerting)")
    p.add_argument("--apiserver", required=True,
                   help="HTTP apiserver URL (apiserver.http.APIServer)")
    p.add_argument("--token", default=os.environ.get("KUBE_TOKEN", ""),
                   help="bearer token for an authn-enabled apiserver "
                        "(env KUBE_TOKEN)")
    p.add_argument("--leader-elect", action="store_true")
    p.add_argument("--port", type=int, default=10270,
                   help="serve /metrics /healthz /alerts /query here "
                        "(0 = ephemeral)")
    p.add_argument("--lock-object-name", default="monitor")
    p.add_argument("--lock-object-namespace", default="kube-system")
    p.add_argument("--target", action="append", default=[],
                   metavar="JOB=URL",
                   help="static scrape target (repeatable), e.g. "
                        "scheduler=http://127.0.0.1:10251")
    p.add_argument("--scrape-interval", type=float, default=15.0)
    p.add_argument("--scrape-timeout", type=float, default=2.0)
    p.add_argument("--retention-samples", type=int, default=600,
                   help="ring-buffer depth per series")
    p.add_argument("--max-series", type=int, default=20000)
    p.add_argument("--alert-for", type=float, default=0.0,
                   help="default for-duration of the built-in SLO alerts")
    p.add_argument("--lease-duration", type=float, default=15.0)
    p.add_argument("--renew-deadline", type=float, default=10.0)
    p.add_argument("--retry-period", type=float, default=2.0)
    return p.parse_args(argv)


async def run(args: argparse.Namespace) -> None:
    from kubernetes_tpu.apiserver.http import RemoteStore
    from kubernetes_tpu.obs.monitor import Monitor

    url = urlsplit(args.apiserver)
    store = RemoteStore(url.hostname, url.port or 80, token=args.token)
    monitor = Monitor(
        store,
        interval=args.scrape_interval,
        scrape_timeout=args.scrape_timeout,
        retention_samples=args.retention_samples,
        max_series=args.max_series,
        alert_for_s=args.alert_for)
    for spec in args.target:
        job, _, target_url = spec.partition("=")
        if not job or not target_url:
            raise SystemExit(f"--target wants JOB=URL, got {spec!r}")
        monitor.add_static_target(job, target_url)

    from kubernetes_tpu.obs.http import ObsServer

    obs = ObsServer(registry=monitor.registry,
                    ready_checks={"scraped-once":
                                  lambda: monitor.tsdb.series_count() > 0
                                  or not monitor.targets()},
                    port=args.port, monitor=monitor)
    try:
        await obs.start()
        log.info("monitor API on %s", obs.url)
        monitor.publish(obs.url)
    except OSError as e:
        log.warning("monitor API disabled (port %d unavailable: %s)",
                    args.port, e)
        obs = None

    async def lead():
        await monitor.start()
        log.info("monitor scraping %d static targets + store nodes "
                 "every %.1fs", len(args.target), args.scrape_interval)
        await asyncio.Event().wait()

    try:
        if args.leader_elect:
            from kubernetes_tpu.client.leaderelection import LeaderElector

            elector = LeaderElector(
                store, f"{socket.gethostname()}_{os.getpid()}",
                lock_name=args.lock_object_name,
                lock_namespace=args.lock_object_namespace,
                lease_duration=args.lease_duration,
                renew_deadline=args.renew_deadline,
                retry_period=args.retry_period,
                on_started_leading=lead)
            await elector.run()
            log.warning("lost leader lease; exiting")
        else:
            await lead()
    finally:
        monitor.stop()
        if obs is not None:
            await obs.stop()


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s")
    try:
        asyncio.run(run(parse_args(argv)))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
