"""The standalone solver-as-a-service binary.

One device program, many control planes: start a SolverService (the
continuous batcher over shape-bucketed jit caches) and its HTTP front
end, pre-register the named tenants, warm the shape buckets, and serve
until interrupted:

    python -m kubernetes_tpu.cmd.solversvc \
        --port 10260 --tenant prod --tenant staging \
        --window-ms 5 --seats 32 --warmup-bucket 16

A stock Go kube-scheduler joins as a tenant with nothing but an
extender policy pointing at ``urlPrefix:
http://host:10260/tenants/<name>``; native clients use
``/tenants/<name>/solve`` (gangs, preemption, batch binds) and
``/tenants/<name>/state`` for cache-capable node sync.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import sys

log = logging.getLogger(__name__)


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="kubernetes-tpu-solversvc",
        description="multi-tenant solve service (continuous batching)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=10260,
                   help="HTTP front end port (0 = ephemeral)")
    p.add_argument("--tenant", action="append", default=[],
                   metavar="NAME", help="pre-register a tenant (repeatable)")
    p.add_argument("--auto-register", action="store_true",
                   help="register unknown tenants on first request instead "
                        "of returning 404")
    p.add_argument("--window-ms", type=float, default=5.0,
                   help="micro-batch coalescing window")
    p.add_argument("--seats", type=int, default=32,
                   help="concurrent solve seats shared APF-style across "
                        "tenants")
    p.add_argument("--queue-wait", type=float, default=2.0,
                   help="max seconds a request may queue for a seat before "
                        "a 429")
    p.add_argument("--deadline", type=float, default=5.0,
                   help="per-request HTTP deadline (504 past this)")
    p.add_argument("--batch-pods", type=int, default=64,
                   help="device batch capacity in pod rows")
    p.add_argument("--nodes", type=int, default=256,
                   help="initial node capacity (grows by pow-2 rebuild)")
    p.add_argument("--warmup-bucket", action="append", type=int, default=[],
                   metavar="PODS",
                   help="pre-compile this pod bucket at startup (repeatable)")
    return p.parse_args(argv)


async def run(args: argparse.Namespace) -> None:
    from kubernetes_tpu.solversvc.core import SolverService
    from kubernetes_tpu.solversvc.server import SolverFrontend
    from kubernetes_tpu.state.layout import Capacities

    svc = SolverService(
        caps=Capacities(num_nodes=args.nodes, batch_pods=args.batch_pods),
        window_s=args.window_ms / 1000.0,
        total_seats=args.seats,
        queue_wait_s=args.queue_wait)
    for name in args.tenant:
        svc.register_tenant(name)
    frontend = SolverFrontend(
        svc, host=args.host, port=args.port, deadline_s=args.deadline,
        warmup_buckets=tuple(args.warmup_bucket),
        auto_register=args.auto_register)
    await frontend.start()
    log.info("solversvc serving %d tenant(s) on %s (window %.1fms, "
             "%d seats)", len(args.tenant), frontend.url, args.window_ms,
             args.seats)
    try:
        await asyncio.Event().wait()
    finally:
        await frontend.stop()


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s")
    try:
        asyncio.run(run(parse_args(argv)))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
