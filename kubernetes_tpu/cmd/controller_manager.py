"""The controller-manager binary: the kube-controller-manager analog
(cmd/kube-controller-manager/app/controllermanager.go — leader-elected
process running the reconcile loops against one apiserver).

    python -m kubernetes_tpu.cmd.controller_manager \
        --apiserver http://127.0.0.1:8080 --leader-elect
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import socket
import sys
from urllib.parse import urlsplit

log = logging.getLogger(__name__)


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="kubernetes-tpu-controller-manager",
        description="reconcile-loop manager (kube-controller-manager analog)")
    p.add_argument("--apiserver", required=True,
                   help="HTTP apiserver URL (apiserver.http.APIServer)")
    p.add_argument("--token", default=os.environ.get("KUBE_TOKEN", ""),
                   help="bearer token for an authn-enabled apiserver "
                        "(env KUBE_TOKEN)")
    p.add_argument("--leader-elect", action="store_true")
    p.add_argument("--lock-object-name", default="kube-controller-manager")
    p.add_argument("--lock-object-namespace", default="kube-system")
    p.add_argument("--node-monitor-grace-period", type=float, default=40.0)
    p.add_argument("--pod-eviction-timeout", type=float, default=300.0)
    p.add_argument("--node-eviction-rate", type=float, default=0.1)
    return p.parse_args(argv)


async def run(args: argparse.Namespace) -> None:
    from kubernetes_tpu.apiserver.http import RemoteStore
    from kubernetes_tpu.controllers import ControllerManager

    url = urlsplit(args.apiserver)
    store = RemoteStore(url.hostname, url.port or 80, token=args.token)
    mgr = ControllerManager(store, node_lifecycle_kwargs=dict(
        grace_period=args.node_monitor_grace_period,
        eviction_timeout=args.pod_eviction_timeout,
        eviction_rate=args.node_eviction_rate))

    async def lead():
        await mgr.start()
        log.info("controllers running against %s", args.apiserver)
        await asyncio.Event().wait()

    try:
        if args.leader_elect:
            from kubernetes_tpu.client.leaderelection import LeaderElector

            elector = LeaderElector(
                store, f"{socket.gethostname()}_{os.getpid()}",
                lock_name=args.lock_object_name,
                lock_namespace=args.lock_object_namespace,
                on_started_leading=lead)
            await elector.run()
            log.warning("lost leader lease; exiting")
        else:
            await lead()
    finally:
        mgr.stop()


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s")
    try:
        asyncio.run(run(parse_args(argv)))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
