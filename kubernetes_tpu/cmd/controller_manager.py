"""The controller-manager binary: the kube-controller-manager analog
(cmd/kube-controller-manager/app/controllermanager.go — leader-elected
process running the reconcile loops against one apiserver).

    python -m kubernetes_tpu.cmd.controller_manager \
        --apiserver http://127.0.0.1:8080 --leader-elect
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import socket
import sys
from urllib.parse import urlsplit

log = logging.getLogger(__name__)


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="kubernetes-tpu-controller-manager",
        description="reconcile-loop manager (kube-controller-manager analog)")
    p.add_argument("--apiserver", required=True,
                   help="HTTP apiserver URL (apiserver.http.APIServer)")
    p.add_argument("--token", default=os.environ.get("KUBE_TOKEN", ""),
                   help="bearer token for an authn-enabled apiserver "
                        "(env KUBE_TOKEN)")
    p.add_argument("--leader-elect", action="store_true")
    p.add_argument("--port", type=int, default=10252,
                   help="serve /metrics, /healthz and /readyz here "
                        "(0 = ephemeral; reference --port default 10252)")
    p.add_argument("--lock-object-name", default="kube-controller-manager")
    p.add_argument("--lock-object-namespace", default="kube-system")
    p.add_argument("--node-monitor-period", type=float, default=5.0)
    p.add_argument("--node-monitor-grace-period", type=float, default=40.0)
    p.add_argument("--pod-eviction-timeout", type=float, default=300.0)
    p.add_argument("--node-eviction-rate", type=float, default=0.1)
    p.add_argument("--terminated-pod-gc-threshold", type=int,
                   default=12500)
    # leader-election timing (reference --leader-elect-lease-duration etc.)
    p.add_argument("--lease-duration", type=float, default=15.0)
    p.add_argument("--renew-deadline", type=float, default=10.0)
    p.add_argument("--retry-period", type=float, default=2.0)
    p.add_argument("--config", default="",
                   help="KubeControllerManagerConfiguration JSON "
                        "(componentconfig; explicit flags take precedence)")
    args = p.parse_args(argv)
    if args.config:
        from kubernetes_tpu.models.componentconfig import (
            KubeControllerManagerConfiguration,
            apply_config_to_args,
            explicit_dests,
        )

        cfg = KubeControllerManagerConfiguration.from_file(args.config)
        apply_config_to_args(cfg, args, explicit_dests(p, argv), {
            "leaderElect": "leader_elect",
            "lockObjectName": "lock_object_name",
            "lockObjectNamespace": "lock_object_namespace",
            "nodeMonitorPeriod": "node_monitor_period",
            "nodeMonitorGracePeriod": "node_monitor_grace_period",
            "podEvictionTimeout": "pod_eviction_timeout",
            "terminatedPodGCThreshold": "terminated_pod_gc_threshold",
        })
        if cfg.featureGates:
            from kubernetes_tpu.utils.features import DEFAULT_FEATURE_GATE

            DEFAULT_FEATURE_GATE.set_from_map(cfg.featureGates)
    return args


async def run(args: argparse.Namespace) -> None:
    from kubernetes_tpu.apiserver.http import RemoteStore
    from kubernetes_tpu.controllers import ControllerManager

    url = urlsplit(args.apiserver)
    store = RemoteStore(url.hostname, url.port or 80, token=args.token)
    from kubernetes_tpu.controllers.hpa import AnnotationMetrics

    mgr = ControllerManager(
        store,
        node_lifecycle_kwargs=dict(
            monitor_period=args.node_monitor_period,
            grace_period=args.node_monitor_grace_period,
            eviction_timeout=args.pod_eviction_timeout,
            eviction_rate=args.node_eviction_rate),
        podgc_threshold=args.terminated_pod_gc_threshold,
        hpa_metrics=AnnotationMetrics(store))

    # the healthz/metrics mux every component serves
    # (controllermanager.go:141 starts it before the election)
    from kubernetes_tpu.obs.http import ObsServer

    obs = ObsServer(ready_checks={"informers-synced": lambda: mgr.synced},
                    port=args.port)
    try:
        await obs.start()
        log.info("observability endpoints on %s", obs.url)
    except OSError as e:
        # a standby on the same host must still contend for the lease
        # even when the leader holds the default port
        log.warning("observability endpoints disabled "
                    "(port %d unavailable: %s)", args.port, e)
        obs = None

    async def lead():
        await mgr.start()
        log.info("controllers running against %s", args.apiserver)
        await asyncio.Event().wait()

    try:
        if args.leader_elect:
            from kubernetes_tpu.client.leaderelection import LeaderElector

            elector = LeaderElector(
                store, f"{socket.gethostname()}_{os.getpid()}",
                lock_name=args.lock_object_name,
                lock_namespace=args.lock_object_namespace,
                lease_duration=args.lease_duration,
                renew_deadline=args.renew_deadline,
                retry_period=args.retry_period,
                on_started_leading=lead)
            await elector.run()
            log.warning("lost leader lease; exiting")
        else:
            await lead()
    finally:
        mgr.stop()
        if obs is not None:
            await obs.stop()


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s")
    try:
        asyncio.run(run(parse_args(argv)))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
