"""The standalone federation control-plane binary.

Runs the federation hub's controller set — cluster health (with capacity
reporting), multi-type workload sync, service DNS, and optionally the
GlobalPlanner — against a federation apiserver, resolving each member
Cluster's `spec.serverAddress` to a RemoteStore:

    python -m kubernetes_tpu.cmd.federation \
        --apiserver http://127.0.0.1:8080 --planner --leader-elect

Leader election guards the whole control plane: the GlobalPlanner and the
sync controllers must run as ONE instance or two planners would stamp
over each other's plan annotations (same discipline as the descheduler).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import socket
import sys
from urllib.parse import urlsplit

log = logging.getLogger(__name__)


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="kubernetes-tpu-federation",
        description="federation control plane (health + sync + planner)")
    p.add_argument("--apiserver", required=True,
                   help="federation apiserver URL (the hub store)")
    p.add_argument("--token", default=os.environ.get("KUBE_TOKEN", ""),
                   help="bearer token for an authn-enabled apiserver "
                        "(env KUBE_TOKEN)")
    p.add_argument("--leader-elect", action="store_true")
    p.add_argument("--port", type=int, default=10272,
                   help="serve /metrics, /healthz and /readyz here "
                        "(0 = ephemeral)")
    p.add_argument("--lock-object-name", default="federation")
    p.add_argument("--lock-object-namespace", default="kube-system")
    p.add_argument("--federation-name", default="fed")
    p.add_argument("--dns-zone", default="example.com")
    p.add_argument("--health-period", type=float, default=10.0,
                   help="member probe cadence (Ready + capacity report)")
    p.add_argument("--planner", action="store_true",
                   help="run the GlobalPlanner (device-solved cross-"
                        "cluster placement for placement=global workloads)")
    p.add_argument("--plan-interval", type=float, default=2.0)
    p.add_argument("--lease-duration", type=float, default=15.0)
    p.add_argument("--renew-deadline", type=float, default=10.0)
    p.add_argument("--retry-period", type=float, default=2.0)
    return p.parse_args(argv)


def member_client_factory(token: str = ""):
    """Resolve a member Cluster to a RemoteStore for its serverAddress
    (one cached client per address — probes run every few seconds)."""
    from kubernetes_tpu.apiserver.http import RemoteStore

    clients: dict[str, RemoteStore] = {}

    def factory(cluster):
        address = cluster.server_address
        if not address:
            raise ConnectionError(
                f"cluster {cluster.metadata.name} has no serverAddress")
        client = clients.get(address)
        if client is None:
            url = urlsplit(address)
            client = RemoteStore(url.hostname, url.port or 80, token=token)
            clients[address] = client
        return client

    return factory


async def run(args: argparse.Namespace) -> None:
    from kubernetes_tpu.apiserver.http import RemoteStore
    from kubernetes_tpu.federation.kubefed import FederationControlPlane

    url = urlsplit(args.apiserver)
    store = RemoteStore(url.hostname, url.port or 80, token=args.token)
    plane = FederationControlPlane(
        store, member_client_factory(args.token),
        federation_name=args.federation_name,
        dns_zone=args.dns_zone,
        health_period=args.health_period,
        planner=args.planner,
        plan_interval=args.plan_interval)

    from kubernetes_tpu.obs.http import ObsServer

    obs = ObsServer(
        ready_checks={"informers-synced":
                      lambda: plane.clusters._synced.is_set()
                      and plane.workloads._synced.is_set()},
        port=args.port)
    try:
        await obs.start()
        log.info("observability endpoints on %s", obs.url)
    except OSError as e:
        log.warning("observability endpoints disabled "
                    "(port %d unavailable: %s)", args.port, e)
        obs = None

    async def lead():
        await plane.start()
        log.info("federation control plane running against %s%s",
                 args.apiserver,
                 " (planner on)" if args.planner else "")
        await asyncio.Event().wait()

    try:
        if args.leader_elect:
            from kubernetes_tpu.client.leaderelection import LeaderElector

            elector = LeaderElector(
                store, f"{socket.gethostname()}_{os.getpid()}",
                lock_name=args.lock_object_name,
                lock_namespace=args.lock_object_namespace,
                lease_duration=args.lease_duration,
                renew_deadline=args.renew_deadline,
                retry_period=args.retry_period,
                on_started_leading=lead)
            await elector.run()
            log.warning("lost leader lease; exiting")
        else:
            await lead()
    finally:
        plane.stop()
        if obs is not None:
            await obs.stop()


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s")
    try:
        asyncio.run(run(parse_args(argv)))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
