"""The standalone descheduler binary.

Like the monitor and the autoscaler, the gang-defragmentation loop can
run as its own leader-elected deployment instead of inside the
controller-manager (the in-manager loop behind `enable_descheduler=True`
is the default — use one or the other, never both, or two planners will
stamp over each other's cooldowns).

    python -m kubernetes_tpu.cmd.descheduler \
        --apiserver http://127.0.0.1:8080 --leader-elect

Policy knobs (--max-moves and friends) are ctor defaults; a stored
DeschedulePolicy object overrides them live, `kubectl get dsp` shows it.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import socket
import sys
from urllib.parse import urlsplit

log = logging.getLogger(__name__)


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="kubernetes-tpu-descheduler",
        description="gang-defragmentation descheduler (what-if planner)")
    p.add_argument("--apiserver", required=True,
                   help="HTTP apiserver URL (apiserver.http.APIServer)")
    p.add_argument("--token", default=os.environ.get("KUBE_TOKEN", ""),
                   help="bearer token for an authn-enabled apiserver "
                        "(env KUBE_TOKEN)")
    p.add_argument("--leader-elect", action="store_true")
    p.add_argument("--port", type=int, default=10271,
                   help="serve /metrics, /healthz and /readyz here "
                        "(0 = ephemeral)")
    p.add_argument("--lock-object-name", default="descheduler")
    p.add_argument("--lock-object-namespace", default="kube-system")
    p.add_argument("--scan-interval", type=float, default=2.0)
    p.add_argument("--max-moves", type=int, default=8)
    p.add_argument("--priority-cutoff", type=int, default=0)
    p.add_argument("--cooldown", type=float, default=300.0)
    p.add_argument("--rollback-after", type=float, default=60.0)
    p.add_argument("--dry-run", action="store_true")
    p.add_argument("--lease-duration", type=float, default=15.0)
    p.add_argument("--renew-deadline", type=float, default=10.0)
    p.add_argument("--retry-period", type=float, default=2.0)
    return p.parse_args(argv)


async def run(args: argparse.Namespace) -> None:
    from kubernetes_tpu.apiserver.http import RemoteStore
    from kubernetes_tpu.descheduler import Descheduler

    url = urlsplit(args.apiserver)
    store = RemoteStore(url.hostname, url.port or 80, token=args.token)
    descheduler = Descheduler(
        store,
        scan_interval=args.scan_interval,
        max_moves=args.max_moves,
        priority_cutoff=args.priority_cutoff,
        cooldown=args.cooldown,
        rollback_after=args.rollback_after,
        dry_run=args.dry_run)

    from kubernetes_tpu.obs.http import ObsServer

    obs = ObsServer(
        ready_checks={"informers-synced":
                      lambda: descheduler.nodes._synced.is_set()
                      and descheduler.pods._synced.is_set()},
        port=args.port)
    try:
        await obs.start()
        log.info("observability endpoints on %s", obs.url)
    except OSError as e:
        log.warning("observability endpoints disabled "
                    "(port %d unavailable: %s)", args.port, e)
        obs = None

    async def lead():
        await descheduler.start()
        log.info("descheduler running against %s%s", args.apiserver,
                 " (dry-run)" if args.dry_run else "")
        await asyncio.Event().wait()

    try:
        if args.leader_elect:
            from kubernetes_tpu.client.leaderelection import LeaderElector

            elector = LeaderElector(
                store, f"{socket.gethostname()}_{os.getpid()}",
                lock_name=args.lock_object_name,
                lock_namespace=args.lock_object_namespace,
                lease_duration=args.lease_duration,
                renew_deadline=args.renew_deadline,
                retry_period=args.retry_period,
                on_started_leading=lead)
            await elector.run()
            log.warning("lost leader lease; exiting")
        else:
            await lead()
    finally:
        descheduler.stop()
        if obs is not None:
            await obs.stop()


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s")
    try:
        asyncio.run(run(parse_args(argv)))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
