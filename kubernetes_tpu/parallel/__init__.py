from kubernetes_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    make_sharded_scheduler,
    pad_state,
    padded_num_nodes,
    shard_batch,
    shard_state,
)
