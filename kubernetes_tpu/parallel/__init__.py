from kubernetes_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    make_sharded_scheduler,
    shard_batch,
    shard_state,
)
