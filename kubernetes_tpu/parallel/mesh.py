"""Device-mesh sharding of the scheduler computation.

The reference's only intra-scheduler parallelism is a 16-goroutine fan-out
over nodes (`workqueue.Parallelize(16, len(nodes), checkNode)`,
core/generic_scheduler.go:204,352). The TPU-native equivalent shards the
**node axis** of the cluster-state tensors across a `jax.sharding.Mesh` so
predicates/priorities evaluate on all chips at once over ICI; cross-chip
argmax/normalization reductions (the analog of the priority Reduce goroutines,
:353-364) become XLA collectives inserted by GSPMD.

Axis mapping from the ML-parallelism vocabulary to this domain (SURVEY.md
SS2.8/SS5.7): the node axis plays the role of sequence/tensor parallelism (the
dimension that outgrows one chip — 15k+ nodes), and the pod-batch axis plays
data parallelism for the embarrassingly parallel phase A. Phase B's scan is
sequential by construction (serial-equivalence), so its per-step vector work
shards over nodes only.

Multi-host scale-out (DCN between slices) uses the same specs: `make_mesh`
accepts any device list, and jax.distributed initialization supplies the
global device set.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubernetes_tpu.models.policy import DEFAULT_POLICY, Policy
from kubernetes_tpu.ops.solver import schedule_batch
from kubernetes_tpu.state.cluster_state import ClusterState
from kubernetes_tpu.state.pod_batch import PodBatch

NODE_AXIS = "nodes"


def make_mesh(devices=None) -> Mesh:
    """1-D mesh over all (or given) devices, node axis sharded across it."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    return Mesh(devices.reshape(-1), (NODE_AXIS,))


def state_sharding(mesh: Mesh) -> ClusterState:
    """Pytree of NamedShardings: node-axis arrays shard dim 0 across the
    mesh; cluster-global arrays (taint-universe attributes) replicate."""
    from kubernetes_tpu.state.cluster_state import NODE_AXIS_FIELDS

    sharded = NamedSharding(mesh, P(NODE_AXIS))
    repl = NamedSharding(mesh, P())
    return ClusterState(**{
        f: sharded if f in NODE_AXIS_FIELDS else repl
        for f in ClusterState.__dataclass_fields__})


def batch_sharding(mesh: Mesh) -> PodBatch:
    """Pod batches are replicated: every chip sees the whole pending batch
    (they are small — the node axis is the big one)."""
    spec = NamedSharding(mesh, P())
    return jax.tree.map(lambda _: spec, PodBatch(
        **{f: 0 for f in PodBatch.__dataclass_fields__}))


def padded_num_nodes(num_nodes: int, mesh_size: int) -> int:
    """Smallest multiple of mesh_size >= num_nodes — the node-axis shape a
    mesh of that size can shard evenly."""
    return -(-num_nodes // mesh_size) * mesh_size


def pad_state(state: ClusterState, mesh: Mesh) -> ClusterState:
    """Pad the node axis with sentinel rows (valid=False, zero allocatable,
    topology=-1 — the empty_state row shape) up to the next mesh multiple.
    Sentinel rows fail the validity predicate, so they can never receive a
    pod and never contribute to scoring: the padded program's decisions are
    bit-identical to the unpadded one's."""
    from kubernetes_tpu.state.cluster_state import NODE_AXIS_FIELDS

    target = padded_num_nodes(state.num_nodes, mesh.size)
    pad = target - state.num_nodes
    if pad == 0:
        return state

    def pad_field(name: str, arr):
        arr = np.asarray(arr)
        fill = -1 if name == "topology" else 0
        return np.pad(arr, [(0, pad)] + [(0, 0)] * (arr.ndim - 1),
                      constant_values=fill)

    return state.replace(**{f: pad_field(f, getattr(state, f))
                            for f in NODE_AXIS_FIELDS})


def shard_state(state: ClusterState, mesh: Mesh) -> ClusterState:
    state = pad_state(state, mesh)
    return jax.device_put(state, state_sharding(mesh))


def shard_batch(batch: PodBatch, mesh: Mesh) -> PodBatch:
    return jax.device_put(batch, batch_sharding(mesh))


def make_sharded_scheduler(mesh: Mesh, policy: Policy = DEFAULT_POLICY,
                           caps=None, prows=None, flags=None, packed=False):
    """jit schedule_batch with node-axis sharding constraints.

    Returns fn(state, batch, rr) -> SolverResult whose ledger outputs stay
    node-sharded (so batch-to-batch chaining never gathers to one chip).
    `prows` (PolicyRows, replicated) is closed over as a constant — it is
    fixed for the life of the policy. `flags` (BatchFlags) gates
    batch-content-neutral kernels out of the compiled program. With
    `packed=True` the returned fn takes (state, fblob, iblob, rr) — the
    two-blob transport of pod_batch.pack_batch, replicated like the batch.
    """
    from kubernetes_tpu.ops.solver import ALL_ACTIVE, SolverResult

    if flags is None:
        flags = ALL_ACTIVE

    st = state_sharding(mesh)
    bt = batch_sharding(mesh)
    repl = NamedSharding(mesh, P())
    nodes_spec = NamedSharding(mesh, P(NODE_AXIS))
    out_shardings = SolverResult(
        assignments=repl, scores=repl, feasible_counts=repl,
        new_requested=nodes_spec, new_nonzero=nodes_spec,
        new_port_count=nodes_spec, rr_end=repl,
        new_podsel=nodes_spec, new_term=nodes_spec,
        new_vol_any=nodes_spec, new_vol_rw=nodes_spec,
        new_attach=nodes_spec,
        preempt_node=repl, victim_count=repl,
        # scale_sim probes: per-node placement counts stay node-sharded
        # (optional fields are None in non-probe programs; a sharding on a
        # None output is an empty pytree-prefix match, so one out_shardings
        # covers every flag combination)
        placed_per_node=nodes_spec,
    )
    if packed:
        from kubernetes_tpu.state.pod_batch import unpack_batch

        # victims (a VictimTable or None) shards its node axis: prio[N,S],
        # req[N,S,R] and ok[N,S] all lead with the node dim, and the
        # in_shardings leaf is a pytree prefix, valid for both structures
        vic = nodes_spec
        jfn = jax.jit(
            lambda state, fblob, iblob, rr, victims: schedule_batch(
                state, unpack_batch(fblob, iblob, caps), rr, policy,
                caps=caps, prows=prows, flags=flags, allow_fused=False,
                victims=victims),
            in_shardings=(st, repl, repl, repl, vic),
            out_shardings=out_shardings,
        )

        def packed_fn(state, fblob, iblob, rr, victims=None):
            return jfn(state, fblob, iblob, rr, victims)

        return packed_fn
    return jax.jit(
        lambda state, batch, rr: schedule_batch(state, batch, rr, policy,
                                                caps=caps, prows=prows,
                                                flags=flags,
                                                allow_fused=False),
        in_shardings=(st, bt, repl),
        out_shardings=out_shardings,
    )
