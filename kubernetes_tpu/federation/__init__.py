from kubernetes_tpu.federation.sync import (  # noqa: F401
    ClusterHealthController,
    FederatedSyncController,
    split_replicas,
)
