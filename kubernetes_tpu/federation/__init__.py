from kubernetes_tpu.federation.planner import GlobalPlanner  # noqa: F401
from kubernetes_tpu.federation.sync import (  # noqa: F401
    ClusterHealthController,
    FederatedSyncController,
    split_replicas,
)
