"""GlobalPlanner: device-solved cross-cluster placement with spillover.

ROADMAP item 4's observation made concrete: the batched filter/score
program in `ops/solver.py` answers *cross-cluster* placement unchanged if
each Ready member cluster becomes one "node" row. The planner owns a
private ScaleSimulator twin (the autoscaler's what-if engine — same
StateDB/EncodeCache/jit-cache shape, zero new BatchFlags) whose rows are
synthetic Nodes built from each member's reported aggregate free capacity
(`Cluster.status.capacity`, written by the ClusterHealthController probe);
globally-placed workloads — ReplicaSets/Deployments/PodGroups annotated
`federation.ktpu.io/placement: global` — become synthetic pod rows, one
per replica, with gang semantics preserved through the existing
gang_id/gang_min columns (a PodGroup or gang-annotated workload places at
quorum across clusters or not at all). One solve assigns every replica a
cluster; the decision lands as the `federation.ktpu.io/planned-placement`
annotation on the hub object, which the FederatedSyncController consumes
in place of its weighted split — the ensure machinery (create / rescale /
delete per member) is unchanged.

Spillover: a placement that a member *rejects* (the sync controller's
rejection ledger) or that *overcommits* a member — the planner's own
charged demand exceeds the member's refreshed free capacity while its
reported autoscaler headroom is exhausted (every NodeGroup at max-size) —
masks that cluster's row for `mask_cycles` planning cycles and re-enters
the affected workloads into the next batch, so demand drains to siblings
instead of wedging.

Composes both ways: pass `solver_service=` to mount the planner as a
`solversvc/` tenant (the hub becomes one more client of
solver-as-a-service instead of owning a device program), and every plan
write stamps a traceparent (`trace.ktpu.io/context`) that rides the
synced objects so one trace stitches hub decision -> member bind.
"""

from __future__ import annotations

import asyncio
import copy
import hashlib
import json
import logging
import time

import numpy as np

from kubernetes_tpu.api.objects import Cluster, Node, Pod
from kubernetes_tpu.apiserver.store import Conflict, NotFound, ObjectStore
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.gang import GROUP_MIN_ANNOTATION, GROUP_NAME_ANNOTATION
from kubernetes_tpu.models.policy import DEFAULT_POLICY, Policy
from kubernetes_tpu.obs.tracing import TRACE_ANNOTATION, TRACER
from kubernetes_tpu.state.cluster_state import pod_requests, resource_rows
from kubernetes_tpu.state.layout import Capacities, Resource
from kubernetes_tpu.utils.clock import SYSTEM_CLOCK, Clock

log = logging.getLogger(__name__)

# opt-in: only annotated workloads are planned globally (everything else
# keeps the weighted-split path)
PLACEMENT_ANNOTATION = "federation.ktpu.io/placement"
PLACEMENT_GLOBAL = "global"
# the planner's decision, consumed by FederatedSyncController in place of
# split_replicas: {"clusters": {name: replicas}, "replicas": total,
# "template": fingerprint, "unplaced": n}
PLAN_ANNOTATION = "federation.ktpu.io/planned-placement"
ZONE_LABEL = "failure-domain.beta.kubernetes.io/zone"
# synthetic-row name prefix for plan pods ("~" is illegal in DNS-1123, so
# a plan row can never collide with a real object; SIM_NODE_PREFIX idiom)
PLAN_POD_PREFIX = "~plan~"

# the workload kinds the planner reads (PodGroups place whole gangs)
PLANNED_KINDS = ("ReplicaSet", "Deployment", "PodGroup")


def _metrics() -> tuple:
    global _METRICS
    if _METRICS is None:
        from kubernetes_tpu.obs import metrics as m

        _METRICS = (
            m.REGISTRY.counter(
                "federation_planner_cycles_total",
                "Planning cycles the GlobalPlanner has run."),
            m.REGISTRY.counter(
                "federation_planner_placements_total",
                "Workload plans written (one per workload per decision)."),
            m.REGISTRY.counter(
                "federation_planner_spillovers_total",
                "Workloads re-entered after a member rejection or "
                "headroom-exhausted overcommit masked their cluster."),
            m.REGISTRY.histogram(
                "federation_planner_solve_seconds",
                "One batched cross-cluster device solve."),
        )
    return _METRICS


_METRICS = None


def is_global(obj) -> bool:
    """Does this workload opt into planner-driven placement?"""
    return obj.metadata.annotations.get(PLACEMENT_ANNOTATION) \
        == PLACEMENT_GLOBAL


def parse_plan(obj) -> dict | None:
    """The planner's decision annotation, or None when absent/corrupt."""
    raw = obj.metadata.annotations.get(PLAN_ANNOTATION)
    if not raw:
        return None
    try:
        doc = json.loads(raw)
    except ValueError:
        return None
    if not isinstance(doc, dict) or \
            not isinstance(doc.get("clusters"), dict):
        return None
    return doc


def template_fingerprint(obj) -> str:
    """Stable digest of the pod template: a template edit re-plans (the
    requests the rows are charged with may have changed)."""
    blob = json.dumps(obj.spec.get("template") or {}, sort_keys=True)
    return hashlib.sha1(blob.encode()).hexdigest()[:12]


def format_capacity(rows: np.ndarray) -> dict[str, str]:
    """Device-unit resource vector -> v1 quantity map (negative values
    clamp to 0: a member may report less free than the planner charged)."""
    out: dict[str, str] = {}
    for name, (row, kind) in Resource.NAMES.items():
        v = max(0, int(rows[row]))
        if v == 0 and name not in ("cpu", "memory", "pods"):
            continue
        if kind == "milli":
            out[name] = f"{v}m"
        elif kind == "mem":
            out[name] = f"{v}Mi"
        else:
            out[name] = str(v)
    return out


def cluster_node(cluster: Cluster,
                 free: dict[str, str] | None = None) -> Node:
    """Encode one Ready member as a schedulable node row. Allocatable is
    the member's reported aggregate free capacity (optionally pre-charged
    by the caller); a single-zone member carries its zone label so
    zone-aware templates keep meaning at cluster granularity."""
    name = cluster.metadata.name
    labels = {"kubernetes.io/hostname": name}
    zones = cluster.zones
    if len(zones) == 1:
        labels[ZONE_LABEL] = zones[0]
    cap = dict(free if free is not None else cluster.free_capacity)
    return Node.from_dict({
        "metadata": {"name": name, "labels": labels},
        "status": {"allocatable": cap, "capacity": dict(cap),
                   "conditions": [{"type": "Ready", "status": "True"}]}})


def workload_gang(obj) -> tuple[int, int] | None:
    """(members, quorum) for a gang workload, None for a plain one. A
    PodGroup is always a gang (spec.minMember); a ReplicaSet/Deployment
    opts in with the scheduling.ktpu.io/group-name annotation."""
    if obj.kind == "PodGroup":
        members = int(obj.spec.get("members") or obj.min_member)
        return max(members, obj.min_member), obj.min_member
    if GROUP_NAME_ANNOTATION not in obj.metadata.annotations:
        return None
    members = obj.replicas
    raw = obj.metadata.annotations.get(GROUP_MIN_ANNOTATION)
    try:
        quorum = int(raw) if raw else members
    except ValueError:
        quorum = members
    return members, max(1, min(quorum, members))


def workload_replicas(obj) -> int:
    if obj.kind == "PodGroup":
        return workload_gang(obj)[0]
    return obj.replicas


def workload_pods(obj) -> list[Pod]:
    """Synthetic pod rows for one globally-placed workload: one per
    replica, carrying the template's requests; gang workloads carry the
    group annotations so the simulator's gang columns (contiguous runs,
    all-or-nothing at quorum) apply unchanged."""
    template = obj.spec.get("template") or {}
    spec = copy.deepcopy(template.get("spec") or {})
    spec.pop("nodeName", None)  # plan rows are never pre-bound
    labels = dict((template.get("metadata") or {}).get("labels") or {})
    gang = workload_gang(obj)
    count = gang[0] if gang else workload_replicas(obj)
    annotations: dict[str, str] = {}
    if gang:
        annotations[GROUP_NAME_ANNOTATION] = \
            f"{PLAN_POD_PREFIX}{obj.kind}~{obj.metadata.name}"
        annotations[GROUP_MIN_ANNOTATION] = str(gang[1])
    ns = obj.metadata.namespace
    pods = []
    for i in range(count):
        pods.append(Pod.from_dict({
            "metadata": {
                "name": f"{PLAN_POD_PREFIX}{obj.kind}~"
                        f"{obj.metadata.name}~{i}",
                "namespace": ns,
                "labels": labels,
                "annotations": dict(annotations)},
            "spec": spec}))
    return pods


class GlobalPlanner:
    """The federation hub's planning loop (leader-electable like the
    descheduler: run one instance, or put it behind a LeaderElector).

    Per cycle: refresh cluster rows from Ready members' reported capacity
    (charged with the planner's own outstanding plans so batches
    compose), detect spillover (rejections + headroom-exhausted
    overcommit -> mask rows, re-enter workloads), encode every workload
    needing a plan as synthetic pod rows, run ONE batched device solve,
    and write each decision back as the plan annotation the sync
    controller consumes."""

    def __init__(self, fed_store: ObjectStore, cluster_informer: Informer,
                 workload_informers: dict[str, Informer],
                 caps: Capacities | None = None,
                 policy: Policy = DEFAULT_POLICY,
                 plan_interval: float = 1.0,
                 mask_cycles: int = 3,
                 solver_service=None, solver_tenant: str = "federation",
                 sync_controller=None,
                 clock: Clock = SYSTEM_CLOCK):
        self.store = fed_store
        self.clusters = cluster_informer
        self.workloads = dict(workload_informers)
        self.caps = caps or Capacities(num_nodes=32, batch_pods=64)
        self.plan_interval = plan_interval
        self.mask_cycles = mask_cycles
        self.clock = clock
        self.svc = solver_service
        self.tenant = solver_tenant
        self.sync = sync_controller
        self.sim = None
        if solver_service is None:
            from kubernetes_tpu.autoscaler.simulator import ScaleSimulator

            self.sim = ScaleSimulator(caps=self.caps, policy=policy)
        else:
            solver_service.register_tenant(solver_tenant)
        self._rows: set[str] = set()          # cluster names encoded
        self._masked: dict[str, int] = {}     # cluster -> cycles left
        self._replan: set[tuple[str, str]] = set()   # (kind, key)
        self._task: asyncio.Task | None = None
        # counters mirrored as attributes for tests/bench
        self.cycles = 0
        self.placements = 0
        self.spillovers = 0
        self.spill_by_cluster: dict[str, int] = {}
        self.solve_count = 0
        self.solve_seconds = 0.0
        self.last_decision: dict[str, dict[str, int]] = {}

    # ---- lifecycle ----

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._task = loop.create_task(self._loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        while True:
            try:
                await self.run_once()
            except asyncio.CancelledError:
                return
            except Exception:  # noqa: BLE001 — planner must survive a bad cycle
                log.exception("global planner cycle failed")
            await asyncio.sleep(self.plan_interval)

    # ---- planning cycle ----

    async def run_once(self) -> int:
        """One planning cycle; returns the number of plans written."""
        _metrics()[0].inc()
        self.cycles += 1
        members = {c.metadata.name: c
                   for c in self.clusters.items()
                   if c.ready and c.capacity}
        self._age_masks(members)

        pending = self._pending_workloads()
        planned = [(obj, plan) for obj, plan in self._planned_workloads()
                   if (obj.kind, obj.key) not in
                   {(o.kind, o.key) for o in pending}]
        self._detect_spillover(members, planned)
        if self._replan:
            keys = {(o.kind, o.key) for o in pending}
            for obj, _plan in planned:
                if (obj.kind, obj.key) in self._replan and \
                        (obj.kind, obj.key) not in keys:
                    pending.append(obj)
            planned = [(o, p) for o, p in planned
                       if (o.kind, o.key) not in self._replan]

        charged = self._charged(planned)
        await self._sync_rows(members, charged)
        if not pending or not (set(members) - set(self._masked)):
            return 0

        batch: list[Pod] = []
        spans: list[tuple[object, int, int]] = []
        for obj in pending:
            pods = workload_pods(obj)
            if not pods:
                continue
            if len(batch) + len(pods) > self.caps.batch_pods:
                continue  # tail waits for the next cycle's batch
            spans.append((obj, len(batch), len(pods)))
            batch.extend(pods)
        if not batch:
            return 0

        t0 = time.perf_counter()
        names = await self._solve(batch)
        dt = time.perf_counter() - t0
        self.solve_count += 1
        self.solve_seconds += dt
        _metrics()[3].observe(dt)

        written = 0
        with TRACER.start_span(
                "federation.plan",
                attrs={"workloads": len(spans),
                       "clusters": len(members)}) as cycle_span:
            for obj, start, count in spans:
                assigned = names[start:start + count]
                counts: dict[str, int] = {}
                for n in assigned:
                    if n is not None:
                        counts[n] = counts.get(n, 0) + 1
                unplaced = count - sum(counts.values())
                with TRACER.start_span(
                        f"plan {obj.kind}/{obj.key}",
                        parent=cycle_span.context,
                        attrs={"clusters": len(counts),
                               "unplaced": unplaced}) as span:
                    if self._write_plan(obj, counts, unplaced,
                                        span.context.to_traceparent()):
                        written += 1
                        self.placements += 1
                        _metrics()[1].inc()
                self._replan.discard((obj.kind, obj.key))
                self.last_decision[f"{obj.kind}/{obj.key}"] = counts
        self._write_cluster_status(members)
        return written

    # ---- workload selection ----

    def _iter_global(self):
        for kind in PLANNED_KINDS:
            informer = self.workloads.get(kind)
            if informer is None:
                continue
            for obj in sorted(informer.items(), key=lambda o: o.key):
                if is_global(obj):
                    yield obj

    def _pending_workloads(self) -> list:
        out = []
        for obj in self._iter_global():
            plan = parse_plan(obj)
            if plan is None \
                    or (obj.kind, obj.key) in self._replan \
                    or plan.get("replicas") != workload_replicas(obj) \
                    or plan.get("template") != template_fingerprint(obj) \
                    or int(plan.get("unplaced", 0)) > 0:
                out.append(obj)
        return out

    def _planned_workloads(self) -> list:
        out = []
        for obj in self._iter_global():
            plan = parse_plan(obj)
            if plan is not None:
                out.append((obj, plan))
        return out

    # ---- capacity accounting & spillover ----

    def _charged(self, planned) -> dict[str, np.ndarray]:
        """Per-cluster resource demand of every outstanding plan, in
        device units — the planner deducts its own decisions from the
        rows so consecutive batches never overcommit a member between
        capacity refreshes."""
        charged: dict[str, np.ndarray] = {}
        for obj, plan in planned:
            pods = workload_pods(obj)
            if not pods:
                continue
            per_replica = pod_requests(pods[0])
            for cname, count in plan["clusters"].items():
                if count <= 0:
                    continue
                row = charged.setdefault(
                    cname, np.zeros((Resource.COUNT,), np.float32))
                row += per_replica * int(count)
        return charged

    def _detect_spillover(self, members, planned) -> None:
        """Mask clusters that rejected a placement or whose refreshed
        report no longer covers the planner's charge with zero autoscaler
        headroom left, and re-enter the workloads planned there."""
        charged = self._charged(planned)
        saturated: set[str] = set()
        for name, cluster in members.items():
            charge = charged.get(name)
            if charge is None:
                continue
            free = resource_rows(cluster.free_capacity)
            if cluster.headroom <= 0 and bool((charge > free + 0.5).any()):
                saturated.add(name)
        rejected: dict[tuple[str, str], set[str]] = {}
        if self.sync is not None:
            for kind, key, cname in self.sync.take_rejections():
                rejected.setdefault((kind, key), set()).add(cname)
        if not saturated and not rejected:
            return
        for obj, plan in planned:
            hits = {c for c, n in plan["clusters"].items() if n > 0
                    and (c in saturated
                         or c in rejected.get((obj.kind, obj.key), ()))}
            if not hits:
                continue
            for cname in hits:
                self._masked[cname] = self.mask_cycles
                self.spill_by_cluster[cname] = \
                    self.spill_by_cluster.get(cname, 0) + 1
            self._replan.add((obj.kind, obj.key))
            self.spillovers += 1
            _metrics()[2].inc()
            log.info("spillover: %s/%s re-enters planning (masked %s)",
                     obj.kind, obj.key, ",".join(sorted(hits)))

    def _age_masks(self, members) -> None:
        for name in list(self._masked):
            self._masked[name] -= 1
            if self._masked[name] <= 0 or name not in members:
                del self._masked[name]

    # ---- solver backends ----

    async def _sync_rows(self, members, charged) -> None:
        want: dict[str, Node] = {}
        for name in sorted(members):
            if name in self._masked:
                continue
            free = resource_rows(members[name].free_capacity)
            charge = charged.get(name)
            if charge is not None:
                free = free - charge
            want[name] = cluster_node(members[name], format_capacity(free))
        for name in sorted(self._rows - set(want)):
            if self.sim is not None:
                self.sim.remove_node(name)
            else:
                self.svc.remove_node(self.tenant, name)
        for name, node in want.items():
            if self.sim is not None:
                self.sim.upsert_node(node)
            else:
                self.svc.upsert_node(self.tenant, node)
        self._rows = set(want)

    async def _solve(self, batch: list[Pod]) -> list[str | None]:
        if self.svc is not None:
            verdict = await self.svc.solve(self.tenant, batch, bind=False)
            return list(verdict.assignments)
        # the device solve holds the GIL through XLA dispatch: keep it off
        # the hub's event loop like every member probe
        return await asyncio.to_thread(self.sim.solve_assignments, batch)

    # ---- decision write-back ----

    def _write_plan(self, obj, counts: dict[str, int], unplaced: int,
                    traceparent: str) -> bool:
        plan = {"clusters": dict(sorted(counts.items())),
                "replicas": workload_replicas(obj),
                "template": template_fingerprint(obj),
                "unplaced": unplaced}
        encoded = json.dumps(plan, sort_keys=True)
        ns, name = obj.key.split("/", 1)
        try:
            current = self.store.get(obj.kind, name, ns)
        except NotFound:
            return False
        if current.metadata.annotations.get(PLAN_ANNOTATION) == encoded:
            # decision unchanged (the informer may lag a cycle): re-writing
            # would only churn the trace annotation and the members
            return False

        def mutate(fresh):
            fresh.metadata.annotations[PLAN_ANNOTATION] = encoded
            fresh.metadata.annotations[TRACE_ANNOTATION] = traceparent
            return fresh

        try:
            self.store.guaranteed_update(obj.kind, name, ns, mutate)
        except (NotFound, Conflict):
            return False
        return True

    def _write_cluster_status(self, members) -> None:
        """Surface the planner's view on each Cluster object (`kubectl
        describe cluster` shows the last decision + spillover count)."""
        for name, cluster in members.items():
            placements = {w: c.get(name, 0)
                          for w, c in sorted(self.last_decision.items())
                          if c.get(name, 0) > 0}
            entry = {
                "lastDecision": placements,
                "lastDecisionAt": round(self.clock.now(), 3),
                "placements": int(sum(placements.values())),
                "spillovers": self.spill_by_cluster.get(name, 0),
                "masked": name in self._masked,
            }
            current = cluster.planner_status
            if {k: v for k, v in current.items() if k != "lastDecisionAt"} \
                    == {k: v for k, v in entry.items()
                        if k != "lastDecisionAt"}:
                continue

            def mutate(fresh, entry=entry):
                fresh.status["planner"] = entry
                return fresh

            try:
                self.store.guaranteed_update("Cluster", name, "default",
                                             mutate)
            except (NotFound, Conflict):
                pass
