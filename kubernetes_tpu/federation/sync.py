"""Federation: multi-cluster workload propagation.

The federation/ tree's core loops re-designed over this framework's stores
(reference federation/pkg/federation-controller):

- **ClusterHealthController** (cluster/clustercontroller.go): probes each
  registered member cluster and maintains its Ready condition — an
  unreachable member drops out of placement. The probe also aggregates the
  member's capacity (summed schedulable-node allocatable minus bound pod
  requests, zone labels, autoscaler headroom from NodeGroup bounds) into
  `Cluster.status.capacity` — the rows the GlobalPlanner encodes.
- **FederatedSyncController** (federatedtypes/ + sync/schedulingtypes):
  watches federated workloads in the federation control plane and ensures
  per-cluster copies in every Ready member — creating, rescaling, and
  deleting (incl. members removed from the split and federated objects
  deleted upstream). Replica-carrying kinds (ReplicaSet, Deployment,
  PodGroup) split `spec.replicas`/`spec.minMember` across members by the
  `federation.kubernetes.io/replica-set-preferences` weights (equal
  weights by default, largest-remainder rounding) — unless the workload is
  annotated `federation.ktpu.io/placement: global`, in which case the
  GlobalPlanner's `planned-placement` decision replaces the weighted split
  and the planner's trace/plan annotations ride the member copies.
  Whole-copy kinds (Secret, ConfigMap) land verbatim on every Ready
  member. Member rejections (a member store refusing a write for any
  reason other than the usual CAS races) feed a ledger the planner drains
  to trigger spillover.

Member access goes through a client factory resolving a Cluster object to
its ObjectStore-compatible client (RemoteStore for spec.serverAddress; the
tests inject in-process stores), so the same loop drives real HTTP members
and fixtures alike.
"""

from __future__ import annotations

import asyncio
import logging

import numpy as np

from kubernetes_tpu.api.objects import NodeCondition  # noqa: F401 (doc link)
from kubernetes_tpu.apiserver.store import AlreadyExists, Conflict, NotFound, ObjectStore
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.controllers.base import ReconcileController
from kubernetes_tpu.federation.planner import (
    ZONE_LABEL,
    format_capacity,
    is_global,
    parse_plan,
)
from kubernetes_tpu.gang import GROUP_MIN_ANNOTATION, GROUP_NAME_ANNOTATION
from kubernetes_tpu.state.cluster_state import pod_requests, resource_rows
from kubernetes_tpu.state.layout import Resource

log = logging.getLogger(__name__)

PREFERENCES_ANNOTATION = "federation.kubernetes.io/replica-set-preferences"
CLUSTER_LABEL = "federation.kubernetes.io/cluster"

# kind -> the spec field the per-member split lands in
REPLICA_FIELD = {"ReplicaSet": "replicas", "Deployment": "replicas",
                 "PodGroup": "minMember"}
# kinds propagated verbatim to every Ready member
COPY_KINDS = ("Secret", "ConfigMap")
SYNCED_KINDS = tuple(REPLICA_FIELD) + COPY_KINDS


def split_replicas(total: int, clusters: list[str],
                   weights: dict[str, float] | None = None) -> dict[str, int]:
    """Weighted split with largest-remainder rounding (the planner's
    distribution, federation-controller/pkg/planner/planner.go)."""
    if not clusters:
        return {}
    weights = weights or {}
    w = [max(0.0, float(weights.get(c, 1.0))) for c in clusters]
    total_w = sum(w) or float(len(clusters))
    if sum(w) == 0:
        w = [1.0] * len(clusters)
    exact = [total * wi / total_w for wi in w]
    floors = [int(e) for e in exact]
    remainder = total - sum(floors)
    order = sorted(range(len(clusters)),
                   key=lambda i: (-(exact[i] - floors[i]), clusters[i]))
    for i in order[:remainder]:
        floors[i] += 1
    return dict(zip(clusters, floors))


def member_capacity(nodes, pods, groups) -> dict:
    """Aggregate one member's capacity report from its listed objects
    (runs inside the probe thread): allocatable is summed over
    schedulable Ready nodes; free subtracts every bound, non-terminal
    pod's requests; headroom is the autoscaler's remaining expansion
    (NodeGroup max-size minus attained size, summed)."""
    alloc = np.zeros((Resource.COUNT,), np.float32)
    zones: set[str] = set()
    schedulable: set[str] = set()
    for node in nodes:
        ready = any(c.type == "Ready" and c.status == "True"
                    for c in node.status.conditions)
        if not ready or node.spec.unschedulable:
            continue
        schedulable.add(node.metadata.name)
        alloc += resource_rows(node.status.effective_allocatable())
        zone = node.metadata.labels.get(ZONE_LABEL)
        if zone:
            zones.add(zone)
    used = np.zeros((Resource.COUNT,), np.float32)
    for pod in pods:
        if not pod.spec.node_name \
                or pod.spec.node_name not in schedulable \
                or pod.status.phase in ("Succeeded", "Failed"):
            continue
        used += pod_requests(pod)
    headroom = sum(
        max(0, g.max_size - max(g.target_size, g.ready_nodes))
        for g in groups)
    return {"allocatable": format_capacity(alloc),
            "free": format_capacity(alloc - used),
            "zones": sorted(zones),
            "nodes": len(schedulable),
            "headroom": int(headroom)}


class ClusterHealthController(ReconcileController):
    """Maintain each member Cluster's Ready condition AND capacity report
    by probing it, on a periodic monitor cadence (clusterMonitorPeriod,
    cluster/clustercontroller.go) — health must track outages and
    recoveries, not just watch events, and the planner's rows must track
    real member load."""

    workers = 1

    def __init__(self, fed_store: ObjectStore, cluster_informer: Informer,
                 client_factory, monitor_period: float = 10.0):
        super().__init__()
        self.name = "cluster-health-controller"
        self.store = fed_store
        self.clusters = cluster_informer
        self.client_factory = client_factory
        self.monitor_period = monitor_period
        cluster_informer.add_handler(self._on_cluster)

    def _on_cluster(self, event) -> None:
        if event.type == "ADDED":
            self.enqueue(event.obj.metadata.name)

    def _probe(self, cluster) -> dict:
        """One member probe (blocking HTTP — runs in a thread): list the
        member's nodes/pods/node-groups and fold them into the capacity
        report. Any failure marks the member unhealthy."""
        client = self.client_factory(cluster)
        nodes = client.list("Node")
        pods = client.list("Pod")
        groups = client.list("NodeGroup")
        return member_capacity(nodes, pods, groups)

    async def sync(self, key: str) -> None:
        cluster = self.clusters.get(key)
        if cluster is None:
            return
        capacity = None
        try:
            # member probes are blocking HTTP: keep them off the event loop
            capacity = await asyncio.to_thread(self._probe, cluster)
            ready = "True"
        except Exception:  # noqa: BLE001 — any failure = unhealthy
            ready = "False"
        # re-probe on the monitor cadence regardless of outcome
        self.enqueue_after(key, self.monitor_period)
        current = next((c for c in cluster.status.get("conditions", [])
                        if c.get("type") == "Ready"), None)
        if current is not None and current.get("status") == ready \
                and (capacity is None or cluster.capacity == capacity):
            return

        def mutate(obj):
            # patch the Ready entry in place: other condition types belong
            # to other writers
            conditions = obj.status.setdefault("conditions", [])
            entry = next((c for c in conditions
                          if c.get("type") == "Ready"), None)
            if entry is None:
                conditions.append({"type": "Ready", "status": ready})
            else:
                entry["status"] = ready
            if capacity is not None:
                obj.status["capacity"] = capacity
            return obj

        try:
            self.store.guaranteed_update("Cluster", key, "default", mutate)
        except (NotFound, Conflict):
            pass


class FederatedSyncController(ReconcileController):
    workers = 2

    def __init__(self, fed_store: ObjectStore, rs_informer: Informer,
                 cluster_informer: Informer, client_factory,
                 informers: dict[str, Informer] | None = None):
        super().__init__()
        self.name = "federated-sync-controller"
        self.store = fed_store
        self.clusters = cluster_informer
        self.client_factory = client_factory
        # kind -> informer; the historical single-informer signature keeps
        # working (ReplicaSet-only federation)
        self.informers: dict[str, Informer] = {"ReplicaSet": rs_informer}
        if informers:
            self.informers.update(informers)
        for informer in self.informers.values():
            informer.add_handler(self._on_workload)
        cluster_informer.add_handler(self._on_cluster)
        # keys of federated objects we have propagated (so a DELETED event
        # can clean the members without the source object)
        self._managed: set[tuple[str, str]] = set()
        # member write rejections since the last drain: (kind, key,
        # cluster) — the GlobalPlanner turns these into spillover
        self._rejections: set[tuple[str, str, str]] = set()

    def _on_workload(self, event) -> None:
        if event.obj.kind in self.informers:
            self.enqueue(f"{event.obj.kind}/{event.obj.key}")

    def _on_cluster(self, event) -> None:
        # membership/health changes re-plan every federated workload
        for kind, informer in self.informers.items():
            for obj in informer.items():
                self.enqueue(f"{kind}/{obj.key}")

    def take_rejections(self) -> list[tuple[str, str, str]]:
        """Drain the member-rejection ledger (planner spillover input)."""
        out = sorted(self._rejections)
        self._rejections.clear()
        return out

    def _ready_members(self):
        return sorted((c for c in self.clusters.items() if c.ready),
                      key=lambda c: c.metadata.name)

    def _preferences(self, obj) -> dict[str, float]:
        import json

        raw = obj.metadata.annotations.get(PREFERENCES_ANNOTATION)
        if not raw:
            return {}
        try:
            prefs = json.loads(raw)
            return {name: float(spec.get("weight", 1))
                    for name, spec in (prefs.get("clusters") or {}).items()}
        except (ValueError, TypeError, AttributeError):
            log.warning("bad %s annotation on %s", PREFERENCES_ANNOTATION,
                        obj.key)
            return {}

    async def sync(self, key: str) -> None:
        kind, rest = key.split("/", 1)
        ns, name = rest.split("/", 1)
        informer = self.informers.get(kind)
        obj = informer.get(name, ns) if informer is not None else None
        if obj is None:
            # federated object deleted: remove from EVERY member (reachable
            # or not — unreachable ones retry until clean, so a recovering
            # member cannot resurrect an orphan)
            failed = await self._cleanup(kind, ns, name)
            if failed:
                self.enqueue_after(key, 1.0)
            else:
                self._managed.discard((kind, rest))
            return
        self._managed.add((kind, rest))
        members = self._ready_members()
        planned = False
        if kind in REPLICA_FIELD:
            if is_global(obj):
                # the GlobalPlanner owns this workload's distribution: no
                # decision yet means nothing to ensure (the plan
                # annotation's arrival re-enqueues the key)
                plan = parse_plan(obj)
                if plan is None:
                    return
                planned = True
                counts = {c: int(n) for c, n in plan["clusters"].items()}
            else:
                counts = split_replicas(
                    self._total_replicas(obj),
                    [c.metadata.name for c in members],
                    self._preferences(obj))
        else:
            counts = {}
        for cluster in members:
            # member CRUD is blocking HTTP: run each member's reconcile in
            # a worker thread so a slow member never stalls the event loop
            retry = await asyncio.to_thread(
                self._reconcile_member, cluster, obj, kind, ns, name,
                counts.get(cluster.metadata.name, 0), planned)
            if retry:
                self.enqueue_after(key, 0.05)

    def _total_replicas(self, obj) -> int:
        if obj.kind == "PodGroup":
            return obj.min_member
        return obj.replicas

    async def _cleanup(self, kind: str, ns: str, name: str) -> bool:
        """Delete the propagated object from all members; True if any
        member could not be cleaned yet."""
        failed = False
        for cluster in sorted(self.clusters.items(),
                              key=lambda c: c.metadata.name):
            def delete_one(cluster=cluster):
                try:
                    self.client_factory(cluster).delete(kind, name, ns)
                except NotFound:
                    pass

            try:
                await asyncio.to_thread(delete_one)
            except Exception:  # noqa: BLE001 — unreachable member: retry
                failed = True
        return failed

    def _member_annotations(self, obj, want: int,
                            planned: bool) -> dict[str, str]:
        """Hub annotations ride the member copy (incl. the planner's plan
        + traceparent, stitching hub decision -> member bind into one
        trace); a planned gang's member slice rewrites group-min to its
        own size so each cluster's slice binds all-or-nothing."""
        ann = dict(obj.metadata.annotations)
        if planned and GROUP_NAME_ANNOTATION in ann:
            ann[GROUP_MIN_ANNOTATION] = str(max(1, want))
        return ann

    def _record_rejection(self, kind: str, ns: str, name: str,
                          cluster) -> None:
        cname = cluster.metadata.name
        self._rejections.add((kind, f"{ns}/{name}", cname))
        log.warning("member %s rejected %s %s/%s", cname, kind, ns, name)

    def _reconcile_member(self, cluster, obj, kind: str, ns: str, name: str,
                          want: int, planned: bool) -> bool:
        """Ensure one member's copy (runs in a worker thread). Returns True
        when the key should be retried."""
        if kind in COPY_KINDS:
            return self._reconcile_copy(cluster, obj, kind, ns, name)
        client = self.client_factory(cluster)
        field = REPLICA_FIELD[kind]
        ann = self._member_annotations(obj, want, planned)
        try:
            current = client.get(kind, name, ns)
        except NotFound:
            current = None
        if current is None:
            copy = obj.clone()
            # hub rv is meaningless in the member store: strip before CREATE
            copy.metadata.resource_version = ""  # ktpu: allow[store-rmw]
            copy.metadata.labels = dict(copy.metadata.labels)
            copy.metadata.labels[CLUSTER_LABEL] = cluster.metadata.name
            copy.metadata.annotations = ann
            copy.spec[field] = want
            try:
                client.create(copy)
            except AlreadyExists:
                return True
            except (Conflict, NotFound):
                return True
            except Exception:  # noqa: BLE001 — member refused the object
                self._record_rejection(kind, ns, name, cluster)
                return False
            return False
        drift = int(current.spec.get(field) or 0) != int(want) \
            or current.spec.get("template") != obj.spec.get("template") \
            or any(current.metadata.annotations.get(k) != v
                   for k, v in ann.items())
        if drift:
            fresh = current.clone()
            fresh.spec = dict(obj.spec)
            fresh.spec[field] = want
            fresh.metadata.annotations = dict(current.metadata.annotations)
            fresh.metadata.annotations.update(ann)
            try:
                # CAS against the member's version just read: a racing
                # member-side writer wins and the key is retried
                client.update(fresh)
            except (Conflict, NotFound):
                return True
            except Exception:  # noqa: BLE001 — member refused the write
                self._record_rejection(kind, ns, name, cluster)
                return False
        return False

    def _reconcile_copy(self, cluster, obj, kind: str, ns: str,
                        name: str) -> bool:
        """Ensure one member's verbatim copy of a config kind."""
        client = self.client_factory(cluster)
        try:
            current = client.get(kind, name, ns)
        except NotFound:
            current = None
        if current is None:
            copy = obj.clone()
            copy.metadata.resource_version = ""  # ktpu: allow[store-rmw]
            copy.metadata.labels = dict(copy.metadata.labels)
            copy.metadata.labels[CLUSTER_LABEL] = cluster.metadata.name
            try:
                client.create(copy)
            except AlreadyExists:
                return True
            except Exception:  # noqa: BLE001 — member refused the object
                self._record_rejection(kind, ns, name, cluster)
                return False
            return False
        drift = current.data != obj.data
        if kind == "Secret":
            drift = drift or getattr(current, "type", None) != \
                getattr(obj, "type", None)
        if drift:
            fresh = current.clone()
            fresh.data = dict(obj.data)
            if kind == "Secret":
                fresh.type = obj.type
            try:
                client.update(fresh)
            except (Conflict, NotFound):
                return True
            except Exception:  # noqa: BLE001 — member refused the write
                self._record_rejection(kind, ns, name, cluster)
                return False
        return False
