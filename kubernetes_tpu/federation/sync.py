"""Federation: multi-cluster workload propagation.

The federation/ tree's core loops re-designed over this framework's stores
(reference federation/pkg/federation-controller):

- **ClusterHealthController** (cluster/clustercontroller.go): probes each
  registered member cluster and maintains its Ready condition — an
  unreachable member drops out of placement.
- **FederatedSyncController** (federatedtypes/replicaset.go + the
  replica-set scheduler sync/schedulingtypes): watches federated
  ReplicaSets in the federation control plane, splits `spec.replicas`
  across Ready members by the `federation.kubernetes.io/replica-set-
  preferences` weights (equal weights by default, largest-remainder
  rounding), and ensures a per-cluster ReplicaSet in every member —
  creating, rescaling, and deleting (incl. members removed from the split
  and federated objects deleted upstream).

Member access goes through a client factory resolving a Cluster object to
its ObjectStore-compatible client (RemoteStore for spec.serverAddress; the
tests inject in-process stores), so the same loop drives real HTTP members
and fixtures alike.
"""

from __future__ import annotations

import asyncio
import logging

from kubernetes_tpu.api.objects import NodeCondition  # noqa: F401 (doc link)
from kubernetes_tpu.apiserver.store import AlreadyExists, Conflict, NotFound, ObjectStore
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.controllers.base import ReconcileController

log = logging.getLogger(__name__)

PREFERENCES_ANNOTATION = "federation.kubernetes.io/replica-set-preferences"
CLUSTER_LABEL = "federation.kubernetes.io/cluster"


def split_replicas(total: int, clusters: list[str],
                   weights: dict[str, float] | None = None) -> dict[str, int]:
    """Weighted split with largest-remainder rounding (the planner's
    distribution, federation-controller/pkg/planner/planner.go)."""
    if not clusters:
        return {}
    weights = weights or {}
    w = [max(0.0, float(weights.get(c, 1.0))) for c in clusters]
    total_w = sum(w) or float(len(clusters))
    if sum(w) == 0:
        w = [1.0] * len(clusters)
    exact = [total * wi / total_w for wi in w]
    floors = [int(e) for e in exact]
    remainder = total - sum(floors)
    order = sorted(range(len(clusters)),
                   key=lambda i: (-(exact[i] - floors[i]), clusters[i]))
    for i in order[:remainder]:
        floors[i] += 1
    return dict(zip(clusters, floors))


class ClusterHealthController(ReconcileController):
    """Maintain each member Cluster's Ready condition by probing it, on a
    periodic monitor cadence (clusterMonitorPeriod,
    cluster/clustercontroller.go) — health must track outages and
    recoveries, not just watch events."""

    workers = 1

    def __init__(self, fed_store: ObjectStore, cluster_informer: Informer,
                 client_factory, monitor_period: float = 10.0):
        super().__init__()
        self.name = "cluster-health-controller"
        self.store = fed_store
        self.clusters = cluster_informer
        self.client_factory = client_factory
        self.monitor_period = monitor_period
        cluster_informer.add_handler(self._on_cluster)

    def _on_cluster(self, event) -> None:
        if event.type == "ADDED":
            self.enqueue(event.obj.metadata.name)

    async def sync(self, key: str) -> None:
        cluster = self.clusters.get(key)
        if cluster is None:
            return
        try:
            # member probes are blocking HTTP: keep them off the event loop
            await asyncio.to_thread(
                lambda: self.client_factory(cluster).list("Node"))
            ready = "True"
        except Exception:  # noqa: BLE001 — any failure = unhealthy
            ready = "False"
        # re-probe on the monitor cadence regardless of outcome
        self.enqueue_after(key, self.monitor_period)
        current = next((c for c in cluster.status.get("conditions", [])
                        if c.get("type") == "Ready"), None)
        if current is not None and current.get("status") == ready:
            return

        def mutate(obj):
            # patch the Ready entry in place: other condition types belong
            # to other writers
            conditions = obj.status.setdefault("conditions", [])
            entry = next((c for c in conditions
                          if c.get("type") == "Ready"), None)
            if entry is None:
                conditions.append({"type": "Ready", "status": ready})
            else:
                entry["status"] = ready
            return obj

        try:
            self.store.guaranteed_update("Cluster", key, "default", mutate)
        except (NotFound, Conflict):
            pass


class FederatedSyncController(ReconcileController):
    workers = 2

    def __init__(self, fed_store: ObjectStore, rs_informer: Informer,
                 cluster_informer: Informer, client_factory):
        super().__init__()
        self.name = "federated-replicaset-controller"
        self.store = fed_store
        self.workloads = rs_informer
        self.clusters = cluster_informer
        self.client_factory = client_factory
        rs_informer.add_handler(self._on_workload)
        cluster_informer.add_handler(self._on_cluster)
        # keys of federated objects we have propagated (so a DELETED event
        # can clean the members without the source object)
        self._managed: set[str] = set()

    def _on_workload(self, event) -> None:
        if event.obj.kind == "ReplicaSet":
            self.enqueue(event.obj.key)

    def _on_cluster(self, event) -> None:
        # membership/health changes re-plan every federated workload
        for rs in self.workloads.items():
            self.enqueue(rs.key)

    def _ready_members(self):
        return sorted((c for c in self.clusters.items() if c.ready),
                      key=lambda c: c.metadata.name)

    def _preferences(self, rs) -> dict[str, float]:
        import json

        raw = rs.metadata.annotations.get(PREFERENCES_ANNOTATION)
        if not raw:
            return {}
        try:
            prefs = json.loads(raw)
            return {name: float(spec.get("weight", 1))
                    for name, spec in (prefs.get("clusters") or {}).items()}
        except (ValueError, TypeError, AttributeError):
            log.warning("bad %s annotation on %s", PREFERENCES_ANNOTATION,
                        rs.key)
            return {}

    async def sync(self, key: str) -> None:
        ns, name = key.split("/", 1)
        rs = self.workloads.get(name, ns)
        if rs is None:
            # federated object deleted: remove from EVERY member (reachable
            # or not — unreachable ones retry until clean, so a recovering
            # member cannot resurrect an orphan)
            failed = await self._cleanup(ns, name)
            if failed:
                self.enqueue_after(key, 1.0)
            else:
                self._managed.discard(key)
            return
        self._managed.add(key)
        members = self._ready_members()
        plan = split_replicas(rs.replicas,
                              [c.metadata.name for c in members],
                              self._preferences(rs))
        for cluster in members:
            # member CRUD is blocking HTTP: run each member's reconcile in
            # a worker thread so a slow member never stalls the event loop
            retry = await asyncio.to_thread(
                self._reconcile_member, cluster, rs, ns, name,
                plan.get(cluster.metadata.name, 0))
            if retry:
                self.enqueue_after(key, 0.05)

    async def _cleanup(self, ns: str, name: str) -> bool:
        """Delete the propagated object from all members; True if any
        member could not be cleaned yet."""
        failed = False
        for cluster in sorted(self.clusters.items(),
                              key=lambda c: c.metadata.name):
            def delete_one(cluster=cluster):
                try:
                    self.client_factory(cluster).delete(
                        "ReplicaSet", name, ns)
                except NotFound:
                    pass

            try:
                await asyncio.to_thread(delete_one)
            except Exception:  # noqa: BLE001 — unreachable member: retry
                failed = True
        return failed

    def _reconcile_member(self, cluster, rs, ns: str, name: str,
                          want: int) -> bool:
        """Ensure one member's copy (runs in a worker thread). Returns True
        when the key should be retried."""
        client = self.client_factory(cluster)
        try:
            current = client.get("ReplicaSet", name, ns)
        except NotFound:
            current = None
        if current is None:
            copy = rs.clone()
            # hub rv is meaningless in the member store: strip before CREATE
            copy.metadata.resource_version = ""  # ktpu: allow[store-rmw]
            copy.metadata.labels = dict(copy.metadata.labels)
            copy.metadata.labels[CLUSTER_LABEL] = cluster.metadata.name
            copy.spec["replicas"] = want
            try:
                client.create(copy)
            except AlreadyExists:
                return True
            return False
        if current.replicas != want \
                or current.spec.get("template") != rs.spec.get("template"):
            fresh = current.clone()
            fresh.spec = dict(rs.spec)
            fresh.spec["replicas"] = want
            try:
                # CAS against the member's version just read: a racing
                # member-side writer wins and the key is retried
                client.update(fresh)
            except (Conflict, NotFound):
                return True
        return False
