"""Federated Services + cross-cluster DNS.

The federation service controller + dnsprovider analogs (reference
federation/pkg/federation-controller/service/servicecontroller.go and
federation/pkg/dnsprovider/dns.go):

- **DNSProvider SPI**: the zone/rrset surface the reference abstracts over
  google-clouddns/aws-route53/coredns; `FakeDNSProvider` is the in-memory
  member of the family (the reference ships one too, for its tests).
- **FederatedServiceController**: watches Services in the federation
  control plane, ensures a copy in every Ready member, collects each
  member's LoadBalancer ingress, and maintains the reference's DNS record
  chain (service/dns.go ensureDNSRrsets):

    <svc>.<ns>.<federation>.svc.<zone>                global A: all healthy
    <svc>.<ns>.<federation>.svc.<cluster>.<zone>      per-cluster: A when
        the member is healthy and has ingress, else CNAME falling back to
        the global name (the reference's zone->region->global fallback,
        collapsed one level because members are the placement unit here)

  A member outage therefore FLIPS its record from A to CNAME and drops
  its IPs from the global set — the cross-cluster failover signal DNS
  clients follow.
"""

from __future__ import annotations

import asyncio
import logging

from kubernetes_tpu.apiserver.store import AlreadyExists, NotFound, ObjectStore
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.controllers.base import ReconcileController
from kubernetes_tpu.federation.sync import CLUSTER_LABEL

log = logging.getLogger(__name__)


class FakeDNSProvider:
    """In-memory dnsprovider (reference dnsprovider/providers/.../fake):
    rrsets keyed by (fqdn, type)."""

    def __init__(self):
        self.records: dict[tuple[str, str], tuple[str, ...]] = {}

    def ensure(self, name: str, rrtype: str, rrdatas: list[str]) -> None:
        """Create-or-replace one rrset (EnsureResourceRecordSet)."""
        if rrdatas:
            self.records[(name, rrtype)] = tuple(sorted(rrdatas))
        else:
            self.records.pop((name, rrtype), None)

    def delete(self, name: str, rrtype: str | None = None) -> None:
        if rrtype is not None:
            self.records.pop((name, rrtype), None)
            return
        for key in [k for k in self.records if k[0] == name]:
            self.records.pop(key, None)

    def lookup(self, name: str, rrtype: str) -> tuple[str, ...]:
        return self.records.get((name, rrtype), ())


def service_ingress_ips(svc) -> list[str]:
    """LoadBalancer ingress IPs of one (member) Service object."""
    status = getattr(svc, "status", None) or {}
    if hasattr(status, "get"):
        lb = status.get("loadBalancer") or {}
    else:
        lb = getattr(status, "load_balancer", None) or {}
    return [e.get("ip") for e in (lb.get("ingress") or []) if e.get("ip")]


class FederatedServiceController(ReconcileController):
    """Propagate Services to Ready members and keep the DNS chain fresh.

    DNS health re-evaluates on the monitor cadence as well as on watch
    events — a member's ingress appearing/vanishing happens in the MEMBER
    cluster, which the federation control plane only sees by polling."""

    workers = 2

    def __init__(self, fed_store: ObjectStore, svc_informer: Informer,
                 cluster_informer: Informer, client_factory,
                 dns: FakeDNSProvider, federation_name: str = "fed",
                 dns_zone: str = "example.com",
                 monitor_period: float = 0.5):
        super().__init__()
        self.name = "federated-service-controller"
        self.store = fed_store
        self.services = svc_informer
        self.clusters = cluster_informer
        self.client_factory = client_factory
        self.dns = dns
        self.federation_name = federation_name
        self.dns_zone = dns_zone
        self.monitor_period = monitor_period
        self._monitor_task: asyncio.Task | None = None
        # per-cluster record names we have written, per service key — so a
        # member UNJOINED from the federation gets its records retracted
        # (sync only iterates current members; without this, an unjoined
        # cluster's A record would serve its stale IP forever)
        self._written: dict[str, set[str]] = {}
        svc_informer.add_handler(self._on_service)
        cluster_informer.add_handler(self._on_cluster)

    def _on_service(self, event) -> None:
        self.enqueue(event.obj.key)

    def _on_cluster(self, event) -> None:
        for svc in self.services.items():
            self.enqueue(svc.key)

    async def start(self) -> None:
        await super().start()
        self._monitor_task = asyncio.get_running_loop().create_task(
            self._monitor())

    def stop(self) -> None:
        super().stop()
        if self._monitor_task is not None:
            self._monitor_task.cancel()
            self._monitor_task = None

    async def _monitor(self) -> None:
        while True:
            await asyncio.sleep(self.monitor_period)
            for svc in self.services.items():
                self.enqueue(svc.key)

    # ---- naming (service/dns.go getResolvedEndpoints naming scheme) ----

    def global_name(self, ns: str, name: str) -> str:
        return (f"{name}.{ns}.{self.federation_name}.svc."
                f"{self.dns_zone}")

    def cluster_name(self, ns: str, name: str, cluster: str) -> str:
        return (f"{name}.{ns}.{self.federation_name}.svc.{cluster}."
                f"{self.dns_zone}")

    # ---- reconcile ----

    async def sync(self, key: str) -> None:
        ns, name = key.split("/", 1)
        svc = self.services.get(name, ns)
        members = sorted(self.clusters.items(),
                         key=lambda c: c.metadata.name)
        if svc is None:
            # federated service deleted: clean members + all DNS records
            for cluster in members:
                def delete_one(cluster=cluster):
                    try:
                        self.client_factory(cluster).delete(
                            "Service", name, ns)
                    except NotFound:
                        pass
                try:
                    await asyncio.to_thread(delete_one)
                except Exception:  # noqa: BLE001 — unreachable: retry
                    self.enqueue_after(key, 1.0)
            self.dns.delete(self.global_name(ns, name))
            for cname in self._written.pop(key, set()) | {
                    c.metadata.name for c in members}:
                self.dns.delete(self.cluster_name(ns, name, cname))
            return

        healthy_ips: dict[str, list[str]] = {}
        for cluster in members:
            cname = cluster.metadata.name
            if not cluster.ready:
                continue
            ips = await asyncio.to_thread(
                self._reconcile_member, cluster, svc, ns, name)
            if ips is None:
                self.enqueue_after(key, 0.2)
                ips = []
            if ips:
                healthy_ips[cname] = ips

        all_ips = sorted({ip for ips in healthy_ips.values()
                          for ip in ips})
        gname = self.global_name(ns, name)
        self.dns.ensure(gname, "A", all_ips)
        member_names = {c.metadata.name for c in members}
        for cname in member_names:
            record = self.cluster_name(ns, name, cname)
            ips = healthy_ips.get(cname)
            if ips:
                self.dns.ensure(record, "A", ips)
                self.dns.delete(record, "CNAME")
            else:
                # unhealthy/ingress-less member: fall back to the
                # federation-wide name (service/dns.go's CNAME chain)
                self.dns.delete(record, "A")
                self.dns.ensure(record, "CNAME", [gname] if all_ips else [])
        # retract records of clusters that LEFT the federation
        for gone in self._written.get(key, set()) - member_names:
            self.dns.delete(self.cluster_name(ns, name, gone))
        self._written[key] = member_names

    def _reconcile_member(self, cluster, svc, ns: str,
                          name: str) -> list[str] | None:
        """Ensure the member's Service and return its ingress IPs (runs in
        a worker thread; None = member unreachable, retry)."""
        client = self.client_factory(cluster)
        try:
            current = client.get("Service", name, ns)
        except NotFound:
            copy = svc.clone()
            # hub rv is meaningless in the member store: strip before CREATE
            copy.metadata.resource_version = ""  # ktpu: allow[store-rmw]
            copy.metadata.labels = dict(copy.metadata.labels)
            copy.metadata.labels[CLUSTER_LABEL] = cluster.metadata.name
            try:
                client.create(copy)
            except AlreadyExists:
                pass
            except Exception:  # noqa: BLE001
                return None
            return []
        except Exception:  # noqa: BLE001
            return None
        return service_ingress_ips(current)
