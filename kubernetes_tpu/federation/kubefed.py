"""kubefed: federation bootstrap + member join/unjoin.

The federation/cmd/kubefed analog (kubefed.go; init_.go deploys the
federation control plane into a host cluster, join.go registers a member
by creating a Cluster object + credentials secret). Here the control
plane is an in-process store + controller set, and joining wires a
Cluster object whose `spec.serverAddressByClientCIDRs` points at the
member's apiserver:

    python -m kubernetes_tpu.federation.kubefed join mem-1 \
        --host-server http://fed-apiserver:8080 \
        --cluster-server http://member-apiserver:8080

`FederationControlPlane` is the library form used by tests and embedders:
one call builds the health/sync/service-DNS controllers over a federation
store (kubefed init's controller-manager half).
"""

from __future__ import annotations

import argparse
import sys

from kubernetes_tpu.api.objects import Cluster
from kubernetes_tpu.apiserver.store import AlreadyExists, NotFound, ObjectStore
from kubernetes_tpu.client.informer import Informer

FEDERATION_NAMESPACE = "federation-system"


def make_cluster(name: str, server_address: str = "") -> Cluster:
    """The Cluster registry object kubefed join creates (join.go:214)."""
    return Cluster.from_dict({
        "metadata": {"name": name},
        "spec": {"serverAddressByClientCIDRs": [
            {"clientCIDR": "0.0.0.0/0",
             "serverAddress": server_address}]},
    })


def join(fed_store, name: str, server_address: str = "") -> None:
    """Register a member cluster (idempotent)."""
    try:
        fed_store.create(make_cluster(name, server_address))
    except AlreadyExists:
        pass


def unjoin(fed_store, name: str) -> None:
    try:
        fed_store.delete("Cluster", name, "default")
    except NotFound:
        pass


class FederationControlPlane:
    """kubefed init's controller half over a federation store: cluster
    health + workload sync + service DNS, one start()/stop() pair."""

    def __init__(self, fed_store: ObjectStore, client_factory,
                 dns=None, federation_name: str = "fed",
                 dns_zone: str = "example.com",
                 health_period: float = 1.0):
        from kubernetes_tpu.federation.dns import (
            FakeDNSProvider,
            FederatedServiceController,
        )
        from kubernetes_tpu.federation.sync import (
            ClusterHealthController,
            FederatedSyncController,
        )

        self.store = fed_store
        self.dns = dns if dns is not None else FakeDNSProvider()
        self.clusters = Informer(fed_store, "Cluster")
        self.workloads = Informer(fed_store, "ReplicaSet")
        self.services = Informer(fed_store, "Service")
        self.health = ClusterHealthController(
            fed_store, self.clusters, client_factory,
            monitor_period=health_period)
        self.sync = FederatedSyncController(
            fed_store, self.workloads, self.clusters, client_factory)
        self.service_dns = FederatedServiceController(
            fed_store, self.services, self.clusters, client_factory,
            self.dns, federation_name=federation_name, dns_zone=dns_zone)

    async def start(self) -> None:
        for informer in (self.clusters, self.workloads, self.services):
            informer.start()
        for informer in (self.clusters, self.workloads, self.services):
            await informer.wait_for_sync()
        await self.health.start()
        await self.sync.start()
        await self.service_dns.start()

    def stop(self) -> None:
        self.service_dns.stop()
        self.sync.stop()
        self.health.stop()
        for informer in (self.clusters, self.workloads, self.services):
            informer.stop()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="kubefed", description="federation bootstrap (join/unjoin)")
    p.add_argument("command", choices=["join", "unjoin"])
    p.add_argument("name")
    p.add_argument("--host-server", required=True,
                   help="federation control-plane apiserver URL")
    p.add_argument("--cluster-server", default="",
                   help="member apiserver URL (join)")
    p.add_argument("--token", default="")
    args = p.parse_args(argv)

    from urllib.parse import urlparse

    from kubernetes_tpu.apiserver.http import RemoteStore

    url = urlparse(args.host_server)
    fed = RemoteStore(url.hostname, url.port or 8080, token=args.token,
                      tls=url.scheme == "https")
    if args.command == "join":
        join(fed, args.name, args.cluster_server)
        print(f"cluster {args.name!r} joined")
    else:
        unjoin(fed, args.name)
        print(f"cluster {args.name!r} unjoined")
    return 0


if __name__ == "__main__":
    sys.exit(main())
