"""kubefed: federation bootstrap + member join/unjoin.

The federation/cmd/kubefed analog (kubefed.go; init_.go deploys the
federation control plane into a host cluster, join.go registers a member
by creating a Cluster object + credentials secret). Here the control
plane is an in-process store + controller set, and joining wires a
Cluster object whose `spec.serverAddressByClientCIDRs` points at the
member's apiserver:

    python -m kubernetes_tpu.federation.kubefed join mem-1 \
        --host-server http://fed-apiserver:8080 \
        --cluster-server http://member-apiserver:8080

`FederationControlPlane` is the library form used by tests and embedders:
one call builds the health/sync/service-DNS controllers over a federation
store (kubefed init's controller-manager half).
"""

from __future__ import annotations

import argparse
import sys

from kubernetes_tpu.api.objects import Cluster
from kubernetes_tpu.apiserver.store import AlreadyExists, NotFound, ObjectStore
from kubernetes_tpu.client.informer import Informer

FEDERATION_NAMESPACE = "federation-system"


def make_cluster(name: str, server_address: str = "") -> Cluster:
    """The Cluster registry object kubefed join creates (join.go:214)."""
    return Cluster.from_dict({
        "metadata": {"name": name},
        "spec": {"serverAddressByClientCIDRs": [
            {"clientCIDR": "0.0.0.0/0",
             "serverAddress": server_address}]},
    })


def join(fed_store, name: str, server_address: str = "") -> None:
    """Register a member cluster (idempotent)."""
    try:
        fed_store.create(make_cluster(name, server_address))
    except AlreadyExists:
        pass


def unjoin(fed_store, name: str) -> None:
    try:
        fed_store.delete("Cluster", name, "default")
    except NotFound:
        pass


class FederationControlPlane:
    """kubefed init's controller half over a federation store: cluster
    health + workload sync + service DNS, one start()/stop() pair."""

    def __init__(self, fed_store: ObjectStore, client_factory,
                 dns=None, federation_name: str = "fed",
                 dns_zone: str = "example.com",
                 health_period: float = 1.0,
                 planner: bool = False, plan_interval: float = 1.0,
                 planner_caps=None, solver_service=None):
        from kubernetes_tpu.federation.dns import (
            FakeDNSProvider,
            FederatedServiceController,
        )
        from kubernetes_tpu.federation.sync import (
            SYNCED_KINDS,
            ClusterHealthController,
            FederatedSyncController,
        )

        self.store = fed_store
        self.dns = dns if dns is not None else FakeDNSProvider()
        self.clusters = Informer(fed_store, "Cluster")
        self.workload_informers = {
            kind: Informer(fed_store, kind) for kind in SYNCED_KINDS}
        self.workloads = self.workload_informers["ReplicaSet"]
        self.services = Informer(fed_store, "Service")
        self.health = ClusterHealthController(
            fed_store, self.clusters, client_factory,
            monitor_period=health_period)
        self.sync = FederatedSyncController(
            fed_store, self.workloads, self.clusters, client_factory,
            informers={k: v for k, v in self.workload_informers.items()
                       if k != "ReplicaSet"})
        self.service_dns = FederatedServiceController(
            fed_store, self.services, self.clusters, client_factory,
            self.dns, federation_name=federation_name, dns_zone=dns_zone)
        self.planner = None
        if planner:
            from kubernetes_tpu.federation.planner import (
                PLANNED_KINDS,
                GlobalPlanner,
            )

            self.planner = GlobalPlanner(
                fed_store, self.clusters,
                {k: self.workload_informers[k] for k in PLANNED_KINDS},
                caps=planner_caps, plan_interval=plan_interval,
                solver_service=solver_service,
                sync_controller=self.sync)

    def _informers(self):
        return (self.clusters, self.services,
                *self.workload_informers.values())

    async def start(self) -> None:
        for informer in self._informers():
            informer.start()
        for informer in self._informers():
            await informer.wait_for_sync()
        await self.health.start()
        await self.sync.start()
        await self.service_dns.start()
        if self.planner is not None:
            await self.planner.start()

    def stop(self) -> None:
        if self.planner is not None:
            self.planner.stop()
        self.service_dns.stop()
        self.sync.stop()
        self.health.stop()
        for informer in self._informers():
            informer.stop()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="kubefed", description="federation bootstrap (join/unjoin)")
    p.add_argument("command", choices=["join", "unjoin"])
    p.add_argument("name")
    p.add_argument("--host-server", required=True,
                   help="federation control-plane apiserver URL")
    p.add_argument("--cluster-server", default="",
                   help="member apiserver URL (join)")
    p.add_argument("--token", default="")
    args = p.parse_args(argv)

    from urllib.parse import urlparse

    from kubernetes_tpu.apiserver.http import RemoteStore

    url = urlparse(args.host_server)
    fed = RemoteStore(url.hostname, url.port or 8080, token=args.token,
                      tls=url.scheme == "https")
    if args.command == "join":
        join(fed, args.name, args.cluster_server)
        print(f"cluster {args.name!r} joined")
    else:
        unjoin(fed, args.name)
        print(f"cluster {args.name!r} unjoined")
    return 0


if __name__ == "__main__":
    sys.exit(main())
