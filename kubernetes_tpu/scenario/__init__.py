"""Scenario plane: seeded day-in-the-life traces, the full-stack soak
driver, and adversarial scenario search (ROADMAP item 5)."""

from kubernetes_tpu.scenario.traces import (  # noqa: F401
    EVENT_KINDS,
    Event,
    FaultShift,
    FlapBurst,
    GangWidthShift,
    MUTATION_KINDS,
    RateSpike,
    Tape,
    TraceConfig,
    TraceEngine,
    make_tape,
    mutation_from_dict,
    mutation_to_dict,
)

__all__ = [
    "EVENT_KINDS", "Event", "FaultShift", "FlapBurst", "GangWidthShift",
    "MUTATION_KINDS", "RateSpike", "Tape", "TraceConfig", "TraceEngine",
    "make_tape", "mutation_from_dict", "mutation_to_dict",
]
