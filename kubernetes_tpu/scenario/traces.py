"""Seeded workload-trace engine: a compressed "day" as a typed event tape.

The unit of scenario is a :class:`Tape` — a :class:`TraceConfig` header
plus an ordered list of :class:`Event` rows — generated bit-identically
from ``TraceConfig.seed``.  Two properties carry the whole design:

* **Replayability** — every random draw comes from a ``random.Random``
  child seeded from ``(seed, tick)``.  No ambient ``random`` module
  state, no wall clock (ktpu-lint R4 scopes this package).
* **Mutation locality** — because each tick owns its RNG stream, a
  mutation that perturbs tick window ``[a, b)`` (a rate spike, a fault
  shift) leaves every event whose *origin* tick falls outside the
  window byte-identical.  The scenario search (search.py) leans on this:
  it can stack mutations and still diff tapes event-by-event.

Shapes follow the public cluster traces: diurnal sinusoid arrival
intensity (Borg/Alibaba both show a ~2x day/night swing), heavy-tailed
request sizes, exponential lifetimes with a long-running mass, and a
three-tier priority mix (prod / batch / best-effort).
"""

from __future__ import annotations

import hashlib
import json
import math
import random
from dataclasses import dataclass, field, fields, replace

# Event kinds -----------------------------------------------------------

SUBMIT = "submit"            # one pod
SUBMIT_GANG = "submit-gang"  # `width` pods under one gang annotation
DELETE = "delete"            # job (or gang) reaches end of lifetime
NODE_ADD = "node-add"        # operator adds a node (kubelet joins)
NODE_DRAIN = "node-drain"    # node drained: pods evicted, node removed
NODE_FLAP = "node-flap"      # heartbeat stops -> NotReady -> recovers
WATCH_EXPIRE = "watch-expire"    # FaultPlane: compact watch history
WATCHER_DROP = "watcher-drop"    # FaultPlane: sever live watchers
BROWNOUT = "brownout"            # FaultPlane: set injected error rate

EVENT_KINDS = (SUBMIT, SUBMIT_GANG, DELETE, NODE_ADD, NODE_DRAIN,
               NODE_FLAP, WATCH_EXPIRE, WATCHER_DROP, BROWNOUT)

_TICK_MIX = 2654435761  # Knuth multiplicative hash, keeps tick streams apart


@dataclass(frozen=True)
class Event:
    """One row of the tape.  Serialises to a single stable text line."""

    tick: int
    kind: str
    name: str
    origin: int = 0       # tick whose RNG stream produced this event
    cpu_m: int = 0        # millicores per pod
    mem_mi: int = 0       # Mi per pod
    width: int = 1        # gang width (1 for plain submits)
    priority: int = 0     # numeric pod priority
    lifetime: int = 0     # ticks until delete (0 = long-running)
    down: int = 0         # node-flap: ticks until recovery
    rate: float = 0.0     # brownout: injected error rate at this tick

    def to_line(self) -> str:
        # `rate` serialises only when set, so every pre-brownout tape
        # line stays byte-identical (locality diffs and stored artifacts
        # both lean on that)
        tail = f" rate={self.rate}" if self.kind == BROWNOUT else ""
        return (f"{self.tick} {self.kind} {self.name or '-'} "
                f"origin={self.origin} cpu={self.cpu_m} mem={self.mem_mi} "
                f"w={self.width} prio={self.priority} "
                f"life={self.lifetime} down={self.down}" + tail)

    @classmethod
    def from_line(cls, line: str) -> "Event":
        head, *kv = line.split()
        tick, kind, name = int(head), kv[0], kv[1]
        vals = dict(p.split("=", 1) for p in kv[2:])
        return cls(tick=tick, kind=kind,
                   name="" if name == "-" else name,
                   origin=int(vals["origin"]), cpu_m=int(vals["cpu"]),
                   mem_mi=int(vals["mem"]), width=int(vals["w"]),
                   priority=int(vals["prio"]), lifetime=int(vals["life"]),
                   down=int(vals["down"]),
                   rate=float(vals.get("rate", 0.0)))


@dataclass(frozen=True)
class TraceConfig:
    """Everything the generator needs; the whole header is the seed."""

    seed: int = 0
    ticks: int = 96            # compressed day: 96 x 15-min slots
    nodes: int = 16            # initial hollow-node fleet
    node_cpu: str = "16"
    node_memory: str = "32Gi"
    autoscale_max: int = 4     # extra nodes the autoscaler may add
    # arrival process
    base_rate: float = 3.0     # mean submits per tick at the diurnal mean
    diurnal_amplitude: float = 0.6
    # job shapes (Borg-ish)
    gang_fraction: float = 0.2
    gang_widths: tuple = (2, 4, 8)
    gang_width_weights: tuple = (4, 2, 1)
    priority_mix: tuple = ((1000, 2), (100, 5), (0, 3))  # (prio, weight)
    cpu_choices_m: tuple = (100, 250, 500, 1000, 2000)
    cpu_weights: tuple = (40, 30, 15, 10, 5)
    mean_lifetime_ticks: float = 12.0
    long_running_frac: float = 0.05
    # cluster churn
    flap_rate: float = 0.0     # P(node flap) per tick
    flap_down_ticks: int = 3
    drain_every: int = 0       # 0 = never
    add_every: int = 0
    # FaultPlane timings
    watch_expire_ticks: tuple = ()
    watcher_drop_ticks: tuple = ()
    # mutation surfaces (normally installed by Mutation.apply)
    rate_spikes: tuple = ()    # ((start, end, mult), ...)
    flap_bursts: tuple = ()    # ((tick, count), ...)
    zones: int = 1             # failure domains: node i sits in zone
    #                            i // ceil(nodes / zones)
    brownouts: tuple = ()      # ((start, end, peak_error_rate), ...)
    zone_failures: tuple = ()  # ((tick, zone, down_ticks), ...)

    def to_dict(self) -> dict:
        d = {}
        for f in fields(self):
            v = getattr(self, f.name)
            d[f.name] = list(v) if isinstance(v, tuple) else v
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "TraceConfig":
        kw = {}
        for f in fields(cls):
            if f.name not in d:
                continue
            v = d[f.name]
            if isinstance(v, list):
                v = tuple(tuple(x) if isinstance(x, list) else x for x in v)
            kw[f.name] = v
        return cls(**kw)


# Mutations -------------------------------------------------------------
#
# A mutation is a small frozen dataclass with ``apply(cfg) -> cfg`` and a
# stable dict form, so a found scenario serialises as (seed, [mutations])
# and replays from the artifact alone.


@dataclass(frozen=True)
class RateSpike:
    """Multiply arrival intensity inside ``[start, end)``."""

    start: int
    end: int
    mult: float = 4.0
    kind: str = field(default="rate-spike", init=False)

    def apply(self, cfg: TraceConfig) -> TraceConfig:
        return replace(cfg, rate_spikes=cfg.rate_spikes
                       + ((self.start, self.end, self.mult),))


@dataclass(frozen=True)
class GangWidthShift:
    """Scale every gang width (and the gang fraction) by ``factor``."""

    factor: float = 2.0
    kind: str = field(default="gang-width-shift", init=False)

    def apply(self, cfg: TraceConfig) -> TraceConfig:
        widths = tuple(max(1, int(w * self.factor))
                       for w in cfg.gang_widths)
        frac = min(0.9, cfg.gang_fraction * max(1.0, self.factor / 2.0))
        return replace(cfg, gang_widths=widths, gang_fraction=frac)


@dataclass(frozen=True)
class FaultShift:
    """Slide the FaultPlane timings (watch expiry / watcher drops) by
    ``delta`` ticks — fault-vs-load phase is a classic failure surface."""

    delta: int
    kind: str = field(default="fault-shift", init=False)

    def apply(self, cfg: TraceConfig) -> TraceConfig:
        hi = max(1, cfg.ticks - 1)

        def sh(ts):
            return tuple(min(hi, max(0, t + self.delta)) for t in ts)

        return replace(cfg, watch_expire_ticks=sh(cfg.watch_expire_ticks),
                       watcher_drop_ticks=sh(cfg.watcher_drop_ticks))


@dataclass(frozen=True)
class FlapBurst:
    """Flap ``count`` extra nodes at ``tick`` (correlated failure)."""

    tick: int
    count: int = 2
    kind: str = field(default="flap-burst", init=False)

    def apply(self, cfg: TraceConfig) -> TraceConfig:
        return replace(cfg, flap_bursts=cfg.flap_bursts
                       + ((self.tick, self.count),))


@dataclass(frozen=True)
class ApiserverBrownout:
    """Ramp the FaultPlane's injected error rate over ``[start, end)``:
    a brownout, not an outage — verbs fail with rising-then-falling
    probability (triangular, peaking at ``peak`` mid-window), and the
    rate drops back to zero at ``end``.  Retry storms under partial
    availability are a different failure surface than a clean kill."""

    start: int
    end: int
    peak: float = 0.5
    kind: str = field(default="apiserver-brownout", init=False)

    def apply(self, cfg: TraceConfig) -> TraceConfig:
        return replace(cfg, brownouts=cfg.brownouts
                       + ((self.start, self.end, self.peak),))


@dataclass(frozen=True)
class CorrelatedZoneFailure:
    """Flap EVERY node in one failure domain at ``tick`` for ``down``
    ticks — a rack/zone power event, the correlated cousin of the
    independent per-node flap.  Installs enough zones for the target to
    exist; node ``i`` lives in zone ``i // ceil(nodes / zones)``."""

    tick: int
    zone: int = 0
    down: int = 4
    kind: str = field(default="zone-failure", init=False)

    def apply(self, cfg: TraceConfig) -> TraceConfig:
        return replace(cfg, zones=max(cfg.zones, self.zone + 1),
                       zone_failures=cfg.zone_failures
                       + ((self.tick, self.zone, self.down),))


MUTATION_KINDS = {"rate-spike": RateSpike, "gang-width-shift": GangWidthShift,
                  "fault-shift": FaultShift, "flap-burst": FlapBurst,
                  "apiserver-brownout": ApiserverBrownout,
                  "zone-failure": CorrelatedZoneFailure}


def mutation_to_dict(m) -> dict:
    d = {"kind": m.kind}
    for f in fields(m):
        if f.name != "kind":
            d[f.name] = getattr(m, f.name)
    return d


def mutation_from_dict(d: dict):
    cls = MUTATION_KINDS[d["kind"]]
    return cls(**{k: v for k, v in d.items() if k != "kind"})


# Tape ------------------------------------------------------------------


@dataclass
class Tape:
    config: TraceConfig
    events: list

    def to_text(self) -> str:
        header = json.dumps(self.config.to_dict(), sort_keys=True,
                            separators=(",", ":"))
        return "\n".join([header] + [e.to_line() for e in self.events]) + "\n"

    @classmethod
    def from_text(cls, text: str) -> "Tape":
        lines = [ln for ln in text.splitlines() if ln.strip()]
        cfg = TraceConfig.from_dict(json.loads(lines[0]))
        return cls(cfg, [Event.from_line(ln) for ln in lines[1:]])

    def checksum(self) -> str:
        return hashlib.sha256(self.to_text().encode()).hexdigest()[:16]

    def with_events(self, events) -> "Tape":
        return Tape(self.config, list(events))

    def with_nodes(self, nodes: int) -> "Tape":
        return Tape(replace(self.config, nodes=nodes), list(self.events))

    def counts(self) -> dict:
        out: dict = {}
        for e in self.events:
            out[e.kind] = out.get(e.kind, 0) + 1
        return out

    def pods_submitted(self) -> int:
        return sum(e.width if e.kind == SUBMIT_GANG else 1
                   for e in self.events
                   if e.kind in (SUBMIT, SUBMIT_GANG))


# Generator -------------------------------------------------------------


def _wchoice(rng: random.Random, items, weights):
    total = sum(weights)
    x = rng.random() * total
    for item, w in zip(items, weights):
        x -= w
        if x < 0:
            return item
    return items[-1]


def _poisson(rng: random.Random, lam: float) -> int:
    if lam <= 0:
        return 0
    if lam > 60:  # Knuth underflows; normal approximation is fine here
        return max(0, int(rng.gauss(lam, math.sqrt(lam)) + 0.5))
    limit = math.exp(-lam)
    k, p = 0, 1.0
    while True:
        p *= rng.random()
        if p <= limit:
            return k
        k += 1


class TraceEngine:
    """Generates a :class:`Tape` from a config plus optional mutations."""

    def __init__(self, config: TraceConfig | None = None, mutations=()):
        cfg = config or TraceConfig()
        for m in mutations:
            cfg = m.apply(cfg)
        self.config = cfg

    def _tick_rng(self, tick: int) -> random.Random:
        cfg = self.config
        return random.Random((cfg.seed << 24) ^ ((tick * _TICK_MIX)
                                                 & 0xFFFFFFFF))

    def _rate_at(self, tick: int) -> float:
        cfg = self.config
        phase = 2.0 * math.pi * tick / max(1, cfg.ticks)
        lam = cfg.base_rate * max(
            0.0, 1.0 + cfg.diurnal_amplitude * math.sin(phase - math.pi / 2))
        for start, end, mult in cfg.rate_spikes:
            if start <= tick < end:
                lam *= mult
        return lam

    def generate(self) -> Tape:
        cfg = self.config
        prios = [p for p, _ in cfg.priority_mix]
        prio_w = [w for _, w in cfg.priority_mix]
        events: list[Event] = []
        pending_deletes: dict[int, list[Event]] = {}

        for t in range(cfg.ticks):
            # deletes scheduled by earlier ticks land first, in the order
            # their submits drew them (deterministic)
            events.extend(pending_deletes.pop(t, ()))
            # brownout rows are RNG-free (deterministic triangular ramp)
            # and precede the tick's submits so the rate governs them;
            # not drawing from `rng` keeps even the window's own submit
            # stream byte-identical when the mutation is stacked
            for start, end, peak in cfg.brownouts:
                if start <= t < end:
                    x = (t - start + 0.5) / max(1, end - start)
                    events.append(Event(
                        t, BROWNOUT, "", origin=t,
                        rate=round(peak * (1.0 - abs(2.0 * x - 1.0)), 4)))
                elif t == end and end < cfg.ticks:
                    events.append(Event(t, BROWNOUT, "", origin=t,
                                        rate=0.0))
            rng = self._tick_rng(t)
            for i in range(_poisson(rng, self._rate_at(t))):
                is_gang = rng.random() < cfg.gang_fraction
                cpu = _wchoice(rng, cfg.cpu_choices_m, cfg.cpu_weights)
                prio = _wchoice(rng, prios, prio_w)
                if rng.random() < cfg.long_running_frac:
                    life = 0
                else:
                    life = 1 + int(rng.expovariate(
                        1.0 / max(0.5, cfg.mean_lifetime_ticks)))
                if is_gang:
                    width = _wchoice(rng, cfg.gang_widths,
                                     cfg.gang_width_weights)
                    ev = Event(t, SUBMIT_GANG, f"g{t}-{i}", origin=t,
                               cpu_m=cpu, mem_mi=cpu, width=width,
                               priority=prio, lifetime=life)
                else:
                    ev = Event(t, SUBMIT, f"j{t}-{i}", origin=t,
                               cpu_m=cpu, mem_mi=cpu, priority=prio,
                               lifetime=life)
                events.append(ev)
                if life and t + life < cfg.ticks:
                    pending_deletes.setdefault(t + life, []).append(
                        Event(t + life, DELETE, ev.name, origin=t,
                              width=ev.width))
            # node churn
            flaps = 1 if rng.random() < cfg.flap_rate else 0
            for btick, count in cfg.flap_bursts:
                if btick == t:
                    flaps += count
            for _ in range(flaps):
                events.append(Event(t, NODE_FLAP,
                                    f"soak-{rng.randrange(cfg.nodes):05d}",
                                    origin=t, down=cfg.flap_down_ticks))
            # correlated zone failure: every node of the domain flaps at
            # once — RNG-free so the rows land without perturbing the
            # tick's stream (zone membership is positional)
            for ftick, zone, down in cfg.zone_failures:
                if ftick == t:
                    per = -(-cfg.nodes // max(1, cfg.zones))  # ceil
                    for i in range(zone * per,
                                   min(cfg.nodes, (zone + 1) * per)):
                        events.append(Event(t, NODE_FLAP, f"soak-{i:05d}",
                                            origin=t, down=down))
            if cfg.add_every and t and t % cfg.add_every == 0:
                events.append(Event(t, NODE_ADD, f"soak-add-{t}", origin=t))
            if cfg.drain_every and t and t % cfg.drain_every == 0:
                events.append(Event(t, NODE_DRAIN,
                                    f"soak-{rng.randrange(cfg.nodes):05d}",
                                    origin=t))
            # FaultPlane timings
            if t in cfg.watch_expire_ticks:
                events.append(Event(t, WATCH_EXPIRE, "", origin=t))
            if t in cfg.watcher_drop_ticks:
                events.append(Event(t, WATCHER_DROP, "", origin=t))
        return Tape(cfg, events)


def make_tape(config: TraceConfig | None = None, mutations=()) -> Tape:
    return TraceEngine(config, mutations).generate()
