"""Adversarial scenario search: mutate a trace toward gate violations,
then delta-debug the failing tape to a minimal replayable artifact.

The search half is property-based testing turned offensive: the property
is "the control plane holds its gates" (exactly-once binds, zero racy
writes, zero stalls, flat memory ceilings, p99 bound — soak.py's
``violations``), and the generator walks TraceConfig mutation space
(rate spikes, gang-width shifts, fault-timing shifts, flap bursts)
uphill on soak.py's graded ``pressure`` signal until a gate breaks.

The shrink half is classic ddmin (Zeller & Hildebrandt, TSE'02) over
the event tape: first the minimal violating *prefix* (binary search),
then chunk-removal minimization of the surviving events, then the
minimal node count — every probe a full replay through the evaluator,
every step counted, so tests can assert bounded convergence.

Everything is driven by one ``evaluate(tape) -> (violations, pressure)``
callable. The real one wraps :func:`~kubernetes_tpu.scenario.soak.
run_soak` (:func:`soak_evaluator`); tests plug in cheap pure-tape
predicates to pin the search/shrink mechanics deterministically.

A found-and-shrunk scenario prints as a replay artifact:
``KTPU_SCENARIO_SEED`` + the mutation stack as JSON + the minimal tape —
one command reproduces the failure.
"""

from __future__ import annotations

import json
import random
from dataclasses import dataclass, field

from kubernetes_tpu.scenario.traces import (
    ApiserverBrownout,
    CorrelatedZoneFailure,
    FaultShift,
    FlapBurst,
    GangWidthShift,
    RateSpike,
    Tape,
    TraceConfig,
    make_tape,
    mutation_to_dict,
)


def default_mutations(rng: random.Random, cfg: TraceConfig) -> list:
    """The seeded mutation menu: one candidate of each family, drawn
    from ``rng`` so a search replays from its seed."""
    span = max(2, cfg.ticks // 8)
    start = rng.randrange(max(1, cfg.ticks - span))
    return [
        RateSpike(start=start, end=start + span,
                  mult=2.0 + 6.0 * rng.random()),
        GangWidthShift(factor=1.5 + rng.random() * 2.0),
        FaultShift(delta=rng.randrange(-span, span + 1)),
        FlapBurst(tick=rng.randrange(cfg.ticks),
                  count=1 + rng.randrange(4)),
        ApiserverBrownout(start=start, end=start + span,
                          peak=0.2 + 0.6 * rng.random()),
        CorrelatedZoneFailure(tick=rng.randrange(cfg.ticks),
                              zone=rng.randrange(max(1, cfg.zones)),
                              down=2 + rng.randrange(4)),
    ]


@dataclass
class ShrunkScenario:
    """A minimal violating tape plus the bookkeeping tests assert on."""

    tape: Tape
    violations: list
    steps: int                  # evaluator calls the shrink consumed
    from_events: int            # tape size before shrinking
    mutations: list = field(default_factory=list)

    def artifact(self) -> str:
        muts = json.dumps([mutation_to_dict(m) for m in self.mutations],
                          separators=(",", ":"))
        lines = [
            "# ktpu scenario artifact — minimal failing tape",
            f"# violations: {'; '.join(self.violations) or '(none)'}",
            f"# replay: KTPU_SCENARIO_SEED={self.tape.config.seed} "
            "python -m kubernetes_tpu.scenario.search --replay <this file>",
            f"# KTPU_SCENARIO_SEED={self.tape.config.seed}",
            f"# KTPU_SCENARIO_MUTATIONS={muts}",
        ]
        return "\n".join(lines) + "\n" + self.tape.to_text()


@dataclass
class SearchResult:
    found: bool
    evaluations: int
    mutations: list
    violations: list
    pressure: float
    shrunk: ShrunkScenario | None = None

    def __str__(self) -> str:
        if not self.found:
            return (f"no violation in {self.evaluations} evaluations "
                    f"(best pressure {self.pressure:.2f})")
        sh = self.shrunk
        return (f"violation after {self.evaluations} evaluations: "
                f"{'; '.join(self.violations)} — shrunk "
                f"{sh.from_events} -> {len(sh.tape.events)} events / "
                f"{sh.tape.config.nodes} nodes in {sh.steps} steps")


def _violates(evaluate, tape: Tape, counter: list) -> list:
    counter[0] += 1
    violations, _ = evaluate(tape)
    return violations


def shrink(tape: Tape, evaluate, *, keep_mutations=()) -> ShrunkScenario:
    """Delta-debug a violating tape to a minimal one.

    Three passes, all counted in ``steps``: (1) binary-search the
    shortest violating event *prefix* (churn failures are usually
    triggered by everything up to some straw — later events are noise);
    (2) ddmin chunk removal over the surviving events; (3) binary-search
    the minimal initial node count. If a probe stops violating, the
    candidate is simply rejected — non-monotone evaluators cost extra
    probes, never correctness."""
    counter = [0]
    events = list(tape.events)
    from_events = len(events)

    # pass 1: minimal violating prefix
    lo, hi = 1, len(events)
    while lo < hi:
        mid = (lo + hi) // 2
        if _violates(evaluate, tape.with_events(events[:mid]), counter):
            hi = mid
        else:
            lo = mid + 1
    if _violates(evaluate, tape.with_events(events[:lo]), counter):
        events = events[:lo]
    # else: non-monotone around the boundary — keep the full tape

    # pass 2: ddmin chunk removal
    n = 2
    while len(events) >= 2:
        chunk = (len(events) + n - 1) // n
        reduced = False
        for i in range(n):
            cand = events[:i * chunk] + events[(i + 1) * chunk:]
            if not cand:
                continue
            if _violates(evaluate, tape.with_events(cand), counter):
                events = cand
                n = max(2, n - 1)
                reduced = True
                break
        if not reduced:
            if n >= len(events):
                break
            n = min(len(events), 2 * n)

    # pass 3: minimal node count
    cur = tape.with_events(events)
    lo, hi = 1, cur.config.nodes
    while lo < hi:
        mid = (lo + hi) // 2
        if _violates(evaluate, cur.with_nodes(mid), counter):
            hi = mid
        else:
            lo = mid + 1
    cand = cur.with_nodes(lo)
    if _violates(evaluate, cand, counter):
        cur = cand

    violations, _ = evaluate(cur)
    counter[0] += 1
    return ShrunkScenario(tape=cur, violations=violations,
                          steps=counter[0], from_events=from_events,
                          mutations=list(keep_mutations))


class ScenarioSearch:
    """Seeded greedy hill-climb over mutation stacks.

    Each round draws the mutation menu from the search's own
    ``random.Random(seed)`` stream, tries each candidate on top of the
    current stack, and keeps the one that raises ``pressure`` the most.
    The first tape whose ``violations`` is non-empty goes straight to
    :func:`shrink`. Fully deterministic from ``seed``."""

    def __init__(self, config: TraceConfig, evaluate, *, seed: int = 0,
                 rounds: int = 8, do_shrink: bool = True):
        self.config = config
        self.evaluate = evaluate
        self.seed = seed
        self.rounds = rounds
        self.do_shrink = do_shrink

    def run(self) -> SearchResult:
        rng = random.Random(self.seed)
        evaluations = 0
        stack: list = []

        def ev(muts):
            nonlocal evaluations
            evaluations += 1
            tape = make_tape(self.config, muts)
            violations, pressure = self.evaluate(tape)
            return tape, violations, pressure

        tape, violations, best = ev(stack)
        if not violations:
            for _ in range(self.rounds):
                gain = None
                for m in default_mutations(rng, self.config):
                    tape, violations, pressure = ev(stack + [m])
                    if violations:
                        stack = stack + [m]
                        break
                    if pressure > best and (gain is None
                                            or pressure > gain[1]):
                        gain = (m, pressure)
                if violations:
                    break
                if gain is not None:
                    stack = stack + [gain[0]]
                    best = gain[1]
        if not violations:
            return SearchResult(found=False, evaluations=evaluations,
                                mutations=stack, violations=[],
                                pressure=best)
        shrunk = None
        if self.do_shrink:
            shrunk = shrink(tape, self.evaluate, keep_mutations=stack)
            evaluations += shrunk.steps
        return SearchResult(found=True, evaluations=evaluations,
                            mutations=stack, violations=violations,
                            pressure=max(best, 1.0), shrunk=shrunk)


@dataclass
class NightlyResult:
    """Outcome of one nightly sweep: which seeds ran, what (if anything)
    was found, and where the replay artifact landed."""

    seeds: list
    found_seed: int | None = None
    result: SearchResult | None = None
    artifact_path: str | None = None


def nightly_search(make_config, evaluate, *, base_seed: int = 0,
                   nights: int = 4, rounds: int = 4,
                   out_path: str = "ktpu-scenario-artifact.txt",
                   log=lambda msg: None) -> NightlyResult:
    """The nightly scenario-search job: ``nights`` independent seeded
    searches against HEAD (seed ``base_seed + i`` for night ``i``, so a
    sweep is as replayable as a single search).  The first violation is
    shrunk and its artifact — ``KTPU_SCENARIO_SEED`` line, mutation
    stack, minimal tape — is written to ``out_path``: the morning
    engineer replays with one command instead of re-searching.  A clean
    sweep writes nothing."""
    seeds: list = []
    for i in range(nights):
        seed = base_seed + i
        seeds.append(seed)
        result = ScenarioSearch(make_config(seed), evaluate, seed=seed,
                                rounds=rounds).run()
        log(f"night {i + 1}/{nights} seed={seed}: {result}")
        if result.found:
            with open(out_path, "w") as f:
                f.write(result.shrunk.artifact())
            log(f"artifact -> {out_path}")
            return NightlyResult(seeds, found_seed=seed, result=result,
                                 artifact_path=out_path)
    return NightlyResult(seeds)


def soak_evaluator(**soak_kwargs):
    """The production evaluator: play the tape through the full control
    plane (:func:`~kubernetes_tpu.scenario.soak.run_soak`) and return
    its gate verdict. Every kwarg is forwarded (tick_seconds,
    p99_bound_ms, ...), so the search probes exactly the bench's
    configuration."""
    from kubernetes_tpu.scenario.soak import run_soak

    def evaluate(tape: Tape):
        result = run_soak(tape=tape, **soak_kwargs)
        return result.violations, result.pressure

    return evaluate


def main(argv=None) -> int:
    import argparse
    import os
    import sys

    ap = argparse.ArgumentParser(
        description="search trace-scenario space for gate violations, "
        "replay a shrunk artifact, or run the nightly sweep")
    ap.add_argument("--seed", type=int,
                    default=int(os.environ.get("KTPU_SCENARIO_SEED", 0)))
    ap.add_argument("--rounds", type=int, default=4)
    ap.add_argument("--nodes", type=int, default=8)
    ap.add_argument("--ticks", type=int, default=32)
    ap.add_argument("--rate", type=float, default=2.0)
    ap.add_argument("--tick-seconds", type=float, default=0.02)
    ap.add_argument("--p99-ms", type=float, default=0.0)
    ap.add_argument("--replay", metavar="FILE",
                    help="evaluate a saved tape artifact instead of "
                    "searching")
    ap.add_argument("--nightly", type=int, default=0, metavar="N",
                    help="nightly job: N independent seeded searches "
                    "(seed, seed+1, ...), auto-writing the shrunk "
                    "artifact of the first find to --out")
    ap.add_argument("--out", metavar="FILE",
                    help="write the shrunk artifact here (nightly "
                    "default: ktpu-scenario-artifact.txt)")
    args = ap.parse_args(argv)

    evaluate = soak_evaluator(tick_seconds=args.tick_seconds,
                              p99_bound_ms=args.p99_ms)
    if args.replay:
        with open(args.replay) as f:
            text = "".join(ln for ln in f if not ln.startswith("#"))
        violations, pressure = evaluate(Tape.from_text(text))
        print(f"pressure {pressure:.2f}; violations: "
              f"{'; '.join(violations) or '(none)'}")
        return 1 if violations else 0

    def make_config(seed: int) -> TraceConfig:
        return TraceConfig(seed=seed, ticks=args.ticks, nodes=args.nodes,
                           base_rate=args.rate, flap_rate=0.05,
                           watch_expire_ticks=(args.ticks // 3,),
                           watcher_drop_ticks=(2 * args.ticks // 3,))

    if args.nightly:
        nightly = nightly_search(
            make_config, evaluate, base_seed=args.seed,
            nights=args.nightly, rounds=args.rounds,
            out_path=args.out or "ktpu-scenario-artifact.txt", log=print)
        return 1 if nightly.found_seed is not None else 0

    result = ScenarioSearch(make_config(args.seed), evaluate,
                            seed=args.seed, rounds=args.rounds).run()
    print(result)
    if result.shrunk is not None:
        artifact = result.shrunk.artifact()
        if args.out:
            with open(args.out, "w") as f:
                f.write(artifact)
            print(f"artifact -> {args.out}")
        else:
            sys.stdout.write(artifact)
    return 1 if result.found else 0


if __name__ == "__main__":
    raise SystemExit(main())
